"""Device aggregation kernels: vectorized segmented reductions.

Reference analog: the Aggregator/BufferAggregator implementations
(processing/src/main/java/org/apache/druid/query/aggregation/ — per-row
`aggregate()` calls in the cursor hot loop, TimeseriesQueryEngine.java:87).

TPU-first inversion: an AggKernel consumes a whole block at once —
(columns, row mask, per-row group key) → per-group partial state via
`jax.ops.segment_sum/min/max`. One XLA op replaces millions of virtual calls.
States combine across segments/chips (host numpy or psum over ICI) and
finalize host-side. The same kernels serve timeseries (key = time bucket),
topN (key = bucket×cardinality + dim id) and groupBy (key = fused dim ids) —
the unification the reference approximates with three separate engines.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from druid_tpu.data.segment import Segment, ValueType
from druid_tpu.engine import hll as hll_mod
from druid_tpu.engine.filters import FilterNode, plan_filter
from druid_tpu.query import aggregators as A

INT32_MAX = np.int32(2**31 - 1)
INT64_MAX = np.int64(2**63 - 1)
INT64_MIN = np.int64(-(2**63))


def _seg_sum(values, keys, num):
    import jax
    return jax.ops.segment_sum(values, keys, num_segments=num)


def _seg_min(values, keys, num):
    import jax
    return jax.ops.segment_min(values, keys, num_segments=num)


def _seg_max(values, keys, num):
    import jax
    return jax.ops.segment_max(values, keys, num_segments=num)


@dataclass
class MMPlan:
    """A kernel's one-hot-matmul decomposition (see engine/mmagg.py).

    The engine builds the [block, G] one-hot of (key ∧ mask) once per block
    and contracts it against every registered kernel's value rows in two
    batched matmuls: int8 rows accumulate in int32 (exact ≤7-bit limbs),
    bfloat16 rows accumulate in float32 (hi/lo/lo2 triple splits).

    fields:    columns make_rows reads (staged/padded by the engine)
    n_i8:      number of int8 rows this kernel contributes
    n_bf16:    number of bf16 rows
    make_rows: (cols_block, mask_block) -> (list of int8 [B] rows,
               list of bf16 [B] rows)
    finish:    (i32_parts [n_i8, G], f32_parts [n_bf16, G], num) -> state,
               shaped like the kernel's scatter `update` state
    """
    fields: Tuple[str, ...]
    n_i8: int
    n_bf16: int
    make_rows: object
    finish: object


class AggKernel:
    """One aggregator's device update + host combine/finalize."""

    #: how partial states merge across segments/devices inside a traced
    #: program: "sum" (psum), "min"/"max" (pmin/pmax), or "fold" (all_gather
    #: + pairwise device_combine). The device analog of host `combine`.
    reduce_kind = "fold"

    def __init__(self, spec: A.AggregatorSpec):
        self.spec = spec
        self.name = spec.name

    def signature(self) -> str:
        raise NotImplementedError

    def aux_arrays(self) -> List[np.ndarray]:
        return []

    def filter_trees(self) -> List[FilterNode]:
        """Planned filter trees this kernel owns (FilteredKernel chains) —
        the walk bitmap-word staging and slot assignment use."""
        return []

    def required_device_columns(self) -> Optional[set]:
        """Staged columns update() actually reads, when narrower than the
        spec's required_columns (None = use the spec's). FilteredKernel
        overrides: a filter subtree compiled to device bitmap words reads
        resident words, not columns, so filter-only columns stop staging."""
        return None

    def update(self, cols: Dict, mask, keys, num: int, aux: Iterator):
        """Traced: per-group partial state (device pytree)."""
        raise NotImplementedError

    def host_post(self, state, segment: Segment):
        """Convert device state to host combine-ready state."""
        return np.asarray(state)

    def device_post(self, state, time0):
        """Traced: make a per-segment state segment-origin independent
        (e.g. relative→absolute time) so states combine on device across
        segments with different time origins."""
        return state

    def host_from_device(self, state):
        """Convert a device_post-ed, device-combined state to the host
        combine-ready form (same shape host_post produces)."""
        return np.asarray(state)

    def device_combine(self, a, b):
        """Traced pairwise state combine (for reduce_kind == "fold")."""
        raise NotImplementedError

    def combine(self, a, b):
        raise NotImplementedError

    def empty_state(self, n: int):
        """Identity state of length n (host), for sparse merge alignment."""
        raise NotImplementedError

    def finalize_array(self, state) -> np.ndarray:
        """Per-group finalized values (host)."""
        return state

    def finalize_value(self, v):
        return self.spec.finalize(v)

    # ---- blocked path (small group spaces) ------------------------------
    # For num_groups ≲ 2k, a scanned [block, G] masked broadcast-reduce is
    # ~5x faster than scatter on TPU (scatter serializes; broadcast-reduce
    # runs at VPU width). Kernels opting in implement a per-block partial
    # from the `valid` (rows × groups bool) matrix.

    def blocked_supported(self, cols_avail) -> bool:
        return False

    def blocked_init(self, num: int, cols: Dict):
        """Zero carry; `cols` is the full traced array dict (for dtypes)."""
        raise NotImplementedError

    def blocked_step(self, carry, cols_block: Dict, valid, num: int):
        """valid: bool [B, num]; returns updated carry ([num]-shaped)."""
        raise NotImplementedError

    def blocked_finish(self, carry):
        """Carry → the same state `update` would produce."""
        return carry

    # ---- one-hot matmul path (MXU, small group spaces) ------------------
    # For num_groups ≲ 4k, contracting an int8/bf16 one-hot against value
    # rows on the MXU beats both scatter and the VPU broadcast path (~2-10x
    # measured on v5e). Kernels whose update is a per-group SUM of per-row
    # values opt in by returning an MMPlan.

    def mm_plan(self, cols_avail: Dict, padded_rows: int) -> Optional[MMPlan]:
        return None

    # ---- pallas path (sorted projections, any group-space size) ---------
    # Descriptor consumed by engine/pallas_agg.pallas_reduce: ("count",),
    # ("sum_i32"|"sum_f32"|"min_i32"|"max_i32"|"min_f32"|"max_f32", field,
    # ...), ("zero",)/("empty",) for missing columns, or None (ineligible).

    def pallas_op(self, cols_avail: Dict) -> Optional[tuple]:
        return None


class CountKernel(AggKernel):
    reduce_kind = "sum"

    def signature(self):
        return "count"

    def pallas_op(self, cols_avail):
        return ("count",)

    def update(self, cols, mask, keys, num, aux):
        import jax.numpy as jnp
        return _seg_sum(mask.astype(jnp.int32), keys, num)

    def host_post(self, state, segment):
        return np.asarray(state, dtype=np.int64)

    def combine(self, a, b):
        return a + b

    def empty_state(self, n):
        return np.zeros(n, dtype=np.int64)

    def blocked_supported(self, cols_avail):
        return True

    def blocked_init(self, num, cols):
        import jax.numpy as jnp
        return jnp.zeros(num, jnp.int32)

    def blocked_step(self, carry, cols_block, valid, num):
        import jax.numpy as jnp
        # dtype pinned so the scan carry stays int32 under x64
        return carry + valid.astype(jnp.int32).sum(axis=0, dtype=jnp.int32)

    def mm_plan(self, cols_avail, padded_rows):
        import jax.numpy as jnp
        if padded_rows >= 2**31:
            return None

        def make(cols, mask):
            return [jnp.ones(mask.shape, jnp.int8)], []

        def fin(i8, bf, num):
            return i8[0]
        return MMPlan((), 1, 0, make, fin)


class SumKernel(AggKernel):
    reduce_kind = "sum"
    _DTYPES = {ValueType.LONG: "int64", ValueType.FLOAT: "float32",
               ValueType.DOUBLE: "float64"}

    def __init__(self, spec, vtype: ValueType, segment: Optional[Segment] = None):
        super().__init__(spec)
        self.vtype = vtype
        # code-domain constant sum (data/cascade.py ladder): a LONG column
        # whose cached min == max sums as constant × group count — the
        # column neither stages nor decodes (required_device_columns = {}).
        # Exact: Σ c over k int rows ≡ c·k in int64. LONG only — float
        # repetition vs multiplication differ in rounding. The constant
        # rides aux (not the closure), so one program serves every value.
        self.const_value: Optional[int] = None
        # exact narrow path: int32-staged long columns sum via CHUNKED int32
        # scatters (64-bit scatter is limb-emulated, ~5x) with int64
        # accumulation only at group granularity. chunk_rows bounds each
        # per-(chunk, group) partial below 2^30 regardless of skew.
        self.chunk_rows = 0
        # one-hot matmul decomposition: ≤7-bit limb rows of (v - base), base
        # the column min when negative. Eligible when ≤4 limbs cover the range.
        self.mm_limbs = 0
        self.mm_base = 0
        # FLOAT mm eligibility: a non-finite row would poison ALL groups
        # through the one-hot contraction (NaN·0 = NaN), so the matmul path
        # requires a host-verified all-finite staged column. Virtual columns
        # (not segment metrics) can produce NaN on device — ineligible.
        self.mm_float_ok = bool(
            vtype is ValueType.FLOAT and segment is not None
            and spec.field in segment.metrics
            and segment.column_finite(spec.field))
        if vtype is ValueType.LONG and segment is not None \
                and spec.field in segment.metrics:
            from druid_tpu.data import cascade as cascade_mod
            lo, hi = segment.column_minmax(spec.field)
            if lo == hi and cascade_mod.enabled():
                self.const_value = int(lo)
        if vtype is ValueType.LONG and segment is not None \
                and spec.field in segment.metrics \
                and segment.staged_dtype(spec.field) == np.int32:
            lo, hi = segment.column_minmax(spec.field)
            max_abs = max(abs(lo), abs(hi), 1)
            r = (2 ** 30) // max_abs
            # the bound only holds when ≥1024 rows fit under 2^30: values
            # above ~2^20 would wrap the int32 partial inside ONE chunk —
            # stay on the general int64 path instead of flooring the chunk.
            # Power-of-two quantization (any chunk ≤ r keeps the bound):
            # chunk_rows is a plan constant in signature(), and coarse steps
            # let segments with near-identical value ranges share one
            # batched/sharded program instead of splitting shape buckets
            self.chunk_rows = 1 << (r.bit_length() - 1) if r >= 1024 else 0
            base = min(int(lo), 0)
            span = int(hi) - base
            nl = max(1, (span.bit_length() + 6) // 7)
            if nl <= 4:
                self.mm_limbs = nl
                self.mm_base = base

    def signature(self):
        return (f"sum({self.spec.field},{self.vtype.value},{self.chunk_rows},"
                f"mm{self.mm_limbs}:{self.mm_base}:"
                f"{int(self.mm_float_ok)},"
                f"c{int(self.const_value is not None)})")

    def aux_arrays(self):
        if self.const_value is not None:
            return [np.asarray(self.const_value, dtype=np.int64)]
        return []

    def required_device_columns(self):
        # constant column: the update reads NOTHING — the column stops
        # staging entirely (the strongest cascade rung)
        return set() if self.const_value is not None else None

    def mm_plan(self, cols_avail, padded_rows):
        import jax.numpy as jnp
        f = self.spec.field
        if self.const_value is not None:
            # the constant must stay out of the traced closure (aux-only,
            # so one program serves every value) — no mm decomposition
            return None
        # checked before the missing-column branch so plan-time
        # (select_strategy, staged columns only) and trace-time
        # (fuse_filter_update, includes virtual columns) decisions agree
        if self.vtype is ValueType.FLOAT and not self.mm_float_ok:
            return None
        if f not in cols_avail:
            def make(cols, mask):
                return [], []

            def fin(i8, bf, num):
                dt = jnp.float32 if self.vtype is ValueType.FLOAT else jnp.int64
                return jnp.zeros(num, dt)
            return MMPlan((), 0, 0, make, fin)
        if self.vtype is ValueType.FLOAT:
            # bf16 triple split: hi/lo/lo2 capture all 24 f32 mantissa bits;
            # products against the 0/1 one-hot are exact, only the f32
            # accumulation rounds (better than sequential f32 summation)
            def make(cols, mask):
                v = jnp.where(mask, cols[f], 0.0)  # NaN/inf guard off-mask
                hi = v.astype(jnp.bfloat16)
                r1 = v - hi.astype(jnp.float32)
                m1 = r1.astype(jnp.bfloat16)
                r2 = (r1 - m1.astype(jnp.float32)).astype(jnp.bfloat16)
                return [], [hi, m1, r2]

            def fin(i8, bf, num):
                return bf[0] + bf[1] + bf[2]
            return MMPlan((f,), 0, 3, make, fin)
        if self.vtype is ValueType.LONG and self.mm_limbs \
                and padded_rows * 127 < 2**31:
            nl, base = self.mm_limbs, self.mm_base
            n_rows = nl + (1 if base else 0)

            def make(cols, mask):
                v = cols[f] - jnp.int32(base)
                rows = [((v >> (7 * i)) & 127).astype(jnp.int8)
                        for i in range(nl)]
                if base:
                    rows.append(jnp.ones(mask.shape, jnp.int8))
                return rows, []

            def fin(i8, bf, num):
                s = jnp.zeros(num, jnp.int64)
                for i in range(nl):
                    s = s + (i8[i].astype(jnp.int64) << (7 * i))
                if base:
                    s = s + i8[nl].astype(jnp.int64) * base
                return s
            return MMPlan((f,), n_rows, 0, make, fin)
        return None

    def pallas_op(self, cols_avail):
        f = self.spec.field
        if self.const_value is not None:
            return None                   # aux-fed paths only (see mm_plan)
        if f not in cols_avail:
            return ("zero",)
        dt = str(cols_avail[f])
        if self.vtype is ValueType.FLOAT and dt == "float32":
            return ("sum_f32", f)
        # exact int64 via in-kernel lo/hi limbs; chunk_rows ≥ 2048 bounds the
        # per-block partial exactly like the blocked path
        if self.vtype is ValueType.LONG and dt == "int32" \
                and self.chunk_rows >= 2048:
            return ("sum_i32", f, self.chunk_rows)
        return None

    def update(self, cols, mask, keys, num, aux):
        import jax
        import jax.numpy as jnp
        acc_dtype = jnp.dtype(self._DTYPES[self.vtype])
        if self.const_value is not None:
            # code-domain: Σ = constant × per-group row count; the column
            # itself is never read (and was never staged)
            c = next(aux)
            # exact const×count contract; x64 is globally on (engine/__init__)
            return _seg_sum(mask.astype(jnp.int64), keys, num) * c  # druidlint: disable=x64-dtype
        if self.spec.field not in cols:
            # missing column aggregates as null/zero (reference semantics)
            return jnp.zeros((num,), dtype=acc_dtype)
        v = cols[self.spec.field]
        if self.chunk_rows and v.dtype == jnp.int32:
            n = v.shape[0]
            v32 = jnp.where(mask, v, 0)
            if n <= self.chunk_rows:
                return _seg_sum(v32, keys, num).astype(jnp.int64)
            c = -(-n // self.chunk_rows)
            pad = c * self.chunk_rows - n
            if pad:
                v32 = jnp.concatenate([v32, jnp.zeros(pad, jnp.int32)])
                keys_p = jnp.concatenate([keys, jnp.zeros(pad, keys.dtype)])
            else:
                keys_p = keys
            vc = v32.reshape(c, self.chunk_rows)
            kc = keys_p.reshape(c, self.chunk_rows)

            def body(acc, xs):
                vb, kb = xs
                # int64 accumulation at group granularity IS the exact-sum
                # contract (chunk analysis above); x64 is globally on
                return acc + _seg_sum(vb, kb, num).astype(jnp.int64), None  # druidlint: disable=x64-dtype

            # derive the zero carry from the data so it inherits the
            # varying-axis type under shard_map (a plain zeros init is
            # "unvarying" and the scan rejects the mismatch)
            init = jnp.zeros(num, jnp.int64) + (v32[0] * 0).astype(jnp.int64)
            acc, _ = jax.lax.scan(body, init, (vc, kc))
            return acc
        v = jnp.where(mask, v, 0).astype(acc_dtype)
        return _seg_sum(v, keys, num)

    def combine(self, a, b):
        return a + b

    def empty_state(self, n):
        return np.zeros(n, dtype=np.dtype(self._DTYPES[self.vtype]))

    # blocked: int32-narrowed longs (block sums bounded via chunk_rows
    # analysis) and float32; float64 would emulate elementwise — scatter
    # stays cheaper there
    BLOCK_ROWS = 2048

    def blocked_supported(self, cols_avail):
        if self.const_value is not None:
            return False  # the blocked step has no aux stream for c
        if self.spec.field not in cols_avail:
            return True   # missing column: constant zero carry
        if self.vtype is ValueType.FLOAT:
            return True
        return bool(self.chunk_rows) and self.chunk_rows >= self.BLOCK_ROWS

    def blocked_init(self, num, cols):
        import jax.numpy as jnp
        dt = jnp.float32 if self.vtype is ValueType.FLOAT else jnp.int64
        return jnp.zeros(num, dt)

    def blocked_step(self, carry, cols_block, valid, num):
        import jax.numpy as jnp
        if self.spec.field not in cols_block:
            return carry
        v = cols_block[self.spec.field]
        if self.vtype is ValueType.FLOAT:
            part = jnp.where(valid, v[:, None], 0.0).sum(axis=0)
            return carry + part
        part = jnp.where(valid, v[:, None], 0).sum(axis=0)
        return carry + part.astype(jnp.int64)


class MinMaxKernel(AggKernel):
    def __init__(self, spec, vtype: ValueType, is_max: bool,
                 segment: Optional[Segment] = None):
        super().__init__(spec)
        self.vtype = vtype
        self.is_max = is_max
        self.reduce_kind = "max" if is_max else "min"
        # staged dtype participates in program structure (blocked-path
        # eligibility + sentinel dtype), so it must key the jit cache
        self.staged = str(segment.staged_dtype(spec.field)) \
            if segment is not None and spec.field in segment.metrics \
            else ""

    def signature(self):
        return (f"{'max' if self.is_max else 'min'}"
                f"({self.spec.field},{self.vtype.value},{self.staged})")

    @property
    def identity(self):
        if self.vtype == ValueType.LONG:
            return INT64_MIN if self.is_max else INT64_MAX
        return np.float64(-np.inf) if self.is_max else np.float64(np.inf)

    def pallas_op(self, cols_avail):
        f = self.spec.field
        if f not in cols_avail:
            return ("empty",)
        dt = str(cols_avail[f])
        if dt == "int32":
            return ("max_i32" if self.is_max else "min_i32", f)
        if dt == "float32":
            return ("max_f32" if self.is_max else "min_f32", f)
        return None

    def update(self, cols, mask, keys, num, aux):
        import jax.numpy as jnp
        if self.spec.field not in cols:
            return jnp.asarray(np.broadcast_to(self.empty_state(1), (num,)))
        v = cols[self.spec.field]
        # identity in the STAGED dtype (int32-narrowed longs use int32
        # sentinels; casting the int64 sentinel would wrap)
        if jnp.issubdtype(v.dtype, jnp.integer):
            info = jnp.iinfo(v.dtype)
            ident = jnp.asarray(info.min if self.is_max else info.max,
                                dtype=v.dtype)
        else:
            ident = jnp.asarray(-jnp.inf if self.is_max else jnp.inf,
                                dtype=v.dtype)
        v = jnp.where(mask, v, ident)
        return _seg_max(v, keys, num) if self.is_max else _seg_min(v, keys, num)

    def host_post(self, state, segment):
        st = np.asarray(state)
        if self.vtype == ValueType.LONG and st.dtype != np.int64:
            # restore canonical int64 state; narrow sentinels widen to the
            # int64 identity so cross-segment merges stay correct
            narrow_ident = np.iinfo(st.dtype).min if self.is_max \
                else np.iinfo(st.dtype).max
            st64 = st.astype(np.int64)
            st64[st == narrow_ident] = self.identity
            return st64
        return st

    def host_from_device(self, state):
        return self.host_post(state, None)

    def blocked_supported(self, cols_avail):
        if self.spec.field not in cols_avail:
            return True
        dt = cols_avail[self.spec.field]
        return dt in (np.int32, np.float32) or str(dt) in ("int32", "float32")

    def _ident_for(self, dtype):
        import jax.numpy as jnp
        if jnp.issubdtype(dtype, jnp.integer):
            info = jnp.iinfo(dtype)
            return jnp.asarray(info.min if self.is_max else info.max, dtype)
        return jnp.asarray(-jnp.inf if self.is_max else jnp.inf, dtype)

    def blocked_init(self, num, cols):
        import jax.numpy as jnp
        if self.spec.field not in cols:
            return jnp.asarray(np.broadcast_to(self.empty_state(1), (num,)))
        ident = self._ident_for(cols[self.spec.field].dtype)
        return jnp.full(num, ident)

    def blocked_step(self, carry, cols_block, valid, num):
        import jax.numpy as jnp
        if self.spec.field not in cols_block:
            return carry
        v = cols_block[self.spec.field]
        ident = self._ident_for(v.dtype)
        vm = jnp.where(valid, v[:, None], ident)
        part = vm.max(axis=0) if self.is_max else vm.min(axis=0)
        return jnp.maximum(carry, part) if self.is_max \
            else jnp.minimum(carry, part)

    def combine(self, a, b):
        return np.maximum(a, b) if self.is_max else np.minimum(a, b)

    def empty_state(self, n):
        dt = (np.int64 if self.vtype == ValueType.LONG
              else np.float32 if self.vtype == ValueType.FLOAT else np.float64)
        return np.full(n, self.identity, dtype=dt)


class FirstLastKernel(AggKernel):
    """Value at min/max __time per group (reference: aggregation/first, /last).

    Device: two-phase — segment-min/max of time, then segment-min of row index
    among rows hitting that time, then gather the value. State carries
    (absolute time, value) so cross-segment combine is order-correct.
    """

    def __init__(self, spec, vtype: ValueType, is_last: bool,
                 time_field: Optional[str] = None):
        super().__init__(spec)
        self.vtype = vtype
        self.is_last = is_last
        # rolled-up segments carry true event times in a hidden pair column
        # (__ft_<field>, absolute int64); without it, row __time orders
        self.time_field = time_field

    def signature(self):
        return (f"{'last' if self.is_last else 'first'}"
                f"({self.spec.field},{self.vtype.value},"
                f"pt={self.time_field or ''})")

    def update(self, cols, mask, keys, num, aux):
        import jax.numpy as jnp
        pair = self.time_field is not None and self.time_field in cols
        t = cols[self.time_field] if pair else cols["__time_offset"]
        if self.spec.field not in cols:
            e = self.empty_state(1)
            return (jnp.asarray(np.broadcast_to(
                        np.asarray(e["time"], dtype=np.int32).clip(-(2**31), 2**31 - 1),
                        (num,))),
                    jnp.asarray(np.broadcast_to(e["value"], (num,))),
                    jnp.zeros((num,), dtype=bool))
        v = cols[self.spec.field]
        n = t.shape[0]
        if self.is_last:
            ident_t = (jnp.int64(INT64_MIN) if pair
                       else jnp.int32(-(2**31)))
            tbest = _seg_max(jnp.where(mask, t, ident_t), keys, num)
        else:
            ident_t = jnp.int64(INT64_MAX) if pair else INT32_MAX
            tbest = _seg_min(jnp.where(mask, t, ident_t), keys, num)
        cand = mask & (t == tbest[keys])
        idx = jnp.where(cand, jnp.arange(n, dtype=jnp.int32), jnp.int32(n))
        best_idx = _seg_min(idx, keys, num)
        has = best_idx < n
        safe_idx = jnp.clip(best_idx, 0, n - 1)
        val = jnp.where(has, v[safe_idx], 0)
        return (jnp.where(has, tbest, ident_t), val, has)

    def host_post(self, state, segment):
        t, v, has = (np.asarray(s) for s in state)
        t_abs = t.astype(np.int64)
        if self.time_field is None:
            t_abs = t_abs + segment.interval.start
        ident = INT64_MIN if self.is_last else INT64_MAX
        t_abs = np.where(has, t_abs, ident)
        return {"time": t_abs, "value": np.asarray(v), "has": has}

    def device_post(self, state, time0):
        import jax.numpy as jnp
        t, v, has = state
        ident = INT64_MIN if self.is_last else INT64_MAX
        t64 = t.astype(jnp.int64)
        if self.time_field is None:
            t64 = t64 + time0
        t_abs = jnp.where(has, t64, jnp.int64(ident))
        return (t_abs, v, has)

    def device_combine(self, a, b):
        import jax.numpy as jnp
        at, av, ah = a
        bt, bv, bh = b
        if self.is_last:
            take_b = (bt > at) | (~ah & bh)
        else:
            take_b = (bt < at) | (~ah & bh)
        return (jnp.where(take_b, bt, at), jnp.where(take_b, bv, av), ah | bh)

    def host_from_device(self, state):
        t, v, has = (np.asarray(s) for s in state)
        return {"time": t, "value": v, "has": has}

    def combine(self, a, b):
        if self.is_last:
            take_b = (b["time"] > a["time"]) | (~a["has"] & b["has"])
        else:
            take_b = (b["time"] < a["time"]) | (~a["has"] & b["has"])
        return {
            "time": np.where(take_b, b["time"], a["time"]),
            "value": np.where(take_b, b["value"], a["value"]),
            "has": a["has"] | b["has"],
        }

    def empty_state(self, n):
        ident = INT64_MIN if self.is_last else INT64_MAX
        vdt = (np.int64 if self.vtype == ValueType.LONG
               else np.float32 if self.vtype == ValueType.FLOAT else np.float64)
        return {"time": np.full(n, ident, dtype=np.int64),
                "value": np.zeros(n, dtype=vdt),
                "has": np.zeros(n, dtype=bool)}

    def finalize_array(self, state):
        return np.where(state["has"], state["value"], 0)


class FilteredKernel(AggKernel):
    """Delegate kernel gated by an extra filter mask
    (reference: FilteredAggregatorFactory)."""

    def __init__(self, spec: A.FilteredAggregator, child: AggKernel,
                 filter_node: FilterNode):
        super().__init__(spec)
        self.child = child
        self.filter_node = filter_node
        self.reduce_kind = child.reduce_kind

    def signature(self):
        return f"filtered({self.filter_node.signature()},{self.child.signature()})"

    def aux_arrays(self):
        return self.filter_node.aux_arrays() + self.child.aux_arrays()

    def filter_trees(self):
        return [self.filter_node] + self.child.filter_trees()

    def required_device_columns(self):
        child = self.child.required_device_columns()
        if child is None:
            child = set(self.spec.delegate.required_columns())
        return child | self.filter_node.required_device_columns()

    def update(self, cols, mask, keys, num, aux):
        fmask = self.filter_node.build(cols, aux)
        return self.child.update(cols, mask & fmask, keys, num, aux)

    def host_post(self, state, segment):
        return self.child.host_post(state, segment)

    def device_post(self, state, time0):
        return self.child.device_post(state, time0)

    def device_combine(self, a, b):
        return self.child.device_combine(a, b)

    def host_from_device(self, state):
        return self.child.host_from_device(state)

    def combine(self, a, b):
        return self.child.combine(a, b)

    def empty_state(self, n):
        return self.child.empty_state(n)

    def finalize_array(self, state):
        return self.child.finalize_array(state)


class HllKernel(AggKernel):
    """cardinality / hyperUnique via scatter-max register updates
    (see druid_tpu/engine/hll.py)."""

    reduce_kind = "max"  # register merge = elementwise max (HLL fold)

    def __init__(self, spec, fields: Sequence[str], segment: Segment,
                 log2m: int, by_row: bool):
        super().__init__(spec)
        self.fields = tuple(fields)
        self.log2m = log2m
        self.by_row = by_row
        self._tables = []
        for f in self.fields:
            col = segment.dims.get(f)
            met = segment.metrics.get(f)
            if col is not None:
                if by_row:
                    tbl = segment.aux_cached(
                        ("hll_hash", f), lambda c=col: hll_mod.dim_hash_table(c.dictionary))
                    self._tables.append(("dim_hash", f, tbl))
                else:
                    reg, rho = segment.aux_cached(
                        ("hll_regrho", f, log2m),
                        lambda c=col: hll_mod.dim_register_tables(c.dictionary, log2m))
                    self._tables.append(("dim_regrho", f, (reg, rho)))
            elif met is not None and met.type is ValueType.COMPLEX:
                # pre-aggregated HLL register column (ingest-time hyperUnique)
                if by_row:
                    raise ValueError(
                        f"byRow cardinality cannot consume pre-aggregated "
                        f"hyperUnique column {f!r}; use hyperUnique instead")
                if met.values.shape[1] != (1 << log2m):
                    raise ValueError(
                        f"hyperUnique column {f!r} has {met.values.shape[1]} "
                        f"registers, query expects {1 << log2m}")
                self._tables.append(("complex", f, None))
            elif met is not None or f == "__time":
                self._tables.append(("numeric", f, None))
            else:
                self._tables.append(("missing", f, None))

    def signature(self):
        # field names must be part of the signature: the jit caches are keyed
        # by it, and the traced closure reads cols[field]
        kinds = ",".join(f"{k}:{f}" for k, f, _ in self._tables)
        return f"hll({self.log2m},{self.by_row},{kinds})"

    def aux_arrays(self):
        out = []
        for kind, f, tbl in self._tables:
            if kind == "dim_hash":
                out.append(tbl)
            elif kind == "dim_regrho":
                out.extend(tbl)
        return out

    def update(self, cols, mask, keys, num, aux):
        import jax.numpy as jnp
        regs = None
        if self.by_row:
            h = None
            for kind, f, _ in self._tables:
                if kind == "dim_hash":
                    tbl = next(aux)
                    hf = tbl[cols[f]]
                elif kind == "numeric":
                    v = cols[f] if f != "__time" else cols["__time_offset"]
                    # floats hash by bit pattern — truncating to int would
                    # collapse every value sharing an integer part
                    hf = hll_mod.splitmix64_device(
                        v.astype(jnp.float64).view(jnp.uint64)
                        if jnp.issubdtype(v.dtype, jnp.floating) else
                        v.astype(jnp.int64).astype(jnp.uint64))
                else:
                    continue
                h = hf if h is None else hll_mod.splitmix64_device(
                    h * jnp.uint64(31) + hf)
            if h is None:
                h = jnp.zeros(mask.shape, dtype=jnp.uint64)
            reg, rho = hll_mod.register_of_device(h, self.log2m)
            regs = hll_mod.update_registers(regs, rho, reg, keys, mask, num,
                                            self.log2m)
            return regs
        for kind, f, _ in self._tables:
            if kind == "complex":
                rows = cols[f].astype(jnp.int32)  # [n, m] registers
                part = _seg_max(
                    jnp.where(mask[:, None], rows, 0), keys, num)
                regs = part if regs is None else jnp.maximum(regs, part)
                continue
            if kind == "dim_regrho":
                reg_t = next(aux)
                rho_t = next(aux)
                reg = reg_t[cols[f]]
                rho = rho_t[cols[f]]
            elif kind == "numeric":
                v = cols[f] if f != "__time" else cols["__time_offset"]
                h = hll_mod.splitmix64_device(
                    v.astype(jnp.float64).view(jnp.uint64)
                    if jnp.issubdtype(v.dtype, jnp.floating) else
                    v.astype(jnp.int64).astype(jnp.uint64))
                reg, rho = hll_mod.register_of_device(h, self.log2m)
            else:
                continue
            regs = hll_mod.update_registers(regs, rho, reg, keys, mask, num,
                                            self.log2m)
        if regs is None:
            import jax.numpy as jnp
            regs = jnp.zeros((num, 1 << self.log2m), dtype=jnp.int32)
        return regs

    def host_post(self, state, segment):
        return np.asarray(state)

    def combine(self, a, b):
        return np.maximum(a, b)

    def empty_state(self, n):
        return np.zeros((n, 1 << self.log2m), dtype=np.int32)

    def finalize_array(self, state):
        est = hll_mod.estimate_array(state, self.log2m)
        if getattr(self.spec, "round", False):
            est = np.rint(est).astype(np.int64)
        return est


def _numeric_type(segment: Segment, field: str, default=ValueType.DOUBLE) -> ValueType:
    if field in segment.metrics:
        return segment.metrics[field].type
    if field == "__time":
        return ValueType.LONG
    return default


# extension-registered kernels: spec class → factory(spec, segment)
_EXTENSION_KERNELS: Dict[type, object] = {}


def register_kernel(spec_cls: type, factory) -> None:
    _EXTENSION_KERNELS[spec_cls] = factory


def make_kernel(spec: A.AggregatorSpec, segment: Segment,
                device_bitmap: Optional[bool] = None) -> AggKernel:
    """`device_bitmap`: how a FILTERED aggregator's filter plans — None
    follows the process default (filters.device_bitmap_enabled), so
    filtered aggregators ride resident bitmap words / the fused megakernel
    instead of forcing decoded filter columns. The sharded mesh path also
    follows the default: its stack carries the words as per-segment slots
    on the mapped axis."""
    factory = _EXTENSION_KERNELS.get(type(spec))
    if factory is not None:
        return factory(spec, segment)
    if isinstance(spec, A.CountAggregator):
        return CountKernel(spec)
    if isinstance(spec, A.LongSumAggregator):
        return SumKernel(spec, ValueType.LONG, segment)
    if isinstance(spec, A.DoubleSumAggregator):
        return SumKernel(spec, ValueType.DOUBLE, segment)
    if isinstance(spec, A.FloatSumAggregator):
        return SumKernel(spec, ValueType.FLOAT, segment)
    if isinstance(spec, A.LongMinAggregator):
        return MinMaxKernel(spec, ValueType.LONG, False, segment)
    if isinstance(spec, A.LongMaxAggregator):
        return MinMaxKernel(spec, ValueType.LONG, True, segment)
    if isinstance(spec, A.DoubleMinAggregator):
        return MinMaxKernel(spec, ValueType.DOUBLE, False, segment)
    if isinstance(spec, A.DoubleMaxAggregator):
        return MinMaxKernel(spec, ValueType.DOUBLE, True, segment)
    if isinstance(spec, A.FloatMinAggregator):
        return MinMaxKernel(spec, ValueType.FLOAT, False, segment)
    if isinstance(spec, A.FloatMaxAggregator):
        return MinMaxKernel(spec, ValueType.FLOAT, True, segment)
    if isinstance(spec, (A.FirstAggregator, A.LastAggregator)):
        tf = f"__ft_{spec.field}"
        return FirstLastKernel(spec, ValueType(spec.kind),
                               isinstance(spec, A.LastAggregator),
                               tf if tf in segment.metrics else None)
    if isinstance(spec, A.FilteredAggregator):
        child = make_kernel(spec.delegate, segment,
                            device_bitmap=device_bitmap)
        # bitmap-eligible subtrees compile to DeviceBitmapNodes (process
        # default): the words ride the staged-arrays dict under globally
        # assigned slots (filters.assign_bitmap_slots) and contribute no
        # kernel aux, so batching's value-compare still holds — the
        # filtered agg rides the fused/batched programs instead of forcing
        # its filter columns to stage decoded
        node = plan_filter(spec.filter, segment,
                           device_bitmap=device_bitmap)
        return FilteredKernel(spec, child, node)
    if isinstance(spec, A.HyperUniqueAggregator):
        return HllKernel(spec, (spec.field,), segment, spec.log2m, by_row=False)
    if isinstance(spec, A.CardinalityAggregator):
        return HllKernel(spec, spec.fields, segment, spec.log2m, spec.by_row)
    raise ValueError(f"no kernel for aggregator {type(spec).__name__}")
