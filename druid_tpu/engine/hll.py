"""HyperLogLog on device: registers as int32 arrays, merges as elementwise max.

Capability parity with the reference's HyperLogLogCollector
(hll/src/main/java/org/apache/druid/hll/HyperLogLogCollector.java:53 — dense
register arrays in ByteBuffers, fold = per-register max, harmonic estimator).

TPU-first reformulation (SURVEY §2.9): the branchy per-row register update
becomes a vectorized scatter-max — rows map to (bucket, register) pairs and
one `segment_max` updates a [num_buckets * m] register grid. String values
are hashed host-side *per dictionary entry* (cardinality-sized work, cached
per segment) so the device only gathers (register, rho) by dictionary id;
numeric columns hash on device with splitmix64.
"""
from __future__ import annotations

from typing import Tuple

import numpy as np

DEFAULT_LOG2M = 11


# ---------------------------------------------------------------------------
# Hashing
# ---------------------------------------------------------------------------

def _splitmix64_np(x: np.ndarray) -> np.ndarray:
    """Vectorized splitmix64 finalizer (host, numpy uint64)."""
    x = x.astype(np.uint64)
    with np.errstate(over="ignore"):
        x = (x + np.uint64(0x9E3779B97F4A7C15))
        x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        x = x ^ (x >> np.uint64(31))
    return x


def hash_strings(values) -> np.ndarray:
    """Deterministic 64-bit hashes of strings (FNV-1a + splitmix finalizer)."""
    out = np.empty(len(values), dtype=np.uint64)
    FNV_OFFSET = 0xCBF29CE484222325
    FNV_PRIME = 0x100000001B3
    MASK = 0xFFFFFFFFFFFFFFFF
    for i, v in enumerate(values):
        h = FNV_OFFSET
        for b in v.encode("utf-8"):
            h = ((h ^ b) * FNV_PRIME) & MASK
        out[i] = h
    return _splitmix64_np(out)


def hash_to_register(hashes: np.ndarray, log2m: int) -> Tuple[np.ndarray, np.ndarray]:
    """hash -> (register index, rho) where rho = 1 + leading-zero count of the
    remaining (64 - log2m) bits, capped for int register storage."""
    m = 1 << log2m
    reg = (hashes & np.uint64(m - 1)).astype(np.int32)
    rest = (hashes >> np.uint64(log2m)).astype(np.uint64)
    width = 64 - log2m
    # leading zeros of `rest` within `width` bits
    rho = np.zeros(rest.shape, dtype=np.int32)
    x = rest.copy()
    # position of highest set bit via float log2 is unsafe; do bit halving
    hb = np.zeros(rest.shape, dtype=np.int32)
    for shift in (32, 16, 8, 4, 2, 1):
        mask_bits = x >= (np.uint64(1) << np.uint64(shift))
        hb = np.where(mask_bits, hb + shift, hb)
        x = np.where(mask_bits, x >> np.uint64(shift), x)
    nonzero = rest != 0
    rho = np.where(nonzero, width - 1 - hb + 1, width + 1).astype(np.int32)
    return reg, rho


def dim_register_tables(dictionary, log2m: int = DEFAULT_LOG2M):
    """Per-dictionary-id (register, rho) tables for device gather."""
    hashes = hash_strings(dictionary.values)
    return hash_to_register(hashes, log2m)


def dim_hash_table(dictionary) -> np.ndarray:
    """Per-dictionary-id raw 64-bit hashes (for byRow combined hashing)."""
    return hash_strings(dictionary.values)


# ---------------------------------------------------------------------------
# Device-side pieces (traced under jit)
# ---------------------------------------------------------------------------

def splitmix64_device(x):
    """splitmix64 under jit (uint64; x64 enabled)."""
    import jax.numpy as jnp
    x = x.astype(jnp.uint64)
    x = x + jnp.uint64(0x9E3779B97F4A7C15)
    x = (x ^ (x >> jnp.uint64(30))) * jnp.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> jnp.uint64(27))) * jnp.uint64(0x94D049BB133111EB)
    return x ^ (x >> jnp.uint64(31))


def register_of_device(hashes, log2m: int):
    """Device analog of hash_to_register."""
    import jax.numpy as jnp
    m = 1 << log2m
    reg = (hashes & jnp.uint64(m - 1)).astype(jnp.int32)
    rest = (hashes >> jnp.uint64(log2m))
    width = 64 - log2m
    # highest-set-bit via progressive halving (branch-free)
    hb = jnp.zeros(rest.shape, dtype=jnp.int32)
    x = rest
    for shift in (32, 16, 8, 4, 2, 1):
        big = x >= (jnp.uint64(1) << jnp.uint64(shift))
        hb = jnp.where(big, hb + shift, hb)
        x = jnp.where(big, x >> jnp.uint64(shift), x)
    rho = jnp.where(rest != 0, width - hb, width + 1).astype(jnp.int32)
    return reg, rho


def update_registers(registers, rho, reg_idx, bucket_ids, mask, num_buckets: int,
                     log2m: int):
    """segment-max scatter of rho into a [num_buckets, m] register grid."""
    import jax
    import jax.numpy as jnp
    m = 1 << log2m
    safe_b = jnp.clip(bucket_ids, 0, num_buckets - 1)
    seg = safe_b.astype(jnp.int32) * m + reg_idx
    rho_m = jnp.where(mask, rho, 0)
    upd = jax.ops.segment_max(rho_m, seg, num_segments=num_buckets * m)
    upd = jnp.maximum(upd, 0).reshape(num_buckets, m)
    if registers is None:
        return upd.astype(jnp.int32)
    return jnp.maximum(registers, upd.astype(jnp.int32))


# ---------------------------------------------------------------------------
# Estimation (host)
# ---------------------------------------------------------------------------

def estimate(registers: np.ndarray, log2m: int = DEFAULT_LOG2M) -> float:
    """Classic HLL estimator with small/large-range corrections
    (semantics-parity with HyperLogLogCollector.estimateCardinality)."""
    regs = np.asarray(registers)
    if regs.ndim > 1:
        regs = regs.reshape(-1)
    m = 1 << log2m
    assert regs.shape[0] == m, f"expected {m} registers, got {regs.shape}"
    alpha = 0.7213 / (1 + 1.079 / m)
    power = np.power(2.0, -regs.astype(np.float64))
    raw = alpha * m * m / power.sum()
    if raw <= 2.5 * m:
        zeros = int((regs == 0).sum())
        if zeros:
            return m * np.log(m / zeros)
    two64 = 2.0 ** 64
    if raw > two64 / 30.0:
        return -two64 * np.log(1.0 - raw / two64)
    return float(raw)


def combine_registers(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """fold = elementwise max (HyperLogLogCollector.fold)."""
    return np.maximum(a, b)


def estimate_array(registers: np.ndarray, log2m: int = DEFAULT_LOG2M) -> np.ndarray:
    """Vectorized estimator over a [G, m] register grid -> float64[G]."""
    regs = np.asarray(registers)
    if regs.ndim == 1:
        regs = regs[None, :]
    m = 1 << log2m
    assert regs.shape[-1] == m
    alpha = 0.7213 / (1 + 1.079 / m)
    power = np.power(2.0, -regs.astype(np.float64))
    raw = alpha * m * m / power.sum(axis=-1)
    zeros = (regs == 0).sum(axis=-1)
    small = raw <= 2.5 * m
    with np.errstate(divide="ignore"):
        lin = np.where(zeros > 0, m * np.log(m / np.maximum(zeros, 1)), raw)
    out = np.where(small & (zeros > 0), lin, raw)
    two64 = 2.0 ** 64
    big = out > two64 / 30.0
    out = np.where(big, -two64 * np.log1p(-out / two64), out)
    return out
