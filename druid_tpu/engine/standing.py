"""Standing queries over streaming ingest: incremental device programs.

A dashboard watching a live datasource used to pay a full re-scan of every
sink on every refresh — the one serving shape Druid's realtime nodes were
built for was our least efficient. A `StandingQuery` compiles an eligible
aggregate query ONCE against the live datasource (TiLT-style incremental
stream compilation, PAPERS.md) and, on every tick, folds ONLY what was
appended since its last high-water mark per sink:

  * The incremental quantum is the HYDRANT. Persisted hydrants are
    immutable, so their per-group partial states are computed exactly once,
    ever, and cached; a tick pays device work only for hydrants sealed
    since the sink's high-water mark plus the (size-bounded) live hydrant
    when its change marker advanced. This quantum is what makes the parity
    gate provable: a from-scratch re-scan computes the SAME per-hydrant
    partials through the SAME device program and merges them in the SAME
    order, so every emitted snapshot is bit-identical (floats included) to
    the re-scan — a finer row-range quantum would re-associate float
    additions and break bit-parity.
  * All of a tick's folds across every sink go through ONE
    make_partials_by_segment call, so shape-compatible hydrants fuse into
    shared device dispatches (engine/batching.py) — and N structurally
    identical subscriptions (server/subscriptions.py) share one
    StandingQuery, so the whole dashboard fleet costs one program per tick.
  * Live-hydrant refolds are the repeated-(segment, program) shape the
    megakernel's donated carries were built for: each tick's snapshot
    Segment adopts its predecessor as carry donor
    (Segment.adopt_carries_from), so the per-group partial grids parked in
    the device pool ride back DONATED (DeviceSegmentPool.take) into the
    next tick's program instead of re-allocating HBM.
  * Emission is watermark-driven: with a uniform granularity the standing
    query (context {"standingEmit": "bucket"}) emits when the event-time
    watermark seals a granularity bucket (or late data lands in a sealed
    one); the default ("change") emits on any fold. Every emission is a
    full consistent snapshot of the current world.
  * Publish cutover is exactly-once: when a sink publishes, the published
    segment's contribution replaces the sink's incremental partials in ONE
    locked swap — no emission can ever see a row twice or not at all
    across the persist/publish boundary.

`DRUID_TPU_STANDING=0` (or set_enabled(False)) restores the re-scan world:
ticks discard cached partials and recompute everything from scratch, with
identical results.
"""
from __future__ import annotations

import hashlib
import os
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from druid_tpu.engine import engines
from druid_tpu.query.model import (GroupByQuery, Query, TimeseriesQuery,
                                   TopNQuery)
from druid_tpu.utils.emitter import Monitor
from druid_tpu.utils.intervals import condense

_ENABLED = os.environ.get("DRUID_TPU_STANDING", "1") != "0"


def set_enabled(on: bool) -> bool:
    """Toggle incremental standing execution; returns the previous value.
    Disabled, every tick re-scans from scratch (the pre-standing world) —
    results are identical, only the incremental caching is off."""
    global _ENABLED
    prev = _ENABLED
    _ENABLED = bool(on)
    return prev


def enabled() -> bool:
    return _ENABLED


class StandingIneligible(ValueError):
    """The query shape cannot compile to a standing program."""


#: refuse standing programs whose fixed bucket index space would be
#: enormous (an eternity interval at minute granularity); dashboards query
#: bounded windows, and the re-scan path still serves anything else
MAX_STANDING_BUCKETS = 1 << 16


def _bucket_count_bounded(granularity, iv) -> int:
    """Bucket count of `iv`, computed WITHOUT materializing bucket arrays
    (an eternity interval at minute granularity would otherwise try to
    allocate petabytes inside the eligibility check) and capped just past
    the standing limit — callers only need 'over or not'."""
    if granularity.is_uniform:
        p = granularity.period_ms
        first = granularity.bucket_start(iv.start)
        return max(int((iv.end - first + p - 1) // p), 0)
    # calendar granularities: months are the narrowest (≥ 28 days) — an
    # interval too wide even at that floor is over the cap without
    # iterating; otherwise the bounded walk is at most ~cap steps
    from druid_tpu.utils.granularity import MS_DAY
    if iv.width > (MAX_STANDING_BUCKETS + 1) * 28 * MS_DAY:
        return MAX_STANDING_BUCKETS + 1
    n = 0
    cur = granularity.bucket_start(iv.start)
    while cur < iv.end and n <= MAX_STANDING_BUCKETS:
        n += 1
        cur = granularity.next_bucket(cur)
    return n


def check_eligible(query: Query) -> None:
    """Raise StandingIneligible unless `query` can run standing: an
    aggregate type over plain (non-union, non-nested) datasources, no
    bySegment, and a FINITE bucket space — the standing program's bucket
    index space is fixed at subscribe time (the broker's bounded-intervals
    discipline), so unbounded windows cannot compile."""
    if not isinstance(query, (TimeseriesQuery, TopNQuery, GroupByQuery)):
        raise StandingIneligible(
            f"standing queries must aggregate; got {query.query_type}")
    if query.inner_query is not None or query.union_datasources:
        raise StandingIneligible("nested/union datasources cannot stand")
    if query.context_map.get("bySegment"):
        raise StandingIneligible("bySegment results cannot stand")
    ivs = condense(query.intervals)
    if not ivs:
        raise StandingIneligible("no query intervals")
    if not query.granularity.is_all:
        n = sum(_bucket_count_bounded(query.granularity, iv) for iv in ivs)
        if n > MAX_STANDING_BUCKETS:
            raise StandingIneligible(
                f"granularity buckets exceed the standing limit "
                f"({MAX_STANDING_BUCKETS}); bound the query interval")


# ---------------------------------------------------------------------------
# Stats (the query/standing/* metric source)
# ---------------------------------------------------------------------------

class StandingStats:
    """Process-wide counters for every standing program's tick activity."""

    def __init__(self):
        self._lock = threading.Lock()
        self.ticks = 0
        self.folds = 0
        self.rows = 0
        self.cutovers = 0

    def record_tick(self, folds: int, rows: int, cutovers: int) -> None:
        with self._lock:
            self.ticks += 1
            self.folds += folds
            self.rows += rows
            self.cutovers += cutovers

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return {"ticks": self.ticks, "folds": self.folds,
                    "rows": self.rows, "cutovers": self.cutovers}


_STATS = StandingStats()


def stats() -> StandingStats:
    return _STATS


class StandingMetricsMonitor(Monitor):
    """Per-tick deltas of the standing subsystem's counters."""

    def __init__(self, source: Optional[StandingStats] = None):
        self.source = source or stats()
        self._last = self.source.snapshot()

    def do_monitor(self, emitter):
        s = self.source.snapshot()
        last, self._last = self._last, s
        emitter.metric("query/standing/ticks", s["ticks"] - last["ticks"])
        emitter.metric("query/standing/folds", s["folds"] - last["folds"])
        emitter.metric("query/standing/rows", s["rows"] - last["rows"])
        emitter.metric("query/standing/cutovers",
                       s["cutovers"] - last["cutovers"])


def resolve_emit(query: Query, emit: Optional[str] = None) -> str:
    """Normalize the emission policy a query asks for: context
    `standingEmit` ("change" | "bucket"), with "bucket" degrading to
    "change" for non-uniform granularities. The hub's dedupe key includes
    this (the structure signature strips context, and two subscribers
    with different emission policies must NOT share one program)."""
    emit = emit or str(query.context_map.get("standingEmit") or "change")
    if emit not in ("change", "bucket"):
        raise StandingIneligible(f"unknown standingEmit {emit!r}")
    if emit == "bucket" and not query.granularity.is_uniform:
        # bucket sealing needs fixed-width buckets; "all"/calendar
        # granularities emit on change
        emit = "change"
    return emit


def _with_marker(index, ident, n_hydrants: int):
    """Produce (live snapshot, its exact high-water marker) — one lock
    hold inside the index (IncrementalIndex.snapshot_with_marker), so the
    marker can neither lag the compaction nor include concurrent
    appends the snapshot does not cover."""
    seg, m = index.snapshot_with_marker(ident.version, ident.partition)
    return seg, (n_hydrants,) + m


# ---------------------------------------------------------------------------
# Per-sink incremental state
# ---------------------------------------------------------------------------

@dataclass
class _SinkState:
    """One sink's folded contribution. Mode "live": per-hydrant cached
    partials + the live hydrant's latest fold. Mode "published": the
    published segment's fold replaced everything (the cutover)."""
    ident: object
    mode: str = "live"                       # "live" | "published"
    hydrant_partials: List[object] = field(default_factory=list)
    hydrant_segs: List[object] = field(default_factory=list)
    live_partial: Optional[object] = None
    live_seg: Optional[object] = None
    live_marker: Optional[Tuple] = None
    published_seg: Optional[object] = None   # pending until the swap folds
    published_partial: Optional[object] = None

    def partials(self) -> List[object]:
        if self.mode == "published":
            return [self.published_partial] \
                if self.published_partial is not None else []
        out = list(self.hydrant_partials)
        if self.live_partial is not None:
            out.append(self.live_partial)
        return out

    def segments(self) -> List[object]:
        if self.mode == "published":
            return [self.published_seg] \
                if self.published_partial is not None else []
        out = list(self.hydrant_segs)
        if self.live_partial is not None:
            out.append(self.live_seg)
        return out


@dataclass(frozen=True)
class StandingSnapshot:
    """One emission: the rows, their identity (etag), and the event-time
    watermark state at emission time."""
    rows: list
    etag: str
    version: int
    watermark: Optional[int]
    sealed_through: Optional[int]


class StandingQuery:
    """One compiled standing program over the live sinks of one or more
    Appenderators (all sharing the query's datasource).

    Listener protocol (Appenderator.add_listener): sink_created /
    sink_published / sink_dropped arrive from ingest threads; tick() from
    the driver (scheduler flush loop or SubscriptionHub); snapshot()/rows()
    from serving threads. Device folds always run OUTSIDE the lock — the
    lock only guards the state dictionaries and the version counter."""

    def __init__(self, query: Query,
                 appenderators: Sequence[object] = (),
                 emit: Optional[str] = None):
        check_eligible(query)
        self.query = query
        from druid_tpu.cluster.cache import query_cache_key
        self.signature = query_cache_key(query)
        self._sig_digest = hashlib.sha1(
            self.signature.encode()).hexdigest()[:16]
        self.emit = resolve_emit(query, emit)
        self._lock = threading.RLock()
        # sink id -> state, in first-appearance order: the merge order is
        # part of the bit-parity contract (float combines associate in
        # world order, exactly like the re-scan's per-segment partials)
        self._sinks: "Dict[str, _SinkState]" = {}
        self._order: List[str] = []
        self._apps: List[object] = []
        self._version = 0
        self._watermark: Optional[int] = None
        self._sealed_through: Optional[int] = None
        self._pending_structural = False     # sink add/drop since last tick
        self._rows_cache: Optional[Tuple[int, list]] = None
        self._closed = False
        for app in appenderators:
            self.attach(app)

    # ---- wiring --------------------------------------------------------
    def attach(self, appenderator) -> None:
        """Start standing over an appenderator's sinks (existing + future).
        Datasources must match — a standing program is one datasource.
        Idempotent: racing retro-wire paths (hub attach vs subscribe)
        cannot double-attach."""
        if appenderator.datasource != self.query.datasource:
            raise ValueError(
                f"appenderator [{appenderator.datasource}] does not serve "
                f"[{self.query.datasource}]")
        with self._lock:
            if self._closed:
                raise RuntimeError("standing query closed")
            if any(a is appenderator for a in self._apps):
                return
            self._apps.append(appenderator)
        appenderator.add_listener(self)

    def close(self) -> None:
        """Detach from every appenderator and drop all folded state."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            apps, self._apps = self._apps, []
            self._sinks.clear()
            self._order.clear()
            self._rows_cache = None
        for app in apps:
            app.remove_listener(self)

    # ---- Appenderator listener protocol --------------------------------
    def sink_created(self, ident) -> None:
        with self._lock:
            if self._closed or ident.id in self._sinks:
                return
            self._sinks[ident.id] = _SinkState(ident=ident)
            self._order.append(ident.id)
            self._pending_structural = True

    def sink_published(self, ident, segment) -> None:
        """The sink's merged historical segment exists (the driver is about
        to hand off + drop). Remember it; the NEXT tick performs the
        exactly-once cutover swap."""
        with self._lock:
            st = self._sinks.get(ident.id)
            if st is not None:
                st.published_seg = segment

    def sink_dropped(self, ident) -> None:
        with self._lock:
            st = self._sinks.get(ident.id)
            if st is None:
                return
            if st.published_seg is None:
                # dropped WITHOUT publish (discarded task): the rows are
                # gone from the world — remove the contribution whole
                del self._sinks[ident.id]
                self._order.remove(ident.id)
            else:
                st.mode = "published"        # swap folds on the next tick
            self._pending_structural = True

    # ---- the tick ------------------------------------------------------
    def tick(self) -> Optional[StandingSnapshot]:
        """Fold everything appended since the last high-water marks; emit
        (returns a snapshot, bumping the version) per the emission policy,
        or None when nothing warranted an emission.

        Ticks are lock-free across their device folds and safe to run
        concurrently: installs are idempotent (hydrant slots are indexed,
        live folds carry lexicographically monotonic markers), so a
        racing duplicate tick wastes work but can never double-count or
        regress state."""
        emit = self._tick_once()
        return self.snapshot() if emit else None

    def _tick_once(self) -> bool:
        with self._lock:
            if self._closed:
                return False
            if not enabled():
                # re-scan world: forget every cached fold so the pass
                # below recomputes all of it from scratch
                for st in self._sinks.values():
                    st.hydrant_partials = []
                    st.hydrant_segs = []
                    st.live_partial = None
                    st.live_seg = None
                    st.live_marker = None
                    st.published_partial = None
                self._rows_cache = None
            work = self._plan_folds_locked()
        folded = self._fold(work)
        with self._lock:
            changed, rows_folded, cutovers, late = \
                self._install_locked(work, folded)
            emit = self._emission_locked(changed, cutovers, late)
        n_folds = sum(1 for g in folded if g is not None)
        _STATS.record_tick(n_folds, rows_folded, cutovers)
        return emit

    def _plan_folds_locked(self) -> List[Tuple]:
        """Work items (kind, sink_id, marker, segment-producer) for every
        fold this tick owes. Snapshot production (to_segment) is deferred
        to outside the lock — it compacts the live index."""
        work: List[Tuple] = []
        for app in self._apps:
            for ident, hydrants, index in app.standing_states():
                st = self._sinks.get(ident.id)
                if st is None:
                    # raced sink_created: adopt it now, same world order
                    st = self._sinks[ident.id] = _SinkState(ident=ident)
                    self._order.append(ident.id)
                    self._pending_structural = True
                if st.mode != "live":
                    continue
                n_folded = len(st.hydrant_partials)
                for j, h in enumerate(hydrants[n_folded:], start=n_folded):
                    work.append(("hydrant", ident.id, j, h))
                # the high-water mark: (sealed hydrants, live generation,
                # pending rows) advances on every content change — id()
                # reuse across index rollovers cannot fake staleness
                marker = (len(hydrants),) + index.change_marker()
                if index.n_rows > 0 and marker != st.live_marker:
                    # the producer returns (snapshot, post-compaction
                    # marker): snapshotting compacts the index (bumping
                    # its generation), and storing the PRE-compaction
                    # marker would make the very next quiet tick look
                    # changed and re-fold the whole live hydrant
                    work.append((
                        "live", ident.id, marker,
                        lambda ix=index, iv=ident, h=len(hydrants):
                        _with_marker(ix, iv, h)))
                elif index.n_rows == 0 and st.live_partial is not None \
                        and marker != st.live_marker:
                    # live index rolled over empty (persist sealed it all)
                    work.append(("live-empty", ident.id, marker, None))
        for sid, st in self._sinks.items():
            if st.mode == "published" and st.published_partial is None \
                    and st.published_seg is not None:
                work.append(("published", sid, None, st.published_seg))
        return work

    def _fold(self, work: List[Tuple]) -> List[Optional[object]]:
        """Run every owed fold in ONE batched partial-production call
        (shape-compatible hydrants fuse across sinks). Returns per-item
        AggregatePartials (None for non-fold items)."""
        segs = []
        idx = []
        for i, (kind, sid, marker, seg) in enumerate(work):
            if kind == "live-empty":
                continue
            if kind == "hydrant":
                with self._lock:
                    st = self._sinks.get(sid)
                    # a persist sealed the previously-folded LIVE snapshot
                    # verbatim: its fold IS the hydrant's fold, no device
                    # work owed (the common quiet-persist case)
                    if st is not None and st.live_seg is seg \
                            and st.live_partial is not None:
                        continue
            if callable(seg):
                seg, post_marker = seg()
                # install compares and stores the post-compaction marker
                # the snapshot actually describes
                work[i] = (kind, sid, post_marker, seg)
                with self._lock:
                    st = self._sinks.get(sid)
                    donor = st.live_seg if st is not None else None
                if donor is not None and donor is not seg:
                    # donated-carry bridge: the fresh snapshot inherits
                    # the previous generation's parked partial grids
                    seg.adopt_carries_from(donor)
            segs.append(seg)
            idx.append(i)
        out: List[Optional[object]] = [None] * len(work)
        if segs:
            parts = engines.make_partials_by_segment(self.query, segs,
                                                     clamp=False)
            for i, seg, ap in zip(idx, segs, parts):
                out[i] = (seg, ap)
        return out

    def _install_locked(self, work, folded):
        """Install fold results; returns (changed, rows_folded, cutovers,
        late_data). A sink that changed mode while its fold was in flight
        discards the stale result."""
        changed = False
        rows_folded = 0
        cutovers = 0
        late = False
        sealed = self._sealed_through
        def fresher(st, marker):
            return st.live_marker is None or marker > st.live_marker

        for item, got in zip(work, folded):
            kind, sid, marker, item_seg = item
            st = self._sinks.get(sid)
            if st is None:
                continue
            if kind == "live-empty":
                if st.mode == "live" and fresher(st, marker):
                    st.live_partial = None
                    st.live_seg = None
                    st.live_marker = marker
                    changed = True
                continue
            if got is None:
                if kind == "hydrant" and st.mode == "live" \
                        and marker == len(st.hydrant_partials) \
                        and st.live_seg is item_seg \
                        and st.live_partial is not None:
                    # sealed-live reuse: the persist sealed the snapshot
                    # we already folded — promote that fold to hydrant
                    # rank, zero device work
                    st.hydrant_partials.append(st.live_partial)
                    st.hydrant_segs.append(st.live_seg)
                    st.live_partial = None
                    st.live_seg = None
                    changed = True
                continue
            seg, ap = got
            if kind == "hydrant" and st.mode == "live" \
                    and marker == len(st.hydrant_partials):
                # `marker` is the hydrant SLOT index: a duplicate install
                # (concurrent tick) misses the slot and drops out
                self._note_watermark(seg)
                late = late or (sealed is not None
                                and seg.n_rows > 0
                                and seg.min_time < sealed)
                st.hydrant_partials.append(ap)
                st.hydrant_segs.append(seg)
                rows_folded += seg.n_rows
                changed = True
            elif kind == "live" and st.mode == "live" \
                    and fresher(st, marker):
                self._note_watermark(seg)
                late = late or (sealed is not None
                                and seg.n_rows > 0
                                and seg.min_time < sealed)
                prev_rows = st.live_seg.n_rows \
                    if st.live_seg is not None else 0
                st.live_partial = ap
                st.live_seg = seg
                st.live_marker = marker
                rows_folded += max(seg.n_rows - prev_rows, 0)
                changed = True
            elif kind == "published" and st.mode == "published" \
                    and st.published_partial is None:
                # THE exactly-once cutover: one atomic swap — the
                # incremental partials leave and the published segment's
                # contribution arrives in the same locked mutation
                st.published_partial = ap
                st.hydrant_partials = []
                st.hydrant_segs = []
                st.live_partial = None
                st.live_seg = None
                st.live_marker = None
                cutovers += 1
                changed = True
        if self._pending_structural:
            self._pending_structural = False
            changed = True
        return changed, rows_folded, cutovers, late

    def _note_watermark(self, seg) -> None:
        if seg.n_rows and (self._watermark is None
                           or seg.max_time > self._watermark):
            self._watermark = seg.max_time

    def _emission_locked(self, changed: bool, cutovers: int,
                         late: bool) -> bool:
        if not changed:
            return False
        if self.emit == "bucket":
            boundary = None if self._watermark is None else \
                self.query.granularity.bucket_start(self._watermark)
            advance = boundary is not None and (
                self._sealed_through is None
                or boundary > self._sealed_through)
            if not (advance or late or cutovers):
                return False
            if advance:
                self._sealed_through = boundary
        self._version += 1
        self._rows_cache = None
        return True

    # ---- serving surface ------------------------------------------------
    def _etag_of(self, version: int) -> str:
        return f'"standing-{self._sig_digest}-{version}"'

    def etag(self) -> str:
        with self._lock:
            return self._etag_of(self._version)

    def version(self) -> int:
        with self._lock:
            return self._version

    def watermark(self) -> Optional[int]:
        with self._lock:
            return self._watermark

    def world_segments(self) -> List[object]:
        """The segments the current folded state represents, in merge
        order — the from-scratch re-scan oracle's exact input (tests; the
        DRUID_TPU_STANDING=0 path recomputes from these)."""
        with self._lock:
            out: List[object] = []
            for sid in self._order:
                out.extend(self._sinks[sid].segments())
            return [s for s in out if s is not None]

    def rows(self) -> list:
        """The finished result rows of the current version (cached; the
        merge recomputes only after an emission changed the state)."""
        return self._rows_versioned()[1]

    def _rows_versioned(self) -> Tuple[int, list]:
        """(version, rows) as ONE consistent pair: rows computed against
        version v are never handed out labeled v+1 — a concurrent tick
        bumping the version mid-merge triggers a recompute, so a
        subscriber can never get 304-stuck on stale rows under a fresh
        etag."""
        while True:
            with self._lock:
                version = self._version
                if self._rows_cache is not None \
                        and self._rows_cache[0] == version:
                    return version, self._rows_cache[1]
                parts = []
                for sid in self._order:
                    parts.extend(self._sinks[sid].partials())
            rows = self._finish(self._merged(parts))
            with self._lock:
                if self._version == version:
                    self._rows_cache = (version, rows)
                    return version, rows
            # version moved while merging: recompute against the new state

    def _merged(self, parts) -> "engines.AggregatePartials":
        """Concat in world order; a fresh object carries the query's fixed
        interval space when no partial named one (empty world)."""
        ap = engines.AggregatePartials.concat(parts)
        ivs = ap.intervals if ap.intervals is not None \
            else condense(self.query.intervals)
        return engines.AggregatePartials(ap.partials, ap.dim_values,
                                         ap.spans, ivs)

    def _finish(self, ap) -> list:
        if isinstance(self.query, TimeseriesQuery):
            return engines.finish_timeseries(self.query, ap)
        if isinstance(self.query, TopNQuery):
            return engines.finish_topn(self.query, ap)
        return engines.finish_groupby(self.query, ap)

    def snapshot(self) -> StandingSnapshot:
        version, rows = self._rows_versioned()
        with self._lock:
            return StandingSnapshot(
                rows=rows, etag=self._etag_of(version), version=version,
                watermark=self._watermark,
                sealed_through=self._sealed_through)

    def rescan_rows(self) -> list:
        """From-scratch oracle: recompute the same world with no cached
        state (the parity gate's other half; also the bench baseline)."""
        ap = engines.make_aggregate_partials(self.query,
                                             self.world_segments(),
                                             clamp=False)
        return self._finish(self._merged([ap]))
