"""Batched multi-segment device execution: dispatch amortization without a mesh.

The reference amortizes per-segment cost with a processing pool of
per-segment runners (ChainedExecutionQueryRunner); our non-mesh path instead
paid one device dispatch (and potentially one shape-specialized compile) PER
SEGMENT. Batched-kernel query accelerators solve exactly this by stacking
operator inputs across queries/segments — here:

  1. plan each segment and group shape-compatible ones by plan constants
     (structure signature, staged dtypes, filter/kernel aux, key-dim
     remaps) into SHAPE BUCKETS;
  2. pad rows up a powers-of-two ladder (rungs = 2^i × BATCH_ROW_ALIGN) and
     pin chunk sizes to powers of two, so compile counts stay bounded per
     structure (row ladder × K ladder);
  3. run the shared per-segment body (grouping.make_stacked_segment_fn)
     UNROLLED over the chunk's pooled DeviceBlocks inside ONE jitted
     program — HBM-resident blocks feed the program directly, no
     re-staging, and XLA schedules the K independent reduction subgraphs
     in a single dispatch;
  4. hand back ONE SegmentPartial per segment from that dispatch.

Stragglers — ineligible segments and undersized buckets — fall back to the
per-segment path. Parity is structural, not coincidental: the batched
program runs the SAME traced body (fuse_filter_update) over the same staged
columns and post-processes states with the same host_post, so results are
bit-identical to per-segment execution.

Observability: every dispatch records (segments, fillRatio) for the
`query/batch/*` emitter metrics (BatchMetricsMonitor, wired by
cluster/dataserver.py).
"""
from __future__ import annotations

import collections
import logging
import os
import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from druid_tpu.data import cascade
from druid_tpu.data.segment import DEFAULT_ROW_ALIGN, Segment
from druid_tpu.engine import filters as filters_mod
from druid_tpu.engine import grouping
from druid_tpu.engine.contracts import (BATCH_MAX_SEGMENT_ROWS,
                                        BATCH_MAX_SEGMENTS,
                                        BATCH_MIN_SEGMENTS, BATCH_ROW_ALIGN)
from druid_tpu.engine.filters import ConstNode
from druid_tpu.engine.grouping import (GroupPlan, GroupSpec, KeyDim,
                                       SegmentPartial, assemble_stacked_aux,
                                       aux_equal, keydims_equal,
                                       make_stacked_segment_fn,
                                       needed_columns,
                                       plan_grouped_aggregate,
                                       run_grouped_aggregate,
                                       windowed_window)
from druid_tpu.engine.kernels import AggKernel
from druid_tpu.obs.trace import span as trace_span
from druid_tpu.obs.trace import span_when as trace_span_when
from druid_tpu.query.aggregators import AggregatorSpec
from druid_tpu.utils.emitter import Monitor
from druid_tpu.utils.granularity import Granularity
from druid_tpu.utils.intervals import Interval

# the row ladder is denominated in the staging alignment: a rung IS a valid
# row_align for Segment.device_block, so batch-mates stage to exactly R rows
assert BATCH_ROW_ALIGN == DEFAULT_ROW_ALIGN, \
    "contracts.BATCH_ROW_ALIGN must match data.segment.DEFAULT_ROW_ALIGN"

#: process default; per-query override via context {"batchSegments": false}
_ENABLED = os.environ.get("DRUID_TPU_BATCH", "1").lower() \
    not in ("0", "false", "no")
_ENABLED_LOCK = threading.Lock()


def set_enabled(on: bool) -> bool:
    """Flip the process-wide batching default; returns the previous value
    (bench/test toggle)."""
    global _ENABLED
    with _ENABLED_LOCK:
        prev = _ENABLED
        _ENABLED = bool(on)
        return prev


def enabled() -> bool:
    return _ENABLED


def query_enabled(context: Optional[Dict]) -> bool:
    """Whether batching applies to one query: the process switch AND the
    per-query {"batchSegments": false} context opt-out. The ONE predicate
    the single-query path, the cross-query path, and the scheduler's
    routing (DataNode.fusable) must agree on — an opted-out query gains
    nothing from the scheduler hold and must not serialize on the
    dispatcher thread."""
    if not _ENABLED:
        return False
    return not (context
                and str(context.get("batchSegments", "true")).lower()
                in ("0", "false", "no"))


# Jitted batched programs keyed on (structure, K, R), LRU-bounded + locked
# for the same reasons as grouping._JIT_CACHE (broker thread-pool fan-out).
_JIT_CACHE: "collections.OrderedDict[str, object]" = collections.OrderedDict()
_JIT_CACHE_CAP = 64
_JIT_CACHE_LOCK = threading.Lock()


# ---------------------------------------------------------------------------
# Dispatch statistics (query/batch/* metrics)
# ---------------------------------------------------------------------------

class BatchStats:
    """Aggregate counters + a bounded per-dispatch event queue the emitter
    monitor drains."""

    EVENT_CAP = 4096

    def __init__(self):
        self._lock = threading.Lock()
        self.batches = 0
        self.batched_segments = 0
        self.stacked_rows = 0
        self.stacked_slots = 0          # K × R summed over dispatches
        self.fallback_segments = 0
        self.dropped_events = 0         # per-dispatch events lost to the cap
        self._events: "collections.deque[Tuple[int, float]]" = \
            collections.deque(maxlen=self.EVENT_CAP)

    def record_batch(self, n_segments: int, rows: int, slots: int) -> None:
        fill = rows / slots if slots else 0.0
        with self._lock:
            self.batches += 1
            self.batched_segments += n_segments
            self.stacked_rows += rows
            self.stacked_slots += slots
            if len(self._events) == self.EVENT_CAP:
                # the deque evicts its oldest silently; count the loss so
                # the monitor can surface truncation instead of silently
                # under-reporting the busiest windows
                self.dropped_events += 1
            self._events.append((n_segments, fill))

    def record_fallback(self, n_segments: int) -> None:
        with self._lock:
            self.fallback_segments += n_segments

    def drain_events(self) -> Tuple[List[Tuple[int, float]], int]:
        """Returns (events, dropped-since-last-drain)."""
        with self._lock:
            out = list(self._events)
            self._events.clear()
            dropped, self.dropped_events = self.dropped_events, 0
            return out, dropped

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            fill = (self.stacked_rows / self.stacked_slots
                    if self.stacked_slots else 0.0)
            return {"batches": self.batches,
                    "batchedSegments": self.batched_segments,
                    "fallbackSegments": self.fallback_segments,
                    "stackedRows": self.stacked_rows,
                    "stackedSlots": self.stacked_slots,
                    "fillRatio": fill}


_STATS = BatchStats()


def stats() -> BatchStats:
    return _STATS


class BatchMetricsMonitor(Monitor):
    """Emits one query/batch/segments + query/batch/fillRatio pair per
    recorded dispatch (drained at tick, the CacheMonitor discipline)."""

    def __init__(self, source: Optional[BatchStats] = None):
        self.source = source or _STATS

    def do_monitor(self, emitter):
        events, dropped = self.source.drain_events()
        for n_segments, fill in events:
            emitter.metric("query/batch/segments", n_segments)
            emitter.metric("query/batch/fillRatio", fill)
        if dropped:
            emitter.metric("query/batch/droppedEvents", dropped)


# ---------------------------------------------------------------------------
# Planning / eligibility
# ---------------------------------------------------------------------------

def row_rung(n_rows: int) -> int:
    """Padded-row ladder rung for a segment: the smallest 2^i ×
    BATCH_ROW_ALIGN holding n_rows. Bounds distinct row shapes (and hence
    compiles) per plan structure to the ladder height."""
    blocks = -(-max(n_rows, 1) // BATCH_ROW_ALIGN)
    return BATCH_ROW_ALIGN * (1 << (blocks - 1).bit_length())


@dataclass
class _Plan:
    """One segment's per-query plan, the unit of shape-bucket grouping.
    Wraps the shared host-side GroupPlan (grouping.plan_grouped_aggregate)
    with the batching-only derivations (ladder rung, bucket digest); the
    GroupPlan rides along so straggler fallback re-executes WITHOUT
    re-planning (run_grouped_aggregate(plan=...)).

    Carries its OWN intervals/granularity: a chunk may mix plans from
    several concurrent queries (run_multi_with_batching), so per-query
    origins (relative interval bounds, bucket start) are derived per plan,
    not from the chunk reference. `req` tags the owning request — the
    queryId of the split-back."""
    segment: Segment
    kds: Tuple[KeyDim, ...]
    index: int                       # position in the caller's segment list
    gplan: GroupPlan
    intervals: Tuple[Interval, ...] = ()
    granularity: Granularity = None
    req: int = 0                     # owning request (multi-query split-back)
    #: False = straggler (runs per-segment, but still through this gplan)
    eligible: bool = False
    f_aux: List[np.ndarray] = None
    k_aux: List[np.ndarray] = None
    columns: Tuple[str, ...] = ()
    col_dtypes: Dict[str, np.dtype] = None
    rung: int = 0
    packs: Tuple = ()                # pack descriptor (data/packed.py)
    cascades: Tuple = ()             # cascade descriptor (data/cascade.py)
    digest: Tuple = None             # hashable shape-bucket prefilter

    @property
    def spec(self) -> GroupSpec:
        return self.gplan.spec

    @property
    def filter_node(self):
        return self.gplan.filter_node

    @property
    def kernels(self) -> List[AggKernel]:
        return self.gplan.kernels

    @property
    def vc_plans(self) -> Tuple:
        return self.gplan.vc_plans

    @property
    def vc_luts(self) -> List[np.ndarray]:
        return self.gplan.vc_luts


def _plan_for(segment: Segment, kds: Sequence[KeyDim], index: int,
              intervals: Sequence[Interval], granularity: Granularity,
              aggs: Sequence[AggregatorSpec], flt,
              virtual_columns: Sequence) -> _Plan:
    """Plan one segment for batched execution. ONE host-side planning pass
    (grouping.plan_grouped_aggregate) serves both outcomes: eligible plans
    group into shape buckets and drive the stacked program; ineligible
    plans (stragglers) keep `eligible=False` and re-execute per-segment
    through run_grouped_aggregate(plan=...) WITHOUT re-planning. The
    eligibility checks mirror distributed.try_sharded minus the
    cross-segment dictionary requirement: batched partials stay PER
    SEGMENT, so raw dictionary ids decode through each segment's own value
    list."""
    kds = tuple(kds)
    gplan = plan_grouped_aggregate(segment, intervals, granularity, kds,
                                   aggs, flt, virtual_columns)
    plan = _Plan(segment=segment, kds=kds, index=index, gplan=gplan,
                 intervals=tuple(intervals), granularity=granularity)
    if segment.n_rows > BATCH_MAX_SEGMENT_ROWS:
        return plan
    if cascade.enabled() and cascade.run_domain_probe(
            segment, intervals, granularity, gplan.spec, gplan.kernels,
            flt, virtual_columns):
        # code-domain eligible: the per-segment straggler path runs it
        # fully over run metadata (run_grouped_aggregate's cascade hook) —
        # stacking it into a row program would decode what never needs
        # decoding
        return plan
    if any(d.host_ids is not None and d.ids_key is None for d in kds):
        # a derived id column with no stable cache identity cannot stage
        # through the pool — keep per-segment. Numeric/expression dims DO
        # carry ids_key, and their query-time dictionaries unify across
        # the query's segments (engines.unify_query_dims), so their plan
        # constants (cardinality, remap) are no longer segment-local —
        # the host-mask-era exclusion is gone.
        return plan
    spec, filter_node, kernels = gplan.spec, gplan.filter_node, gplan.kernels
    if spec.key_mode != "dense" or spec.bucket_mode not in ("all", "uniform"):
        return plan
    if spec.num_total > grouping.BLOCKED_GROUP_LIMIT:
        # bounded group spaces make select_strategy a pure function of
        # (num_total, kernels, dtypes) — identical for the batched rung and
        # the per-segment padding — so the bit-parity contract is
        # STRUCTURAL. Above the limit the choice consults per-segment row
        # clustering (windowed/projection), which could diverge between
        # chunk-mates and reorder float accumulation; those segments are
        # also scatter-compute-bound, where dispatch amortization is noise
        return plan
    if isinstance(filter_node, ConstNode) and not filter_node.value:
        # constant-false: the per-segment path skips the device entirely —
        # batching it would only waste a stacked slot
        return plan
    needed, columns = needed_columns(segment, kds, aggs, flt, virtual_columns,
                                     filter_node=filter_node,
                                     kernels=gplan.kernels)
    # complex (2-D) metric columns — HLL registers, sketch states — stack
    # like any other column now that the mask is in-program; their width is
    # a compile-shape dimension, so it joins the digest below
    col_shapes = tuple(sorted(
        (c, np.asarray(segment.metrics[c].values).shape[1:])
        for c in columns if c in segment.metrics
        and np.asarray(segment.metrics[c].values).ndim > 1))
    col_dtypes: Dict[str, np.dtype] = {
        "__time_offset": np.dtype(np.int32), "__valid": np.dtype(bool)}
    for c in columns:
        col_dtypes[c] = np.dtype(np.int32) if c in segment.dims \
            else np.dtype(segment.staged_dtype(c))
    for d in kds:
        if d.host_ids is not None:
            col_dtypes[d.column] = np.dtype(np.int32)
    plan.eligible = True
    plan.f_aux = filter_node.aux_arrays() if filter_node else []
    plan.k_aux = [a for k in kernels for a in k.aux_arrays()]
    plan.columns = columns
    plan.col_dtypes = col_dtypes
    plan.rung = row_rung(segment.n_rows)
    # cascade + pack descriptors (pure fns of column stats, pow2-quantized
    # widths/bases/run counts precisely so near-identical segments keep
    # sharing buckets): both change the stacked program's treedef, so
    # chunk-mates must agree on them — they join the signature AND the
    # digest (cascade.plan_pair is the same derivation device_block uses)
    plan.cascades, plan.packs = cascade.plan_pair(segment, columns)
    sig = grouping._structure_sig(spec, len(intervals), filter_node, kernels,
                                  gplan.vc_plans, plan.packs, plan.cascades)
    # granularity + bucket count join the digest for CROSS-QUERY grouping:
    # the stacked aux (assemble_stacked_aux) carries one shared period /
    # num_buckets for the whole chunk, so chunk-mates from different
    # queries must agree on them (within one query they are constant and
    # this changes nothing). Interval VALUES stay out — relative bounds
    # are per-segment mapped args (iv_rel), only their COUNT is shape
    # (already in the structure sig).
    plan.digest = (sig, plan.rung, columns, col_shapes,
                   tuple(sorted((c, str(d)) for c, d in col_dtypes.items())),
                   str(granularity), spec.num_buckets)
    return plan


def _compatible(ref: _Plan, cand: _Plan) -> bool:
    """Digest-equal plans still carry array-valued constants (filter LUTs,
    kernel aux, dim remaps, vc string LUTs) that become SHARED aux in the
    stacked program — they must be value-equal."""
    return (keydims_equal(ref.kds, cand.kds)
            and aux_equal(ref.f_aux, cand.f_aux)
            and aux_equal(ref.k_aux, cand.k_aux)
            and aux_equal(ref.vc_luts, cand.vc_luts))


def _shape_buckets(plans: Sequence[_Plan]) -> List[List[_Plan]]:
    """Group plans into shape buckets: digest prefilter, then aux-equality
    subgroups within each digest."""
    by_digest: Dict[Tuple, List[List[_Plan]]] = {}
    for p in plans:
        groups = by_digest.setdefault(p.digest, [])
        for g in groups:
            if _compatible(g[0], p):
                g.append(p)
                break
        else:
            groups.append([p])
    return [g for groups in by_digest.values() for g in groups]


def _pow2_chunks(group: List[_Plan]) -> Tuple[List[List[_Plan]], List[_Plan]]:
    """Split a bucket into power-of-two-sized chunks ≤ BATCH_MAX_SEGMENTS
    (greedy binary decomposition: 13 → 8 + 4 + a 1-straggler). The program
    unrolls one body per segment, so the segment count is a compile-key
    dimension — pinning it to powers of two bounds compiles at
    log2(BATCH_MAX_SEGMENTS) per (structure, rung) instead of one per
    distinct K. Returns (chunks, remainder-for-per-segment-fallback)."""
    out: List[List[_Plan]] = []
    i, n = 0, len(group)
    while n - i >= BATCH_MIN_SEGMENTS:
        size = min(BATCH_MAX_SEGMENTS, 1 << ((n - i).bit_length() - 1))
        out.append(group[i:i + size])
        i += size
    return out, group[i:]


# ---------------------------------------------------------------------------
# The batched device program
# ---------------------------------------------------------------------------

def _build_batched_fn(spec: GroupSpec, kds: Tuple[KeyDim, ...], filter_node,
                      kernels: List[AggKernel], vc_plans: Tuple, K: int):
    """One jitted program for a whole shape bucket: the shared per-segment
    body UNROLLED over the K pooled blocks. Per-segment origins (time0,
    relative interval bounds, bucket origin) index into [K] arrays; plan
    constants ride aux. Unrolling (not vmap) is deliberate: XLA schedules K
    independent reduction subgraphs better than one batched-axis program —
    measured ~3.6x faster than the vmapped equivalent and ~1.5x faster than
    K separate dispatches on the CPU backend — and per-segment partials
    fall out without a stacked-axis slice."""
    import jax

    body = make_stacked_segment_fn(spec, kds, filter_node, kernels, vc_plans)

    def fn(blocks, time0s, iv_rel, bucket_off, aux):
        return tuple(body(blocks[i], time0s[i], iv_rel[i], bucket_off[i], aux)
                     for i in range(K))

    return jax.jit(fn)


def _run_batch(chunk: List[_Plan]) -> Optional[List[SegmentPartial]]:
    """Execute one shape bucket as a single dispatch; None = the bucket
    cannot run stacked (projection-grade group space) and the caller falls
    back per-segment. The chunk may mix plans from several queries
    (run_multi_with_batching): every per-query origin — interval bounds,
    bucket start — is derived from the plan's OWN intervals, so
    cross-query mates produce exactly the partials their own serial run
    would."""
    import jax

    ref = chunk[0]
    R = ref.rung
    K = len(chunk)                  # a power of two by _pow2_chunks

    def _windowed_all():
        w_all = 0
        for p in chunk:
            w = windowed_window(p.segment, p.intervals, p.granularity,
                                p.spec)
            if not w:
                return 0
            w_all = max(w_all, w)
        return w_all

    strategy, window = grouping.select_strategy(
        ref.spec, ref.kernels, ref.col_dtypes, R, _windowed_all)
    if strategy == "projection":
        # sorted projections are per-segment layouts a stacked program
        # cannot share — and projection-grade segments are big enough that
        # per-segment dispatch overhead is already amortized
        return None
    for p in chunk:
        p.spec.strategy, p.spec.window = strategy, window

    blocks = [p.segment.device_block(list(ref.columns), row_align=R)
              for p in chunk]
    assert all(b.padded_rows == R for b in blocks), \
        "ladder rung must equal the staged row count"
    # per-segment derived inputs ride the mapped arrays, not aux: query-time
    # dictionary id columns (unified id spaces — engines.unify_query_dims)
    # and resident filter-bitmap words (engine/filters.py device-bitmap
    # path; each plan stages ITS OWN words — query filter AND filtered
    # aggregators — so chunk-mates from different queries may carry
    # entirely different bitmap filters under one shared program structure)
    bmp_per_slot = filters_mod.stage_device_bitmaps_multi(
        [(p.segment, p.filter_node, p.kernels) for p in chunk], R)
    arrs_per_slot = []
    for p, b, bmp in zip(chunk, blocks, bmp_per_slot):
        arrs = dict(b.arrays)
        for d in p.kds:
            if d.host_ids is not None:
                arrs[d.column] = grouping._pad_device_cached(
                    p.segment, d.ids_key, d.host_ids, R, 0)
        arrs.update(bmp)
        arrs_per_slot.append(arrs)

    clip_lo, clip_hi = -(2**31) + 1, 2**31 - 1
    iv_rel = np.zeros((K, max(len(ref.intervals), 1), 2), dtype=np.int32)
    time0s = np.zeros((K,), dtype=np.int64)
    bucket_off = np.zeros((K,), dtype=np.int32)
    for i, p in enumerate(chunk):
        t0 = p.segment.interval.start
        time0s[i] = t0
        for j, ivl in enumerate(p.intervals):
            iv_rel[i, j, 0] = min(max(ivl.start - t0, clip_lo), clip_hi)
            iv_rel[i, j, 1] = min(max(ivl.end - t0, clip_lo), clip_hi)
        if p.spec.bucket_mode == "uniform":
            bucket_off[i] = min(max(int(p.spec.bucket_starts[0]) - t0,
                                    clip_lo), clip_hi)

    aux = assemble_stacked_aux(ref.spec, ref.kds, ref.f_aux, ref.k_aux,
                               ref.granularity, ref.vc_luts)
    sig = "batched|" + grouping._structure_sig(
        ref.spec, len(ref.intervals), ref.filter_node, ref.kernels,
        ref.vc_plans, ref.packs, ref.cascades) + f"|K={K}|R={R}"
    with _JIT_CACHE_LOCK:
        fn = _JIT_CACHE.get(sig)
        # the miss IS the compile event (jit traces/compiles on the first
        # call below) — timing stays at the existing dispatch boundary
        compiled = fn is None
        if fn is None:
            fn = _build_batched_fn(ref.spec, ref.kds, ref.filter_node,
                                   ref.kernels, ref.vc_plans, K)
            # kds STRUCTURE is a pure function of spec.dims plus the
            # packs/cascades folded into sig; the per-segment id arrays
            # inside kds enter the traced fn as runtime arguments, never
            # as trace constants
            _JIT_CACHE[sig] = fn  # druidlint: disable=unkeyed-trace-input
            while len(_JIT_CACHE) > _JIT_CACHE_CAP:
                _JIT_CACHE.popitem(last=False)
        else:
            _JIT_CACHE.move_to_end(sig)

    from druid_tpu.obs import dispatch as dispatch_mod
    with trace_span("engine/batch/dispatch", segments=K, rows=R,
                    compile=compiled), \
            trace_span_when(compiled, "engine/compile", kind="batched",
                            strategy=strategy):
        outs = fn(tuple(arrs_per_slot), time0s, iv_rel,
                  bucket_off, aux)
    # successful dispatches only (grouping's discipline): a failed batch
    # falls back per-segment and must not double-bill the scoreboard
    dispatch_mod.record("batched")

    out: List[SegmentPartial] = []
    for p, (counts, states) in zip(chunk, outs):
        states_h = jax.tree.map(lambda x: np.asarray(x), states)
        host_states = {k.name: k.host_post(s, p.segment)
                       for k, s in zip(p.kernels, states_h)}
        out.append(SegmentPartial(
            segment=p.segment, spec=p.spec,
            counts=np.asarray(counts, dtype=np.int64),
            states=host_states, kernels=p.kernels))
    _STATS.record_batch(K, sum(p.segment.n_rows for p in chunk), K * R)
    return out


# ---------------------------------------------------------------------------
# Entry point (engines._make_partials)
# ---------------------------------------------------------------------------

def run_with_batching(segs: Sequence[Segment], intervals: Sequence[Interval],
                      granularity: Granularity,
                      kds_per_seg: Sequence[Sequence[KeyDim]],
                      aggs: Sequence[AggregatorSpec], flt,
                      virtual_columns: Sequence = (),
                      context: Optional[Dict] = None,
                      check=None) -> Optional[List[SegmentPartial]]:
    """Produce one SegmentPartial per segment (same order as `segs`), using
    batched dispatches for every shape bucket of ≥ BATCH_MIN_SEGMENTS
    compatible segments and the per-segment path for stragglers. Returns
    None when batching is off / inapplicable (caller runs plain
    per-segment). `check` (optional cancel/timeout probe) fires between
    dispatches — batch and straggler alike."""
    if not query_enabled(context) or len(segs) < BATCH_MIN_SEGMENTS:
        return None

    with trace_span("engine/batch/plan", segments=len(segs)):
        plans = [_plan_for(s, kds, i, intervals, granularity, aggs, flt,
                           virtual_columns)
                 for i, (s, kds) in enumerate(zip(segs, kds_per_seg))]
        buckets = _shape_buckets([p for p in plans if p.eligible])
    if not any(len(b) >= BATCH_MIN_SEGMENTS for b in buckets):
        # nothing batches — but the per-segment planning already happened:
        # run the plain path HERE so the plans are executed, not rebuilt
        return [_run_straggler(p, intervals, granularity, aggs, flt,
                               virtual_columns, check, first=(i == 0))
                for i, p in enumerate(plans)]

    results: List[Optional[SegmentPartial]] = [None] * len(segs)
    dispatched = 0
    for bucket in buckets:
        if len(bucket) < BATCH_MIN_SEGMENTS:
            continue
        chunks, _remainder = _pow2_chunks(bucket)
        for chunk in chunks:
            if check is not None and dispatched:
                check()
            partials = _run_batch(chunk)
            if partials is None:
                continue
            dispatched += 1
            for p, partial in zip(chunk, partials):
                results[p.index] = partial

    n_fallback = sum(1 for r in results if r is None)
    if dispatched and n_fallback:
        _STATS.record_fallback(n_fallback)
    for i, p in enumerate(plans):
        if results[i] is None:
            results[i] = _run_straggler(p, intervals, granularity, aggs,
                                        flt, virtual_columns, check,
                                        first=not dispatched and i == 0)
    return results


def _run_straggler(p: _Plan, intervals, granularity, aggs, flt,
                   virtual_columns, check, first: bool) -> SegmentPartial:
    """Per-segment execution reusing the plan built for bucket grouping
    (the ROADMAP's 'stragglers are planned twice' follow-on, closed)."""
    if check is not None and not first:
        check()
    return run_grouped_aggregate(
        p.segment, intervals, granularity, p.kds, aggs, flt,
        virtual_columns=virtual_columns, plan=p.gplan)


# ---------------------------------------------------------------------------
# Cross-query entry point (server/scheduler.py via engines)
# ---------------------------------------------------------------------------

@dataclass
class BatchWork:
    """One query's segment work, as submitted to run_multi_with_batching —
    the same argument tuple run_with_batching takes, boxed so a scheduler
    flush can carry many of them."""
    segs: Sequence[Segment]
    intervals: Sequence[Interval]
    granularity: Granularity
    kds_per_seg: Sequence[Sequence[KeyDim]]
    aggs: Sequence[AggregatorSpec]
    flt: object = None
    virtual_columns: Sequence = ()
    context: Optional[Dict] = None
    check: Optional[object] = None   # cancel/timeout probe for THIS query


def run_multi_with_batching(work: Sequence[BatchWork],
                            on_batch=None) -> List[object]:
    """Cross-query fused execution: plan every request's segments, group
    plans into shape buckets ACROSS requests (the _Plan digest already
    carries everything two dispatches must agree on, plus granularity /
    bucket count for the cross-query case), run each bucket as single
    dispatches, and split partials back per request by the plan's `req`
    tag.

    Returns one entry per request: a List[SegmentPartial] (same order as
    that request's `segs`) or the Exception that request's check raised —
    one cancelled/timed-out query must not fail its batch-mates. Results
    are bit-identical to running each request through run_with_batching /
    the per-segment path serially: the chunk a plan lands in changes only
    WHICH dispatch computes it, never what it computes (per-plan origins,
    strategy a pure function of digest-shared constants).

    `on_batch(n_queries, n_segments, fill_ratio)` fires per fused dispatch
    — the scheduler's query/crossBatch/* metrics hook."""
    all_plans: List[List[_Plan]] = []
    with trace_span("engine/batch/plan",
                    queries=len(work),
                    segments=sum(len(w.segs) for w in work)):
        for r, w in enumerate(work):
            opted_out = not query_enabled(w.context)
            plans = []
            for i, (s, kds) in enumerate(zip(w.segs, w.kds_per_seg)):
                p = _plan_for(s, kds, i, w.intervals, w.granularity,
                              w.aggs, w.flt, w.virtual_columns)
                p.req = r
                if opted_out:
                    p.eligible = False
                plans.append(p)
            all_plans.append(plans)
        buckets = _shape_buckets([p for plans in all_plans
                                  for p in plans if p.eligible])

    results: List[List[Optional[SegmentPartial]]] = \
        [[None] * len(plans) for plans in all_plans]
    dead: Dict[int, BaseException] = {}

    def _poll_checks():
        for r, w in enumerate(work):
            if r in dead or w.check is None:
                continue
            try:
                w.check()
            except Exception as e:
                dead[r] = e

    dispatched = 0
    for bucket in buckets:
        if len(bucket) < BATCH_MIN_SEGMENTS:
            continue
        chunks, _remainder = _pow2_chunks(bucket)
        for chunk in chunks:
            if dispatched:
                _poll_checks()
            live = [p for p in chunk if p.req not in dead]
            if not live:
                continue
            if len(live) < len(chunk):
                # a cancelled mate shrank the chunk below its pow2 size —
                # K is a compile key, so dispatching the odd size would
                # pay a one-off compile; survivors take the (cached)
                # per-segment straggler path instead
                continue
            try:
                partials = _run_batch(live)
            except Exception:
                # a batch-specific failure must not kill queries that
                # would succeed serially: participants fall back to the
                # per-segment straggler path below
                logging.getLogger(__name__).exception(
                    "batched dispatch failed; falling back per-segment")
                continue
            if partials is None:
                continue
            dispatched += 1
            if on_batch is not None:
                slots = len(live) * live[0].rung
                rows = sum(p.segment.n_rows for p in live)
                on_batch(len({p.req for p in live}), len(live),
                         rows / slots if slots else 0.0)
            for p, partial in zip(live, partials):
                results[p.req][p.index] = partial

    _poll_checks()
    out: List[object] = []
    for r, (w, plans) in enumerate(zip(work, all_plans)):
        if r in dead:
            out.append(dead[r])
            continue
        res = results[r]
        n_fallback = sum(1 for x in res if x is None)
        if dispatched and n_fallback:
            _STATS.record_fallback(n_fallback)
        try:
            for i, p in enumerate(plans):
                if res[i] is None:
                    res[i] = _run_straggler(
                        p, w.intervals, w.granularity, w.aggs, w.flt,
                        w.virtual_columns, w.check,
                        first=not dispatched and i == 0)
        except Exception as e:
            out.append(e)
            continue
        out.append(res)
    return out
