"""Per-query-type engines over the unified grouped-aggregate program.

Reference analogs:
  timeseries — query/timeseries/TimeseriesQueryEngine.java:40
  topN       — query/topn/TopNQueryEngine.java:48 (+PooledTopNAlgorithm)
  groupBy    — query/groupby/epinephelinae/GroupByQueryEngineV2.java:91
  scan       — query/scan/ScanQueryEngine.java:55
  select     — query/select/SelectQueryEngine.java
  search     — query/search/SearchQueryRunnerFactory.java (UseIndexesStrategy)
  timeBoundary / segmentMetadata / dataSourceMetadata —
      query/timeboundary/, query/metadata/SegmentAnalyzer.java,
      query/datasourcemetadata/

Result row shapes mirror the reference's JSON wire format (timestamps kept as
epoch millis ints; the HTTP layer renders ISO strings).
"""
from __future__ import annotations

import json
import os
import threading
import time
import weakref
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from druid_tpu.data.segment import Segment, ValueType
from druid_tpu.engine import batching
from druid_tpu.engine.filters import host_mask
from druid_tpu.engine.grouping import KeyDim, run_grouped_aggregate
from druid_tpu.engine.merge import merge_partials
from druid_tpu.parallel import distributed
from druid_tpu.query.model import (DefaultLimitSpec, DimensionSpec, GroupByQuery,
                                   ListFilteredDimensionSpec, ScanQuery,
                                   SearchQuery, SegmentMetadataQuery, SelectQuery,
                                   TimeBoundaryQuery, TimeseriesQuery, TopNQuery,
                                   DataSourceMetadataQuery)
from druid_tpu.query.postaggs import compute_postaggs
from druid_tpu.utils.granularity import Granularity
from druid_tpu.utils.intervals import Interval, condense


# ---------------------------------------------------------------------------
# Shared helpers
# ---------------------------------------------------------------------------

def _segments_for(segments: Sequence[Segment],
                  intervals: Sequence[Interval]) -> List[Segment]:
    return [s for s in segments
            if any(s.interval.overlaps(iv) for iv in intervals)]


def _clamp_to_data(intervals: Sequence[Interval],
                   segs: Sequence[Segment]) -> List[Interval]:
    """Intersect query intervals with the extent of the matched segments.
    The reference never materializes buckets outside segment data (cursors
    exist per granularity bucket *within* segments —
    QueryableIndexStorageAdapter.makeCursors); clamping keeps eternity-
    interval queries from enumerating unbounded bucket ranges."""
    if not segs:
        return list(intervals)
    lo = min(s.min_time for s in segs)
    hi = max(s.max_time for s in segs) + 1
    data = Interval(lo, hi)
    out = []
    for iv in intervals:
        x = iv.intersect(data)
        if x is not None and x.width > 0:
            out.append(x)
    return out


def _keydim_for(segment: Segment, spec: DimensionSpec) -> Tuple[KeyDim, List[str]]:
    """Build a KeyDim (+ local id -> output value list) for one dimension spec.

    Extraction fns and listFiltered run host-side over the dictionary,
    producing an id remap table (cached per segment) — the analog of the
    reference applying ExtractionFn per row, at O(cardinality) instead of
    O(rows)."""
    from druid_tpu.query.model import ExpressionDimensionSpec
    if isinstance(spec, ExpressionDimensionSpec):
        return _expr_keydim(segment, spec)
    col = segment.dims.get(spec.dimension)
    num_ids = None
    num_key = None
    dim_col = spec.dimension
    if col is None:
        m = segment.metrics.get(spec.dimension)
        if m is None or np.asarray(m.values).ndim != 1:
            return KeyDim(None, 1, None), [""]
        # numeric dimension handler (reference: Double/Long/Float
        # DimensionHandler + GroupByQueryEngineV2 numeric grouping): build a
        # query-time dictionary over the column's values — the device groups
        # by compact int32 ids exactly like a string dim, decode emits the
        # numeric values
        num_key = ("numdim", spec.dimension)

        def _compute_num():
            uniq, inv = np.unique(m.values, return_inverse=True)
            return inv.astype(np.int32), [v.item() for v in uniq]
        num_ids, num_vals = segment.aux_cached(num_key, _compute_num)
        dim_col = f"__numdim_{spec.dimension}"

    fn = spec.extraction_fn
    whitelist = None
    is_white = True
    if isinstance(spec, ListFilteredDimensionSpec):
        whitelist = set(spec.values)
        is_white = spec.is_whitelist

    if fn is None and whitelist is None:
        if col is None:
            return KeyDim(dim_col, max(len(num_vals), 1), None,
                          host_ids=num_ids,
                          ids_key=("numdim_ids", spec.dimension)), \
                (num_vals or [""])
        return KeyDim(spec.dimension, col.cardinality, None), col.dictionary.values

    cache_key = ("keydim", spec.dimension,
                 json.dumps(fn.cache_key(), sort_keys=True) if fn else None,
                 tuple(sorted(whitelist)) if whitelist is not None else None,
                 is_white)

    def _compute():
        # extraction fns see the STRING form of numeric values (reference
        # ExtractionFn contract)
        vals = [str(v) for v in num_vals] if col is None \
            else col.dictionary.values
        raw = fn.apply_all(vals) if fn else vals
        outs = ["" if o is None else str(o) for o in raw]
        keep = [True] * len(outs)
        if whitelist is not None:
            for i, o in enumerate(outs):
                inside = o in whitelist
                keep[i] = inside if is_white else not inside
        uniq = sorted({o for o, k in zip(outs, keep) if k})
        index = {v: i for i, v in enumerate(uniq)}
        remap = np.asarray(
            [index[o] if k else -1 for o, k in zip(outs, keep)], dtype=np.int32)
        return remap, uniq

    remap, uniq = segment.aux_cached(cache_key, _compute)
    return KeyDim(dim_col, max(len(uniq), 1), remap, host_ids=num_ids,
                  ids_key=("numdim_ids", spec.dimension)
                  if num_ids is not None else None), (uniq or [""])


def _expr_keydim(segment: Segment, spec) -> Tuple[KeyDim, List]:
    """Host-evaluate an expression dimension into a per-segment value
    dictionary (numeric dims generalized to computed values; string dims
    bind decoded so string comparisons/CASE work)."""
    from druid_tpu.engine.filters import _bind_string_dims
    from druid_tpu.utils.expression import parse_expression

    cache_key = ("exprdim", spec.expression, spec.output_type)

    def _compute():
        expr = parse_expression(spec.expression)
        bindings: Dict[str, np.ndarray] = {"__time": segment.time_ms}
        for name, m in segment.metrics.items():
            if np.asarray(m.values).ndim == 1:
                bindings[name] = m.values
        _bind_string_dims(expr, segment, bindings)
        vals = np.broadcast_to(np.asarray(expr.evaluate(bindings)),
                               (segment.n_rows,))
        uniq, inv = np.unique(vals, return_inverse=True)
        out = [v.item() if hasattr(v, "item") else v for v in uniq]
        if spec.output_type == "string":
            out = [str(v) for v in out]
        return inv.astype(np.int32), out

    ids, vals = segment.aux_cached(cache_key, _compute)
    return KeyDim(f"__exprdim_{spec.output_name}", max(len(vals), 1), None,
                  host_ids=ids,
                  ids_key=("exprdim_ids", spec.expression,
                           spec.output_type)), (vals or [""])


def _bucket_starts(granularity: Granularity,
                   intervals: Sequence[Interval]) -> np.ndarray:
    if granularity.is_all:
        # single global bucket (matches grouping.make_group_spec)
        first = min((iv.start for iv in intervals), default=0)
        return np.asarray([first], dtype=np.int64) if intervals \
            else np.zeros(0, dtype=np.int64)
    parts = [granularity.bucket_starts(iv) for iv in intervals]
    return np.concatenate(parts) if parts else np.zeros(0, dtype=np.int64)


def _covered_buckets(granularity: Granularity, starts: np.ndarray,
                     data_spans: Sequence[Tuple[int, int]],
                     intervals: Sequence[Interval]) -> np.ndarray:
    """Buckets whose span intersects actual segment data (mirrors the
    reference emitting one row per cursor bucket). `data_spans` are
    (min_time, max_time) extents of the contributing segments."""
    if len(starts) == 0:
        return np.zeros(0, dtype=bool)
    spans = []
    for mn, mx in data_spans:
        for iv in intervals:
            lo = max(mn, iv.start)
            hi = min(mx + 1, iv.end)
            if lo < hi:
                spans.append((lo, hi))
    if not spans:
        return np.zeros(len(starts), dtype=bool)
    if granularity.is_all:
        return np.ones(len(starts), dtype=bool)
    if granularity.is_uniform:
        ends = starts + granularity.period_ms
    else:
        ends = np.asarray([granularity.next_bucket(int(st)) for st in starts],
                          dtype=np.int64)
    los = np.asarray([lo for lo, _ in spans], dtype=np.int64)
    his = np.asarray([hi for _, hi in spans], dtype=np.int64)
    # bucket i covered iff any span overlaps [starts[i], ends[i])
    return ((starts[:, None] < his[None, :])
            & (ends[:, None] > los[None, :])).any(axis=1)


def _vectorized_postaggs(postaggs, value_arrays: Dict[str, np.ndarray]):
    out = dict(value_arrays)
    for pa in postaggs:
        out[pa.name] = pa.compute(out)
    return out


def _make_partials(segs, intervals, query, kds_per_seg, vals_per_seg,
                   check=None):
    """Produce (partials, dim_values): ONE sharded device program when a mesh
    is active and the segments agree on plan constants; else batched
    multi-segment dispatches over shape-compatible segments (one jitted
    program per shape bucket with the per-segment body unrolled inside it —
    deliberately NOT vmapped, see engine/batching.py); else the per-segment
    path. All variants merge host-side except the sharded one.

    `check` (cancel/timeout probe) runs at every dispatch boundary: between
    per-segment programs, between batched shape-bucket dispatches, and
    before the single sharded program."""
    from druid_tpu.obs.trace import span as trace_span
    if check is not None:
        check()
    with trace_span("engine/partials", segments=len(segs)):
        merged = distributed.try_sharded(segs, intervals, query.granularity,
                                         kds_per_seg, query.aggregations,
                                         query.filter, query.virtual_columns)
        if merged is not None:
            return [merged], [vals_per_seg[0]]
        partials = batching.run_with_batching(
            segs, intervals, query.granularity, kds_per_seg,
            query.aggregations, query.filter, query.virtual_columns,
            context=query.context_map, check=check)
        if partials is None:
            partials = []
            for s, kds in zip(segs, kds_per_seg):
                if check is not None and partials:
                    check()
                partials.append(run_grouped_aggregate(
                    s, intervals, query.granularity, kds, query.aggregations,
                    query.filter, virtual_columns=query.virtual_columns))
        return partials, list(vals_per_seg)


# ---------------------------------------------------------------------------
# Partial production / finish split (the broker's scatter-gather seam)
# ---------------------------------------------------------------------------

class AggregatePartials:
    """Partial aggregation states from one producer (data node / local run).

    The unit shipped from data nodes to the broker: states are plain
    host arrays, dim_values are merged-dictionary string lists, spans are
    (min_time, max_time) data extents for bucket-coverage accounting.
    Reference analog: the non-finalized per-segment sequences a historical
    streams back before the broker's mergeResults."""

    def __init__(self, partials, dim_values, spans, intervals):
        self.partials = partials          # List[SegmentPartial]
        self.dim_values = dim_values      # parallel: List[List[List[str]]]
        self.spans = spans                # List[(min_ms, max_ms)]
        self.intervals = intervals        # intervals partials were built with

    @staticmethod
    def concat(parts: Sequence["AggregatePartials"]) -> "AggregatePartials":
        parts = [p for p in parts if p is not None]
        out = AggregatePartials([], [], [], None)
        for p in parts:
            out.partials += list(p.partials)
            out.dim_values += list(p.dim_values)
            out.spans += list(p.spans)
            if out.intervals is None:
                out.intervals = p.intervals
        return out


def make_aggregate_partials(query, segments: Sequence[Segment],
                            clamp: bool = True,
                            check=None) -> AggregatePartials:
    """Produce partial states for a timeseries/topN/groupBy query over local
    segments. `clamp=False` is used by the broker path: it pre-bounds the
    query intervals globally so bucket index spaces align across nodes.
    `check` (optional cancel/timeout probe) fires at dispatch boundaries."""
    return _make_aggregate_partials_with_segs(query, segments, clamp,
                                              check)[0]


def make_partials_by_segment(query, segments: Sequence[Segment],
                             clamp: bool = False,
                             check=None) -> List[AggregatePartials]:
    """One single-segment AggregatePartials PER INPUT SEGMENT (parallel to
    `segments`; a segment outside the query intervals yields an EMPTY
    partials object). The data node's segment-cache miss path runs its
    whole miss set through here — ONE call, so shape-compatible misses
    batch into shared dispatches (engine/batching.py) — and splits the
    results back into per-segment cache entries."""
    ap, segs = _make_aggregate_partials_with_segs(query, segments, clamp,
                                                  check)
    if len(ap.partials) != len(segs):
        # the sharded path fused the set into one merged partial (mesh
        # active) — per-segment states no longer exist, so compute each
        # segment singly; callers needing the split semantics (the cache
        # population path) get correct entries at per-segment cost. The
        # cancel probe keeps firing at every dispatch boundary.
        out = []
        for i, s in enumerate(segments):
            if check is not None and i:
                check()
            out.append(make_aggregate_partials(query, [s], clamp=clamp))
        return out
    return _split_by_segment(ap, segs, segments)


def _split_by_segment(ap: AggregatePartials, segs: Sequence[Segment],
                      segments: Sequence[Segment]
                      ) -> List[AggregatePartials]:
    """Split a per-segment AggregatePartials (partials parallel to `segs`)
    into one entry per input segment; a segment absent from `segs` (outside
    the query intervals) yields an EMPTY partials object — exactly what the
    per-miss cache loop would have stored for it."""
    remaining: Dict[int, List[int]] = {}
    for i, s in enumerate(segs):
        remaining.setdefault(id(s), []).append(i)
    out = []
    for s in segments:
        idxs = remaining.get(id(s))
        if idxs:
            i = idxs.pop(0)
            out.append(AggregatePartials([ap.partials[i]],
                                         [ap.dim_values[i]],
                                         [ap.spans[i]], ap.intervals))
        else:
            out.append(AggregatePartials([], [], [], ap.intervals))
    return out


def split_partials_by_segment(ap: AggregatePartials,
                              segments: Sequence[Segment]
                              ) -> List[AggregatePartials]:
    """Public splitter for per-segment partial sets produced WITHOUT mesh
    fusion (make_aggregate_partials_multi items): `ap.partials` is parallel
    to `_segments_for(segments, ap.intervals)` by construction, so the
    per-input-segment split is exact. The data node's scheduler-fused
    segment-cache path uses this to turn one fused wave's results back
    into per-segment cache entries identical to the serial path's."""
    segs = _segments_for(segments, ap.intervals or [])
    assert len(ap.partials) == len(segs), \
        "split_partials_by_segment needs unfused per-segment partials"
    return _split_by_segment(ap, segs, segments)


#: TTL for cached union-remap id columns: a rolling ingest window retires
#: segments' union digests, and the per-(segment, dim) aux slot would pin
#: its last n_rows×4B remap forever (the aux cache has no eviction). The
#: sweeper below clears any slot idle past this, so stale remaps stop
#: pinning host memory while hot dashboards (re-touched every query) never
#: expire. Override via DRUID_TPU_UNIDIM_TTL_S; <= 0 disables expiry.
_UNIDIM_TTL_S = float(os.environ.get("DRUID_TPU_UNIDIM_TTL_S", "900"))
_UNIDIM_LOCK = threading.Lock()


class _UnidimSlot(dict):
    """Weakref-able remap slot ({union digest: remapped ids}) with a
    last-touch stamp; the registry holds weak references only, so a
    collected segment's slots vanish without bookkeeping. Identity
    hash/eq: dict is unhashable and content-equality would collide
    distinct (empty) slots inside the WeakSet registry."""
    __slots__ = ("__weakref__", "touched")
    __hash__ = object.__hash__

    def __eq__(self, other):
        return self is other

    def __ne__(self, other):
        return self is not other


_UNIDIM_SLOTS: "weakref.WeakSet[_UnidimSlot]" = weakref.WeakSet()


def set_unidim_ttl(seconds: float) -> float:
    """Set the union-remap TTL; returns the previous value (test hook)."""
    global _UNIDIM_TTL_S
    with _UNIDIM_LOCK:
        prev = _UNIDIM_TTL_S
        _UNIDIM_TTL_S = float(seconds)
        return prev


def _sweep_unidim(now: float) -> int:
    """Clear every union-remap slot idle past the TTL; returns the number
    of slots cleared. Runs at each unify_query_dims entry — eviction needs
    no background thread because the only growth source is this path."""
    cleared = 0
    with _UNIDIM_LOCK:
        ttl = _UNIDIM_TTL_S
        if ttl <= 0:
            return 0
        for slot in list(_UNIDIM_SLOTS):
            if slot and now - getattr(slot, "touched", now) > ttl:
                slot.clear()
                cleared += 1
    return cleared


def unify_query_dims(segs: Sequence[Segment], kds_per_seg,
                     vals_per_seg) -> None:
    """Unify per-segment QUERY-TIME dictionaries (numeric/expression
    dimension handlers: KeyDim.host_ids) into ONE id space across the
    query's segments, in place. Each segment's local ids remap host-side
    into the sorted union of every segment's values (cached per (segment,
    union digest)), so plan constants — cardinality, decode list — stop
    being segment-local and shape-compatible segments batch
    (engine/batching.py; the host-mask era excluded these). Results are
    unchanged: ids decode to exactly the same values, the space is merely
    shared."""
    import hashlib
    if len(segs) < 2 or not kds_per_seg or not kds_per_seg[0]:
        return
    now = time.monotonic()
    _sweep_unidim(now)
    for j in range(len(kds_per_seg[0])):
        col = [kds[j] for kds in kds_per_seg]
        if not all(kd.host_ids is not None and kd.remap is None
                   and kd.ids_key is not None for kd in col):
            continue
        lists = [vals[j] for vals in vals_per_seg]
        if all(l == lists[0] for l in lists[1:]):
            continue                  # already one id space
        try:
            union = sorted(set().union(*map(set, lists)))
        except TypeError:
            continue                  # unorderable mixed types: per-segment
        udig = hashlib.sha1(repr(union).encode()).hexdigest()[:16]
        index = {v: i for i, v in enumerate(union)}
        for s, kds, vals in zip(segs, kds_per_seg, vals_per_seg):
            kd = kds[j]
            # ONE resident remapped id column per (segment, dim), replaced
            # when the union digest changes, and TTL-swept when idle
            # (_sweep_unidim): a rolling segment set would otherwise grow
            # a fresh n_rows×4B aux entry per distinct window this segment
            # ever appeared in AND pin the last one forever. Repeated
            # dashboards over a stable set still hit.
            slot = s.aux_cached(("unidim",) + tuple(kd.ids_key),
                                _UnidimSlot)
            with _UNIDIM_LOCK:
                _UNIDIM_SLOTS.add(slot)
            slot.touched = now
            new_ids = slot.get(udig)
            if new_ids is None:
                remap = np.asarray([index[v] for v in vals[j]],
                                   dtype=np.int32)
                new_ids = remap[kd.host_ids]
                slot.clear()
                # the slot was fetched per (segment, kd.ids_key) via
                # aux_cached, so segment/kd state is pinned per slot;
                # udig keys the one free variable (the window's union)
                slot[udig] = new_ids  # druidlint: disable=unkeyed-trace-input
            kds[j] = KeyDim(kd.column, max(len(union), 1), None,
                            host_ids=new_ids,
                            ids_key=("unidim",) + tuple(kd.ids_key)
                            + (udig,))
            vals[j] = list(union)


def _keydims_for_query(query, segs: Sequence[Segment]):
    """Per-segment KeyDims + decode value lists for an aggregate query —
    the one derivation every partial-producing path (single-query, multi-
    query scheduler, by-segment split) shares."""
    if isinstance(query, TimeseriesQuery):
        return [[] for _ in segs], [[] for _ in segs]
    if isinstance(query, TopNQuery):
        keydims = [_keydim_for(s, query.dimension) for s in segs]
        kds_per_seg = [[kd] for kd, _ in keydims]
        vals_per_seg = [[values] for _, values in keydims]
        unify_query_dims(segs, kds_per_seg, vals_per_seg)
        return kds_per_seg, vals_per_seg
    if isinstance(query, GroupByQuery):
        kds_per_seg, vals_per_seg = [], []
        for s in segs:
            kds, vals = [], []
            for d in query.dimensions:
                kd, v = _keydim_for(s, d)
                kds.append(kd)
                vals.append(v)
            kds_per_seg.append(kds)
            vals_per_seg.append(vals)
        unify_query_dims(segs, kds_per_seg, vals_per_seg)
        return kds_per_seg, vals_per_seg
    raise TypeError(f"not an aggregate query: {type(query).__name__}")


def _make_aggregate_partials_with_segs(query, segments: Sequence[Segment],
                                       clamp: bool, check
                                       ) -> Tuple[AggregatePartials,
                                                  List[Segment]]:
    intervals = condense(query.intervals)
    segs = _segments_for(segments, intervals)
    if clamp and not query.granularity.is_all:
        intervals = _clamp_to_data(intervals, segs)
    if not segs:
        return AggregatePartials([], [], [], intervals), segs
    kds_per_seg, vals_per_seg = _keydims_for_query(query, segs)
    partials, dim_values = _make_partials(segs, intervals, query,
                                          kds_per_seg, vals_per_seg,
                                          check=check)
    spans = [(s.min_time, s.max_time) for s in segs]
    return AggregatePartials(partials, dim_values, spans, intervals), segs


def make_aggregate_partials_multi(items, on_batch=None) -> List[object]:
    """Cross-query partial production: one call for a whole scheduler
    flush. `items` is a sequence of (query, segments, check) triples —
    aggregate queries over LOCAL segments, meshless (the scheduler routes
    mesh/cached/row work individually). Returns one entry per item: an
    AggregatePartials, or the Exception that item's cancel/timeout probe
    raised.

    Per-item planning (interval condensing, keydim derivation) is exactly
    the serial path's; only the device dispatches fuse — results are
    bit-identical to calling make_aggregate_partials per item.
    `on_batch(n_queries, n_segments, fill)` observes each fused dispatch
    (the scheduler's query/crossBatch/* hook)."""
    from druid_tpu.engine.batching import BatchWork, run_multi_with_batching
    from druid_tpu.obs.trace import span as trace_span

    work: List[BatchWork] = []
    meta: List[object] = []   # per item: (intervals, segs, vals) | result
    for query, segments, check in items:
        try:
            intervals = condense(query.intervals)
            segs = _segments_for(segments, intervals)
            if not segs:
                meta.append(AggregatePartials([], [], [], intervals))
                continue
            kds_per_seg, vals_per_seg = _keydims_for_query(query, segs)
        except Exception as e:
            meta.append(e)
            continue
        meta.append((intervals, segs, vals_per_seg))
        work.append(BatchWork(
            segs=segs, intervals=intervals, granularity=query.granularity,
            kds_per_seg=kds_per_seg, aggs=query.aggregations,
            flt=query.filter, virtual_columns=query.virtual_columns,
            context=query.context_map, check=check))

    with trace_span("engine/partials", queries=len(work),
                    segments=sum(len(w.segs) for w in work)):
        multi = run_multi_with_batching(work, on_batch=on_batch)

    out: List[object] = []
    it = iter(multi)
    for m in meta:
        if not isinstance(m, tuple):
            out.append(m)            # precomputed empty result / error
            continue
        intervals, segs, vals_per_seg = m
        got = next(it)
        if isinstance(got, BaseException):
            out.append(got)
            continue
        spans = [(s.min_time, s.max_time) for s in segs]
        out.append(AggregatePartials(got, list(vals_per_seg), spans,
                                     intervals))
    return out


# ---------------------------------------------------------------------------
# Timeseries
# ---------------------------------------------------------------------------

def run_by_segment(query, segments: Sequence[Segment]) -> List[dict]:
    """context.bySegment=true: per-segment UNMERGED results, each wrapped
    with its segment identity (reference: BySegmentQueryRunner.java — the
    caching/debug surface where the broker sees exactly what every segment
    contributed)."""
    from dataclasses import replace
    inner = replace(query, context=tuple(
        (k, v) for k, v in query.context_map.items() if k != "bySegment"))
    out: List[dict] = []
    intervals = condense(query.intervals)
    for s in _segments_for(segments, intervals):
        if isinstance(query, TimeseriesQuery):
            rows = finish_timeseries(
                inner, make_aggregate_partials(inner, [s]))
        elif isinstance(query, TopNQuery):
            rows = finish_topn(inner, make_aggregate_partials(inner, [s]))
        else:
            rows = finish_groupby(inner, make_aggregate_partials(inner, [s]))
        out.append({
            "timestamp": rows[0]["timestamp"] if rows else None,
            "result": {"results": rows, "segment": str(s.id),
                       "interval": str(s.interval)},
            "bySegment": True,
        })
    return out


def run_timeseries(query: TimeseriesQuery, segments: Sequence[Segment]) -> List[dict]:
    return finish_timeseries(query, make_aggregate_partials(query, segments))


def finish_timeseries(query: TimeseriesQuery,
                      ap: AggregatePartials) -> List[dict]:
    intervals = ap.intervals if ap.intervals is not None \
        else condense(query.intervals)
    starts = _bucket_starts(query.granularity, intervals)
    if not ap.partials or len(starts) == 0:
        return []
    buckets, _, counts, states, kernels = merge_partials(
        ap.partials, [[] for _ in ap.partials])
    finalized = {k.name: k.finalize_array(states[k.name]) for k in kernels}

    covered = _covered_buckets(query.granularity, starts, ap.spans, intervals)
    empty_defaults = {k.name: k.finalize_array(k.empty_state(1))[0]
                      for k in kernels}

    by_bucket = {int(b): i for i, b in enumerate(buckets)}
    rows = []
    for bi, st in enumerate(starts):
        gi = by_bucket.get(bi)
        if gi is None:
            if not covered[bi] or query.skip_empty_buckets:
                continue
            vals = {name: _scalar(v) for name, v in empty_defaults.items()}
        else:
            if query.skip_empty_buckets and counts[gi] == 0:
                continue
            vals = {k.name: _scalar(finalized[k.name][gi]) for k in kernels}
        vals = compute_postaggs(query.post_aggregations, vals)
        rows.append({"timestamp": int(st), "result": vals})
    if query.descending:
        rows.reverse()
    return rows


def _scalar(v):
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    if isinstance(v, np.ndarray) and v.ndim == 0:
        return v.item()
    return v


# ---------------------------------------------------------------------------
# TopN
# ---------------------------------------------------------------------------

def run_topn(query: TopNQuery, segments: Sequence[Segment]) -> List[dict]:
    return finish_topn(query, make_aggregate_partials(query, segments))


def finish_topn(query: TopNQuery, ap: AggregatePartials) -> List[dict]:
    intervals = ap.intervals if ap.intervals is not None \
        else condense(query.intervals)
    starts = _bucket_starts(query.granularity, intervals)
    if not ap.partials or len(starts) == 0:
        return []
    buckets, dim_vals, counts, states, kernels = merge_partials(
        ap.partials, ap.dim_values)
    finalized = {k.name: k.finalize_array(states[k.name]) for k in kernels}
    arrays = _vectorized_postaggs(query.post_aggregations, finalized)
    values = dim_vals[0] if dim_vals else np.zeros(0, dtype=object)
    out_name = query.dimension.output_name

    # live groups only
    live = counts > 0
    buckets, values = buckets[live], values[live]
    arrays = {k: np.asarray(v)[live] for k, v in arrays.items()}

    ordering = query.metric_ordering
    rows = []
    covered = _covered_buckets(query.granularity, starts, ap.spans, intervals)
    for bi, st in enumerate(starts):
        sel = buckets == bi
        if not sel.any():
            if covered[bi]:
                rows.append({"timestamp": int(st), "result": []})
            continue
        idx = np.flatnonzero(sel)
        if ordering in ("lexicographic",):
            order = np.argsort(values[idx].astype(str))
        elif ordering == "inverted_lexicographic":
            order = np.argsort(values[idx].astype(str))[::-1]
        elif ordering == "strlen":
            order = np.argsort([len(str(v)) for v in values[idx]])
        else:
            metric_arr = np.asarray(arrays[query.metric], dtype=np.float64)
            order = np.argsort(-metric_arr[idx], kind="stable")
            if ordering == "inverted":
                order = order[::-1]
        top = idx[order[: query.threshold]]
        result = []
        for gi in top:
            entry = {out_name: values[gi]}
            for name, arr in arrays.items():
                entry[name] = _scalar(np.asarray(arr)[gi])
            result.append(entry)
        rows.append({"timestamp": int(st), "result": result})
    return rows


# ---------------------------------------------------------------------------
# GroupBy
# ---------------------------------------------------------------------------

def run_groupby(query: GroupByQuery, segments: Sequence[Segment]) -> List[dict]:
    return finish_groupby(query, make_aggregate_partials(query, segments))


def finish_groupby(query: GroupByQuery, ap: AggregatePartials) -> List[dict]:
    intervals = ap.intervals if ap.intervals is not None \
        else condense(query.intervals)
    starts = _bucket_starts(query.granularity, intervals)
    if not ap.partials or len(starts) == 0:
        return []
    buckets, dim_vals, counts, states, kernels = merge_partials(
        ap.partials, ap.dim_values)
    finalized = {k.name: k.finalize_array(states[k.name]) for k in kernels}
    arrays = _vectorized_postaggs(query.post_aggregations, finalized)

    live = counts > 0
    out_names = [d.output_name for d in query.dimensions]
    rows = _emit_groupby_rows(starts, buckets, dim_vals, arrays, live, out_names,
                              kernels, query)

    if query.subtotals:
        rows = rows + _subtotal_rows(query, starts, buckets, dim_vals, counts,
                                     states, kernels)

    if query.having is not None:
        rows = [r for r in rows if query.having.evaluate(r["event"])]
    rows = _apply_limit_spec(rows, query.limit_spec, out_names)
    return rows


def _emit_groupby_rows(starts, buckets, dim_vals, arrays, live, out_names,
                       kernels, query) -> List[dict]:
    # columnar → row dicts via one .tolist() per column: at 100k+ groups the
    # per-element numpy scalar extraction would dominate the whole query
    idxs = np.flatnonzero(live)
    n = len(idxs)
    if len(starts):
        ts = np.asarray(starts)[np.asarray(buckets)[idxs]].tolist()
    else:
        ts = [0] * n
    agg_names = [k.name for k in kernels] + [p.name for p in query.post_aggregations]
    cols = [(name, np.asarray(vals)[idxs].tolist())
            for name, vals in zip(out_names, dim_vals)]
    cols += [(name, np.asarray(arrays[name])[idxs].tolist())
             for name in agg_names]
    rows = []
    for i in range(n):
        event = {name: lst[i] for name, lst in cols}
        rows.append({"version": "v1", "timestamp": int(ts[i]),
                     "event": event})
    return rows


def _subtotal_rows(query, starts, buckets, dim_vals, counts, states,
                   kernels) -> List[dict]:
    """Re-group merged results for each subtotal spec (reference:
    GroupByStrategyV2.processSubtotalsSpec)."""
    out_names = [d.output_name for d in query.dimensions]
    rows = []
    live = np.flatnonzero(counts > 0)
    for subset in query.subtotals:
        keep = [i for i, n in enumerate(out_names) if n in subset]
        groups: Dict[tuple, dict] = {}
        for gi in live:
            key = (int(buckets[gi]),) + tuple(dim_vals[i][gi] for i in keep)
            g = groups.get(key)
            if g is None:
                g = {"states": {k.name: _state_at(states[k.name], gi)
                                for k in kernels}}
                groups[key] = g
            else:
                for k in kernels:
                    g["states"][k.name] = k.combine(
                        g["states"][k.name], _state_at(states[k.name], gi))
        for key, g in sorted(groups.items(), key=lambda kv: str(kv[0])):
            event = {}
            for j, i in enumerate(keep):
                event[out_names[i]] = key[1 + j]
            vals = {k.name: _scalar(k.finalize_array(g["states"][k.name])[0])
                    for k in kernels}
            event.update(compute_postaggs(query.post_aggregations, vals))
            rows.append({"version": "v1",
                         "timestamp": int(starts[key[0]]) if len(starts) else 0,
                         "event": event})
    return rows


def _state_at(state, gi):
    if isinstance(state, dict):
        return {k: _state_at(v, gi) for k, v in state.items()}
    return np.asarray(state)[gi:gi + 1]


def _apply_limit_spec(rows: List[dict], limit_spec: Optional[DefaultLimitSpec],
                      dim_names: List[str]) -> List[dict]:
    if limit_spec is None:
        return rows
    if limit_spec.columns:
        # stable multi-column sort: apply columns in reverse significance order
        for c in reversed(limit_spec.columns):
            descending = c.direction == "descending"

            def one_key(row, col=c):
                # "__timestamp" orders by the granularity bucket (used by
                # SQL ORDER BY on a FLOOR(__time TO ...) projection)
                v = row["timestamp"] if col.dimension == "__timestamp" \
                    else row["event"].get(col.dimension)
                if col.dimension_order == "numeric" or not isinstance(v, str):
                    try:
                        v = float(v)
                    except (TypeError, ValueError):
                        v = float("-inf")
                return v
            rows = sorted(rows, key=one_key, reverse=descending)
    start = limit_spec.offset
    end = None if limit_spec.limit is None else start + limit_spec.limit
    return rows[start:end]


# ---------------------------------------------------------------------------
# Scan / Select (raw row export, host-side)
# ---------------------------------------------------------------------------

def _masked_row_ids(segment: Segment, query) -> np.ndarray:
    intervals = condense(query.intervals)
    t = segment.time_ms
    m = np.zeros(segment.n_rows, dtype=bool)
    for iv in intervals:
        m |= (t >= iv.start) & (t < iv.end)
    m &= host_mask(query.filter, segment,
                   getattr(query, "virtual_columns", ()))
    return np.flatnonzero(m)


def _decode_rows(segment: Segment, row_ids: np.ndarray,
                 columns: Sequence[str]) -> List[dict]:
    cols: Dict[str, np.ndarray] = {}
    for c in columns:
        if c == "__time":
            cols[c] = segment.time_ms[row_ids]
        elif c in segment.dims:
            col = segment.dims[c]
            vals = np.asarray(col.dictionary.values, dtype=object)
            cols[c] = vals[col.ids[row_ids]] if col.cardinality else \
                np.full(len(row_ids), "", dtype=object)
        elif c in segment.metrics:
            cols[c] = segment.metrics[c].values[row_ids]
    out = []
    for i in range(len(row_ids)):
        out.append({c: _scalar(v[i]) for c, v in cols.items()})
    return out


def iter_scan(query: ScanQuery, segments: Sequence[Segment]):
    """Lazy scan: yields one ScanResultValue batch at a time, a segment is
    only filtered/decoded when its batch is pulled, and `batch_size`
    bounds events per batch — the Sequence-analog streaming surface
    (reference: ScanQueryEngine returning a BaseSequence of batches)."""
    intervals = condense(query.intervals)
    segs = _segments_for(segments, intervals)
    if query.order == "descending":
        segs = sorted(segs, key=lambda s: s.min_time, reverse=True)
    else:
        segs = sorted(segs, key=lambda s: s.min_time)
    remaining = query.limit if query.limit is not None else None
    to_skip = query.offset
    batch = max(int(query.batch_size), 1)
    for s in segs:
        if remaining is not None and remaining <= 0:
            return
        row_ids = _masked_row_ids(s, query)
        if query.order == "descending":
            row_ids = row_ids[::-1]
        if to_skip:
            if to_skip >= len(row_ids):
                to_skip -= len(row_ids)
                continue
            row_ids = row_ids[to_skip:]
            to_skip = 0
        if remaining is not None:
            row_ids = row_ids[:remaining]
            remaining -= len(row_ids)
        columns = list(query.columns) or (
            ["__time"] + list(s.dims.keys()) + list(s.metrics.keys()))
        for i in range(0, len(row_ids), batch):
            events = _decode_rows(s, row_ids[i:i + batch], columns)
            if events:
                yield {"segmentId": str(s.id), "columns": columns,
                       "events": events}


def run_scan(query: ScanQuery, segments: Sequence[Segment]) -> List[dict]:
    return list(iter_scan(query, segments))


def run_select(query: SelectQuery, segments: Sequence[Segment]) -> List[dict]:
    intervals = condense(query.intervals)
    segs = _segments_for(segments, intervals)
    segs = sorted(segs, key=lambda s: s.min_time, reverse=query.descending)
    paging = dict(query.paging_spec)
    threshold = query.threshold
    events = []
    new_paging: Dict[str, int] = {}
    for s in segs:
        if threshold <= 0:
            break
        row_ids = _masked_row_ids(s, query)
        if query.descending:
            row_ids = row_ids[::-1]
        start = paging.get(str(s.id), -1) + 1
        row_ids = row_ids[start:start + threshold]
        threshold -= len(row_ids)
        columns = (["__time"] + (list(query.dimensions) or list(s.dims.keys()))
                   + (list(query.metrics) or list(s.metrics.keys())))
        for off, ev in zip(range(start, start + len(row_ids)),
                           _decode_rows(s, row_ids, columns)):
            events.append({"segmentId": str(s.id), "offset": off, "event": ev})
            new_paging[str(s.id)] = off
    ts = int(min((s.min_time for s in segs), default=0))
    return [{"timestamp": ts,
             "result": {"pagingIdentifiers": new_paging, "events": events}}]


# ---------------------------------------------------------------------------
# Search
# ---------------------------------------------------------------------------

def run_search(query: SearchQuery, segments: Sequence[Segment]) -> List[dict]:
    intervals = condense(query.intervals)
    segs = _segments_for(segments, intervals)
    if not segs:
        return []
    needle = query.value if query.case_sensitive else query.value.lower()

    def matches(v: str) -> bool:
        h = v if query.case_sensitive else v.lower()
        return needle in h

    hits: Dict[Tuple[str, str], int] = {}
    for s in segs:
        row_ids = _masked_row_ids(s, query)
        dims = list(query.search_dimensions) or list(s.dims.keys())
        for d in dims:
            col = s.dims.get(d)
            if col is None:
                continue
            cnt = np.bincount(col.ids[row_ids], minlength=col.cardinality)
            for vid, c in enumerate(cnt):
                if c > 0 and matches(col.dictionary.values[vid]):
                    key = (d, col.dictionary.values[vid])
                    hits[key] = hits.get(key, 0) + int(c)

    entries = [{"dimension": d, "value": v, "count": c}
               for (d, v), c in hits.items()]
    if query.sort == "strlen":
        entries.sort(key=lambda e: (len(e["value"]), e["value"], e["dimension"]))
    else:
        entries.sort(key=lambda e: (e["value"], e["dimension"]))
    entries = entries[: query.limit]
    ts = int(min(iv.start for iv in intervals))
    return [{"timestamp": ts, "result": entries}]


# ---------------------------------------------------------------------------
# TimeBoundary / SegmentMetadata / DataSourceMetadata
# ---------------------------------------------------------------------------

def run_time_boundary(query: TimeBoundaryQuery,
                      segments: Sequence[Segment]) -> List[dict]:
    intervals = condense(query.intervals)
    segs = _segments_for(segments, intervals)
    min_t, max_t = None, None
    for s in segs:
        if query.filter is None and len(intervals) == 1 \
                and intervals[0].contains_interval(Interval(s.min_time, s.max_time + 1)):
            lo, hi = s.min_time, s.max_time
        else:
            row_ids = _masked_row_ids(s, query)
            if len(row_ids) == 0:
                continue
            t = s.time_ms[row_ids]
            lo, hi = int(t.min()), int(t.max())
        min_t = lo if min_t is None else min(min_t, lo)
        max_t = hi if max_t is None else max(max_t, hi)
    if min_t is None:
        return []
    result = {}
    if query.bound in (None, "minTime"):
        result["minTime"] = min_t
    if query.bound in (None, "maxTime"):
        result["maxTime"] = max_t
    ts = min_t if query.bound != "maxTime" else max_t
    return [{"timestamp": ts, "result": result}]


def _analyze_segment(segment: Segment, query: SegmentMetadataQuery) -> dict:
    """reference: query/metadata/SegmentAnalyzer.java"""
    cols = {}
    names = list(query.to_include) or (
        ["__time"] + list(segment.dims.keys()) + list(segment.metrics.keys()))
    want = set(query.analysis_types)
    for c in names:
        info: Dict[str, object] = {"hasMultipleValues": False,
                                   "errorMessage": None}
        if c == "__time":
            info["type"] = "LONG"
            if "size" in want:
                info["size"] = int(segment.time_ms.nbytes)
            if "minmax" in want:
                info["minValue"] = segment.min_time
                info["maxValue"] = segment.max_time
        elif c in segment.dims:
            col = segment.dims[c]
            info["type"] = "STRING"
            if "cardinality" in want:
                info["cardinality"] = col.cardinality
            if "size" in want:
                info["size"] = int(col.ids.nbytes)
            if "minmax" in want and col.cardinality:
                info["minValue"] = col.dictionary.values[0]
                info["maxValue"] = col.dictionary.values[-1]
        elif c in segment.metrics:
            m = segment.metrics[c]
            info["type"] = m.type.value.upper()
            if "size" in want:
                info["size"] = int(m.values.nbytes)
            if "minmax" in want and segment.n_rows:
                info["minValue"] = _scalar(m.values.min())
                info["maxValue"] = _scalar(m.values.max())
        else:
            continue
        cols[c] = info
    return {"id": str(segment.id),
            "intervals": [str(segment.interval)] if "interval" in want else None,
            "columns": cols,
            "size": segment.size_bytes(),
            "numRows": segment.n_rows}


def run_segment_metadata(query: SegmentMetadataQuery,
                         segments: Sequence[Segment]) -> List[dict]:
    intervals = condense(query.intervals)
    segs = _segments_for(segments, intervals)
    analyses = [_analyze_segment(s, query) for s in segs]
    if not query.merge or not analyses:
        return analyses
    merged = analyses[0]
    for a in analyses[1:]:
        merged["size"] += a["size"]
        merged["numRows"] += a["numRows"]
        if merged["intervals"] is not None and a["intervals"]:
            merged["intervals"] = sorted(set(merged["intervals"] + a["intervals"]))
        for c, info in a["columns"].items():
            if c not in merged["columns"]:
                merged["columns"][c] = info
            else:
                tgt = merged["columns"][c]
                for k in ("size",):
                    if k in info and k in tgt:
                        tgt[k] += info[k]
                for k in ("cardinality",):
                    if k in info and k in tgt:
                        tgt[k] = max(tgt[k], info[k])
                if "minValue" in info and "minValue" in tgt:
                    tgt["minValue"] = min(tgt["minValue"], info["minValue"])
                    tgt["maxValue"] = max(tgt["maxValue"], info["maxValue"])
    merged["id"] = "merged"
    return [merged]


def run_datasource_metadata(query: DataSourceMetadataQuery,
                            segments: Sequence[Segment]) -> List[dict]:
    if not segments:
        return []
    mx = max(s.max_time for s in segments)
    return [{"timestamp": mx, "result": {"maxIngestedEventTime": mx}}]
