"""One-dispatch megakernel: fused bitmap filter + packed decode + aggregation.

PRs 9-10 made value columns resident as bit-packed words and filter
bitmaps resident as packed words — but a COLD query still paid up to three
device dispatches: the bitmap-algebra fill wave (engine/filters.py
`_eval_structure`), then the aggregation program (packed decode at the
program top + reduce). This module closes ROADMAP item 4: the whole query
becomes ONE device program, following the decompress-inside-the-operator
design of *GPU Acceleration of SQL Analytics on Compressed Data* and the
accelerator-serving framing of *Tailwind* (PAPERS.md).

The fused path, per bitmap-eligible filter subtree:

  * `megaize` replaces each planned DeviceBitmapNode whose COMBINED words
    are not already pool-resident with a MegaBitmapNode: its per-leaf row
    bitmaps stage as resident words (1 bit/row, the width-1 instance of
    the data/packed.py tile-planar layout) and the AND/OR/NOT/XOR word
    algebra evaluates INLINE in the one traced program — no fill dispatch,
    no combined-words materialization in HBM. Hot dashboards whose
    combined words ARE resident keep the cached bit-test path (also one
    dispatch); the megakernel is the one-shot/cold-query story.
  * On the pallas (sorted-projection) strategy, `mega_reduce` runs the
    fused aggregation kernel: packed value columns arrive AS WORDS and
    unpack per VMEM tile (engine/pallas_agg.py discipline), and the row
    mask arrives AS WORDS too — the interval/validity mask packs to words
    in-program, ANDs with the filter word algebra, and the kernel performs
    a Mosaic-safe sub-lane unpack per block ((1, 128) of word VMEM instead
    of an (R, 128) int32 row mask — ~32x less mask VMEM traffic). No
    decoded column and no row-width mask ever hits HBM.
  * Per-group partial buffers DONATE across executions (`donate_argnums`,
    the pjit plumbing of SNIPPETS.md [1]/[2]): the raw accumulator grids
    of one run park in the device pool and are handed back — donated — to
    the next run of the same (segment, program) pair, so standing/repeated
    queries driven by the scheduler's flush loop (PR 7) update partials in
    place with zero per-tick HBM churn. The kernel re-initializes the
    grids at grid step 0, so donated reuse is bit-identical to fresh
    zero buffers (the donation-aliasing parity contract).

Parity discipline (PR 9): the fused path is bit-identical to the staged
path — the mask BITS are exactly the staged algebra's, and the kernel's
block/accumulation order is pallas_agg's, so counts/int sums match
bitwise and float sums reduce in the same order.

Opt-out: `DRUID_TPU_MEGAKERNEL=0` (or set_enabled(False)) keeps the
staged fill-wave + resident-combined-words path everywhere.
"""
from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from druid_tpu.engine import pallas_agg
from druid_tpu.engine.contracts import (BLK_SMALL_W, MEGA_MASK_ROW_ALIGN,
                                        MEGA_MASK_VPW, MEGA_MASK_WIDTH,
                                        donation_supported)
from druid_tpu.engine.filters import (AndNode, DeviceBitmapNode, FilterNode,
                                      NotNode, OrNode, _leaf_digest,
                                      bitmap_pool_key, collect_bitmap_nodes,
                                      perm_digest)
from druid_tpu.utils.emitter import Monitor

#: process default; opt-out via DRUID_TPU_MEGAKERNEL=0 or set_enabled(False)
_ENABLED = os.environ.get("DRUID_TPU_MEGAKERNEL", "1").lower() \
    not in ("0", "false", "no")
#: tests force donation on (CPU ignores donation silently) or off
_FORCE_DONATE: Optional[bool] = None
#: tests force the carry take/park handoff without real donation (CPU)
_FORCE_CARRY: Optional[bool] = None
_STATE_LOCK = threading.Lock()


def set_enabled(on: bool) -> bool:
    """Flip the process-wide megakernel default; returns the previous value
    (bench/test toggle, the batching/packed.set_enabled discipline)."""
    global _ENABLED
    with _STATE_LOCK:
        prev = _ENABLED
        _ENABLED = bool(on)
        return prev


def enabled() -> bool:
    return _ENABLED


def set_force_donate(on: Optional[bool]) -> Optional[bool]:
    """Override donation support detection (None = autodetect). Forcing
    donation ON where the backend does not support it (CPU) is undefined
    behavior — this hook exists for accelerator-run experiments only."""
    global _FORCE_DONATE
    with _STATE_LOCK:
        prev = _FORCE_DONATE
        _FORCE_DONATE = on
        return prev


def donation_enabled() -> bool:
    """Whether the fused program donates its carry buffers. The platform
    decision lives in ONE place — contracts.donation_supported (tri-state
    DRUID_TPU_DONATE, backend autodetect) — so every donation-enable path
    routes through the shared gate donorguard's donate-platform-gate
    rule recognizes; this function only layers the test override on top."""
    if _FORCE_DONATE is not None:
        return _FORCE_DONATE
    return donation_supported()


def set_force_carry(on: Optional[bool]) -> Optional[bool]:
    """Override carry_enabled detection (None = follow donation). Lets CPU
    tests exercise the take/park handoff and its fresh-vs-carried parity
    without real donation."""
    global _FORCE_CARRY
    with _STATE_LOCK:
        prev = _FORCE_CARRY
        _FORCE_CARRY = on
        return prev


def carry_enabled() -> bool:
    """Whether executions pool-park their raw grids and ride them back as
    carries. Without donation the parked grids would only consume pool
    budget (the buffers are never aliased into outputs), so the handoff
    follows donation support by default."""
    if _FORCE_CARRY is not None:
        return _FORCE_CARRY
    return donation_enabled()


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


# ---------------------------------------------------------------------------
# Stats (query/megakernel/* metrics)
# ---------------------------------------------------------------------------

class MegaStats:
    """hits = bitmap subtrees fused inline; fallbacks = bitmap subtrees
    that did NOT fuse (megakernel disabled, or resident combined words
    already serve them); donated_bytes = carry-buffer bytes handed back
    donated across executions."""

    def __init__(self):
        self._lock = threading.Lock()
        self.hits = 0
        self.fallbacks = 0
        self.donated_bytes = 0

    def record_hit(self, n: int = 1) -> None:
        with self._lock:
            self.hits += n

    def record_fallback(self, n: int = 1) -> None:
        with self._lock:
            self.fallbacks += n

    def record_donated(self, nbytes: int) -> None:
        with self._lock:
            self.donated_bytes += nbytes

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return {"hits": self.hits, "fallbacks": self.fallbacks,
                    "donatedBytes": self.donated_bytes}


_STATS = MegaStats()


def stats() -> MegaStats:
    return _STATS


class MegakernelMonitor(Monitor):
    """Emits query/megakernel/{hits,fallbacks,donatedBytes} per tick
    (deltas over the tick window, the FilterBitmapMonitor discipline)."""

    def __init__(self, source: Optional[MegaStats] = None):
        self.source = source or _STATS
        self._last = self.source.snapshot()

    def do_monitor(self, emitter):
        s = self.source.snapshot()
        last, self._last = self._last, s
        emitter.metric("query/megakernel/hits", s["hits"] - last["hits"])
        emitter.metric("query/megakernel/fallbacks",
                       s["fallbacks"] - last["fallbacks"])
        emitter.metric("query/megakernel/donatedBytes",
                       s["donatedBytes"] - last["donatedBytes"])


# ---------------------------------------------------------------------------
# The fused filter node
# ---------------------------------------------------------------------------

class MegaBitmapNode(FilterNode):
    """A bitmap-eligible subtree fused INTO the aggregation program.

    Unlike DeviceBitmapNode (whose combined words are built by a separate
    fill dispatch and cached), this node's LEAVES are the resident device
    data — one word array per leaf, 1 bit/row in the width-1 tile-planar
    packed layout — and the word algebra traces inline. The algebra
    STRUCTURE is therefore program structure and joins the signature
    (exactly like the fill-program jit cache keyed on it)."""

    def __init__(self, structure, leaves: List[Tuple[str, np.ndarray]],
                 slot: int):
        self.structure = structure
        self.leaves = leaves
        self.slot = slot

    @classmethod
    def from_bitmap(cls, node: DeviceBitmapNode) -> "MegaBitmapNode":
        return cls(node.structure, list(node.leaves), node.slot)

    # same rendering/digest as the staged node — the pool-key contract is
    # shared, only the residency story differs
    structure_sig = DeviceBitmapNode.structure_sig
    digest = DeviceBitmapNode.digest

    def leaf_col(self, j: int) -> str:
        return f"__fleaf{self.slot}_{j}"

    def signature(self) -> str:
        return f"mega({self.slot}:{self.structure_sig()})"

    def required_device_columns(self):
        return set()

    def words_traced(self, cols: Dict):
        """Traced: the combined mask words (int32 [staged_rows/32]) — the
        one-shot word algebra, inline in the program instead of the
        separate fill dispatch. The algebra is
        filters.combine_structure_words — the SAME evaluator the staged
        fill program uses, so the two paths cannot drift."""
        import jax.numpy as jnp

        from druid_tpu.engine.filters import combine_structure_words

        def leaf_words(i):
            return cols[self.leaf_col(i)]

        def const_words(value):
            ref = cols[self.leaf_col(0)]
            fill = jnp.int32(-1) if value else jnp.int32(0)
            return jnp.full(ref.shape, fill, jnp.int32)

        return combine_structure_words(self.structure, leaf_words,
                                       const_words)

    def build(self, cols, aux):
        # XLA fallback (non-pallas strategies): combine words, then expand
        # to row bools — still inside the ONE traced program; XLA fuses the
        # expand into the mask consumers
        w = self.words_traced(cols)
        return expand_mask_words(w, cols["__valid"].shape[0])


def collect_mega_nodes(node: Optional[FilterNode]) -> List[MegaBitmapNode]:
    """Every MegaBitmapNode in a planned tree, deterministic DFS order."""
    out: List[MegaBitmapNode] = []

    def walk(n):
        if isinstance(n, MegaBitmapNode):
            out.append(n)
        elif isinstance(n, (AndNode, OrNode)):
            for c in n.children:
                walk(c)
        elif isinstance(n, NotNode):
            walk(n.child)
    if node is not None:
        walk(node)
    return out


def split_for_kernel(node: Optional[FilterNode]
                     ) -> Tuple[List[MegaBitmapNode], Optional[FilterNode]]:
    """(top-level AND-conjunct mega nodes, residual row-domain tree).

    Only mega nodes that are the root or direct AND conjuncts can combine
    in the WORD domain with the program's base mask; any other placement
    (under OR/NOT, or mixed deeper) stays in the residual tree and expands
    to row bools via MegaBitmapNode.build — still one dispatch, just
    without the in-kernel word-mask saving. The residual preserves child
    order, so its aux-consumption order matches the full tree's (mega
    nodes contribute no aux)."""
    if node is None:
        return [], None
    if isinstance(node, MegaBitmapNode):
        return [node], None
    if isinstance(node, AndNode):
        megas = [c for c in node.children if isinstance(c, MegaBitmapNode)]
        rest = [c for c in node.children
                if not isinstance(c, MegaBitmapNode)]
        if not megas:
            return [], node
        residual = None if not rest else \
            rest[0] if len(rest) == 1 else AndNode(rest)
        return megas, residual
    return [], node


# ---------------------------------------------------------------------------
# Planner hooks: megaize a planned tree / a kernel set
# ---------------------------------------------------------------------------

def megaize(filter_node: Optional[FilterNode], segment, padded_rows: int,
            perm_dig: Optional[str] = None) -> Optional[FilterNode]:
    """Rebuild a planned tree with every DeviceBitmapNode whose combined
    words are NOT already pool-resident replaced by a MegaBitmapNode (the
    one-shot inline path). Resident combined words — created by batched
    waves or staged-mode runs; the mega path itself never materializes
    them — keep the cached bit-test path instead of being re-derived.
    A purely per-segment hot query therefore re-runs the inline word
    algebra each time: a few word-wide VPU ops in-program, cheaper than
    the fill dispatch it replaces either way."""
    if filter_node is None or not collect_bitmap_nodes(filter_node):
        return filter_node

    def rebuild(n):
        if isinstance(n, DeviceBitmapNode):
            key = bitmap_pool_key(n, padded_rows, perm_dig)
            if segment.device_contains(key):
                _STATS.record_fallback()
                return n
            _STATS.record_hit()
            return MegaBitmapNode.from_bitmap(n)
        if isinstance(n, AndNode):
            return AndNode([rebuild(c) for c in n.children])
        if isinstance(n, OrNode):
            return OrNode([rebuild(c) for c in n.children])
        if isinstance(n, NotNode):
            return NotNode(rebuild(n.child))
        return n

    return rebuild(filter_node)


def megaize_kernels(kernels: Sequence, segment, padded_rows: int,
                    perm_dig: Optional[str] = None) -> None:
    """In-place megaize of every filtered-aggregator tree (kernels are
    single-use per execution — grouping.GroupPlan contract)."""
    from druid_tpu.engine.kernels import FilteredKernel
    for k in kernels:
        while isinstance(k, FilteredKernel):
            k.filter_node = megaize(k.filter_node, segment, padded_rows,
                                    perm_dig)
            k = k.child


def record_disabled_fallback(filter_node: Optional[FilterNode],
                             kernels: Sequence = ()) -> None:
    """Stats-only: bitmap subtrees that stay on the staged path because the
    megakernel is disabled."""
    n = len(collect_bitmap_nodes(filter_node))
    for k in kernels:
        for tree in k.filter_trees():
            n += len(collect_bitmap_nodes(tree))
    if n:
        _STATS.record_fallback(n)


# ---------------------------------------------------------------------------
# Mask-word packing (host + traced) and leaf staging
# ---------------------------------------------------------------------------

_LANE = 128


def staged_mask_rows(padded_rows: int) -> int:
    """Row count mask/leaf word arrays are sized for: covers every pallas
    row padding (n2 = round_up(max(rows, BLK), BLK) for BLK ≤ BLK_SMALL_W)
    rounded to whole 128-lane word rows."""
    return _round_up(max(padded_rows, BLK_SMALL_W), MEGA_MASK_ROW_ALIGN)


def expand_mask_words(words, rows: int):
    """Traced: width-1 tile-planar words → bool rows (the width-1 instance
    of data/packed.unpack_device; exact, so fused and staged masks carry
    identical bits)."""
    import jax.numpy as jnp
    w2 = words.reshape(-1, _LANE)
    sh = jnp.arange(MEGA_MASK_VPW, dtype=jnp.int32)
    bits = (w2[:, None, :] >> sh[None, :, None]) & jnp.int32(1)
    return bits.reshape(-1)[:rows].astype(bool)


def pack_mask_words_traced(mask):
    """Traced: bool rows (length a multiple of MEGA_MASK_ROW_ALIGN) →
    width-1 tile-planar int32 words. Disjoint bit positions, so the OR
    fold is exact; XLA fuses the row-mask computation into this pack, so
    no row-width mask materializes."""
    import jax.numpy as jnp
    m3 = mask.astype(jnp.int32).reshape(-1, MEGA_MASK_VPW, _LANE)
    words = m3[:, 0, :]
    for s in range(1, MEGA_MASK_VPW):
        words = words | (m3[:, s, :] << jnp.int32(s))
    return words.reshape(-1)


def stage_mega_leaves(segment, filter_node: Optional[FilterNode],
                      kernels: Sequence, padded_rows: int,
                      perm: Optional[np.ndarray] = None,
                      perm_key=None) -> Dict[str, object]:
    """Resident per-leaf mask words for every MegaBitmapNode in the query
    filter and the filtered-aggregator trees: {leaf col: int32 words}.
    Pool-cached per (dim, lut digest, staged rows, permutation digest) —
    the projection (permuted-layout) path stages PERMUTED words under its
    own digest, so original-order and permuted layouts never mix."""
    from druid_tpu.data import packed as packed_mod

    nodes = collect_mega_nodes(filter_node)
    for k in kernels:
        for tree in k.filter_trees():
            nodes.extend(collect_mega_nodes(tree))
    if not nodes:
        return {}
    n_w = staged_mask_rows(padded_rows)
    pdg = perm_digest(perm_key)
    out: Dict[str, object] = {}
    for node in nodes:
        for j, (dim, lut) in enumerate(node.leaves):
            key = ("megaleaf", dim, _leaf_digest(lut), n_w, pdg)

            def _build(dim=dim, lut=lut):
                import jax

                from druid_tpu.data import cascade as cascade_mod
                b = None
                if perm is None and cascade_mod.enabled():
                    # RLE-run-aware build: the match bit is decided once
                    # PER RUN (one LUT gather over run values + a repeat),
                    # not once per row — same output words bit-for-bit, so
                    # the resident cache and kernel paths compose unchanged
                    info = cascade_mod.column_run_info(segment, dim)
                    if info is not None:
                        values, ends, nr = info
                        lengths = np.diff(np.concatenate([[0], ends]))
                        b = np.repeat(lut[values], lengths)
                if b is None:
                    col = segment.dims[dim]
                    bm = col.bitmap_index().union_of(np.flatnonzero(lut))
                    b = bm.to_bool()
                    if perm is not None:
                        b = b[perm]
                padded = np.zeros(n_w, dtype=bool)
                padded[: b.shape[0]] = b
                return jax.device_put(
                    packed_mod.pack_padded(padded, MEGA_MASK_WIDTH, 0))

            out[node.leaf_col(j)] = segment.device_cached(key, _build)
    return out


# ---------------------------------------------------------------------------
# Donated carry buffers
# ---------------------------------------------------------------------------

def carry_defs(kernels: Sequence, col_dtypes: Dict, num_total: int,
               span: int) -> List[Tuple[Tuple[int, int], object]]:
    """[(shape, np dtype)] of the fused program's raw accumulator grids —
    the donated-carry allocation spec. MUST equal mega_reduce's out_shapes
    (both derive from pallas_agg.build_out_defs + plan_window)."""
    ops = [k.pallas_op(col_dtypes) for k in kernels]
    _, W = pallas_agg.plan_window(span)
    G2 = _round_up(num_total, 128) + W
    return [((G2 // 128, 128), dt)
            for _, dt in pallas_agg.build_out_defs(ops)]


def fresh_carries(defs: Sequence[Tuple[Tuple[int, int], object]]) -> Tuple:
    """Zero host carries (the cold-tick donation placeholders). Content is
    never read — the kernel re-initializes every grid at step 0 — so zeros
    vs a prior tick's partials are bit-identical by construction."""
    return tuple(np.zeros(shape, dtype=dt) for shape, dt in defs)


def discard_carries(carries: Optional[Sequence]) -> None:
    """Explicitly release carry grids popped for a dispatch that FAILED:
    donation may have invalidated their buffers mid-flight, so they can be
    neither re-parked nor reused — the exception path must discharge the
    ownership the take popped, or the grids dangle as untracked HBM while
    the pool's byte accounting (already decremented by take) looks clean.
    Host placeholder carries (fresh zeros) have no device buffer and are
    skipped. Both donorguard's take-without-repark rule and the donor
    witness (tools/druidlint/donorwitness.py) recognize this call as the
    exception-path ownership discharge."""
    for a in carries or ():
        delete = getattr(a, "delete", None)
        if delete is None:
            continue
        try:
            delete()
        except Exception:  # druidlint: disable=swallowed-exception
            # an already-invalidated donated buffer raises on delete; the
            # goal (buffer gone, accounting truthful) already holds
            pass


# ---------------------------------------------------------------------------
# The fused pallas program (strategy "megakernel")
# ---------------------------------------------------------------------------

def mega_reduce(arrays: Dict, mask, key, mega_nodes: Sequence[MegaBitmapNode],
                kernels: Sequence, num_total: int, span: int,
                packed_cols: Optional[Dict] = None):
    """Traced: (counts, per-kernel states, raw accumulator grids).

    pallas_agg.pallas_reduce's contract plus the fused-mask inputs: the
    base row mask packs to words in-program, ANDs with each mega node's
    inline word algebra, and the kernel unpacks ONE (1, 128) word tile per
    block (sub-lane shifts at bit base (block % (32/R))·R) instead of
    receiving a row mask — masked rows read the key sentinel exactly as
    the staged kernel's keyx fold does, so results are bit-identical. The
    raw grids ride back so the caller can park them as donated carries."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    col_dtypes = {c: a.dtype for c, a in arrays.items()}
    ops = [k.pallas_op(col_dtypes) for k in kernels]
    assert all(o is not None for o in ops), \
        "megakernel strategy selected but a kernel has no pallas op"

    BLK, W = pallas_agg.plan_window(span)
    assert BLK, f"span {span} too wide for the pallas window"
    R = BLK // 128
    Wr = W // 128
    BPW = MEGA_MASK_VPW // R            # blocks per mask word row
    SENTINEL = jnp.int32(2**31 - 1)     # host-side key padding only

    n = mask.shape[0]
    n2 = _round_up(max(n, BLK), BLK)
    n2m = _round_up(n2, MEGA_MASK_ROW_ALIGN)
    G2 = _round_up(num_total, 128) + W
    nblk = n2 // BLK

    def pad_rows(a, fill):
        if n2 == n:
            return a
        return jnp.concatenate([a, jnp.full((n2 - n,), fill, a.dtype)])

    # the fused mask: base row mask (validity ∧ intervals ∧ residual
    # filter) packs to words in-program; each top-level mega conjunct ANDs
    # in the word domain. Padding rows pack as 0 bits — masked.
    maskp = mask
    if n2m != n:
        maskp = jnp.concatenate(
            [mask, jnp.zeros((n2m - n,), jnp.bool_)])
    mwords = pack_mask_words_traced(maskp)
    need_w = n2m // 32
    for node in mega_nodes:
        w = node.words_traced(arrays)
        if w.shape[0] > need_w:
            w = w[:need_w]
        elif w.shape[0] < need_w:
            # staged arrays cover staged_mask_rows(padded) ≥ n2m by
            # construction; zero-fill is the safe (masked) default anyway
            w = jnp.concatenate(
                [w, jnp.zeros((need_w - w.shape[0],), w.dtype)])
        mwords = mwords & w
    mwords2 = mwords.reshape(n2m // MEGA_MASK_ROW_ALIGN, 128)

    # keys stage RAW (no mask fold): the kernel sentinels masked rows from
    # the word bits, reproducing the staged keyx = where(mask, key,
    # SENTINEL) exactly
    keyx = pad_rows(key.astype(jnp.int32), SENTINEL).reshape(n2 // 128, 128)

    uniq_fields = pallas_agg.op_fields(ops)
    pcs = {}
    if packed_cols:
        for f in uniq_fields:
            pc = packed_cols.get(f)
            # no decode-counter record: split_resident counted each
            # packed column once at the program top (pallas_agg's rule)
            if pc is not None and R % pc.vpw == 0 and pc.rows == n:
                pcs[f] = pc
    dense_fields = [f for f in uniq_fields if f not in pcs]
    packed_fields = [f for f in uniq_fields if f in pcs]
    field_ix = {f: i for i, f in enumerate(dense_fields + packed_fields)}
    vals2 = [pad_rows(arrays[f], np.array(0, arrays[f].dtype))
             .reshape(n2 // 128, 128) for f in dense_fields]
    packed_desc = []
    packed_rws = []
    for f in packed_fields:
        pc = pcs[f]
        words = pc.words
        pad_w = n2 // pc.vpw - words.shape[0]
        if pad_w:
            words = jnp.concatenate(
                [words, jnp.zeros((pad_w,), words.dtype)])
        vals2.append(words.reshape(n2 // pc.vpw // 128, 128))
        packed_desc.append((pc.width, pc.vpw, pc.base))
        packed_rws.append(R // pc.vpw)

    K = None
    for op in ops:
        if op[0] == "sum_i32":
            k_op = max(op[2] // BLK, 1)
            K = k_op if K is None else min(K, k_op)

    out_defs = pallas_agg.build_out_defs(ops)
    slot_ix = {name: j for j, (name, _) in enumerate(out_defs)}
    assert len(out_defs) == pallas_agg.op_slots(ops), \
        "out_defs drifted from op_slots — update pallas_agg.build_out_defs"

    def kernel(key_ref, mw_ref, *refs):
        vrefs = refs[:len(uniq_fields)]
        orefs = refs[len(uniq_fields):]
        i = pl.program_id(0)

        @pl.when(i == jnp.int32(0))
        def _init():
            for j, (name, dt) in enumerate(out_defs):
                if name.startswith("m"):
                    op = ops[int(name[1:])]
                    if op[0] == "min_i32":
                        ident = jnp.int32(2**31 - 1)
                    elif op[0] == "max_i32":
                        ident = jnp.int32(-(2**31))
                    elif op[0] == "min_f32":
                        ident = jnp.float32(jnp.inf)
                    else:
                        ident = jnp.float32(-jnp.inf)
                    orefs[j][:, :] = jnp.full((G2 // 128, 128), ident)
                else:
                    orefs[j][:, :] = jnp.zeros((G2 // 128, 128), dt)

        # sub-lane mask unpack: this block's R tile rows live in ONE word
        # row at bit base (i % BPW)·R — a (1, 128) word tile expands to the
        # (R, 128) bit tile with shifts along the sublane axis, no gather
        wt = mw_ref[:, :]                          # (1, 128) int32
        bit0 = (i % jnp.int32(BPW)) * jnp.int32(R)
        sh = bit0 + jax.lax.broadcasted_iota(jnp.int32, (1, R, 128), 1)
        mbit = ((wt[:, None, :] >> sh) & jnp.int32(1)).reshape(R, 128)

        kb = key_ref[:, :]                         # (R, 128) int32
        # the key sentinel is built INSIDE the kernel: a closure-captured
        # jnp scalar is rejected as a captured tracer (the BENCH_r04
        # constant-capture class)
        kb = jnp.where(mbit > jnp.int32(0), kb, jnp.int32(2**31 - 1))
        base = jnp.min(kb)
        c128 = jnp.int32(128)
        abase = (base // c128) * c128
        abase = jnp.maximum(jnp.minimum(abase, jnp.int32(G2 - W)),
                            jnp.int32(0))
        local = kb - abase
        r0 = abase // c128
        lane = jax.lax.broadcasted_iota(jnp.int32, (R, 128, 128), 2)

        vals_t = [vrefs[j][:, :] for j in range(len(dense_fields))]
        for j, (wd, vpw, vbase) in enumerate(packed_desc):
            pwt = vrefs[len(dense_fields) + j][:, :]
            psh = jnp.int32(wd) * jax.lax.broadcasted_iota(
                jnp.int32, (R // vpw, vpw, 128), 1)
            pv = (pwt[:, None, :] >> psh) & jnp.int32((1 << wd) - 1)
            if vbase:
                pv = pv + jnp.int32(vbase)
            vals_t.append(pv.reshape(R, 128))

        for wr in range(Wr):
            match = ((local - wr * 128)[:, :, None] == lane)
            row = r0 + wr
            cnt = jnp.sum(match.astype(jnp.int32), axis=(0, 1),
                          dtype=jnp.int32)
            cref = orefs[slot_ix["count"]]
            cref[row, :] = cref[row, :] + cnt
            for oi, op in enumerate(ops):
                if op[0] in ("count", "zero", "empty"):
                    continue
                v = vals_t[field_ix[op[1]]]
                if op[0] == "sum_i32":
                    part = jnp.sum(jnp.where(match, v[:, :, None],
                                             jnp.int32(0)),
                                   axis=(0, 1), dtype=jnp.int32)
                    ref = orefs[slot_ix[f"lo{oi}"]]
                    ref[row, :] = ref[row, :] + part
                elif op[0] == "sum_f32":
                    part = jnp.sum(jnp.where(match, v[:, :, None],
                                             jnp.float32(0)), axis=(0, 1),
                                   dtype=jnp.float32)
                    ref = orefs[slot_ix[f"f{oi}"]]
                    ref[row, :] = ref[row, :] + part
                else:
                    kind = op[0]
                    if kind == "min_i32":
                        ident, red = jnp.int32(2**31 - 1), jnp.min
                        comb = jnp.minimum
                    elif kind == "max_i32":
                        ident, red = jnp.int32(-(2**31)), jnp.max
                        comb = jnp.maximum
                    elif kind == "min_f32":
                        ident, red = jnp.float32(jnp.inf), jnp.min
                        comb = jnp.minimum
                    else:
                        ident, red = jnp.float32(-jnp.inf), jnp.max
                        comb = jnp.maximum
                    part = red(jnp.where(match, v[:, :, None], ident),
                               axis=(0, 1))
                    ref = orefs[slot_ix[f"m{oi}"]]
                    ref[row, :] = comb(ref[row, :], part)

        if K is not None:
            @pl.when((i % jnp.int32(K)) == jnp.int32(K - 1))
            def _flush():
                for oi, op in enumerate(ops):
                    if op[0] != "sum_i32":
                        continue
                    lo_ref = orefs[slot_ix[f"lo{oi}"]]
                    hi_ref = orefs[slot_ix[f"hi{oi}"]]
                    lo = lo_ref[:, :]
                    hi_ref[:, :] = hi_ref[:, :] + (lo >> 16)
                    lo_ref[:, :] = lo & 0xFFFF

    out_shapes = [jax.ShapeDtypeStruct((G2 // 128, 128), dt)
                  for _, dt in out_defs]
    # index-map constants built typed inside the lambdas (the BENCH_r04
    # Mosaic (i32, i64) func.return class; tracecheck guards it). The mask
    # word tile's index map OVERLAPS deliberately: BPW consecutive blocks
    # read the same (1, 128) word row at different bit bases.
    grid_spec = pl.GridSpec(
        grid=(nblk,),
        in_specs=([pl.BlockSpec((R, 128), lambda i: (i, jnp.int32(0)),
                                memory_space=pltpu.VMEM)]
                  + [pl.BlockSpec((1, 128),
                                  lambda i: (i // BPW, jnp.int32(0)),
                                  memory_space=pltpu.VMEM)]
                  + [pl.BlockSpec((R, 128), lambda i: (i, jnp.int32(0)),
                                  memory_space=pltpu.VMEM)]
                  * len(dense_fields)
                  + [pl.BlockSpec((Rw, 128), lambda i: (i, jnp.int32(0)),
                                  memory_space=pltpu.VMEM)
                     for Rw in packed_rws]),
        out_specs=[pl.BlockSpec((G2 // 128, 128),
                                lambda i: (jnp.int32(0), jnp.int32(0)),
                                memory_space=pltpu.VMEM)] * len(out_defs),
    )
    outs = pl.pallas_call(
        kernel, out_shape=out_shapes, grid_spec=grid_spec,
        interpret=pallas_agg._interpret(),
    )(keyx, mwords2, *vals2)
    flat = [o.reshape(-1)[:num_total] for o in outs]

    counts = flat[slot_ix["count"]]
    states = []
    for oi, (k, op) in enumerate(zip(kernels, ops)):
        if op[0] == "count":
            states.append(counts)
        elif op[0] == "sum_i32":
            lo = flat[slot_ix[f"lo{oi}"]].astype(jnp.int64)
            hi = flat[slot_ix[f"hi{oi}"]].astype(jnp.int64)
            states.append((hi << 16) + lo)
        elif op[0] == "sum_f32":
            states.append(flat[slot_ix[f"f{oi}"]])
        elif op[0] in ("min_i32", "max_i32", "min_f32", "max_f32"):
            states.append(flat[slot_ix[f"m{oi}"]])
        elif op[0] in ("zero", "empty"):
            states.append(jnp.asarray(
                np.broadcast_to(k.empty_state(1), (num_total,)).copy()))
        else:  # pragma: no cover
            raise AssertionError(f"unknown pallas op {op}")
    return counts, tuple(states), tuple(outs)
