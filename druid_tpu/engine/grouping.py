"""The unified grouped-aggregate device program.

One XLA program implements all three aggregating engines of the reference:
  * timeseries  — key = time bucket                (TimeseriesQueryEngine.java:87)
  * topN        — key = bucket × cardinality + id  (PooledTopNAlgorithm.java:111)
  * groupBy     — key = fused dim ids              (GroupByQueryEngineV2.java:413)

The program is: mask = valid ∧ time-in-intervals ∧ filter; key = fused
(bucket, dim ids); for each aggregator a segmented reduction over key. The
per-(structure) jitted callable is cached — XLA recompiles only when shapes
change, playing the role of the reference's SpecializationService bytecode
cache and of GroupBy's ByteBufferHashTable (dense keys replace open-addressing
hashing, the BufferArrayGrouper insight generalized).

Two key modes:
  * dense   — group space B × ∏cardinalities small enough for a dense grid;
    dim id columns fuse on device (optionally through remap tables, which
    implement extraction fns, listFiltered, and cross-segment dictionary
    unification).
  * host    — high-cardinality fallback: the fused key column is compacted
    host-side with np.unique (cached per segment, the analog of the
    reference's per-segment dictionaries) and the device reduces over compact
    ids. Plays the role of GroupBy's SpillingGrouper for cardinalities that
    would not fit a dense grid.

Reduction strategies (chosen per (segment, query) by `select_strategy`,
measured rates on a v5e chip at 12.5M rows):
  * "mm"       — one-hot MXU matmul (engine/mmagg.py), G ≤ 4096, all
    aggregators sum-decomposable. ~790M rows/s at G=1024.
  * "windowed" — big-G local-dense path for dimension-sorted segments (the
    reference's rollup sort order): each 1k-row block's keys span < W, so a
    [block, W] local grid reduces on the VPU and the per-block grids scatter
    into the full grid at block granularity (#blocks×W ≪ N elements).
    ~300M rows/s at G=131072 vs ~77M for scatter.
  * "blocked"  — scanned [block, G] masked broadcast-reduce, G ≤ 2048.
  * "mixed"    — per-kernel blocked where supported, else scatter
    (segment_sum/min/max); the fully general fallback.
"""
from __future__ import annotations

import collections
import logging
import math
import os
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from druid_tpu.data import cascade as cascade_mod
from druid_tpu.data import packed as packed_mod
from druid_tpu.data.segment import DeviceBlock, Segment
from druid_tpu.engine import filters as filters_mod
from druid_tpu.engine import megakernel
from druid_tpu.engine.filters import (ConstNode, FilterNode, plan_filter,
                                      simplify_node)
from druid_tpu.obs import dispatch as dispatch_mod
from druid_tpu.obs.trace import span as trace_span
from druid_tpu.obs.trace import span_when as trace_span_when
from druid_tpu.engine.kernels import AggKernel, make_kernel
from druid_tpu.query.aggregators import AggregatorSpec
from druid_tpu.utils.granularity import Granularity
from druid_tpu.utils.intervals import Interval

DENSE_GROUP_LIMIT = 1 << 21  # max dense key space per (bucket × groups) grid


def pad_pow2(n: int, floor: int = 8) -> int:
    return max(floor, 1 << max(0, math.ceil(math.log2(max(n, 1)))))


@dataclass
class KeyDim:
    """One grouping dimension: ids column (+ optional remap) with cardinality.

    column=None means the dimension is absent from the segment — it
    contributes a constant id 0 (value "" at decode time), matching the
    reference's treatment of missing columns as null.

    host_ids set means the ids come from a derived host array rather than a
    segment dim column (numeric dimension handlers: a query-time dictionary
    over a metric column's values — DoubleDimensionHandler capability);
    `column` is then a synthetic name the executor stages the array under,
    and ids_key its cache identity for the padded device copy.
    """
    column: Optional[str]
    cardinality: int             # output cardinality (after remap)
    remap: Optional[np.ndarray]  # int32[input_card] -> output id or -1
    host_ids: Optional[np.ndarray] = None
    ids_key: Optional[Tuple] = None


@dataclass
class GroupSpec:
    """Bucketing + grouping config for one segment execution."""
    bucket_starts: np.ndarray          # int64 [B] bucket start timestamps
    bucket_mode: str                   # "all" | "uniform" | "host"
    uniform_period: int = 0
    uniform_first_offset: int = 0      # first bucket start - segment time0
    host_bucket_ids: Optional[np.ndarray] = None  # int32 [padded]
    key_mode: str = "dense"            # "dense" | "host"
    dims: Tuple[KeyDim, ...] = ()
    host_keys: Optional[np.ndarray] = None        # int32 [padded] compact ids
    host_unique: Optional[np.ndarray] = None      # raw fused keys per compact id
    num_total: int = 1                 # padded dense key-space size
    strategy: str = "mixed"            # reduction strategy (select_strategy)
    window: int = 0                    # local window W for "windowed"
    # stable cache identities for host_keys / host_bucket_ids so their padded
    # device copies persist in the segment cache across query executions
    # (re-device_put of a 100M-row key column costs ~400MB of H2D per query)
    host_keys_cache: Optional[Tuple] = None
    host_bucket_cache: Optional[Tuple] = None

    @property
    def num_buckets(self) -> int:
        return int(len(self.bucket_starts))


@dataclass
class SegmentPartial:
    """Per-segment partial aggregation result (host-side)."""
    segment: Segment
    spec: GroupSpec
    counts: np.ndarray                    # int64 [num_total]
    states: Dict[str, object]             # agg name -> host state
    kernels: List[AggKernel]


# ---------------------------------------------------------------------------
# Plan construction helpers
# ---------------------------------------------------------------------------

def _fused_raw_keys(segment: Segment, bucket_mode: str, bucket_starts,
                    period: int, B: int, host_bucket,
                    dims: Tuple[KeyDim, ...]) -> np.ndarray:
    """Host: int64 fused (bucket, dim ids) key per row; -1 = invalid row
    (out of bucket range or remapped-away dim value)."""
    if bucket_mode == "all":
        b = np.zeros(segment.n_rows, dtype=np.int64)
    elif bucket_mode == "uniform":
        b = (segment.time_ms - int(bucket_starts[0])) // period
        b = np.where((b < 0) | (b >= B), -1, b)
    else:
        b = host_bucket.astype(np.int64)
    key = b
    valid = b >= 0
    for d in dims:
        if d.column is None:
            continue
        ids = d.host_ids if d.host_ids is not None \
            else segment.dims[d.column].ids
        if d.remap is not None:
            ids = d.remap[ids]
            valid &= ids >= 0
        key = key * d.cardinality + ids
    return np.where(valid, key, -1)


@dataclass
class Projection:
    """A sorted, key-compacted view of one segment for one key structure —
    the query-time analog of the reference's rollup sort order + dictionary
    (IndexMergerV9 row ordering; Druid 31 'projections'). Built once per
    (segment, granularity, intervals, dims) and cached on the segment; the
    row permutation clusters equal group keys so big-G aggregations reduce
    over a small local window instead of scattering across the full grid."""
    order: np.ndarray       # int32 [n] row permutation (invalid rows first)
    keys: np.ndarray        # int32 [n] sorted compact ids (-1 = invalid)
    unique: np.ndarray      # int64 [G] raw fused key per compact id
    max_span: int           # max key span over WINDOW_BLOCK-row blocks


def build_projection(segment: Segment, intervals: Sequence[Interval],
                     granularity: Granularity,
                     spec: "GroupSpec") -> Projection:
    cache_key = ("projection", str(granularity),
                 tuple((iv.start, iv.end) for iv in intervals),
                 tuple((d.column, d.cardinality,
                        None if d.remap is None else d.remap.tobytes())
                       for d in spec.dims))

    def _compute():
        raw = _fused_raw_keys(segment, spec.bucket_mode, spec.bucket_starts,
                              spec.uniform_period, spec.num_buckets,
                              spec.host_bucket_ids, spec.dims)
        n = raw.shape[0]
        order = np.argsort(raw, kind="stable")
        sr = raw[order]
        n_invalid = int(np.searchsorted(sr, 0))  # -1 rows sort first
        valid_sorted = sr[n_invalid:]
        keys = np.full(n, -1, dtype=np.int32)
        if valid_sorted.size:
            newgrp = np.empty(valid_sorted.shape, dtype=bool)
            newgrp[0] = True
            np.not_equal(valid_sorted[1:], valid_sorted[:-1], out=newgrp[1:])
            unique = valid_sorted[newgrp]
            keys[n_invalid:] = np.cumsum(newgrp) - 1
        else:
            unique = np.zeros(0, dtype=np.int64)
        # max masked key span over WINDOW_BLOCK-row blocks (the sorted layout
        # keeps this near the per-block distinct-group count)
        blk = WINDOW_BLOCK
        npad = ((n + blk - 1) // blk) * blk if n else blk
        kp = np.full(npad, np.iinfo(np.int32).max, dtype=np.int64)
        kp[:n] = np.where(keys >= 0, keys.astype(np.int64),
                          np.iinfo(np.int32).max)
        kb = kp.reshape(-1, blk)
        lo = kb.min(axis=1)
        kneg = np.where(kp == np.iinfo(np.int32).max,
                        np.iinfo(np.int64).min, kp).reshape(-1, blk)
        hi = kneg.max(axis=1)
        span = np.maximum(hi - lo + 1, 1)
        span = int(span[hi >= 0].max()) if (hi >= 0).any() else 1
        return Projection(order=order.astype(np.int32), keys=keys,
                          unique=unique, max_span=span)

    return segment.aux_cached(cache_key, _compute)


def make_group_spec(segment: Segment, intervals: Sequence[Interval],
                    granularity: Granularity,
                    dims: Sequence[KeyDim]) -> GroupSpec:
    """Choose bucket mode + key mode for this (segment, query) pair."""
    if granularity.is_all:
        # one global bucket across all query intervals (AllGranularity)
        first = min((iv.start for iv in intervals), default=0)
        bucket_starts_list = [np.asarray([first], dtype=np.int64)]
        bucket_starts = bucket_starts_list[0]
    else:
        bucket_starts_list = [granularity.bucket_starts(iv) for iv in intervals]
        bucket_starts = (np.concatenate(bucket_starts_list)
                         if bucket_starts_list else np.zeros(0, dtype=np.int64))
    B = max(int(len(bucket_starts)), 1)

    host_bucket_cache = None
    if granularity.is_all:
        bucket_mode, period, first_off, host_bucket = "all", 0, 0, None
    elif (granularity.is_uniform and len(intervals) == 1):
        bucket_mode = "uniform"
        period = granularity.period_ms
        first_off = int(bucket_starts[0] - segment.interval.start)
        host_bucket = None
    else:
        bucket_mode, period, first_off = "host", 0, 0
        key = ("bucket_ids", str(granularity),
               tuple((iv.start, iv.end) for iv in intervals))

        def _compute():
            ids_parts = []
            offset = 0
            out = np.full(segment.n_rows, -1, dtype=np.int32)
            for iv, starts in zip(intervals, bucket_starts_list):
                ids = granularity.bucket_ids(segment.time_ms, iv)
                sel = ids >= 0
                out[sel] = ids[sel] + offset
                offset += len(starts)
            return out
        host_bucket = segment.aux_cached(key, _compute)
        host_bucket_cache = key

    dims = tuple(dims)
    group_card = 1
    for d in dims:
        group_card *= max(d.cardinality, 1)
    dense_total = B * group_card

    if not dims or dense_total <= DENSE_GROUP_LIMIT:
        return GroupSpec(bucket_starts=bucket_starts, bucket_mode=bucket_mode,
                         uniform_period=period, uniform_first_offset=first_off,
                         host_bucket_ids=host_bucket, key_mode="dense",
                         dims=dims, num_total=pad_pow2(dense_total),
                         host_bucket_cache=host_bucket_cache)

    # host-compacted key path: fuse (bucket, dim ids) host-side and np.unique
    cache_key = ("fused_keys", str(granularity),
                 tuple((iv.start, iv.end) for iv in intervals),
                 tuple((d.column, d.cardinality,
                        None if d.remap is None else d.remap.tobytes())
                       for d in dims))

    def _compute_keys():
        key = _fused_raw_keys(segment, bucket_mode, bucket_starts, period, B,
                              host_bucket, dims)
        uniq, compact = np.unique(key, return_inverse=True)
        # drop the -1 group if present by remapping it to an unused slot
        if len(uniq) and uniq[0] == -1:
            compact = compact - 1  # -1 rows get id -1
            uniq = uniq[1:]
        return uniq, compact.astype(np.int32)

    uniq, compact = segment.aux_cached(cache_key, _compute_keys)
    return GroupSpec(bucket_starts=bucket_starts, bucket_mode=bucket_mode,
                     uniform_period=period, uniform_first_offset=first_off,
                     host_bucket_ids=host_bucket, key_mode="host", dims=dims,
                     host_keys=compact, host_unique=uniq,
                     num_total=pad_pow2(max(len(uniq), 1)),
                     host_keys_cache=cache_key,
                     host_bucket_cache=host_bucket_cache)


# ---------------------------------------------------------------------------
# Device program assembly + jit cache
# ---------------------------------------------------------------------------

# Compiled per-segment programs keyed on the structure signature, LRU-bounded:
# closures capture only plan structure (segment constants arrive via aux at
# call time), but dropped query shapes should still release their executables.
# The lock covers the whole get-or-build sequence: the broker fans segments
# out over a thread pool, and an unsynchronized evict could race a
# move_to_end into KeyError (jit() construction is lazy, so building under
# the lock costs nothing — tracing happens at first call).
_JIT_CACHE: "collections.OrderedDict[str, object]" = collections.OrderedDict()
_JIT_CACHE_CAP = 128
_JIT_CACHE_LOCK = threading.Lock()


def plan_virtual_columns(segment: Segment, virtual_columns: Sequence
                         ) -> Tuple[Tuple, List[np.ndarray]]:
    """Per-(segment, query) virtual-column plan: parse each expression and
    rewrite string-dimension comparisons into per-dictionary-id LUT gathers
    (utils.expression.rewrite_string_sites) — the device never sees string
    semantics, only an aux bool LUT indexed by dictionary ids.

    Returns (vc_plans, luts): vc_plans = ((name, rewritten_expr, out_type,
    n_luts), ...) — structural, shareable across segments with equal
    signatures — and the flat per-segment LUT list for the aux stream."""
    from druid_tpu.utils.expression import (lut_for_site, parse_expression,
                                            rewrite_string_sites)
    plans = []
    luts: List[np.ndarray] = []
    string_dims = frozenset(segment.dims)
    for v in virtual_columns:
        expr, sites = rewrite_string_sites(
            parse_expression(v.expression), string_dims)
        for site in sites:
            luts.append(lut_for_site(
                site, segment.dims[site[0]].dictionary.values))
        plans.append((v.name, expr, v.output_type, len(sites)))
    return tuple(plans), luts


def eval_virtual_columns(arrays: Dict, t_abs, vc_plans, it=None) -> Dict:
    """Traced: evaluate planned expression virtual columns over staged
    columns (reference: ExpressionVirtualColumn) into fused XLA elementwise
    ops; string-comparison LUTs stream in from the aux iterator `it`.
    Shared by the per-segment and sharded program builders."""
    import jax
    import jax.numpy as jnp

    # x64 gate: under JAX's default x64-disabled mode an astype(jnp.int64)
    # silently produces int32 — request the wide dtypes only when the flag
    # is actually on (engine/__init__ enables it), and name the narrow
    # dtypes explicitly otherwise so the truncation is a stated contract,
    # not an accident.
    if jax.config.jax_enable_x64:
        long_dt, double_dt = jnp.int64, jnp.float64
    else:
        long_dt, double_dt = jnp.int32, jnp.float32
    bindings = dict(arrays)
    bindings["__time"] = t_abs
    arrays = dict(arrays)
    for name, expr, out_type, n_luts in vc_plans:
        bindings["__luts"] = [next(it) for _ in range(n_luts)]
        val = expr.evaluate(bindings)
        dt = {"long": long_dt, "double": double_dt,
              "float": jnp.float32}.get(out_type, double_dt)
        arrays[name] = jnp.asarray(val).astype(dt)
        bindings[name] = arrays[name]
    return arrays


def fuse_filter_update(arrays: Dict, mask, key, it,
                       dim_cols: Tuple, has_remap: Tuple,
                       filter_node: Optional[FilterNode],
                       kernels: Sequence[AggKernel], num_total: int,
                       strategy: str = "mixed", window: int = 0,
                       packed_cols: Optional[Dict] = None):
    """Traced: the shared tail of the grouped-aggregate program — fuse dim
    ids into the key (through optional remap tables), apply the filter mask,
    and run every kernel's segmented reduction via the selected strategy.
    Both the per-segment (_build_device_fn) and sharded
    (parallel/distributed.py) builders call this, so keying/update semantics
    cannot diverge between paths.

    `arrays` is the DENSE view (the program top already decoded any
    bit-packed columns — data/packed.py); `packed_cols` carries the
    original PackedColumns so the pallas strategy can consume the words
    directly and unpack per VMEM tile. XLA dead-code-eliminates whichever
    representation a strategy leaves unused."""
    import jax
    import jax.numpy as jnp

    for i in range(len(dim_cols)):
        if dim_cols[i] is None:
            continue
        ids = arrays[dim_cols[i]]
        if has_remap[i]:
            remap = next(it)
            ids = remap[ids]
            mask = mask & (ids >= 0)
        card = next(it)
        key = key * card + jnp.maximum(ids, 0)

    if strategy == "megakernel":
        # the fused one-dispatch variant (engine/megakernel.py): top-level
        # AND-conjunct mega nodes stay in the WORD domain all the way into
        # the pallas kernel; only the residual (row-domain) part of the
        # tree expands here. Masked rows read the key sentinel in-kernel,
        # so results are bit-identical to the staged pallas path.
        mega_nodes, residual = megakernel.split_for_kernel(filter_node)
        if residual is not None:
            mask = mask & residual.build(arrays, it)
        key = jnp.clip(key, 0, num_total - 1).astype(jnp.int32)
        return megakernel.mega_reduce(arrays, mask, key, mega_nodes,
                                      kernels, num_total, window,
                                      packed_cols=packed_cols)

    if filter_node is not None:
        mask = mask & filter_node.build(arrays, it)

    key = jnp.clip(key, 0, num_total - 1).astype(jnp.int32)

    if strategy == "mm":
        from druid_tpu.engine.mmagg import mm_reduce
        col_dtypes = {c: a.dtype for c, a in arrays.items()}
        plans = [k.mm_plan(col_dtypes, mask.shape[0]) for k in kernels]
        # select_strategy validated eligibility against plan-time dtypes; a
        # divergence here (row padding, virtual-column dtype) must fail
        # loudly at plan time, not as an opaque trace error
        missing = [k.signature() for k, p in zip(kernels, plans) if p is None]
        if missing:
            raise AssertionError(
                f"mm strategy selected but kernels have no mm plan at trace "
                f"time: {missing}")
        return mm_reduce(arrays, mask, key, kernels, plans, num_total)

    if strategy == "pallas":
        from druid_tpu.engine import pallas_agg
        return pallas_agg.pallas_reduce(arrays, mask, key, kernels,
                                        num_total, window,
                                        packed_cols=packed_cols)

    if strategy == "windowed":
        return _windowed_reduce(arrays, mask, key, kernels, num_total, window)

    blocked_idx = []
    if strategy in ("blocked", "mixed") and num_total <= BLOCKED_GROUP_LIMIT:
        col_dtypes = {c: a.dtype for c, a in arrays.items()}
        blocked_idx = [i for i, k in enumerate(kernels)
                       if k.blocked_supported(col_dtypes)]
    blocked_states = {}
    counts = None
    if blocked_idx:
        bk = [kernels[i] for i in blocked_idx]
        counts, bstates = _blocked_reduce(arrays, mask, key, bk, num_total)
        blocked_states = dict(zip(blocked_idx, bstates))
    if counts is None:
        counts = jax.ops.segment_sum(mask.astype(jnp.int32), key,
                                     num_segments=num_total)
    # positional states: the jit cache is shared across queries whose
    # aggregators differ only by output name
    states = tuple(blocked_states[i] if i in blocked_states
                   else k.update(arrays, mask, key, num_total, it)
                   for i, k in enumerate(kernels))
    return counts, states


BLOCKED_GROUP_LIMIT = 2048
BLOCK_ROWS = 2048

# ---------------------------------------------------------------------------
# Windowed local-dense reduction (dimension-sorted segments)
# ---------------------------------------------------------------------------

WINDOW_BLOCK = 1024          # rows per local-window block
WINDOW_SUB = 8               # blocks per scan step
WINDOW_CHOICES = (128, 256, 512)


def _windowed_reduce(arrays: Dict, mask, key, kernels: Sequence[AggKernel],
                     num_total: int, W: int):
    """Big-G reduction for segments whose rows are clustered by the grouping
    key (the reference's rollup sort order, IndexMergerV9 row ordering): each
    WINDOW_BLOCK-row block's valid keys span < W, so the block reduces into a
    local [W] grid on the VPU and the per-block grids combine into the full
    [num_total] grid with a scatter over only (#blocks × W) elements."""
    import jax
    import jax.numpy as jnp

    fields = sorted({k.spec.field for k in kernels
                     if getattr(k.spec, "field", None) in arrays})
    n = mask.shape[0]
    step = WINDOW_BLOCK * WINDOW_SUB
    pad = (-n) % step

    def padded(a):
        if not pad:
            return a
        return jnp.concatenate(
            [a, jnp.zeros((pad,) + a.shape[1:], a.dtype)])

    nstep = (n + pad) // step
    keyb = padded(key).reshape(nstep, WINDOW_SUB, WINDOW_BLOCK)
    maskb = padded(mask).reshape(nstep, WINDOW_SUB, WINDOW_BLOCK)
    colsb = {f: padded(arrays[f]).reshape(nstep, WINDOW_SUB, WINDOW_BLOCK)
             for f in fields}
    iota = jnp.arange(W, dtype=keyb.dtype)
    big = jnp.asarray(np.iinfo(np.int32).max, keyb.dtype)
    col_tmpl = {f: arrays[f] for f in fields}

    vary0 = (key[0] * 0) + (mask[0] * 0).astype(key.dtype)

    def body(carry, xs):
        kb, mb = xs[0], xs[1]                    # [WINDOW_SUB, WINDOW_BLOCK]
        cols = dict(zip(fields, xs[2:]))
        base = jnp.min(jnp.where(mb, kb, big), axis=1)
        base = jnp.where(base == big, 0, base)   # fully-masked block
        local = kb - base[:, None]
        valid = (local[:, :, None] == iota[None, None, :]) \
            & mb[:, :, None]                     # [SUB, BLOCK, W]
        cnt = valid.astype(jnp.int32).sum(axis=1, dtype=jnp.int32)
        grids = []
        for k in kernels:
            init0 = k.blocked_init(W, col_tmpl)
            grids.append(jax.vmap(
                lambda c, v, k=k, i0=init0: k.blocked_step(
                    i0, c, v, W))({f: cols[f] for f in fields}, valid))
        return carry, (base, cnt, tuple(grids))

    xs = (keyb, maskb) + tuple(colsb[f] for f in fields)
    _, (bases, cnts, grids) = jax.lax.scan(body, vary0, xs)

    # L2: per-block [W] grids scatter at block granularity. Slots past
    # num_total hold identity values by construction (keys were clipped), so
    # clipping their targets cannot corrupt real groups.
    flat_keys = jnp.clip(
        bases.reshape(-1)[:, None] + iota[None, :], 0, num_total - 1).ravel()
    counts = jax.ops.segment_sum(cnts.reshape(-1), flat_keys,
                                 num_segments=num_total)
    states = []
    for k, g in zip(kernels, grids):
        flat = g.reshape(-1, W).ravel() if g.ndim == 3 else g.reshape(-1)
        if k.reduce_kind == "max":
            st = jax.ops.segment_max(flat, flat_keys, num_segments=num_total)
        elif k.reduce_kind == "min":
            st = jax.ops.segment_min(flat, flat_keys, num_segments=num_total)
        else:
            st = jax.ops.segment_sum(flat, flat_keys, num_segments=num_total)
        states.append(k.blocked_finish(st))
    return counts, tuple(states)


def windowed_window(segment: Segment, intervals: Sequence[Interval],
                    granularity: Granularity, spec: GroupSpec) -> int:
    """Host-side eligibility for the windowed strategy: the smallest W in
    WINDOW_CHOICES covering every WINDOW_BLOCK-row block's fused-key span, or
    0. Conservative: spans are measured over ALL interval-valid rows; any
    query filter only shrinks the row set, so a sub-mask can never widen a
    block's span. Cached per (segment, key structure)."""
    key = ("windowed_span", str(granularity),
           tuple((iv.start, iv.end) for iv in intervals),
           tuple((d.column, d.cardinality,
                  None if d.remap is None else d.remap.tobytes())
                 for d in spec.dims))

    def _compute():
        n = segment.n_rows
        if n == 0:
            return 1
        if spec.bucket_mode == "all":
            b = np.zeros(n, dtype=np.int64)
            ok = np.ones(n, dtype=bool)
        elif spec.bucket_mode == "uniform":
            b = (segment.time_ms - int(spec.bucket_starts[0])) \
                // spec.uniform_period
            ok = (b >= 0) & (b < spec.num_buckets)
        else:
            b = spec.host_bucket_ids[:n].astype(np.int64)
            ok = b >= 0
        k = b
        for d in spec.dims:
            if d.column is None:
                continue
            ids = d.host_ids if d.host_ids is not None \
                else segment.dims[d.column].ids
            if d.remap is not None:
                ids = d.remap[ids]
                ok = ok & (ids >= 0)
            k = k * d.cardinality + np.maximum(ids, 0)
        blk = WINDOW_BLOCK
        npad = ((n + blk - 1) // blk) * blk
        kp = np.full(npad, np.iinfo(np.int64).max, dtype=np.int64)
        kp[:n] = np.where(ok, k, np.iinfo(np.int64).max)
        kb = kp.reshape(-1, blk)
        lo = kb.min(axis=1)
        kneg = np.where(kp == np.iinfo(np.int64).max,
                        np.iinfo(np.int64).min, kp).reshape(-1, blk)
        hi = kneg.max(axis=1)
        span = int(np.maximum(hi - lo + 1, 1).max())
        return span

    span = segment.aux_cached(key, _compute)
    for w in WINDOW_CHOICES:
        if span <= w:
            return w
    return 0


#: measurement override (tools/chip_suite.py; env DRUID_TPU_STRATEGY):
#: force an ELIGIBLE strategy so cutovers are tuned from measured
#: per-backend numbers, not assumptions. Ineligible forces fall through
#: to normal selection.
FORCE_STRATEGY: Optional[str] = os.environ.get("DRUID_TPU_STRATEGY") or None


def select_strategy(spec: GroupSpec, kernels: Sequence[AggKernel],
                    col_dtypes: Dict, padded_rows: int,
                    windowed_w) -> Tuple[str, int]:
    """Pick the reduction strategy for one (segment, query) plan.

    windowed_w: 0/W precomputed by the caller (host span check over every
    participating segment), or a callable invoked lazily only when the
    windowed path is actually a candidate."""
    from druid_tpu.engine.mmagg import MM_GROUP_LIMIT
    num = spec.num_total
    plans = [k.mm_plan(col_dtypes, padded_rows) for k in kernels]
    mm_ok = all(p is not None for p in plans)
    blocked_ok = all(k.blocked_supported(col_dtypes) for k in kernels)
    if FORCE_STRATEGY:
        f = FORCE_STRATEGY
        if f == "mixed":
            return "mixed", 0
        if f == "mm" and mm_ok and num <= MM_GROUP_LIMIT:
            return "mm", 0
        if f == "blocked" and blocked_ok and num <= BLOCKED_GROUP_LIMIT:
            # beyond the limit fuse_filter_update would silently scatter —
            # mislabeled timings are worse than a fallthrough
            return "blocked", 0
        if f == "windowed" and blocked_ok:
            w = windowed_w() if callable(windowed_w) else windowed_w
            if w:
                return "windowed", w
        if f == "projection" and blocked_ok:
            return "projection", 0
    if blocked_ok and num <= 64:
        return "blocked", 0      # near-streaming; scan step scales with 1/G
    if mm_ok and num <= 2048:
        return "mm", 0
    if num > BLOCKED_GROUP_LIMIT and blocked_ok and spec.key_mode == "dense":
        w = windowed_w() if callable(windowed_w) else windowed_w
        if w:
            return "windowed", w
    if blocked_ok and num <= BLOCKED_GROUP_LIMIT:
        return "blocked", 0
    if mm_ok and num <= MM_GROUP_LIMIT:
        return "mm", 0
    if blocked_ok and num > MM_GROUP_LIMIT \
            and padded_rows >= PROJECTION_MIN_ROWS:
        # big group space over a big segment: build/reuse the sorted
        # key-compacted projection and reduce over a local window (pallas on
        # TPU, the XLA windowed path elsewhere) instead of scattering
        return "projection", 0
    return "mixed", 0


PROJECTION_MIN_ROWS = 1 << 20   # below this the one-time sort outweighs wins


def _projection_strategy(proj: Projection, kernels: Sequence[AggKernel],
                         col_dtypes: Dict, num_total: int) -> Tuple[str, int]:
    """Inner reduction over the sorted compacted layout: the fused pallas
    kernel on TPU, the XLA windowed path elsewhere, scatter as last resort."""
    from druid_tpu.engine import pallas_agg
    span = proj.max_span
    if pallas_agg.usable(kernels, col_dtypes, span, num_total):
        return "pallas", span
    for w in WINDOW_CHOICES:
        if span <= w:
            return "windowed", w
    return "mixed", 0


def _blocked_reduce(arrays: Dict, mask, key, kernels: Sequence[AggKernel],
                    num_total: int):
    """Scanned masked broadcast-reduce over row blocks. Returns (counts,
    per-kernel states) shaped exactly like the scatter path's."""
    import jax
    import jax.numpy as jnp

    n = mask.shape[0]
    fields = sorted({k.spec.field for k in kernels
                     if getattr(k.spec, "field", None) in arrays})
    # rows per scan step scale inversely with the group space so the [rows,
    # G] working set stays ~4M cells; tiny G (timeseries) streams in big
    # steps instead of paying scan overhead every 2048 rows
    block_rows = min(65536, max(BLOCK_ROWS, (1 << 22) // max(num_total, 1)))
    c = max(1, -(-n // block_rows))
    padded = c * block_rows

    def pad(a, fill=0):
        if padded == n:
            return a
        return jnp.concatenate(
            [a, jnp.full((padded - n,), fill, a.dtype)])

    keyb = pad(key).reshape(c, block_rows)
    maskb = pad(mask, False).reshape(c, block_rows)
    colsb = {f: pad(arrays[f]).reshape(c, block_rows) for f in fields}
    iota = jnp.arange(num_total, dtype=key.dtype)

    # data-derived zero so carries inherit the varying-axis type under
    # shard_map (a plain zeros init trips the scan vma check); derive from
    # both key and mask — the key can be shard-invariant (all-granularity)
    # while the row mask is sharded
    vary0 = (key[0] * 0) + (mask[0] * 0).astype(key.dtype)
    inits = [jax.tree.map(lambda x: x + vary0.astype(x.dtype),
                          k.blocked_init(num_total, arrays))
             for k in kernels]
    count0 = jnp.zeros(num_total, jnp.int32) + vary0.astype(jnp.int32)

    def body(carry, xs):
        cnt, states = carry
        kb, mb = xs[0], xs[1]
        cblk = dict(zip(fields, xs[2:]))
        valid = (kb[:, None] == iota[None, :]) & mb[:, None]
        # pin the accumulation dtype: under x64 an int32 sum promotes to
        # int64 and the scan carry dtype check fails
        cnt = cnt + valid.astype(jnp.int32).sum(axis=0, dtype=jnp.int32)
        states = tuple(k.blocked_step(s, cblk, valid, num_total)
                       for k, s in zip(kernels, states))
        return (cnt, states), None

    xs = (keyb, maskb) + tuple(colsb[f] for f in fields)
    (counts, states), _ = jax.lax.scan(body, (count0, tuple(inits)), xs)
    return counts, tuple(k.blocked_finish(s)
                         for k, s in zip(kernels, states))


def _structure_sig(spec: GroupSpec, n_intervals: int, filter_node, kernels,
                   vc_plans, packs: Tuple = (), cascades: Tuple = ()) -> str:
    dims_sig = ",".join(
        f"{d.column}:{'remap' if d.remap is not None else 'raw'}" for d in spec.dims)
    # repr(expr) is the rewritten AST structure — two segments share a
    # jitted program only when their LUT sites line up
    vc_sig = ";".join(f"{name}={expr!r}:{out_type}:l{n_luts}"
                      for name, expr, out_type, n_luts in vc_plans)
    return "|".join([
        f"bucket={spec.bucket_mode}",
        f"key={spec.key_mode}",
        f"dims={dims_sig}",
        f"iv={n_intervals}",
        f"vc={vc_sig}",
        f"filt={filter_node.signature() if filter_node else 'none'}",
        f"aggs={';'.join(k.signature() for k in kernels)}",
        f"total={spec.num_total}",
        f"strat={spec.strategy}:{spec.window}",
        # the pack descriptor (data/packed.plan_columns) is program
        # structure: packed inputs have different treedefs/shapes, so two
        # executions share a jitted program only when their packing agrees
        f"packs={packs}",
        # the cascade descriptor (data/cascade.plan_columns) likewise:
        # RLE/delta/FOR/LZ4 inputs are distinct treedefs per descriptor
        f"casc={cascades}",
    ])


def _build_device_fn(spec: GroupSpec, n_intervals: int,
                     filter_node: Optional[FilterNode],
                     kernels: List[AggKernel],
                     vc_plans: Tuple = ()):
    """Build the traced program. Structure-only closure: every segment-specific
    constant arrives via `aux` (device arrays), so one jitted callable serves
    every segment with the same structure.

    The "megakernel" strategy's callable takes a third `carries` argument —
    the previous execution's raw accumulator grids, donated
    (donate_argnums) when the backend supports donation so repeated/
    standing executions reuse the same HBM buffers (the kernel
    re-initializes them at grid step 0, so donated reuse is bit-identical
    to fresh zeros). `keep_unused` holds the carries in the signature:
    they exist purely as donatable buffers, never as data."""
    import jax
    import jax.numpy as jnp

    bucket_mode, key_mode = spec.bucket_mode, spec.key_mode
    num_total = spec.num_total
    n_dims = len(spec.dims)
    dim_cols = tuple(d.column for d in spec.dims)
    has_remap = tuple(d.remap is not None for d in spec.dims)

    def fn(arrays: Dict[str, object], aux: Tuple, carries: Tuple = ()):
        it = iter(aux)
        # decode compressed columns at the program top: HBM keeps the
        # packed/RLE/delta/LZ4 representation, XLA fuses the decode into
        # every consumer; the pallas strategy additionally receives the
        # raw packed words (packed_cols, FOR included) and unpacks per
        # tile inside the kernel instead (data/cascade.split_resident is
        # the ONE decode entry point)
        packed_cols, arrays = cascade_mod.split_resident(arrays)
        t = arrays["__time_offset"]
        mask = arrays["__valid"]

        if vc_plans:
            time0 = next(it)
            # absolute __time needs all 64 bits (epoch millis overflow
            # int32); engine/__init__ enables x64 before any trace runs
            arrays = eval_virtual_columns(arrays, t.astype(jnp.int64) + time0,  # druidlint: disable=x64-dtype
                                          vc_plans, it)

        # time-in-intervals
        iv = next(it)  # int32 [k, 2]
        within = (t[:, None] >= iv[None, :, 0]) & (t[:, None] < iv[None, :, 1])
        mask = mask & jnp.any(within, axis=1)

        # bucket ids
        if key_mode == "host":
            key = arrays["__key"]
            mask = mask & (key >= 0)
            dims_for_key = ()
            remaps_for_key = ()
        else:
            if bucket_mode == "all":
                key = jnp.zeros(t.shape, dtype=jnp.int32)
            elif bucket_mode == "uniform":
                first_off = next(it)
                period = next(it)
                # int32 bucket math: offsets are int32 by construction and
                # uniform periods (≤ week) fit int32; 64-bit div would be
                # limb-emulated on TPU
                b = (t - first_off) // period
                nb = next(it)  # num buckets as device scalar
                mask = mask & (b >= 0) & (b < nb)
                key = b.astype(jnp.int32)
            else:
                key = arrays["__bucket"]
                mask = mask & (key >= 0)
            dims_for_key = dim_cols
            remaps_for_key = has_remap

        return fuse_filter_update(arrays, mask, key, it, dims_for_key,
                                  remaps_for_key, filter_node, kernels,
                                  num_total, strategy=spec.strategy,
                                  window=spec.window,
                                  packed_cols=packed_cols or None)

    if spec.strategy == "megakernel":
        if megakernel.donation_enabled():
            return jax.jit(fn, keep_unused=True, donate_argnums=(2,))
        return jax.jit(fn, keep_unused=True)
    return jax.jit(fn)


def _assemble_aux(spec: GroupSpec, segment: Segment, intervals: Sequence[Interval],
                  filter_node: Optional[FilterNode],
                  kernels: List[AggKernel],
                  vc_plans: Tuple = (),
                  vc_luts: Sequence[np.ndarray] = ()) -> Tuple:
    t0 = segment.interval.start
    clip_lo, clip_hi = -(2**31) + 1, 2**31 - 1
    iv = np.asarray(
        [[min(max(ivl.start - t0, clip_lo), clip_hi),
          min(max(ivl.end - t0, clip_lo), clip_hi)] for ivl in intervals],
        dtype=np.int64).astype(np.int32)
    # order must match the reads in _build_device_fn: vc time0 + string
    # LUTs (if any), then interval bounds, then bucket/dim/filter/kernel aux
    aux: List[np.ndarray] = []
    if vc_plans:
        aux.append(np.asarray(t0, dtype=np.int64))
        aux.extend(vc_luts)
    aux.append(iv)
    if spec.key_mode == "dense":
        if spec.bucket_mode == "uniform":
            aux.append(np.asarray(spec.uniform_first_offset, dtype=np.int32))
            aux.append(np.asarray(spec.uniform_period, dtype=np.int32))
            aux.append(np.asarray(spec.num_buckets, dtype=np.int32))
        for d in spec.dims:
            if d.column is None:
                continue
            if d.remap is not None:
                aux.append(d.remap.astype(np.int32))
            aux.append(np.asarray(d.cardinality, dtype=np.int32))
    if filter_node is not None:
        aux.extend(filter_node.aux_arrays())
    for k in kernels:
        aux.extend(k.aux_arrays())
    return tuple(aux)


# ---------------------------------------------------------------------------
# Shared multi-segment (stacked) execution pieces
#
# Both stacked executions — the batched program (engine/batching.py, the
# per-segment body UNROLLED inside one jit) and the sharded shard_map
# program (parallel/distributed.py, vmapped within each shard) — run ONE
# device program over many segments. They share the per-segment traced
# body and the aux layout below, so keying/filter/update semantics cannot
# diverge from each other (and both call fuse_filter_update, so they
# cannot diverge from the per-segment program either).
# ---------------------------------------------------------------------------

def make_stacked_segment_fn(spec: GroupSpec, kds: Sequence[KeyDim],
                            filter_node: Optional[FilterNode],
                            kernels: Sequence[AggKernel],
                            vc_plans: Tuple = ()):
    """Traced per-segment body for stacked execution: segment-specific
    origins (time0, relative interval bounds, bucket origin) arrive as
    mapped-axis arguments instead of aux constants, so one closure serves
    every segment in the stack. Returns RAW (counts, states) — callers
    apply device_post/host_post as their merge discipline requires."""
    import jax.numpy as jnp

    bucket_mode = spec.bucket_mode
    num_total = spec.num_total
    dim_cols = tuple(d.column for d in kds)
    has_remap = tuple(d.remap is not None for d in kds)

    def per_segment(arrays, time0, iv_rel, bucket_off, aux):
        it = iter(aux)
        # same decode-at-top story as _build_device_fn: stacked blocks may
        # carry bit-packed or cascade-encoded columns — both the batched
        # path and the sharded mesh path stack compressed-resident slots
        # through the device pool and decode them here, in-program
        packed_cols, arrays = cascade_mod.split_resident(arrays)
        t = arrays["__time_offset"]
        mask = arrays["__valid"]

        if vc_plans:
            # expressions may reference absolute __time — the one consumer
            # of 64-bit per-row time (epoch millis overflow int32; x64 is
            # globally on via engine/__init__)
            arrays = eval_virtual_columns(
                arrays, t.astype(jnp.int64) + time0, vc_plans, it)  # druidlint: disable=x64-dtype

        # int32 relative bounds — no 64-bit elementwise time math
        within = (t[:, None] >= iv_rel[None, :, 0]) \
            & (t[:, None] < iv_rel[None, :, 1])
        mask = mask & jnp.any(within, axis=1)

        if bucket_mode == "all":
            key = jnp.zeros(t.shape, dtype=jnp.int32)
        else:
            period = next(it)
            nb = next(it)
            b = (t - bucket_off) // period
            mask = mask & (b >= 0) & (b < nb)
            key = b.astype(jnp.int32)

        return fuse_filter_update(arrays, mask, key, it, dim_cols, has_remap,
                                  filter_node, kernels, num_total,
                                  strategy=spec.strategy, window=spec.window,
                                  packed_cols=packed_cols or None)

    return per_segment


def assemble_stacked_aux(spec: GroupSpec, kds: Sequence[KeyDim],
                         f_aux: Sequence[np.ndarray],
                         k_aux: Sequence[np.ndarray],
                         granularity: Granularity,
                         vc_luts: Sequence[np.ndarray] = ()) -> Tuple:
    """Aux stream for make_stacked_segment_fn's reads: interval bounds and
    bucket origins arrive as per-segment mapped args (NOT aux); only shared
    plan constants live here. vc string-LUTs lead (consumed inside
    eval_virtual_columns first)."""
    aux: List[np.ndarray] = list(vc_luts)
    if spec.bucket_mode == "uniform":
        aux.append(np.asarray(granularity.period_ms, dtype=np.int32))
        aux.append(np.asarray(spec.num_buckets, dtype=np.int32))
    for d in kds:
        if d.column is None:
            continue
        if d.remap is not None:
            aux.append(d.remap.astype(np.int32))
        aux.append(np.asarray(d.cardinality, dtype=np.int32))
    aux.extend(f_aux)
    aux.extend(k_aux)
    return tuple(aux)


def aux_equal(a: Sequence[np.ndarray], b: Sequence[np.ndarray]) -> bool:
    """Plan-constant equality across segments (stacked-eligibility checks)."""
    if len(a) != len(b):
        return False
    for x, y in zip(a, b):
        x, y = np.asarray(x), np.asarray(y)
        if x.dtype != y.dtype or x.shape != y.shape or not np.array_equal(x, y):
            return False
    return True


def keydims_equal(a: Sequence[KeyDim], b: Sequence[KeyDim]) -> bool:
    if len(a) != len(b):
        return False
    for x, y in zip(a, b):
        if x.column != y.column or x.cardinality != y.cardinality:
            return False
        if (x.remap is None) != (y.remap is None):
            return False
        if x.remap is not None and not np.array_equal(x.remap, y.remap):
            return False
    return True


_NO_NODE = object()   # "caller did not plan the filter" sentinel


def needed_columns(segment: Segment, kds: Sequence[KeyDim],
                   aggs: Sequence[AggregatorSpec], flt,
                   virtual_columns: Sequence, filter_node=_NO_NODE,
                   kernels: Optional[Sequence[AggKernel]] = None):
    """Returns (all referenced real-column names, the subset present in
    `segment` — i.e. the columns to stage). When the PLANNED `filter_node`
    is passed (None counts: the filter simplified away), filter needs come
    from its required_device_columns() — subtrees compiled to device
    bitmaps (filters.DeviceBitmapNode) consume no staged columns, so
    filter-only dimensions stop staging. When the PLANNED `kernels` ride
    along, filtered aggregators likewise contribute their planned needs
    (bitmap-compiled aggregator filters read words, not columns)."""
    from druid_tpu.utils.expression import parse_expression
    vc_names = {v.name for v in virtual_columns}
    needed = set()
    for d in kds:
        if d.column is not None:
            needed.add(d.column)
    if filter_node is _NO_NODE:
        if flt is not None:
            needed |= flt.required_columns()
    elif filter_node is not None:
        needed |= filter_node.required_device_columns()
    for i, a in enumerate(aggs):
        kc = kernels[i].required_device_columns() \
            if kernels is not None else None
        needed |= a.required_columns() if kc is None else kc
    for v in virtual_columns:
        needed |= parse_expression(v.expression).required_columns()
    needed -= vc_names
    needed -= {"__time", "__time_offset", "__valid"}
    present = tuple(sorted(c for c in needed
                           if c in segment.dims or c in segment.metrics))
    return needed, present


@dataclass
class GroupPlan:
    """The host-side planning product for one segment's grouped aggregation
    — everything run_grouped_aggregate derives BEFORE staging: group spec,
    simplified filter tree, kernel instances, virtual-column programs.
    Built by plan_grouped_aggregate; the batched path (engine/batching.py)
    plans every segment once for bucket grouping and hands the same plan
    back on straggler fallback so nothing is planned twice.

    Single-use per execution: run_grouped_aggregate mutates spec (strategy
    selection, projection rewrites) — do not share one plan across runs."""
    spec: "GroupSpec"
    filter_node: object
    kernels: List[AggKernel]
    vc_plans: Tuple
    vc_luts: List[np.ndarray]


def plan_grouped_aggregate(segment: Segment, intervals: Sequence[Interval],
                           granularity: Granularity,
                           dims: Sequence[KeyDim],
                           aggs: Sequence[AggregatorSpec], flt,
                           virtual_columns: Sequence = ()) -> GroupPlan:
    """Host-side planning for one segment (no staging, no device work)."""
    vc_plans, vc_luts = plan_virtual_columns(segment, virtual_columns)
    filter_node = simplify_node(plan_filter(flt, segment, virtual_columns))
    kernels = [make_kernel(a, segment) for a in aggs]
    # globally unique bitmap slots across the query filter AND the
    # filtered-aggregator trees — their staged word arrays share one
    # `__fbmpN` namespace in the arrays dict
    filters_mod.assign_bitmap_slots(filter_node, kernels)
    return GroupPlan(
        spec=make_group_spec(segment, intervals, granularity, dims),
        filter_node=filter_node,
        kernels=kernels,
        vc_plans=vc_plans, vc_luts=vc_luts)


def run_grouped_aggregate(segment: Segment, intervals: Sequence[Interval],
                          granularity: Granularity, dims: Sequence[KeyDim],
                          aggs: Sequence[AggregatorSpec],
                          flt, extra_columns: Sequence[str] = (),
                          virtual_columns: Sequence = (),
                          plan: Optional[GroupPlan] = None) -> SegmentPartial:
    """Execute the grouped aggregation for one segment; returns host
    partials. `plan` (a GroupPlan from plan_grouped_aggregate over the SAME
    arguments) skips re-planning — the batched path's straggler fallback
    passes the plan it already built for bucket grouping."""
    from druid_tpu.utils.expression import parse_expression

    if plan is None:
        plan = plan_grouped_aggregate(segment, intervals, granularity, dims,
                                      aggs, flt, virtual_columns)
    spec = plan.spec
    filter_node = plan.filter_node
    kernels = plan.kernels
    vc_plans, vc_luts = plan.vc_plans, plan.vc_luts

    if isinstance(filter_node, ConstNode) and not filter_node.value:
        # constant-false filter: nothing matches — skip the device entirely
        return SegmentPartial(
            segment=segment, spec=spec,
            counts=np.zeros(spec.num_total, dtype=np.int64),
            states={k.name: k.empty_state(spec.num_total) for k in kernels},
            kernels=kernels)

    # code-domain fast path (data/cascade.py): when every referenced
    # column is constant within one shared run partition and the query
    # shape allows it, the whole aggregation executes over run metadata —
    # no row-width column stages, nothing decodes, and the results are
    # bit-identical to the row program (exact int arithmetic, identical
    # identities). batching._plan_for routes eligible segments here.
    if cascade_mod.enabled():
        rd = cascade_mod.try_run_domain(segment, intervals, granularity,
                                        spec, kernels, flt, virtual_columns)
        if rd is not None:
            counts, states = rd
            host_states = {k.name: k.host_post(st, segment)
                           for k, st in zip(kernels, states)}
            return SegmentPartial(segment=segment, spec=spec,
                                  counts=np.asarray(counts, dtype=np.int64),
                                  states=host_states, kernels=kernels)

    vc_names = {v.name for v in virtual_columns}
    base_needed = set(extra_columns)
    if filter_node is not None:
        # the PLANNED tree's column needs, not the raw filter's: subtrees
        # compiled to device bitmaps read resident words, not columns
        base_needed |= filter_node.required_device_columns()
    for a, k in zip(aggs, kernels):
        # the PLANNED kernel's needs where narrower: a filtered agg whose
        # filter compiled to bitmap words reads words, not filter columns
        kc = k.required_device_columns()
        base_needed |= a.required_columns() if kc is None else kc
    for v in virtual_columns:
        base_needed |= parse_expression(v.expression).required_columns()
    base_needed -= vc_names
    base_needed = {c for c in base_needed
                   if c in segment.dims or c in segment.metrics}
    needed = set(base_needed)
    for d in spec.dims:
        if spec.key_mode == "dense" and d.column is not None \
                and d.host_ids is None:
            needed.add(d.column)

    # strategy BEFORE staging: the projection path stages a permuted layout,
    # so dtypes come from staged_dtype, not from a staged block
    from druid_tpu.data.segment import DEFAULT_ROW_ALIGN
    padded_rows = max(DEFAULT_ROW_ALIGN,
                      -(-segment.n_rows // DEFAULT_ROW_ALIGN)
                      * DEFAULT_ROW_ALIGN)
    col_dtypes = {"__time_offset": np.dtype(np.int32),
                  "__valid": np.dtype(bool)}
    for c in needed:
        col_dtypes[c] = np.dtype(np.int32) if c in segment.dims \
            else np.dtype(segment.staged_dtype(c))
    if spec.key_mode == "dense":
        for d in spec.dims:
            if d.host_ids is not None:
                col_dtypes[d.column] = np.dtype(np.int32)
    if spec.key_mode == "host":
        col_dtypes["__key"] = np.dtype(np.int32)
    elif spec.bucket_mode == "host":
        col_dtypes["__bucket"] = np.dtype(np.int32)
    spec.strategy, spec.window = select_strategy(
        spec, kernels, col_dtypes, padded_rows,
        lambda: windowed_window(segment, intervals, granularity, spec))

    perm, perm_key = None, None
    if spec.strategy == "projection":
        proj = build_projection(segment, intervals, granularity, spec)
        spec.key_mode = "host"
        spec.host_keys = proj.keys
        spec.host_unique = proj.unique
        spec.num_total = pad_pow2(max(len(proj.unique), 1))
        col_dtypes.pop("__bucket", None)
        col_dtypes["__key"] = np.dtype(np.int32)
        spec.strategy, spec.window = _projection_strategy(
            proj, kernels, col_dtypes, spec.num_total)
        perm = proj.order
        perm_key = ("projection", str(granularity),
                    tuple((iv.start, iv.end) for iv in intervals),
                    tuple((d.column, d.cardinality,
                           None if d.remap is None else d.remap.tobytes())
                          for d in spec.dims))
        spec.host_keys_cache = perm_key
        needed = base_needed  # key prefused: dim columns stay host-side
        # bitmap subtrees STAY on the words path: the projection's permuted
        # row layout stages its own words under a permutation-digest pool
        # key (filters.bitmap_pool_key), so the bit test aligns with the
        # permuted columns instead of forcing a column-path re-plan

    # megakernel conversion (engine/megakernel.py): bitmap subtrees whose
    # combined words are not already resident fuse INLINE — per-leaf words
    # stay resident, the algebra evaluates inside the ONE aggregation
    # program, and the separate fill dispatch disappears. Resident subtrees
    # keep the cached bit-test path (also one dispatch). Opt-out:
    # DRUID_TPU_MEGAKERNEL=0.
    pdg = filters_mod.perm_digest(perm_key)
    if megakernel.enabled():
        filter_node = megakernel.megaize(filter_node, segment, padded_rows,
                                         pdg)
        megakernel.megaize_kernels(kernels, segment, padded_rows, pdg)
    else:
        megakernel.record_disabled_fallback(filter_node, kernels)

    # cascade + pack descriptors of the staged column set: must be derived
    # IDENTICALLY to device_block's own planning (cascade.plan_pair, the
    # one shared derivation), and both join the jit-cache signature — a
    # cascade-encoded, packed, and decoded staging of the same structure
    # are different programs
    cascades, packs = cascade_mod.plan_pair(segment, sorted(needed),
                                            permuted=perm is not None)
    block = segment.device_block(sorted(needed), perm=perm, perm_key=perm_key)

    arrays = dict(block.arrays)
    if spec.key_mode == "dense":
        for d in spec.dims:
            if d.host_ids is not None:
                # derived id column (numeric dimension): staged via the
                # bounded device cache like any other derived key column
                arrays[d.column] = _pad_device_cached(
                    segment, d.ids_key, d.host_ids, block.padded_rows, 0)
    if spec.key_mode == "host":
        # derived projection keys ride the cascade FOR rung: their value
        # range [-1, num_total) is known exactly, so they range-pack at
        # plan-determined width (data/cascade.for_encode_derived)
        arrays["__key"] = _pad_device_cached(
            segment, spec.host_keys_cache, spec.host_keys,
            block.padded_rows, -1, value_range=(-1, spec.num_total - 1))
    elif spec.bucket_mode == "host":
        arrays["__bucket"] = _pad_device_cached(
            segment, spec.host_bucket_cache, spec.host_bucket_ids,
            block.padded_rows, -1, value_range=(-1, spec.num_buckets - 1))
    # resident filter-bitmap words (engine/filters.py device-bitmap path):
    # cached per (segment, filter structure, aux digest, permutation
    # digest) in the same pool; filtered-aggregator trees stage alongside
    # the query filter's, and the projection path stages PERMUTED words
    arrays.update(filters_mod.stage_device_bitmaps(
        segment, filter_node, block.padded_rows, kernels=kernels,
        perm=perm, perm_key=perm_key))
    # per-leaf mask words for inline-fused (mega) subtrees
    arrays.update(megakernel.stage_mega_leaves(
        segment, filter_node, kernels, block.padded_rows,
        perm=perm, perm_key=perm_key))

    # the fused pallas variant: when the projection strategy landed on the
    # pallas kernel AND the tree carries top-level AND-conjunct mega nodes,
    # the mask rides into the kernel as words (the 32x mask-VMEM cut) and
    # the partial grids become donatable carries
    if spec.strategy == "pallas" \
            and megakernel.split_for_kernel(filter_node)[0]:
        spec.strategy = "megakernel"

    aux = _assemble_aux(spec, segment, intervals, filter_node, kernels,
                        vc_plans, vc_luts)
    while True:
        sig = _structure_sig(spec, len(intervals), filter_node, kernels,
                             vc_plans, packs, cascades)
        if spec.strategy == "megakernel":
            # donation changes the jit construction (donate_argnums) and
            # the carry handoff changes the carries treedef (empty vs full
            # tuple), so both key the program cache; carry buffers key off
            # the same sig
            sig += f"|mk={int(megakernel.donation_enabled())}" \
                f"{int(megakernel.carry_enabled())}"
        with _JIT_CACHE_LOCK:
            fn = _JIT_CACHE.get(sig)
            # the builder-idiom miss IS the compile event: jit tracing +
            # XLA compilation happen inside the first call below, so the
            # dispatch span (and, on miss, the nested engine/compile span)
            # time the existing dispatch boundary — no extra syncs
            compiled = fn is None
            if fn is None:
                fn = _build_device_fn(spec, len(intervals), filter_node,
                                      kernels, vc_plans)
                _JIT_CACHE[sig] = fn
                while len(_JIT_CACHE) > _JIT_CACHE_CAP:
                    _JIT_CACHE.popitem(last=False)
            else:
                _JIT_CACHE.move_to_end(sig)
        try:
            with trace_span("engine/dispatch", strategy=spec.strategy,
                            rows=segment.n_rows, compile=compiled), \
                    trace_span_when(compiled, "engine/compile",
                                    kind="segment",
                                    strategy=spec.strategy):
                if spec.strategy == "megakernel" \
                        and megakernel.carry_enabled():
                    # donated-carry handoff: the previous execution's raw
                    # accumulator grids pop out of the pool and ride back
                    # in as the donated third argument; the new grids park
                    # under the same key for the next tick. Content is
                    # never read (the kernel re-inits at step 0) — the
                    # carry is purely the reusable HBM allocation, so
                    # repeated scheduler-tick execution has zero per-tick
                    # pool growth. A carry popped before a failed call is
                    # deliberately dropped: donation may have invalidated
                    # its buffers mid-flight, so the next tick rebuilds
                    # fresh zeros.
                    cdefs = megakernel.carry_defs(
                        kernels, col_dtypes, spec.num_total, spec.window)
                    carried = segment.device_take(("megacarry", sig))
                    if carried is None:
                        # standing-query bridge: a live sink's fresh
                        # snapshot adopts its predecessor's parked grids
                        # (data/segment.py adopt_carries_from) — carries
                        # are content-free, so cross-generation reuse is
                        # exactly as bit-safe as same-segment reuse
                        donor = segment.carry_donor()
                        if donor is not None:
                            carried = donor.device_take(("megacarry", sig))
                    donated = carried is not None \
                        and len(carried) == len(cdefs) \
                        and megakernel.donation_enabled()
                    if carried is None or len(carried) != len(cdefs):
                        carried = megakernel.fresh_carries(cdefs)
                    # byte accounting BEFORE the dispatch: once the call
                    # returns the carries are donated — invalidated on
                    # accelerator backends — and must not be read again
                    # (donorguard read-after-donate)
                    donated_nbytes = sum(
                        int(getattr(a, "nbytes", 0))
                        for a in carried) if donated else 0
                    try:
                        counts, states, raw = fn(arrays, aux,
                                                 tuple(carried))
                    except BaseException:
                        # the take popped ownership; a dispatch failure
                        # (Mosaic compile error below) may have already
                        # invalidated the donated buffers mid-flight, so
                        # discharge them explicitly — the pool's resident
                        # bytes stay truthful and the next tick rebuilds
                        # fresh zeros (donorguard take-without-repark)
                        megakernel.discard_carries(carried)
                        raise
                    segment.device_cached(("megacarry", sig),
                                          lambda: raw)
                    if donated:
                        megakernel.stats().record_donated(donated_nbytes)
                elif spec.strategy == "megakernel":
                    # no donation support: parking grids in the budgeted
                    # pool would only evict useful entries — run carryless
                    counts, states, _raw = fn(arrays, aux, ())
                else:
                    counts, states = fn(arrays, aux)
            # count the SUCCESSFUL program only (a Mosaic-failure retry
            # must not double-bill the query's dispatch scoreboard)
            dispatch_mod.record("segment")
            break
        except Exception as e:
            if spec.strategy not in ("pallas", "megakernel"):
                raise
            # Mosaic compile failure: latch pallas off for the process and
            # retry on the XLA windowed/mixed path — a kernel bug must not
            # fail user queries (reference queries never depend on which
            # engine strategy runs). A megakernel tree keeps working: its
            # mega nodes expand to row masks in XLA (MegaBitmapNode.build).
            from druid_tpu.engine import pallas_agg
            pallas_agg.mark_broken(e)
            logging.getLogger(__name__).warning(
                "pallas groupBy kernel failed to compile; falling back to "
                "XLA path: %s", e)
            spec.strategy, spec.window = next(
                (("windowed", w) for w in WINDOW_CHOICES
                 if spec.window and spec.window <= w),
                ("mixed", 0))

    host_states = {k.name: k.host_post(st, segment)
                   for k, st in zip(kernels, states)}
    return SegmentPartial(segment=segment, spec=spec,
                          counts=np.asarray(counts, dtype=np.int64),
                          states=host_states, kernels=kernels)


def _pad_device(arr: np.ndarray, padded: int, fill) -> object:
    import jax
    out = np.full((padded,), fill, dtype=arr.dtype)
    out[: arr.shape[0]] = arr
    return jax.device_put(out)


def _pad_device_cached(segment: Segment, cache_key: Optional[Tuple],
                       arr: np.ndarray, padded: int, fill,
                       value_range: Optional[Tuple[int, int]] = None
                       ) -> object:
    """Padded device copy of a derived host column, cached on the segment so
    repeated queries reuse the HBM-resident array exactly like staged data
    columns (data/segment.py device cache, LRU-bounded).

    `value_range=(lo, hi)` marks an int32 column whose exact range is a
    plan constant (`__key`/`__bucket`): when the cascade FOR rung covers
    it, the column stages as base-biased range-packed words instead of
    dense int32 — decoded at the program top like any cascade column."""
    plan = cascade_mod.for_encode_derived(*value_range) \
        if value_range is not None and arr.dtype == np.int32 else None
    if plan is not None:
        w, base = plan

        def _build_for():
            import jax
            out = np.full((padded,), fill, dtype=arr.dtype)
            out[: arr.shape[0]] = arr
            words = packed_mod.pack_padded(out, w, base)
            return cascade_mod.ForColumn(jax.device_put(words), w, base,
                                         padded, str(arr.dtype))
        if cache_key is None:
            return _build_for()
        return segment.device_cached(
            ("devpadfor", cache_key, padded, fill, w, base), _build_for)
    if cache_key is None:
        return _pad_device(arr, padded, fill)
    return segment.device_cached(("devpad", cache_key, padded, fill),
                                 lambda: _pad_device(arr, padded, fill))


def combine_states(kernels: List[AggKernel], a: Dict[str, object],
                   b: Dict[str, object]) -> Dict[str, object]:
    return {k.name: k.combine(a[k.name], b[k.name]) for k in kernels}
