"""Fused Pallas TPU kernel for grouped aggregation over sorted projections.

The hot loop the reference specializes bytecode for
(processing/src/main/java/org/apache/druid/query/groupby/epinephelinae/
GroupByQueryEngineV2.java:413 — per-row hash-table aggregate) becomes ONE
fused TPU kernel over the sorted, key-compacted projection
(druid_tpu/engine/grouping.py Projection):

  * rows arrive clustered by compact group id, so each 1-2k-row block's keys
    span a small window W;
  * the kernel holds the FULL [G] accumulator grid for every aggregator
    resident in VMEM across the whole grid (the BufferArrayGrouper insight,
    scaled to 131k+ groups);
  * each block builds a local window one-hot on the VPU and accumulates into
    the grid with a *dynamic-slice* add at the block's aligned base — the
    block-granular scatter XLA cannot express without a full-grid scatter op;
  * int32 long sums ride a lo/hi limb pair flushed every K blocks, restoring
    exact int64 semantics outside the kernel (the same chunking bound as
    SumKernel.chunk_rows).

Stock-XLA strategies measured 21-77M rows/s on this chip for G≈131k; the
windowed XLA path needs a sorted layout plus an L2 scatter pass. This kernel
fuses the whole reduction.

Value columns that staged bit-packed (data/packed.py) stream into the
kernel AS WORDS: an R//vpw-row tile per block that unpacks to the [R, 128]
value tile with int32 shifts/masks in VMEM — the compressed-domain
execution of the ROADMAP's HBM-wall item. The decoded column never exists
in HBM; unpack is exact, so packed and dense runs are bit-identical.

Off-TPU the projection falls back to the XLA windowed path
(grouping._windowed_reduce); tests exercise this kernel via the pallas
interpreter (force_interpret()).
"""
from __future__ import annotations

import os
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from druid_tpu.engine.contracts import (BLK_SMALL_W, BLK_WIDE_W,
                                        MAX_PALLAS_FIELDS, MAX_PALLAS_GROUPS,
                                        MAX_PALLAS_SLOTS, MAX_W, SPAN_BLOCK)

_FORCE_INTERPRET = False
_BROKEN: Optional[str] = None


def force_interpret(on: bool = True):
    """Testing hook: run the kernel through the pallas interpreter on CPU."""
    global _FORCE_INTERPRET
    _FORCE_INTERPRET = on


def mark_broken(exc: BaseException) -> None:
    """Latch the pallas path off for this process after a Mosaic compile
    failure — the caller already fell back to an XLA strategy; retrying a
    known-broken compile on every query would cost seconds each time."""
    global _BROKEN
    _BROKEN = repr(exc)


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def backend_ok() -> bool:
    """Pallas availability probe — one of the two platform predicates
    blessed by `donorguard-platform-gate` (the other is
    contracts.donation_supported): backend comparisons anywhere else in
    the tree fail the donate-platform-gate rule, so strategy and
    donation decisions cannot scatter into inline checks."""
    if _FORCE_INTERPRET or os.environ.get("DRUID_TPU_PALLAS") == "interpret":
        return True
    if os.environ.get("DRUID_TPU_PALLAS") == "0" or _BROKEN is not None:
        return False
    try:
        import jax
        from jax.experimental import pallas as pl  # noqa: F401
        from jax.experimental.pallas import tpu as pltpu  # noqa: F401
        return jax.default_backend() == "tpu"
    except Exception:  # druidlint: disable=swallowed-exception
        # availability probe: any import/backend failure just means "no
        # pallas here" — the XLA strategies serve every query regardless
        return False


def _interpret() -> bool:
    if _FORCE_INTERPRET or os.environ.get("DRUID_TPU_PALLAS") == "interpret":
        return True
    return False


def plan_window(span: int) -> Tuple[int, int]:
    """(block rows, aligned window W) for a projection span, or (0, 0)."""
    for blk in (BLK_SMALL_W, BLK_WIDE_W):
        eff_span = span * max(blk // SPAN_BLOCK, 1)
        w = _round_up(max(eff_span, 1), 128) + 128
        if w <= MAX_W:
            return blk, w
    return 0, 0


#: ops that read one value column (a VMEM input tile each)
_VALUE_OPS = ("sum_i32", "sum_f32", "min_i32", "max_i32", "min_f32",
              "max_f32")


def op_fields(ops: Sequence) -> list:
    """Distinct value columns the kernel streams in, sorted (the in-spec
    layout pallas_reduce builds)."""
    return sorted({op[1] for op in ops if op[0] in _VALUE_OPS})


def op_slots(ops: Sequence) -> int:
    """Output slot count pallas_reduce's out_defs will have: the counts
    grid + a lo/hi limb pair per int32 sum + one grid per other value op."""
    return 1 + sum(2 if op[0] == "sum_i32" else
                   1 if op[0] in _VALUE_OPS else 0
                   for op in ops)


def build_out_defs(ops: Sequence) -> list:
    """Authoritative output-slot layout for a plan's ops: [(name, np
    dtype)], the counts grid leading. Shared by pallas_reduce, the fused
    megakernel (engine/megakernel.py), and its donated-carry allocator, so
    the three cannot drift; op_slots() (which usable() sized the plan with)
    must agree — asserted at every consumer."""
    out_defs = [("count", np.int32)]
    for i, op in enumerate(ops):
        if op[0] == "count":
            pass                       # shares the leading counts grid
        elif op[0] == "sum_i32":
            out_defs.append((f"lo{i}", np.int32))
            out_defs.append((f"hi{i}", np.int32))
        elif op[0] == "sum_f32":
            out_defs.append((f"f{i}", np.float32))
        elif op[0] in ("min_i32", "max_i32"):
            out_defs.append((f"m{i}", np.int32))
        elif op[0] in ("min_f32", "max_f32"):
            out_defs.append((f"m{i}", np.float32))
        elif op[0] in ("zero", "empty"):
            pass
    return out_defs


def usable(kernels: Sequence, col_dtypes: Dict, span: int,
           num_total: int) -> bool:
    if not backend_ok():
        return False
    if num_total > MAX_PALLAS_GROUPS:
        # the full accumulator grid lives in VMEM across the whole grid;
        # beyond the contract cap the vmem-budget guarantee no longer holds
        return False
    blk, _ = plan_window(span)
    if not blk:
        return False
    ops = [k.pallas_op(col_dtypes) for k in kernels]
    if not all(o is not None for o in ops):
        return False
    return len(op_fields(ops)) <= MAX_PALLAS_FIELDS \
        and op_slots(ops) <= MAX_PALLAS_SLOTS


def pallas_reduce(arrays: Dict, mask, key, kernels: Sequence, num_total: int,
                  span: int, packed_cols: Optional[Dict] = None):
    """Traced: (counts int32 [num_total], per-kernel states), the same
    contract as grouping's scatter/blocked paths.

    `arrays` is the dense view; `packed_cols` (data/packed.py
    PackedColumns) supplies bit-packed words for value fields that staged
    compressed — those stream into the kernel AS WORDS (an R//vpw-row tile
    per block instead of R) and unpack per tile in VMEM, so the decoded
    column never materializes in HBM. Unpack is exact, so results stay
    bit-identical to the dense path."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    col_dtypes = {c: a.dtype for c, a in arrays.items()}
    ops = [k.pallas_op(col_dtypes) for k in kernels]
    assert all(o is not None for o in ops), \
        "pallas strategy selected but a kernel has no pallas op"

    BLK, W = plan_window(span)
    assert BLK, f"span {span} too wide for the pallas window"
    assert num_total <= MAX_PALLAS_GROUPS, \
        f"num_total {num_total} above the pallas group cap (vmem contract)"
    R = BLK // 128
    Wr = W // 128
    SENTINEL = jnp.int32(2**31 - 1)

    n = mask.shape[0]
    n2 = _round_up(max(n, BLK), BLK)
    G2 = _round_up(num_total, 128) + W
    nblk = n2 // BLK

    def pad_rows(a, fill):
        if n2 == n:
            return a
        return jnp.concatenate(
            [a, jnp.full((n2 - n,), fill, a.dtype)])

    keyx = jnp.where(mask, key.astype(jnp.int32), SENTINEL)
    keyx = pad_rows(keyx, SENTINEL).reshape(n2 // 128, 128)

    # kernel inputs: key + one value column per op that reads one (the
    # same layout helper usable() sized the plan with). Dense fields lead,
    # packed fields trail — their word tiles have a different shape, and a
    # stable operand order keeps the in_specs expression analyzable.
    uniq_fields = op_fields(ops)
    assert len(uniq_fields) <= MAX_PALLAS_FIELDS, \
        f"{len(uniq_fields)} value columns exceed the pallas field cap"
    pcs = {}
    if packed_cols:
        for f in uniq_fields:
            pc = packed_cols.get(f)
            # vpw divides R by the PACK_WIDTHS contract; a descriptor that
            # violates it (or a row-count mismatch) falls back to the dense
            # view of that field — correctness never depends on packing.
            # No decode-counter record here: split_resident already
            # counted each packed column once at the program top (the XLA
            # unpack XLA dead-code-eliminates when this kernel consumes
            # the words instead) — recording again would double-count.
            if pc is not None and R % pc.vpw == 0 and pc.rows == n:
                pcs[f] = pc
    dense_fields = [f for f in uniq_fields if f not in pcs]
    packed_fields = [f for f in uniq_fields if f in pcs]
    field_ix = {f: i for i, f in enumerate(dense_fields + packed_fields)}
    vals2 = [pad_rows(arrays[f], np.array(0, arrays[f].dtype))
             .reshape(n2 // 128, 128) for f in dense_fields]
    packed_desc = []                 # (width, vpw, base) per packed field
    packed_rws = []                  # word rows per block, per packed field
    for f in packed_fields:
        pc = pcs[f]
        words = pc.words
        pad_w = n2 // pc.vpw - words.shape[0]
        if pad_w:
            # zero words decode to `base` on padding rows; padding rows
            # carry the key SENTINEL, so no op ever matches them
            words = jnp.concatenate(
                [words, jnp.zeros((pad_w,), words.dtype)])
        vals2.append(words.reshape(n2 // pc.vpw // 128, 128))
        packed_desc.append((pc.width, pc.vpw, pc.base))
        packed_rws.append(R // pc.vpw)

    # flush period for int32 limb sums: lo grows ≤ BLK·max_abs per block and
    # chunk_rows·max_abs ≤ 2^30 by SumKernel's analysis, so chunk_rows // BLK
    # blocks stay under 2^31 even with the ≤ 2^16 post-flush residue
    K = None
    for op in ops:
        if op[0] == "sum_i32":
            k_op = max(op[2] // BLK, 1)
            K = k_op if K is None else min(K, k_op)

    # per-op output slots: (op index, slot kind) — the shared builder, so
    # the megakernel's carry allocator sees exactly this layout
    out_defs = build_out_defs(ops)
    slot_ix = {name: j for j, (name, _) in enumerate(out_defs)}
    # the builder above is authoritative; op_slots() (which usable() sized
    # the plan with) must agree, so a new op kind cannot drift between them
    assert len(out_defs) == op_slots(ops), \
        f"out_defs {len(out_defs)} != op_slots {op_slots(ops)} — a new " \
        f"pallas op kind updated one layout but not the other"
    assert len(out_defs) <= MAX_PALLAS_SLOTS, \
        f"{len(out_defs)} output slots exceed the pallas slot cap"

    def kernel(key_ref, *refs):
        vrefs = refs[:len(uniq_fields)]
        orefs = refs[len(uniq_fields):]
        i = pl.program_id(0)

        @pl.when(i == jnp.int32(0))
        def _init():
            for j, (name, dt) in enumerate(out_defs):
                if name.startswith("m"):
                    op = ops[int(name[1:])]
                    if op[0] == "min_i32":
                        ident = jnp.int32(2**31 - 1)
                    elif op[0] == "max_i32":
                        ident = jnp.int32(-(2**31))
                    elif op[0] == "min_f32":
                        ident = jnp.float32(jnp.inf)
                    else:
                        ident = jnp.float32(-jnp.inf)
                    orefs[j][:, :] = jnp.full((G2 // 128, 128), ident)
                else:
                    orefs[j][:, :] = jnp.zeros((G2 // 128, 128), dt)

        kb = key_ref[:, :]                       # [R, 128] int32
        base = jnp.min(kb)
        # all-scalar int32 math: mixed weak-type promotion recurses forever
        # in the Mosaic conversion helper
        c128 = jnp.int32(128)
        abase = (base // c128) * c128
        abase = jnp.maximum(jnp.minimum(abase, jnp.int32(G2 - W)),
                            jnp.int32(0))
        local = kb - abase                       # valid rows in [0, W)
        r0 = abase // c128
        lane = jax.lax.broadcasted_iota(jnp.int32, (R, 128, 128), 2)

        # materialize every field's [R, 128] value tile once per block.
        # Packed fields arrive as [R // vpw, 128] word tiles and unpack
        # here — int32 shift/mask on the VPU, then a reshape that restores
        # exactly the tile-planar row order pack_padded encoded (value row
        # q*vpw + s lives in word row q at bit slot s); arithmetic >> is
        # safe because the mask cuts the sign-extension bits
        vals_t = [vrefs[j][:, :] for j in range(len(dense_fields))]
        for j, (wd, vpw, base) in enumerate(packed_desc):
            wt = vrefs[len(dense_fields) + j][:, :]      # [R // vpw, 128]
            sh = jnp.int32(wd) * jax.lax.broadcasted_iota(
                jnp.int32, (R // vpw, vpw, 128), 1)
            pv = (wt[:, None, :] >> sh) & jnp.int32((1 << wd) - 1)
            if base:
                pv = pv + jnp.int32(base)
            vals_t.append(pv.reshape(R, 128))

        # per window-row matches, shared across every op
        for wr in range(Wr):
            match = ((local - wr * 128)[:, :, None] == lane)  # [R,128,128]
            row = r0 + wr
            # every sum pins its dtype: under x64 an int32 sum would promote
            # to int64, which Mosaic cannot lower on this chip
            cnt = jnp.sum(match.astype(jnp.int32), axis=(0, 1),
                          dtype=jnp.int32)
            cref = orefs[slot_ix["count"]]
            cref[row, :] = cref[row, :] + cnt
            for oi, op in enumerate(ops):
                if op[0] == "count":
                    continue
                if op[0] in ("zero", "empty"):
                    continue
                v = vals_t[field_ix[op[1]]]
                if op[0] == "sum_i32":
                    part = jnp.sum(jnp.where(match, v[:, :, None],
                                             jnp.int32(0)),
                                   axis=(0, 1), dtype=jnp.int32)
                    ref = orefs[slot_ix[f"lo{oi}"]]
                    ref[row, :] = ref[row, :] + part
                elif op[0] == "sum_f32":
                    part = jnp.sum(jnp.where(match, v[:, :, None],
                                             jnp.float32(0)), axis=(0, 1),
                                   dtype=jnp.float32)
                    ref = orefs[slot_ix[f"f{oi}"]]
                    ref[row, :] = ref[row, :] + part
                else:
                    kind = op[0]
                    if kind == "min_i32":
                        ident, red = jnp.int32(2**31 - 1), jnp.min
                        comb = jnp.minimum
                    elif kind == "max_i32":
                        ident, red = jnp.int32(-(2**31)), jnp.max
                        comb = jnp.maximum
                    elif kind == "min_f32":
                        ident, red = jnp.float32(jnp.inf), jnp.min
                        comb = jnp.minimum
                    else:
                        ident, red = jnp.float32(-jnp.inf), jnp.max
                        comb = jnp.maximum
                    part = red(jnp.where(match, v[:, :, None], ident),
                               axis=(0, 1))
                    ref = orefs[slot_ix[f"m{oi}"]]
                    ref[row, :] = comb(ref[row, :], part)

        if K is not None:
            @pl.when((i % jnp.int32(K)) == jnp.int32(K - 1))
            def _flush():
                for oi, op in enumerate(ops):
                    if op[0] != "sum_i32":
                        continue
                    lo_ref = orefs[slot_ix[f"lo{oi}"]]
                    hi_ref = orefs[slot_ix[f"hi{oi}"]]
                    lo = lo_ref[:, :]
                    hi_ref[:, :] = hi_ref[:, :] + (lo >> 16)
                    lo_ref[:, :] = lo & 0xFFFF

    out_shapes = [jax.ShapeDtypeStruct((G2 // 128, 128), dt)
                  for _, dt in out_defs]
    # index-map constants must be typed AND built inside the lambda: under
    # the repo-global x64 flag a Python-int 0 promotes to i64 and Mosaic
    # fails to legalize the (i32, i64) func.return of the index map, while a
    # closure-captured jnp scalar is rejected as a captured tracer (the
    # BENCH_r04 failure class; tracecheck pallas-accum-dtype guards it).
    # Packed word tiles declare (Rw, 128) = (R // vpw, 128) blocks — the
    # index map is still block-granular, so (i, 0) addresses word rows
    grid_spec = pl.GridSpec(
        grid=(nblk,),
        in_specs=([pl.BlockSpec((R, 128), lambda i: (i, jnp.int32(0)),
                                memory_space=pltpu.VMEM)]
                  * (1 + len(dense_fields))
                  + [pl.BlockSpec((Rw, 128), lambda i: (i, jnp.int32(0)),
                                  memory_space=pltpu.VMEM)
                     for Rw in packed_rws]),
        out_specs=[pl.BlockSpec((G2 // 128, 128),
                                lambda i: (jnp.int32(0), jnp.int32(0)),
                                memory_space=pltpu.VMEM)] * len(out_defs),
    )
    outs = pl.pallas_call(
        kernel, out_shape=out_shapes, grid_spec=grid_spec,
        interpret=_interpret(),
    )(keyx, *vals2)
    outs = [o.reshape(-1)[:num_total] for o in outs]

    counts = outs[slot_ix["count"]]
    states = []
    for oi, (k, op) in enumerate(zip(kernels, ops)):
        if op[0] == "count":
            states.append(counts)
        elif op[0] == "sum_i32":
            lo = outs[slot_ix[f"lo{oi}"]].astype(jnp.int64)
            hi = outs[slot_ix[f"hi{oi}"]].astype(jnp.int64)
            states.append((hi << 16) + lo)
        elif op[0] == "sum_f32":
            states.append(outs[slot_ix[f"f{oi}"]])
        elif op[0] in ("min_i32", "max_i32", "min_f32", "max_f32"):
            states.append(outs[slot_ix[f"m{oi}"]])
        elif op[0] == "zero":
            states.append(jnp.asarray(
                np.broadcast_to(k.empty_state(1), (num_total,)).copy()))
        elif op[0] == "empty":
            states.append(jnp.asarray(
                np.broadcast_to(k.empty_state(1), (num_total,)).copy()))
        else:  # pragma: no cover
            raise AssertionError(f"unknown pallas op {op}")
    return counts, tuple(states)
