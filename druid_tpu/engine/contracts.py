"""Engine ⇄ linter shared contracts — the single source of truth tracecheck
(tools/druidlint/tracecheck.py) validates the Pallas + XLA engine layer
against.

Everything here is a plain Python constant: this module MUST stay importable
without jax/numpy so the linter can load it standalone (by file path, no
package import, no x64 side effects). The engine imports the same names, so
a kernel edit that changes a contract changes exactly one place — and the
tier-1 lint gate re-checks every declared invariant against the new value.

Contract families:
  * tile geometry   — lane width, pallas block/window constants
  * capacity        — pallas group/field/slot caps + the VMEM tile budget
  * dtype lattice   — byte widths, 64-bit dtypes, reduce-identity table
  * AggKernel shape — required methods per reduce_kind
  * symbol bounds   — value ranges for names the abstract interpreter
                      cannot derive from the kernel module's own statements
"""

# ---- tile geometry --------------------------------------------------------

LANE = 128            # TPU lane width: the last dim of every VMEM tile
SUBLANE = 8           # float32 sublane count (min tile is (8, 128))

BLK_SMALL_W = 2048    # pallas rows per block when the window is narrow
BLK_WIDE_W = 1024     # pallas rows per block for wide windows
SPAN_BLOCK = 1024     # block size Projection.max_span is measured over
MAX_W = 1024          # widest supported aligned key window

# ---- capacity -------------------------------------------------------------

#: hard cap on num_total for the pallas strategy: the FULL accumulator grid
#: for every output slot stays resident in VMEM across the whole grid, so
#: the group space must be bounded for the vmem-budget contract to hold.
MAX_PALLAS_GROUPS = 1 << 17

#: max distinct value columns streamed into the kernel (one VMEM input tile
#: each, alongside the key tile).
MAX_PALLAS_FIELDS = 8

#: max output slots (out_defs): 1 counts grid + at most 2 slots per op
#: (the int32 lo/hi limb pair) across MAX_PALLAS_FIELDS ops.
MAX_PALLAS_SLOTS = 1 + 2 * MAX_PALLAS_FIELDS

#: per-core VMEM (v4/v5e/v5p class chips) and the budget the declared tiles
#: must fit in. The budget is deliberately below the physical size: pallas
#: double-buffers input tiles and Mosaic needs scratch headroom.
#: Override per-repo via [tool.druidlint] vmem-cap-bytes.
VMEM_BYTES = 16 * 1024 * 1024
VMEM_BUDGET_BYTES = 12 * 1024 * 1024

#: widest element the pallas kernel ever tiles: ops accept int32/float32
#: only (pallas_op eligibility) and pallas-accum-dtype bans 64-bit inside
#: the kernel body, so 4 bytes bounds every declared tile.
PALLAS_MAX_TILE_DTYPE_BYTES = 4

# ---- batched multi-segment execution --------------------------------------

#: max segments stacked into ONE batched device dispatch (engine/batching.py).
#: Bounds both the stacked [K, R] working set and the worst-case host-side
#: slice/post loop per dispatch.
BATCH_MAX_SEGMENTS = 64

#: below this many shape-compatible segments a batch never forms: one
#: stacked program would dispatch exactly as many device calls as the
#: per-segment path while paying an extra compile.
BATCH_MIN_SEGMENTS = 2

#: rows per segment above which batching stops paying: per-segment dispatch
#: overhead is amortized by compute alone, and the in-program stack of a
#: huge [K, R] block would double its HBM footprint for no win.
BATCH_MAX_SEGMENT_ROWS = 1 << 21

#: base rung of the padded-row ladder (must equal data.segment's
#: DEFAULT_ROW_ALIGN — asserted by engine/batching.py at import). Rungs are
#: powers of two times this, so at most
#: log2(BATCH_MAX_SEGMENT_ROWS / BATCH_ROW_ALIGN) + 1 row shapes exist per
#: plan structure — the compile-count bound of the batched path.
BATCH_ROW_ALIGN = 1024

# ---- compressed-domain packing (data/packed.py) ---------------------------

#: bits per packed storage word (int32 words: the narrowest element Mosaic
#: tiles natively, and the dtype every unpack shift/mask stays in).
PACK_WORD_BITS = 32

#: supported pack widths, each dividing PACK_WORD_BITS so no value crosses a
#: word boundary and values-per-word (vpw = 32 // width) divides the
#: sublane row count of every pallas block (R = BLK // LANE ∈ {8, 16}).
#: Width 2 (vpw 16) is deliberately absent: vpw must divide R for the
#: in-kernel per-tile unpack, and 16 does not divide the wide-window R=8.
#: Quantizing ceil(log2(cardinality)) up to these widths keeps pack
#: descriptors coarse, so near-identical segments share plan signatures
#: (the same design rule as SumKernel.chunk_rows pow2 quantization).
PACK_WIDTHS = (4, 8, 16)

# ---- cascaded encodings (data/cascade.py) ---------------------------------

#: hard cap on the pow2-padded run count of any cascade run array (RLE run
#: values/ends, the run-domain aggregation tables, LZ4 token streams): run
#: metadata must stay small enough that a (CASCADE_MAX_RUNS // LANE, LANE)
#: run tile fits the pallas VMEM budget with room to spare, and that the
#: host-side run planning stays O(small). A column whose padded run count
#: exceeds this is simply not run-compressible — it falls back to
#: bit-packing or decoded staging (correctness never depends on cascades).
CASCADE_MAX_RUNS = 1 << 16

#: run-value tile rows when a kernel streams run metadata as (RUN_TILE_ROWS,
#: LANE) VMEM tiles — the worst case is every run resident at once.
RUN_TILE_ROWS = CASCADE_MAX_RUNS // LANE

# ---- megakernel mask words (engine/megakernel.py) -------------------------

#: bits per row of the megakernel's fused row-mask words: the width-1
#: instance of the data/packed.py tile-planar layout (word[q, l] packs tile
#: rows q*32+s at lane l, bit s), so the host packer (pack_padded) and the
#: in-kernel sub-lane unpack share one canonical encoding with the packed
#: value columns.
MEGA_MASK_WIDTH = 1

#: mask rows per 32-bit word (PACK_WORD_BITS // MEGA_MASK_WIDTH).
MEGA_MASK_VPW = PACK_WORD_BITS // MEGA_MASK_WIDTH

#: rows covered by ONE 128-lane word row of the mask view. Mask word arrays
#: pad to a multiple of this so (rows/32,) words reshape cleanly into
#: (rows/4096, 128) tiles; every pallas block (BLK ∈ {1024, 2048} rows,
#: R = BLK/128 ∈ {8, 16} tile rows) then sits inside ONE word row because
#: MEGA_MASK_VPW % R == 0 — the in-kernel unpack is a pure sub-lane shift
#: at bit base (block % (MEGA_MASK_VPW / R)) · R, no gather, (1, 128) of
#: word VMEM per block instead of an (R, 128) int32 row mask (the 32x mask
#: VMEM cut).
MEGA_MASK_ROW_ALIGN = MEGA_MASK_VPW * LANE

# ---- device filter bitmaps (engine/filters.py device-bitmap algebra) ------

#: bits per device filter-bitmap word (uint32, LSB-first: row r is bit
#: r % 32 of word r // 32 — data/bitmap.py to_words32). Every padded row
#: count is a multiple of BATCH_ROW_ALIGN = 1024, so word arrays always
#: reshape cleanly into (rows/32,) and the in-program bit-test expansion
#: is a pure broadcast shift, no gather.
FILTER_WORD_BITS = 32

#: worst-case bitmap-word rows per pallas-class block: a BLK_SMALL_W-row
#: block covers BLK_SMALL_W / FILTER_WORD_BITS = 64 word rows. The word
#: expansion runs in XLA before any pallas call today; this bound exists so
#: the vmem-budget rule can size a bitmap-word tile if one is ever declared
#: (tests/test_tracecheck.py pins the worst case).
FILTER_WORDS_PER_BLOCK = BLK_SMALL_W // FILTER_WORD_BITS

# ---- device segment pool --------------------------------------------------

#: default HBM byte budget for the process-wide device segment pool
#: (data/devicepool.py): staged DeviceBlocks + derived padded device arrays
#: LRU-evict by ACTUAL array bytes once the pool passes this. Deliberately
#: far below a v5e/v5p core's HBM so query working sets (stacked batches,
#: accumulator grids) always have headroom. Override via the
#: DRUID_TPU_DEVICE_POOL_BYTES env var or DeviceSegmentPool.configure().
DEVICE_POOL_BUDGET_BYTES = 4 * 1024 ** 3

# ---- donation platform gate (donated carry buffers) -----------------------

#: backends whose runtimes honor buffer donation. CPU *accepts*
#: donate_argnums but silently ignores it (with a per-call warning), so
#: only accelerator backends belong here — forcing donation elsewhere is
#: the silent-corruption class donorguard's donate-platform-gate guards.
DONATION_BACKENDS = ("tpu", "gpu")


def donation_supported() -> bool:
    """THE donation platform predicate: every donation-enable decision in
    the engine must route through this one function (donorguard's
    `donate-platform-gate` rule pins the inventory to the configured
    `donorguard-platform-gate` list, which names exactly this).

    Tri-state ``DRUID_TPU_DONATE``: "on"/"1" forces donation (the real-TPU
    bench lever), "off"/"0" disables it, unset/"auto" detects by backend
    (DONATION_BACKENDS). Read LIVE by design — the decision joins the jit
    program signature's mk= field (engine/grouping.py), so a mid-process
    flip keys a fresh program instead of aliasing a cached one. Imports
    stay inside the function: this module must remain loadable standalone,
    without jax, by the linter."""
    import os
    mode = os.environ.get("DRUID_TPU_DONATE", "auto").strip().lower() \
        or "auto"
    if mode in ("on", "1", "force"):
        return True
    if mode in ("off", "0"):
        return False
    try:
        import jax
        return jax.default_backend() in DONATION_BACKENDS
    except Exception:  # druidlint: disable=swallowed-exception
        # availability probe: no backend means no donation, never an error
        return False


# ---- dtype lattice --------------------------------------------------------

DTYPE_BYTES = {
    "bool": 1, "int8": 1, "uint8": 1,
    "int16": 2, "uint16": 2, "float16": 2, "bfloat16": 2,
    "int32": 4, "uint32": 4, "float32": 4,
    "int64": 8, "uint64": 8, "float64": 8,
}

#: dtypes that silently truncate to 32-bit under JAX's default
#: x64-disabled mode (the x64-dtype rule's subject).
X64_DTYPES = ("int64", "uint64", "float64")

#: reduce identity literal → the accumulator dtype it belongs to. A dtype
#: constructor applied to one of these extreme values inside the pallas
#: module must use exactly this dtype (pallas-accum-dtype): the int-min
#: identity / key sentinel is int32 2**31-1, the int-max identity is int32
#: -(2**31), the float min/max identities are float32 ±inf.
REDUCE_IDENTITIES = {
    2 ** 31 - 1: "int32",
    -(2 ** 31): "int32",
    float("inf"): "float32",
    float("-inf"): "float32",
}

# ---- AggKernel shape ------------------------------------------------------

#: every concrete AggKernel subclass must define these (agg-contract).
AGG_REQUIRED_METHODS = ("signature", "update", "combine", "empty_state")

#: additionally required when the class's effective reduce_kind is "fold"
#: (the base-class default): the sharded merge all_gathers states and folds
#: them pairwise on device.
AGG_FOLD_REQUIRED = ("device_combine",)

# ---- symbol bounds for the abstract interpreter ---------------------------

#: name → (lo, hi, multiple_of). Bounds for values tracecheck cannot derive
#: from the scanned module's own assignments: function parameters and
#: results of host-side planning calls. These ARE engine contracts —
#: plan_window returns blk ≤ BLK_SMALL_W and a 128-aligned W ≤ MAX_W,
#: usable() rejects num_total > MAX_PALLAS_GROUPS, and pallas_reduce
#: asserts the field/slot caps — so the static bounds and the runtime
#: checks cannot drift apart.
SYMBOL_BOUNDS = {
    "span": (1, MAX_W, 1),
    "num_total": (1, MAX_PALLAS_GROUPS, 1),
    "n": (1, 1 << 31, 1),
    "BLK": (BLK_WIDE_W, BLK_SMALL_W, LANE),
    "W": (LANE, MAX_W, LANE),
    "len(uniq_fields)": (0, MAX_PALLAS_FIELDS, 1),
    "len(out_defs)": (1, MAX_PALLAS_SLOTS, 1),
    # packed-input variant (pallas_agg packed word tiles): vpw = 32 // width
    # over PACK_WIDTHS, and Rw = R // vpw word rows per block — the worst
    # case (width 16, BLK_SMALL_W) is R // 2 = 8 rows. Enforced at runtime
    # by pallas_reduce's vpw-divides-R assertion.
    "vpw": (2, 8, 2),
    "Rw": (1, 8, 1),
    "len(dense_fields)": (0, MAX_PALLAS_FIELDS, 1),
    "len(packed_rws)": (0, MAX_PALLAS_FIELDS, 1),
    # device filter-bitmap words (engine/filters.py): word rows per block,
    # bounded by FILTER_WORDS_PER_BLOCK — covers the bitmap words' worst-
    # case tile should a kernel ever stream them in.
    "Rw32": (1, FILTER_WORDS_PER_BLOCK, 1),
    # cascade run metadata (data/cascade.py): run counts are pow2-padded and
    # capped at CASCADE_MAX_RUNS by planning (plan_column / the run-domain
    # eligibility check), run-value tiles declare at most RUN_TILE_ROWS
    # (LANE-wide) rows, and a single run can span at most a whole batched
    # segment. These bounds let vmem-budget / pallas-tile-shape statically
    # cover any kernel that streams run tables as (Rrun, 128) tiles.
    "n_runs": (1, CASCADE_MAX_RUNS, 1),
    "Rrun": (1, RUN_TILE_ROWS, 1),
    "run_len": (1, BATCH_MAX_SEGMENT_ROWS, 1),
}
