"""One-hot MXU matmul grouped reduction for small group spaces.

Reference hot loops this replaces: the per-row buffer-aggregate loops of
GroupByQueryEngineV2.java:413 and PooledTopNAlgorithm.java:111. TPU-first
inversion: instead of hashing rows into buckets, each 8k-row block builds the
[block, G] one-hot of (group key ∧ row mask) once, and ALL aggregators
contract against it on the systolic array in two batched matmuls:

  * int8 rows, int32 accumulation — exact: every row value is a ≤7-bit limb,
    so per-block products are exact and the int32 accumulator cannot wrap
    below 2^31 / 127 ≈ 16.9M rows (guarded in MMPlan eligibility);
  * bfloat16 rows, float32 accumulation — float sums ride the bf16 triple
    split (hi/lo/lo2 = all 24 f32 mantissa bits; products against a 0/1
    one-hot are exact, only the f32 accumulate rounds).

Measured on v5e: ~790M rows/s for count+longSum at G=1024 vs ~85M for the
VPU broadcast path and ~77M for scatter.
"""
from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

from druid_tpu.engine.kernels import AggKernel, MMPlan

MM_GROUP_LIMIT = 4096       # beyond this the N*G matmul flops dominate
MM_BLOCK = 8192             # rows per scan step


def mm_reduce(arrays: Dict, mask, key, kernels: Sequence[AggKernel],
              plans: Sequence[MMPlan], num_total: int):
    """Traced: returns (counts [num_total] int32, per-kernel states)."""
    import jax
    import jax.numpy as jnp

    fields = sorted({f for p in plans for f in p.fields})
    n = mask.shape[0]
    pad = (-n) % MM_BLOCK

    def padded(a):
        if not pad:
            return a
        fill = jnp.zeros((pad,) + a.shape[1:], a.dtype)
        return jnp.concatenate([a, fill])

    nblk = (n + pad) // MM_BLOCK
    keyb = padded(key).reshape(nblk, MM_BLOCK)
    maskb = padded(mask).reshape(nblk, MM_BLOCK)
    colsb = {f: padded(arrays[f]).reshape(nblk, MM_BLOCK) for f in fields}
    iota = jnp.arange(num_total, dtype=keyb.dtype)

    n_i8 = 1 + sum(p.n_i8 for p in plans)   # leading row: query row counts
    n_bf = sum(p.n_bf16 for p in plans)

    # data-derived zero so scan carries inherit the varying-axis type under
    # shard_map (same trick as grouping._blocked_reduce)
    vary0 = (key[0] * 0) + (mask[0] * 0).astype(key.dtype)
    acc8_0 = jnp.zeros((n_i8, num_total), jnp.int32) + vary0.astype(jnp.int32)
    accf_0 = jnp.zeros((max(n_bf, 1), num_total), jnp.float32) \
        + vary0.astype(jnp.float32)

    def body(carry, xs):
        acc8, accf = carry
        kb, mb = xs[0], xs[1]
        cols = dict(zip(fields, xs[2:]))
        oh8 = ((kb[:, None] == iota[None, :]) & mb[:, None]).astype(jnp.int8)
        rows8: List = [jnp.ones((MM_BLOCK,), jnp.int8)]
        rowsf: List = []
        for p in plans:
            r8, rf = p.make_rows(cols, mb)
            rows8.extend(r8)
            rowsf.extend(rf)
        lhs8 = jnp.stack(rows8, 0)
        acc8 = acc8 + jax.lax.dot_general(
            lhs8, oh8, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)
        if rowsf:
            lhsf = jnp.stack(rowsf, 0)
            accf = accf + jax.lax.dot_general(
                lhsf, oh8.astype(jnp.bfloat16), (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
        return (acc8, accf), None

    xs = (keyb, maskb) + tuple(colsb[f] for f in fields)
    (acc8, accf), _ = jax.lax.scan(body, (acc8_0, accf_0), xs)

    counts = acc8[0]
    states = []
    o8, of = 1, 0
    for k, p in zip(kernels, plans):
        states.append(p.finish(acc8[o8:o8 + p.n_i8],
                               accf[of:of + p.n_bf16], num_total))
        o8 += p.n_i8
        of += p.n_bf16
    return counts, tuple(states)
