"""Sparse cross-segment merge of partial aggregation states.

Reference analog: the broker/historical merge step — MergeSequence n-way merge
+ QueryToolChest.mergeResults (e.g. TimeseriesBinaryFn, TopN priority-queue
merge, GroupBy RowBasedGrouperHelper). TPU-first design: partials are dense
per-key state arrays; merging is
  1. compact each partial to its non-empty keys,
  2. re-encode keys into a *merged* key space (merged dictionaries play the
     DimensionMergerV9 role),
  3. np.unique over all keys, scatter-align each partial, and combine with
     the kernels' elementwise combine — all vectorized, no per-row loop.
The same states merge across chips with psum/max collectives when segments
share dictionaries (see druid_tpu/parallel/).
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from druid_tpu.data.dictionary import Dictionary, merge_dictionaries
from druid_tpu.engine.grouping import GroupSpec, SegmentPartial
from druid_tpu.engine.kernels import AggKernel


# ---------------------------------------------------------------------------
# State pytree utilities (states are np arrays or dicts of np arrays)
# ---------------------------------------------------------------------------

def state_select(state, idx: np.ndarray):
    if isinstance(state, dict):
        return {k: state_select(v, idx) for k, v in state.items()}
    return state[idx]


def state_scatter(dest, pos: np.ndarray, src):
    if isinstance(dest, dict):
        for k in dest:
            state_scatter(dest[k], pos, src[k])
        return dest
    dest[pos] = src
    return dest


# ---------------------------------------------------------------------------
# Key decoding
# ---------------------------------------------------------------------------

def partial_nonzero_keys(p: SegmentPartial) -> np.ndarray:
    """Indices into the partial's dense key space that actually have rows."""
    return np.flatnonzero(p.counts > 0)


def decode_keys(p: SegmentPartial, keys: np.ndarray) -> Tuple[np.ndarray, List[np.ndarray]]:
    """Decompose dense/compacted keys into (bucket_ids, [dim_ids...])."""
    spec = p.spec
    if spec.key_mode == "host":
        raw = spec.host_unique[keys].astype(np.int64)
    else:
        raw = keys.astype(np.int64)
    dim_ids: List[np.ndarray] = []
    for d in reversed(spec.dims):
        dim_ids.append((raw % d.cardinality).astype(np.int64))
        raw = raw // d.cardinality
    dim_ids.reverse()
    return raw, dim_ids  # raw is now the bucket id


def merge_partials(partials: Sequence[SegmentPartial],
                   dim_values: Sequence[Sequence[Sequence[str]]]):
    """Merge partial states across segments.

    dim_values[p][d] = list mapping local dim id -> string value for partial p,
    dimension d (from each segment's dictionary, after any extraction remap).

    Returns (buckets, dim_value_arrays, counts, states, kernels):
      buckets: int64 [G] bucket index per merged group
      dim_value_arrays: list of object arrays [G] of string values per dim
      counts: int64 [G]; states: merged state pytrees; kernels: from partial 0.
    """
    assert partials
    kernels = partials[0].kernels
    n_dims = len(partials[0].spec.dims)

    # 1. compact each partial + decode
    compacted = []
    for p_i, p in enumerate(partials):
        nz = partial_nonzero_keys(p)
        buckets, dim_ids = decode_keys(p, nz)
        compacted.append((p, nz, buckets, dim_ids))

    # 2. build merged per-dim value spaces
    merged_values: List[List[str]] = []
    value_to_merged: List[Dict[str, int]] = []
    for d in range(n_dims):
        vals = set()
        for p_i, (p, nz, buckets, dim_ids) in enumerate(compacted):
            local_vals = dim_values[p_i][d]
            vals.update(local_vals[int(i)] for i in np.unique(dim_ids[d]))
        # numbers (numeric dims) sort before strings so mixed schemas
        # (column numeric in one segment, absent -> "" in another) never
        # compare across types
        ordered = sorted(vals, key=lambda v: (isinstance(v, str), v))
        merged_values.append(ordered)
        value_to_merged.append({v: i for i, v in enumerate(ordered)})

    # 3. merged key per group entry
    cards = [max(len(v), 1) for v in merged_values]
    merged_keys_per_partial = []
    for p_i, (p, nz, buckets, dim_ids) in enumerate(compacted):
        key = buckets.copy()
        for d in range(n_dims):
            local_vals = dim_values[p_i][d]
            # local id -> merged id remap (vectorized via lookup table)
            # values with no live group in any partial map to -1 (never
            # referenced by dim_ids, which only cover live groups)
            lut = np.fromiter((value_to_merged[d].get(v, -1) for v in local_vals),
                              dtype=np.int64, count=len(local_vals))
            key = key * cards[d] + lut[dim_ids[d]]
        merged_keys_per_partial.append(key)

    all_keys = (np.concatenate(merged_keys_per_partial)
                if merged_keys_per_partial else np.zeros(0, dtype=np.int64))
    uniq = np.unique(all_keys)
    G = len(uniq)

    # 4. align + combine
    counts = np.zeros(G, dtype=np.int64)
    states: Optional[Dict[str, object]] = None
    for (p, nz, buckets, dim_ids), mkeys in zip(compacted, merged_keys_per_partial):
        pos = np.searchsorted(uniq, mkeys)
        np.add.at(counts, pos, p.counts[nz])
        aligned = {}
        for k in kernels:
            dest = k.empty_state(G)
            src = state_select(p.states[k.name], nz)
            aligned[k.name] = state_scatter(dest, pos, src)
        if states is None:
            states = aligned
        else:
            states = {k.name: k.combine(states[k.name], aligned[k.name])
                      for k in kernels}

    # 5. decode merged keys back to (bucket, values)
    raw = uniq.copy()
    dim_value_arrays: List[np.ndarray] = [None] * n_dims
    for d in range(n_dims - 1, -1, -1):
        ids = raw % cards[d]
        raw = raw // cards[d]
        vals = np.asarray(merged_values[d], dtype=object) if merged_values[d] \
            else np.asarray([""], dtype=object)
        dim_value_arrays[d] = vals[ids.astype(np.int64)]
    buckets = raw

    if states is None:
        states = {k.name: k.empty_state(G) for k in kernels}
    return buckets, dim_value_arrays, counts, states, kernels


def finalize_states(kernels: Sequence[AggKernel], states: Dict[str, object],
                    finalize: bool = True) -> Dict[str, np.ndarray]:
    """Per-group finalized (or raw combined) value arrays keyed by agg name."""
    out = {}
    for k in kernels:
        arr = k.finalize_array(states[k.name])
        out[k.name] = arr
    return out
