"""Query engines: queries compile to jit-ted mask + segmented-reduce programs.

x64 is enabled globally: OLAP long sums must not overflow int32, and
timestamps are int64 host-side. Device kernels still use int32/float32 where
hot (time offsets, dictionary ids, float metrics); int64 work on TPU lowers
to emulated 32-bit pairs only where a query actually asks for longs.
"""
import os

import jax

jax.config.update("jax_enable_x64", True)

# persistent XLA compilation cache: repeated-shape queries skip the 20-40s
# cold compile across PROCESSES (the reference's warm JVM + code cache have
# no cold-start; this is our equivalent). Opt out with
# DRUID_TPU_COMPILE_CACHE=0; override the directory by setting it to a path.
def _host_fingerprint() -> str:
    """CPU-feature fingerprint: a shared home directory must not feed one
    machine AOT executables compiled for another's instruction set (XLA
    loads mismatched CPU AOT results with only a warning — SIGILL risk)."""
    import hashlib
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                # x86 lists ISA extensions under "flags", aarch64 under
                # "Features" — either distinguishes incompatible hosts
                if line.startswith(("flags", "Features")):
                    return hashlib.sha1(line.encode()).hexdigest()[:12]
    except OSError:
        pass
    import platform
    ident = f"{platform.machine()}-{platform.processor()}"
    return hashlib.sha1(ident.encode()).hexdigest()[:12]


_cc = os.environ.get("DRUID_TPU_COMPILE_CACHE", "")
if _cc != "0":
    cache_dir = _cc if _cc not in ("", "1") else os.path.expanduser(
        f"~/.cache/druid_tpu/xla-{_host_fingerprint()}")
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:  # cache is an optimization, never a failure
        import logging
        logging.getLogger(__name__).debug(
            "persistent XLA compile cache unavailable at %s", cache_dir,
            exc_info=True)

from druid_tpu.engine.executor import QueryExecutor  # noqa: E402


def release_device_caches(clear_pool: bool = False) -> dict:
    """Drop every process-wide cache that pins device memory across
    queries: the sharded stack cache (whole segment sets held HBM-resident
    — and the segment OBJECTS each entry pins), the jitted-program LRUs
    (closures capture kernel aux arrays), and, with `clear_pool=True`, the
    device segment pool's entries. The ops surface for reclaiming HBM
    without a restart; the leak witness's session check uses it so that
    deliberately-pinned cache state is not mistaken for a leak. Returns
    per-cache drop counts."""
    from druid_tpu.engine import batching, grouping
    from druid_tpu.parallel import distributed

    with grouping._JIT_CACHE_LOCK:
        grouping_n = len(grouping._JIT_CACHE)
        grouping._JIT_CACHE.clear()
    with batching._JIT_CACHE_LOCK:
        batching_n = len(batching._JIT_CACHE)
        batching._JIT_CACHE.clear()
    out = {
        "stack_entries": distributed.clear_stack_cache(),
        "sharded_programs": distributed.clear_fn_cache(),
        "grouping_programs": grouping_n,
        "batching_programs": batching_n,
    }
    if clear_pool:
        from druid_tpu.data.devicepool import device_pool
        pool = device_pool()
        out["pool_resident_bytes"] = pool.snapshot().resident_bytes
        pool.clear()
    return out


__all__ = ["QueryExecutor", "release_device_caches"]
