"""Query engines: queries compile to jit-ted mask + segmented-reduce programs.

x64 is enabled globally: OLAP long sums must not overflow int32, and
timestamps are int64 host-side. Device kernels still use int32/float32 where
hot (time offsets, dictionary ids, float metrics); int64 work on TPU lowers
to emulated 32-bit pairs only where a query actually asks for longs.
"""
import jax

jax.config.update("jax_enable_x64", True)

from druid_tpu.engine.executor import QueryExecutor  # noqa: E402

__all__ = ["QueryExecutor"]
