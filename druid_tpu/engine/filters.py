"""Filter planning: DimFilter trees → device mask programs + host bitmap algebra.

Reference analog: segment/filter/Filters.java:65 (toFilter, CNF,
shouldUseBitmapIndex) and the pre/post-filter split in
QueryableIndexStorageAdapter.makeCursors (:235-282).

TPU-first design:
  * String predicates (selector/in/bound/like/regex/search/javascript) are
    evaluated host-side against the dimension *dictionary* (cardinality-sized,
    tiny) producing a boolean lookup table (LUT). On device the predicate is
    one gather: `lut[ids]`. This one mechanism covers every string matcher the
    reference implements with per-row Predicate objects.
  * Numeric predicates compile to vectorized comparisons on the value column.
  * A FilterNode has a *structural signature* (no embedded constants) so the
    jitted kernel is shared across queries/segments with the same shape;
    constants (LUTs, bounds, remaps) are passed as device arguments. This is
    the XLA analog of the reference's bytecode specialization cache
    (query/monomorphicprocessing/SpecializationService.java:65).
  * `bitmap_of` implements the classic host bitmap-index path (used by the
    search engine, segment pruning, and selectivity estimation), mirroring
    Filter.getBitmapIndex.
"""
from __future__ import annotations

import re
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from druid_tpu.data.bitmap import Bitmap
from druid_tpu.data.dictionary import Dictionary, merge_dictionaries
from druid_tpu.data.segment import Segment, ValueType
from druid_tpu.query import filters as F
from druid_tpu.utils.expression import parse_expression
from druid_tpu.utils.intervals import Interval


# ---------------------------------------------------------------------------
# Device-side filter plan nodes
# ---------------------------------------------------------------------------

class FilterNode:
    """A planned filter; structure is segment-independent, aux arrays are not."""

    def signature(self) -> str:
        raise NotImplementedError

    def aux_arrays(self) -> List[np.ndarray]:
        """Constant device inputs, flattened in deterministic order."""
        return []

    def build(self, cols: Dict[str, object], aux: Iterator):
        """Trace the mask computation. `cols` maps column name -> device array
        (plus "__time_offset"); `aux` yields staged aux arrays in order."""
        raise NotImplementedError


class ConstNode(FilterNode):
    def __init__(self, value: bool):
        self.value = value

    def signature(self):
        return f"const({self.value})"

    def build(self, cols, aux):
        import jax.numpy as jnp
        n = cols["__valid"].shape[0]
        return jnp.full((n,), self.value, dtype=bool)


class LutNode(FilterNode):
    """mask = lut[ids] — all dictionary predicates reduce to this."""

    def __init__(self, dim: str, lut: np.ndarray):
        self.dim = dim
        self.lut = lut.astype(bool)

    def signature(self):
        return f"lut({self.dim})"

    def aux_arrays(self):
        return [self.lut]

    def build(self, cols, aux):
        lut = next(aux)
        return lut[cols[self.dim]]


class NumericCmpNode(FilterNode):
    """lower <= col <= upper with optional strictness; bounds passed as aux."""

    def __init__(self, column: str, lower: Optional[float], upper: Optional[float],
                 lower_strict: bool, upper_strict: bool, dtype):
        self.column = column
        self.lower, self.upper = lower, upper
        self.lower_strict, self.upper_strict = lower_strict, upper_strict
        self.dtype = dtype

    def signature(self):
        return (f"numcmp({self.column},{self.lower is not None},"
                f"{self.upper is not None},{self.lower_strict},{self.upper_strict})")

    def aux_arrays(self):
        out = []
        if self.lower is not None:
            out.append(np.asarray(self.lower, dtype=self.dtype))
        if self.upper is not None:
            out.append(np.asarray(self.upper, dtype=self.dtype))
        return out

    def build(self, cols, aux):
        import jax.numpy as jnp
        v = cols[self.column]
        mask = None
        if self.lower is not None:
            lo = next(aux)
            m = (v > lo) if self.lower_strict else (v >= lo)
            mask = m
        if self.upper is not None:
            hi = next(aux)
            m = (v < hi) if self.upper_strict else (v <= hi)
            mask = m if mask is None else (mask & m)
        if mask is None:
            mask = jnp.ones(v.shape, dtype=bool)
        return mask


class NumericEqNode(FilterNode):
    def __init__(self, column: str, value: float, dtype):
        self.column = column
        self.value = value
        self.dtype = dtype

    def signature(self):
        return f"numeq({self.column})"

    def aux_arrays(self):
        return [np.asarray(self.value, dtype=self.dtype)]

    def build(self, cols, aux):
        return cols[self.column] == next(aux)


class NumericInNode(FilterNode):
    def __init__(self, column: str, values: np.ndarray):
        self.column = column
        self.values = values

    def signature(self):
        return f"numin({self.column},{len(self.values)})"

    def aux_arrays(self):
        return [self.values]

    def build(self, cols, aux):
        import jax.numpy as jnp
        vals = next(aux)
        v = cols[self.column]
        return jnp.any(v[:, None] == vals[None, :], axis=1)


class TimeIntervalsNode(FilterNode):
    """__time within k intervals; offsets relative to block.time0 as aux [k,2]."""

    def __init__(self, offsets: np.ndarray):
        self.offsets = offsets.astype(np.int32)  # shape [k, 2]

    def signature(self):
        return f"timein({self.offsets.shape[0]})"

    def aux_arrays(self):
        return [self.offsets]

    def build(self, cols, aux):
        import jax.numpy as jnp
        iv = next(aux)
        t = cols["__time_offset"]
        m = (t[:, None] >= iv[None, :, 0]) & (t[:, None] < iv[None, :, 1])
        return jnp.any(m, axis=1)


class ColumnCompareNode(FilterNode):
    """dimA == dimB via remap into a merged dictionary id space."""

    def __init__(self, dims: Tuple[str, ...], remaps: List[np.ndarray]):
        self.dims = dims
        self.remaps = remaps

    def signature(self):
        return f"colcmp({','.join(self.dims)})"

    def aux_arrays(self):
        return list(self.remaps)

    def build(self, cols, aux):
        first = next(aux)[cols[self.dims[0]]]
        mask = None
        for d in self.dims[1:]:
            other = next(aux)[cols[d]]
            m = first == other
            mask = m if mask is None else (mask & m)
        return mask


class ExpressionNode(FilterNode):
    """Expression filter traced to XLA elementwise ops. String-dimension
    comparisons are rewritten at plan time into per-dictionary-id boolean
    LUT gathers (utils.expression.rewrite_string_sites) — the device path
    stays purely numeric."""

    def __init__(self, expression: str, time0: int, segment=None):
        from druid_tpu.utils.expression import (lut_for_site,
                                                rewrite_string_sites)
        self.expression = expression
        self.time0 = time0
        string_dims = frozenset(segment.dims) if segment is not None \
            else frozenset()
        self.expr, sites = rewrite_string_sites(
            parse_expression(expression), string_dims)
        self.luts = [lut_for_site(s, segment.dims[s[0]].dictionary.values)
                     for s in sites] if segment is not None else []

    def signature(self):
        # the REWRITTEN AST must key the jit cache: the same expression
        # string over different schemas (dim vs metric column) rewrites to
        # structurally different programs
        return f"expr({self.expr!r};l{len(self.luts)})"

    def aux_arrays(self):
        return [np.asarray(self.time0, dtype=np.int64)] + list(self.luts)

    def build(self, cols, aux):
        import jax.numpy as jnp
        time0 = next(aux)
        bindings = dict(cols)
        bindings["__time"] = cols["__time_offset"].astype(jnp.int64) + time0
        bindings["__luts"] = [next(aux) for _ in self.luts]
        out = self.expr.evaluate(bindings)
        return jnp.asarray(out, dtype=bool) if hasattr(out, "shape") else (
            jnp.full((cols["__valid"].shape[0],), bool(out)))


class AndNode(FilterNode):
    def __init__(self, children: List[FilterNode]):
        self.children = children

    def signature(self):
        return "and(" + ",".join(c.signature() for c in self.children) + ")"

    def aux_arrays(self):
        return [a for c in self.children for a in c.aux_arrays()]

    def build(self, cols, aux):
        mask = self.children[0].build(cols, aux)
        for c in self.children[1:]:
            mask = mask & c.build(cols, aux)
        return mask


class OrNode(FilterNode):
    def __init__(self, children: List[FilterNode]):
        self.children = children

    def signature(self):
        return "or(" + ",".join(c.signature() for c in self.children) + ")"

    def aux_arrays(self):
        return [a for c in self.children for a in c.aux_arrays()]

    def build(self, cols, aux):
        mask = self.children[0].build(cols, aux)
        for c in self.children[1:]:
            mask = mask | c.build(cols, aux)
        return mask


class NotNode(FilterNode):
    def __init__(self, child: FilterNode):
        self.child = child

    def signature(self):
        return "not(" + self.child.signature() + ")"

    def aux_arrays(self):
        return self.child.aux_arrays()

    def build(self, cols, aux):
        return ~self.child.build(cols, aux)


# ---------------------------------------------------------------------------
# String predicate → dictionary LUT
# ---------------------------------------------------------------------------

def _dictionary_lut(d: Dictionary, pred) -> np.ndarray:
    return np.fromiter((bool(pred(v)) for v in d.values), dtype=bool,
                       count=d.cardinality)


def _string_predicate(flt: F.DimFilter):
    """Value-level predicate for a single-dim string filter (used for LUTs and
    for row-level evaluation in having specs). An extraction_fn on the
    filter transforms each dictionary value BEFORE the predicate — exactly
    the reference's dimension-extraction filtering, and still one host LUT
    over the dictionary."""
    ex = getattr(flt, "extraction_fn", None)
    if ex is not None:
        import dataclasses
        base = _string_predicate(dataclasses.replace(flt,
                                                     extraction_fn=None))
        if base is None:
            return None

        def extracted(v, _base=base, _ex=ex):
            out = _ex.apply(v)
            return _base("" if out is None else out)
        return extracted
    # extension filters (e.g. bloom) expose a value_predicate() hook
    if hasattr(flt, "value_predicate"):
        return flt.value_predicate()
    if isinstance(flt, F.SelectorFilter):
        target = "" if flt.value is None else flt.value
        return lambda v: v == target
    if isinstance(flt, F.InFilter):
        vals = {("" if v is None else v) for v in flt.values}
        return lambda v: v in vals
    if isinstance(flt, F.BoundFilter):
        lo, up = flt.lower, flt.upper
        ls, us = flt.lower_strict, flt.upper_strict
        if flt.ordering == "numeric":
            def num_pred(v):
                try:
                    x = float(v)
                except (TypeError, ValueError):
                    return False
                if lo is not None:
                    l = float(lo)
                    if x < l or (ls and x == l):
                        return False
                if up is not None:
                    u = float(up)
                    if x > u or (us and x == u):
                        return False
                return True
            return num_pred

        def lex_pred(v):
            if lo is not None and (v < lo or (ls and v == lo)):
                return False
            if up is not None and (v > up or (us and v == up)):
                return False
            return True
        return lex_pred
    if isinstance(flt, F.LikeFilter):
        rx = re.compile(flt.regex())
        return lambda v: rx.match(v) is not None
    if isinstance(flt, F.RegexFilter):
        rx = re.compile(flt.pattern)
        return lambda v: rx.search(v) is not None
    if isinstance(flt, F.SearchFilter):
        if flt.case_sensitive:
            return lambda v: flt.value in v
        needle = flt.value.lower()
        return lambda v: needle in v.lower()
    if isinstance(flt, F.JavaScriptFilter):
        return flt.predicate
    return None


# ---------------------------------------------------------------------------
# Planner
# ---------------------------------------------------------------------------

def plan_filter(flt: Optional[F.DimFilter], segment: Segment,
                virtual_columns: Sequence = ()) -> Optional[FilterNode]:
    if flt is None:
        return None
    flt = flt.optimize()
    vc_types = {v.name: v.output_type for v in virtual_columns}
    return _plan(flt, segment, vc_types)


def _plan(flt: F.DimFilter, segment: Segment,
          vc_types: Optional[Dict[str, str]] = None) -> FilterNode:
    vc_types = vc_types or {}
    if isinstance(flt, F.TrueFilter):
        return ConstNode(True)
    if isinstance(flt, F.FalseFilter):
        return ConstNode(False)
    if isinstance(flt, F.AndFilter):
        return AndNode([_plan(f, segment, vc_types) for f in flt.fields])
    if isinstance(flt, F.OrFilter):
        return OrNode([_plan(f, segment, vc_types) for f in flt.fields])
    if isinstance(flt, F.NotFilter):
        return NotNode(_plan(flt.field, segment, vc_types))
    if isinstance(flt, F.IntervalFilter):
        if flt.dimension != "__time":
            raise ValueError("interval filter supported on __time only")
        t0 = segment.interval.start
        offs = np.asarray(
            [[max(iv.start - t0, -(2**31) + 1), min(iv.end - t0, 2**31 - 1)]
             for iv in flt.intervals], dtype=np.int64).clip(-(2**31) + 1, 2**31 - 1)
        return TimeIntervalsNode(offs.astype(np.int32))
    if isinstance(flt, F.ColumnComparisonFilter):
        dicts = []
        for d in flt.dimensions:
            col = segment.dims.get(d)
            if col is None:
                raise ValueError(f"columnComparison on non-string dim {d!r}")
            dicts.append(col.dictionary)
        _, remaps = merge_dictionaries(dicts)
        return ColumnCompareNode(flt.dimensions, remaps)
    if isinstance(flt, F.ExpressionFilter):
        return ExpressionNode(flt.expression, segment.interval.start, segment)

    # single-column leaf filters
    dim = getattr(flt, "dimension", None)
    if dim is None:
        raise ValueError(f"cannot plan filter {flt!r}")
    if dim in segment.dims:
        d = segment.dims[dim].dictionary
        pred = _string_predicate(flt)
        if pred is None:
            raise ValueError(f"cannot plan string filter {flt!r}")
        # bound filters on sorted dictionaries could use id ranges
        # (Dictionary.id_range); the LUT is equally one gather so we keep
        # the uniform mechanism.
        return LutNode(dim, _dictionary_lut(d, pred))
    if getattr(flt, "extraction_fn", None) is not None:
        # numeric/time columns have no dictionary to transform
        raise ValueError(
            f"extractionFn filter on non-string column [{dim}]")
    # numeric column (metric) or __time
    if dim == "__time":
        dtype, colname = np.int32, "__time_offset"
        # clip to the int32 offset range (bounds far outside the segment's
        # interval still compare correctly after clipping)
        conv = lambda s: min(max(int(s) - segment.interval.start,
                                 -(2**31) + 1), 2**31 - 2)
    elif dim in segment.metrics:
        vt = segment.metrics[dim].type
        # compare in the column's STAGED dtype — an int64 constant against
        # an int32-narrowed column would promote the whole compare to
        # emulated 64-bit ops on device
        dtype, colname = segment.staged_dtype(dim), dim
        conv = (int if vt == ValueType.LONG else float)
        if vt == ValueType.LONG and dtype == np.int32:
            # constants outside int32 have constant outcomes (every value
            # fits int32 — that is why the column staged narrow)
            return _plan_narrow_long(flt, colname)
    elif dim in vc_types:
        t = vc_types[dim]
        dtype = {"long": np.int64, "float": np.float32}.get(t, np.float64)
        colname = dim
        conv = (int if t == "long" else float)
    else:
        # missing column: selector of null matches all rows, else none
        if isinstance(flt, F.SelectorFilter) and (flt.value is None or flt.value == ""):
            return ConstNode(True)
        return ConstNode(False)

    if isinstance(flt, F.SelectorFilter):
        if flt.value is None:
            return ConstNode(False)
        return NumericEqNode(colname, conv(flt.value), dtype)
    if isinstance(flt, F.InFilter):
        vals = np.asarray([conv(v) for v in flt.values if v is not None], dtype=dtype)
        return NumericInNode(colname, vals)
    if isinstance(flt, F.BoundFilter):
        lo = conv(flt.lower) if flt.lower is not None else None
        hi = conv(flt.upper) if flt.upper is not None else None
        return NumericCmpNode(colname, lo, hi, flt.lower_strict, flt.upper_strict,
                              dtype)
    raise ValueError(f"cannot plan filter {type(flt).__name__} on numeric column")


_I32_MIN, _I32_MAX = -(2**31), 2**31 - 1


def _plan_narrow_long(flt: F.DimFilter, colname: str) -> FilterNode:
    """Numeric filters over int32-staged long columns: in-range constants
    compare in int32; out-of-range constants fold to constants."""
    if isinstance(flt, F.SelectorFilter):
        if flt.value is None:
            return ConstNode(False)
        v = int(flt.value)
        if not (_I32_MIN <= v <= _I32_MAX):
            return ConstNode(False)
        return NumericEqNode(colname, v, np.int32)
    if isinstance(flt, F.InFilter):
        vals = [int(v) for v in flt.values if v is not None]
        vals = [v for v in vals if _I32_MIN <= v <= _I32_MAX]
        if not vals:
            return ConstNode(False)
        return NumericInNode(colname, np.asarray(vals, dtype=np.int32))
    if isinstance(flt, F.BoundFilter):
        lo = int(flt.lower) if flt.lower is not None else None
        hi = int(flt.upper) if flt.upper is not None else None
        if lo is not None and lo > _I32_MAX:
            return ConstNode(False)       # nothing is that large
        if hi is not None and hi < _I32_MIN:
            return ConstNode(False)
        if lo is not None and lo < _I32_MIN:
            lo = None                      # everything passes the lower bound
        if hi is not None and hi > _I32_MAX:
            hi = None
        if lo is None and hi is None:
            return ConstNode(True)
        return NumericCmpNode(colname, lo, hi, flt.lower_strict,
                              flt.upper_strict, np.int32)
    raise ValueError(f"cannot plan filter {type(flt).__name__} on numeric column")


# ---------------------------------------------------------------------------
# Host bitmap-index path (reference: Filter.getBitmapIndex)
# ---------------------------------------------------------------------------

def can_use_bitmap(flt: F.DimFilter, segment: Segment) -> bool:
    if isinstance(flt, (F.TrueFilter, F.FalseFilter)):
        return True
    if isinstance(flt, (F.AndFilter, F.OrFilter)):
        return all(can_use_bitmap(f, segment) for f in flt.fields)
    if isinstance(flt, F.NotFilter):
        return can_use_bitmap(flt.field, segment)
    dim = getattr(flt, "dimension", None)
    return dim in segment.dims and _string_predicate(flt) is not None


def bitmap_of(flt: F.DimFilter, segment: Segment) -> Bitmap:
    """Evaluate an indexable filter purely via bitmap algebra."""
    n = segment.n_rows
    if isinstance(flt, F.TrueFilter):
        return Bitmap.full(n)
    if isinstance(flt, F.FalseFilter):
        return Bitmap.empty(n)
    if isinstance(flt, F.AndFilter):
        return Bitmap.intersection([bitmap_of(f, segment) for f in flt.fields], n)
    if isinstance(flt, F.OrFilter):
        return Bitmap.union([bitmap_of(f, segment) for f in flt.fields], n)
    if isinstance(flt, F.NotFilter):
        return ~bitmap_of(flt.field, segment)
    dim = flt.dimension
    col = segment.dims[dim]
    pred = _string_predicate(flt)
    lut = _dictionary_lut(col.dictionary, pred)
    matching = np.flatnonzero(lut)
    index = col.bitmap_index()
    return index.union_of(matching)


def estimate_selectivity(flt: Optional[F.DimFilter], segment: Segment) -> float:
    """Fraction of rows expected to match (reference:
    Filter.estimateSelectivity); exact when bitmap-indexable."""
    if flt is None:
        return 1.0
    if segment.n_rows == 0:
        return 0.0
    if can_use_bitmap(flt, segment):
        return bitmap_of(flt, segment).cardinality() / segment.n_rows
    return 1.0


# ---------------------------------------------------------------------------
# Row-level evaluation (having specs over result rows)
# ---------------------------------------------------------------------------

def evaluate_filter_on_row(flt: F.DimFilter, row: Dict[str, object]) -> bool:
    if isinstance(flt, F.TrueFilter):
        return True
    if isinstance(flt, F.FalseFilter):
        return False
    if isinstance(flt, F.AndFilter):
        return all(evaluate_filter_on_row(f, row) for f in flt.fields)
    if isinstance(flt, F.OrFilter):
        return any(evaluate_filter_on_row(f, row) for f in flt.fields)
    if isinstance(flt, F.NotFilter):
        return not evaluate_filter_on_row(flt.field, row)
    pred = _string_predicate(flt)
    if pred is None:
        raise ValueError(f"cannot row-evaluate {flt!r}")
    v = row.get(flt.dimension)
    return pred("" if v is None else str(v))


# ---------------------------------------------------------------------------
# Host-side full mask evaluation (scan / search / timeBoundary paths)
# ---------------------------------------------------------------------------

def _bind_string_dims(expr, segment: Segment, bindings: Dict) -> None:
    """Bind every string dim an expression references as a DECODED value
    array — host-path numpy string comparison matches the reference's
    lexicographic semantics directly."""
    for c in expr.required_columns():
        if c in segment.dims and c not in bindings:
            col = segment.dims[c]
            vals = np.asarray(list(col.dictionary.values), dtype=object)
            bindings[c] = vals[col.ids]


def host_mask(flt: Optional[F.DimFilter], segment: Segment,
              virtual_columns: Sequence = ()) -> np.ndarray:
    """Evaluate a filter to a host boolean row mask with vectorized numpy —
    used by the row-export engines (scan/select), search, and timeBoundary,
    where results are host-side anyway."""
    n = segment.n_rows
    if flt is None:
        return np.ones(n, dtype=bool)
    flt = flt.optimize()
    vc_arrays = {}
    if virtual_columns:
        bindings = {"__time": segment.time_ms}
        for name, m in segment.metrics.items():
            bindings[name] = m.values
        for v in virtual_columns:
            expr = parse_expression(v.expression)
            _bind_string_dims(expr, segment, bindings)
            arr = expr.evaluate(bindings)
            vc_arrays[v.name] = np.broadcast_to(np.asarray(arr), (n,))
            bindings[v.name] = vc_arrays[v.name]
    return _host_mask(flt, segment, vc_arrays)


def _host_mask(flt: F.DimFilter, segment: Segment,
               vc_arrays: Optional[Dict[str, np.ndarray]] = None) -> np.ndarray:
    vc_arrays = vc_arrays or {}
    n = segment.n_rows
    if isinstance(flt, F.TrueFilter):
        return np.ones(n, dtype=bool)
    if isinstance(flt, F.FalseFilter):
        return np.zeros(n, dtype=bool)
    if isinstance(flt, F.AndFilter):
        out = np.ones(n, dtype=bool)
        for f in flt.fields:
            out &= _host_mask(f, segment, vc_arrays)
        return out
    if isinstance(flt, F.OrFilter):
        out = np.zeros(n, dtype=bool)
        for f in flt.fields:
            out |= _host_mask(f, segment, vc_arrays)
        return out
    if isinstance(flt, F.NotFilter):
        return ~_host_mask(flt.field, segment, vc_arrays)
    if isinstance(flt, F.IntervalFilter):
        t = segment.time_ms
        out = np.zeros(n, dtype=bool)
        for iv in flt.intervals:
            out |= (t >= iv.start) & (t < iv.end)
        return out
    if isinstance(flt, F.ColumnComparisonFilter):
        dicts = [segment.dims[d].dictionary for d in flt.dimensions]
        _, remaps = merge_dictionaries(dicts)
        first = remaps[0][segment.dims[flt.dimensions[0]].ids]
        out = np.ones(n, dtype=bool)
        for d, remap in zip(flt.dimensions[1:], remaps[1:]):
            out &= first == remap[segment.dims[d].ids]
        return out
    if isinstance(flt, F.ExpressionFilter):
        expr = parse_expression(flt.expression)
        bindings = {"__time": segment.time_ms}
        for name, m in segment.metrics.items():
            bindings[name] = m.values
        _bind_string_dims(expr, segment, bindings)
        bindings.update(vc_arrays)
        out = expr.evaluate(bindings)
        return np.broadcast_to(np.asarray(out, dtype=bool), (n,)).copy()

    dim = getattr(flt, "dimension", None)
    if dim in segment.dims:
        col = segment.dims[dim]
        pred = _string_predicate(flt)
        lut = _dictionary_lut(col.dictionary, pred)
        return lut[col.ids]
    if dim == "__time" or dim in segment.metrics or dim in vc_arrays:
        if dim == "__time":
            vals = segment.time_ms
        elif dim in segment.metrics:
            vals = segment.metrics[dim].values
        else:
            vals = vc_arrays[dim]
        conv = int if (dim == "__time"
                       or (dim in segment.metrics
                           and segment.metrics[dim].type == ValueType.LONG)
                       or (dim in vc_arrays
                           and np.issubdtype(vals.dtype, np.integer))) else float
        if isinstance(flt, F.SelectorFilter):
            if flt.value is None:
                return np.zeros(n, dtype=bool)
            return vals == conv(flt.value)
        if isinstance(flt, F.InFilter):
            targets = np.asarray([conv(v) for v in flt.values if v is not None])
            return np.isin(vals, targets)
        if isinstance(flt, F.BoundFilter):
            out = np.ones(n, dtype=bool)
            if flt.lower is not None:
                lo = conv(flt.lower)
                out &= (vals > lo) if flt.lower_strict else (vals >= lo)
            if flt.upper is not None:
                hi = conv(flt.upper)
                out &= (vals < hi) if flt.upper_strict else (vals <= hi)
            return out
        raise ValueError(f"cannot host-evaluate {type(flt).__name__} on numeric")
    # missing column
    if isinstance(flt, F.SelectorFilter) and (flt.value is None or flt.value == ""):
        return np.ones(n, dtype=bool)
    return np.zeros(n, dtype=bool)


def simplify_node(node: Optional[FilterNode]) -> Optional[FilterNode]:
    """Fold ConstNodes out of a planned tree. Returns None (no filter),
    a ConstNode(False) root (caller short-circuits without a device call —
    constant-mask programs also crash some TPU compiler backends), or a
    const-free tree."""
    if node is None:
        return None
    node = _simplify(node)
    if isinstance(node, ConstNode) and node.value:
        return None
    return node


def _simplify(node: FilterNode) -> FilterNode:
    if isinstance(node, AndNode):
        kids = []
        for c in node.children:
            c = _simplify(c)
            if isinstance(c, ConstNode):
                if not c.value:
                    return ConstNode(False)
                continue
            kids.append(c)
        if not kids:
            return ConstNode(True)
        return kids[0] if len(kids) == 1 else AndNode(kids)
    if isinstance(node, OrNode):
        kids = []
        for c in node.children:
            c = _simplify(c)
            if isinstance(c, ConstNode):
                if c.value:
                    return ConstNode(True)
                continue
            kids.append(c)
        if not kids:
            return ConstNode(False)
        return kids[0] if len(kids) == 1 else OrNode(kids)
    if isinstance(node, NotNode):
        c = _simplify(node.child)
        if isinstance(c, ConstNode):
            return ConstNode(not c.value)
        return NotNode(c)
    return node


# ---------------------------------------------------------------------------
# Row-level evaluation (host): used by ingest-time TransformSpec filters and
# having specs — the analog of the reference's ValueMatcher path
# (query/filter/ValueMatcher.java) for rows that are not yet columnar.
# ---------------------------------------------------------------------------

def make_row_matcher(flt: F.DimFilter):
    """Compile a DimFilter into row(dict)->bool over raw (pre-dictionary)
    values. Dims are strings (None ≡ ""), metrics numeric, __time millis."""
    if isinstance(flt, F.TrueFilter):
        return lambda row: True
    if isinstance(flt, F.FalseFilter):
        return lambda row: False
    if isinstance(flt, F.AndFilter):
        subs = [make_row_matcher(f) for f in flt.fields]
        return lambda row: all(m(row) for m in subs)
    if isinstance(flt, F.OrFilter):
        subs = [make_row_matcher(f) for f in flt.fields]
        return lambda row: any(m(row) for m in subs)
    if isinstance(flt, F.NotFilter):
        sub = make_row_matcher(flt.field)
        return lambda row: not sub(row)
    if isinstance(flt, F.IntervalFilter):
        ivs = flt.intervals
        col = flt.dimension

        def iv_match(row):
            v = row.get(col)
            if v is None:
                return False
            try:
                ms = int(float(v))
            except (TypeError, ValueError):
                return False
            return any(iv.contains(ms) for iv in ivs)
        return iv_match
    if isinstance(flt, F.ColumnComparisonFilter):
        dims = flt.dimensions

        def cc_match(row):
            vals = [("" if row.get(d) is None else str(row.get(d)))
                    for d in dims]
            return all(v == vals[0] for v in vals)
        return cc_match
    if isinstance(flt, F.ExpressionFilter):
        expr = parse_expression(flt.expression)

        def ex_match(row):
            # None ≡ "" — the same null contract as every other row matcher.
            # A numeric expr over a null-bound column raises (e.g. "" > 2);
            # such rows simply don't match, as in the reference.
            try:
                out = expr.evaluate({k: ("" if v is None else v)
                                     for k, v in row.items()})
            except (TypeError, ValueError):
                return False
            try:
                return bool(float(out))
            except (TypeError, ValueError):
                return bool(out)
        return ex_match
    pred = _string_predicate(flt)
    if pred is not None:
        dim = flt.dimension

        def s_match(row):
            v = row.get(dim)
            return pred("" if v is None else str(v))
        return s_match
    raise ValueError(f"cannot row-match filter {type(flt).__name__}")
