"""Filter planning: DimFilter trees → device mask programs + host bitmap algebra.

Reference analog: segment/filter/Filters.java:65 (toFilter, CNF,
shouldUseBitmapIndex) and the pre/post-filter split in
QueryableIndexStorageAdapter.makeCursors (:235-282).

TPU-first design:
  * String predicates (selector/in/bound/like/regex/search/javascript) are
    evaluated host-side against the dimension *dictionary* (cardinality-sized,
    tiny) producing a boolean lookup table (LUT). On device the predicate is
    one gather: `lut[ids]`. This one mechanism covers every string matcher the
    reference implements with per-row Predicate objects.
  * Numeric predicates compile to vectorized comparisons on the value column.
  * A FilterNode has a *structural signature* (no embedded constants) so the
    jitted kernel is shared across queries/segments with the same shape;
    constants (LUTs, bounds, remaps) are passed as device arguments. This is
    the XLA analog of the reference's bytecode specialization cache
    (query/monomorphicprocessing/SpecializationService.java:65).
  * `bitmap_of` implements the classic host bitmap-index path (used by the
    search engine, segment pruning, and selectivity estimation), mirroring
    Filter.getBitmapIndex.
"""
from __future__ import annotations

import collections
import functools
import hashlib
import os
import re
import threading
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

import numpy as np

from druid_tpu.data.bitmap import (AnyBitmap, Bitmap, SparseBitmap,
                                   bitmap_and, bitmap_or, device_repr)
from druid_tpu.data.dictionary import Dictionary, merge_dictionaries
from druid_tpu.data.segment import Segment, ValueType
from druid_tpu.query import filters as F
from druid_tpu.utils.emitter import Monitor
from druid_tpu.utils.expression import parse_expression
from druid_tpu.utils.intervals import Interval

#: process default for the device-bitmap filter path; per-process opt-out
#: via DRUID_TPU_DEVICE_BITMAP=0 or set_device_bitmap_enabled(False).
_DEVICE_BITMAP = os.environ.get("DRUID_TPU_DEVICE_BITMAP", "1").lower() \
    not in ("0", "false", "no")
_DEVICE_BITMAP_LOCK = threading.Lock()


def set_device_bitmap_enabled(on: bool) -> bool:
    """Flip the process-wide device-bitmap default; returns the previous
    value (bench/test toggle, the batching/packed.set_enabled discipline)."""
    global _DEVICE_BITMAP
    with _DEVICE_BITMAP_LOCK:
        prev = _DEVICE_BITMAP
        _DEVICE_BITMAP = bool(on)
        return prev


def device_bitmap_enabled() -> bool:
    return _DEVICE_BITMAP


# ---------------------------------------------------------------------------
# Device-side filter plan nodes
# ---------------------------------------------------------------------------

class FilterNode:
    """A planned filter; structure is segment-independent, aux arrays are not."""

    def signature(self) -> str:
        raise NotImplementedError

    def aux_arrays(self) -> List[np.ndarray]:
        """Constant device inputs, flattened in deterministic order."""
        return []

    def required_device_columns(self) -> Set[str]:
        """Segment columns build() reads from `cols`. Narrower than the
        DimFilter's required_columns: a subtree compiled to a device bitmap
        (DeviceBitmapNode) needs NO staged columns at all — its words ride
        the arrays dict under a synthetic name — so filter-only dimensions
        stop being staged entirely."""
        return set()

    def build(self, cols: Dict[str, object], aux: Iterator):
        """Trace the mask computation. `cols` maps column name -> device array
        (plus "__time_offset"); `aux` yields staged aux arrays in order."""
        raise NotImplementedError


class ConstNode(FilterNode):
    def __init__(self, value: bool):
        self.value = value

    def signature(self):
        return f"const({self.value})"

    def build(self, cols, aux):
        import jax.numpy as jnp
        n = cols["__valid"].shape[0]
        return jnp.full((n,), self.value, dtype=bool)


class LutNode(FilterNode):
    """mask = lut[ids] — all dictionary predicates reduce to this."""

    def __init__(self, dim: str, lut: np.ndarray):
        self.dim = dim
        self.lut = lut.astype(bool)

    def signature(self):
        return f"lut({self.dim})"

    def required_device_columns(self):
        return {self.dim}

    def aux_arrays(self):
        return [self.lut]

    def build(self, cols, aux):
        lut = next(aux)
        return lut[cols[self.dim]]


class NumericCmpNode(FilterNode):
    """lower <= col <= upper with optional strictness; bounds passed as aux."""

    def __init__(self, column: str, lower: Optional[float], upper: Optional[float],
                 lower_strict: bool, upper_strict: bool, dtype):
        self.column = column
        self.lower, self.upper = lower, upper
        self.lower_strict, self.upper_strict = lower_strict, upper_strict
        self.dtype = dtype

    def signature(self):
        return (f"numcmp({self.column},{self.lower is not None},"
                f"{self.upper is not None},{self.lower_strict},{self.upper_strict})")

    def required_device_columns(self):
        return {self.column}

    def aux_arrays(self):
        out = []
        if self.lower is not None:
            out.append(np.asarray(self.lower, dtype=self.dtype))
        if self.upper is not None:
            out.append(np.asarray(self.upper, dtype=self.dtype))
        return out

    def build(self, cols, aux):
        import jax.numpy as jnp
        v = cols[self.column]
        mask = None
        if self.lower is not None:
            lo = next(aux)
            m = (v > lo) if self.lower_strict else (v >= lo)
            mask = m
        if self.upper is not None:
            hi = next(aux)
            m = (v < hi) if self.upper_strict else (v <= hi)
            mask = m if mask is None else (mask & m)
        if mask is None:
            mask = jnp.ones(v.shape, dtype=bool)
        return mask


class NumericEqNode(FilterNode):
    def __init__(self, column: str, value: float, dtype):
        self.column = column
        self.value = value
        self.dtype = dtype

    def signature(self):
        return f"numeq({self.column})"

    def required_device_columns(self):
        return {self.column}

    def aux_arrays(self):
        return [np.asarray(self.value, dtype=self.dtype)]

    def build(self, cols, aux):
        return cols[self.column] == next(aux)


class NumericInNode(FilterNode):
    def __init__(self, column: str, values: np.ndarray):
        self.column = column
        self.values = values

    def signature(self):
        return f"numin({self.column},{len(self.values)})"

    def required_device_columns(self):
        return {self.column}

    def aux_arrays(self):
        return [self.values]

    def build(self, cols, aux):
        import jax.numpy as jnp
        vals = next(aux)
        v = cols[self.column]
        return jnp.any(v[:, None] == vals[None, :], axis=1)


class TimeIntervalsNode(FilterNode):
    """__time within k intervals; offsets relative to block.time0 as aux [k,2]."""

    def __init__(self, offsets: np.ndarray):
        self.offsets = offsets.astype(np.int32)  # shape [k, 2]

    def signature(self):
        return f"timein({self.offsets.shape[0]})"

    def aux_arrays(self):
        return [self.offsets]

    def build(self, cols, aux):
        import jax.numpy as jnp
        iv = next(aux)
        t = cols["__time_offset"]
        m = (t[:, None] >= iv[None, :, 0]) & (t[:, None] < iv[None, :, 1])
        return jnp.any(m, axis=1)


class ColumnCompareNode(FilterNode):
    """dimA == dimB via remap into a merged dictionary id space."""

    def __init__(self, dims: Tuple[str, ...], remaps: List[np.ndarray]):
        self.dims = dims
        self.remaps = remaps

    def signature(self):
        return f"colcmp({','.join(self.dims)})"

    def required_device_columns(self):
        return set(self.dims)

    def aux_arrays(self):
        return list(self.remaps)

    def build(self, cols, aux):
        first = next(aux)[cols[self.dims[0]]]
        mask = None
        for d in self.dims[1:]:
            other = next(aux)[cols[d]]
            m = first == other
            mask = m if mask is None else (mask & m)
        return mask


class ExpressionNode(FilterNode):
    """Expression filter traced to XLA elementwise ops. String-dimension
    comparisons are rewritten at plan time into per-dictionary-id boolean
    LUT gathers (utils.expression.rewrite_string_sites) — the device path
    stays purely numeric."""

    def __init__(self, expression: str, time0: int, segment=None):
        from druid_tpu.utils.expression import (lut_for_site,
                                                rewrite_string_sites)
        self.expression = expression
        self.time0 = time0
        string_dims = frozenset(segment.dims) if segment is not None \
            else frozenset()
        self.expr, sites = rewrite_string_sites(
            parse_expression(expression), string_dims)
        self.luts = [lut_for_site(s, segment.dims[s[0]].dictionary.values)
                     for s in sites] if segment is not None else []

    def signature(self):
        # the REWRITTEN AST must key the jit cache: the same expression
        # string over different schemas (dim vs metric column) rewrites to
        # structurally different programs
        return f"expr({self.expr!r};l{len(self.luts)})"

    def required_device_columns(self):
        return set(self.expr.required_columns())

    def aux_arrays(self):
        return [np.asarray(self.time0, dtype=np.int64)] + list(self.luts)

    def build(self, cols, aux):
        import jax.numpy as jnp
        time0 = next(aux)
        bindings = dict(cols)
        bindings["__time"] = cols["__time_offset"].astype(jnp.int64) + time0
        bindings["__luts"] = [next(aux) for _ in self.luts]
        out = self.expr.evaluate(bindings)
        return jnp.asarray(out, dtype=bool) if hasattr(out, "shape") else (
            jnp.full((cols["__valid"].shape[0],), bool(out)))


class AndNode(FilterNode):
    def __init__(self, children: List[FilterNode]):
        self.children = children

    def signature(self):
        return "and(" + ",".join(c.signature() for c in self.children) + ")"

    def required_device_columns(self):
        out = set()
        for c in self.children:
            out |= c.required_device_columns()
        return out

    def aux_arrays(self):
        return [a for c in self.children for a in c.aux_arrays()]

    def build(self, cols, aux):
        mask = self.children[0].build(cols, aux)
        for c in self.children[1:]:
            mask = mask & c.build(cols, aux)
        return mask


class OrNode(FilterNode):
    def __init__(self, children: List[FilterNode]):
        self.children = children

    def signature(self):
        return "or(" + ",".join(c.signature() for c in self.children) + ")"

    def required_device_columns(self):
        out = set()
        for c in self.children:
            out |= c.required_device_columns()
        return out

    def aux_arrays(self):
        return [a for c in self.children for a in c.aux_arrays()]

    def build(self, cols, aux):
        mask = self.children[0].build(cols, aux)
        for c in self.children[1:]:
            mask = mask | c.build(cols, aux)
        return mask


class NotNode(FilterNode):
    def __init__(self, child: FilterNode):
        self.child = child

    def signature(self):
        return "not(" + self.child.signature() + ")"

    def required_device_columns(self):
        return self.child.required_device_columns()

    def aux_arrays(self):
        return self.child.aux_arrays()

    def build(self, cols, aux):
        return ~self.child.build(cols, aux)


class DeviceBitmapNode(FilterNode):
    """A bitmap-eligible filter subtree compiled to device bitmap algebra.

    The Roaring-informed device path (ROADMAP item 5): per-leaf row bitmaps
    ship density-adaptively (sparse id lists scatter into words ON DEVICE,
    dense leaves ship packed uint32 words) and the subtree's AND/OR/NOT
    combines as word-wise ops in a tiny jitted fill program whose output —
    the combined filter bitmap — lives in the byte-budgeted device pool,
    keyed like the jit caches (structural signature + segment identity +
    aux digest: stage_device_bitmaps). The aggregation program then reads
    the RESIDENT words under `self.col` and derives the row mask by an
    in-program bit test (a broadcast shift, no gather), so:

      * no per-wave host mask upload, no filter-only column staging — the
        words cost 1 bit/row of HBM instead of 32;
      * repeated dashboards hit resident words and skip the bitmap algebra
        entirely (query/filter/* metrics);
      * the program structure is independent of the subtree: ANY two
        bitmap filters share one jitted aggregation program AND can share
        one batched chunk — their words differ per (segment, filter), not
        per program (engine/batching.py fuses across filters).
    """

    def __init__(self, flt: F.DimFilter, segment: Segment):
        self.slot = 0                    # assigned by plan_filter post-walk
        self.leaves: List[Tuple[str, np.ndarray]] = []   # (dim, lut)
        self.structure = self._compile(flt, segment)

    def _compile(self, flt: F.DimFilter, segment: Segment):
        if isinstance(flt, F.TrueFilter):
            return ("const", True)
        if isinstance(flt, F.FalseFilter):
            return ("const", False)
        if isinstance(flt, F.AndFilter):
            return ("and", tuple(self._compile(f, segment)
                                 for f in flt.fields))
        if isinstance(flt, F.OrFilter):
            return ("or", tuple(self._compile(f, segment)
                                for f in flt.fields))
        if isinstance(flt, F.NotFilter):
            return ("not", self._compile(flt.field, segment))
        dim = flt.dimension
        pred = _string_predicate(flt)
        self.leaves.append((dim, _dictionary_lut(segment.dims[dim].dictionary,
                                                 pred)))
        return ("leaf", len(self.leaves) - 1)

    @property
    def col(self) -> str:
        return f"__fbmp{self.slot}"

    def signature(self):
        # deliberately structure-free: the aggregation program sees only
        # resident words + a bit test, so every bitmap subtree in this slot
        # shares one jitted program (the full structure keys the POOL entry
        # via structure_sig/digest instead)
        return f"devbmp({self.slot})"

    def structure_sig(self) -> str:
        def render(node):
            op = node[0]
            if op == "leaf":
                return f"leaf({self.leaves[node[1]][0]})"
            if op == "const":
                return f"const({node[1]})"
            if op == "not":
                return f"not({render(node[1])})"
            return f"{op}(" + ",".join(render(c) for c in node[1]) + ")"
        return render(self.structure)

    def digest(self) -> str:
        """Aux digest: WHICH dictionary ids each leaf matches (the LUT
        bytes). Same structure + same digests + same segment ⇒ same
        resident words — the filter-cache key contract."""
        h = hashlib.sha1(self.structure_sig().encode())
        for dim, lut in self.leaves:
            h.update(dim.encode())
            h.update(lut.tobytes())
        return h.hexdigest()[:20]

    def build(self, cols, aux):
        import jax.numpy as jnp
        w = cols[self.col]                       # uint32 [padded_rows / 32]
        sh = jnp.arange(32, dtype=jnp.uint32)
        bits = (w[:, None] >> sh[None, :]) & jnp.uint32(1)
        return bits.reshape(-1).astype(bool)


def collect_bitmap_nodes(node: Optional[FilterNode]
                         ) -> List[DeviceBitmapNode]:
    """Every DeviceBitmapNode in a planned tree, deterministic DFS order."""
    out: List[DeviceBitmapNode] = []

    def walk(n):
        if isinstance(n, DeviceBitmapNode):
            out.append(n)
        elif isinstance(n, (AndNode, OrNode)):
            for c in n.children:
                walk(c)
        elif isinstance(n, NotNode):
            walk(n.child)
    if node is not None:
        walk(node)
    return out


def assign_bitmap_slots(filter_node: Optional[FilterNode],
                        kernels: Sequence = ()) -> int:
    """Globally unique bitmap slots across ONE execution's trees: the query
    filter first, then every filtered-aggregator tree in kernel order.
    plan_filter slots each tree from 0, so a filtered aggregator's words
    would collide with the query filter's under the same `__fbmpN` name —
    this pass (called once per plan, grouping.plan_grouped_aggregate) makes
    the staged-array namespace collision-free. Returns the slot count."""
    slot = 0
    for node in collect_bitmap_nodes(filter_node):
        node.slot = slot
        slot += 1
    for k in kernels:
        for tree in k.filter_trees():
            for node in collect_bitmap_nodes(tree):
                node.slot = slot
                slot += 1
    return slot


def perm_digest(perm_key) -> Optional[str]:
    """Stable digest of a row-permutation identity (the projection cache
    key) for pool keys; None = original row order."""
    if perm_key is None:
        return None
    return hashlib.sha1(repr(perm_key).encode()).hexdigest()[:16]


def bitmap_pool_key(node: "DeviceBitmapNode", padded_rows: int,
                    perm_dig: Optional[str] = None) -> Tuple:
    """THE pool key for a filter's combined resident words: (structure
    signature, aux digest, padded rows, permutation digest). Shared by the
    staging wave below and the megakernel's residency probe
    (engine/megakernel.megaize), so the two paths cannot key-drift. The
    permutation digest (engine/grouping.py projection layouts) keys
    PERMUTED-row-order words separately from original-order words — the
    permuted path hits its own cache instead of re-planning onto the
    column path."""
    return ("fbmp", node.structure_sig(), node.digest(), padded_rows,
            perm_dig)


# ---------------------------------------------------------------------------
# String predicate → dictionary LUT
# ---------------------------------------------------------------------------

def _dictionary_lut(d: Dictionary, pred) -> np.ndarray:
    return np.fromiter((bool(pred(v)) for v in d.values), dtype=bool,
                       count=d.cardinality)


def _string_predicate(flt: F.DimFilter):
    """Value-level predicate for a single-dim string filter (used for LUTs and
    for row-level evaluation in having specs). An extraction_fn on the
    filter transforms each dictionary value BEFORE the predicate — exactly
    the reference's dimension-extraction filtering, and still one host LUT
    over the dictionary."""
    ex = getattr(flt, "extraction_fn", None)
    if ex is not None:
        import dataclasses
        base = _string_predicate(dataclasses.replace(flt,
                                                     extraction_fn=None))
        if base is None:
            return None

        def extracted(v, _base=base, _ex=ex):
            out = _ex.apply(v)
            return _base("" if out is None else out)
        return extracted
    # extension filters (e.g. bloom) expose a value_predicate() hook
    if hasattr(flt, "value_predicate"):
        return flt.value_predicate()
    if isinstance(flt, F.SelectorFilter):
        target = "" if flt.value is None else flt.value
        return lambda v: v == target
    if isinstance(flt, F.InFilter):
        vals = {("" if v is None else v) for v in flt.values}
        return lambda v: v in vals
    if isinstance(flt, F.BoundFilter):
        lo, up = flt.lower, flt.upper
        ls, us = flt.lower_strict, flt.upper_strict
        if flt.ordering == "numeric":
            def num_pred(v):
                try:
                    x = float(v)
                except (TypeError, ValueError):
                    return False
                if lo is not None:
                    l = float(lo)
                    if x < l or (ls and x == l):
                        return False
                if up is not None:
                    u = float(up)
                    if x > u or (us and x == u):
                        return False
                return True
            return num_pred

        def lex_pred(v):
            if lo is not None and (v < lo or (ls and v == lo)):
                return False
            if up is not None and (v > up or (us and v == up)):
                return False
            return True
        return lex_pred
    if isinstance(flt, F.LikeFilter):
        rx = re.compile(flt.regex())
        return lambda v: rx.match(v) is not None
    if isinstance(flt, F.RegexFilter):
        rx = re.compile(flt.pattern)
        return lambda v: rx.search(v) is not None
    if isinstance(flt, F.SearchFilter):
        if flt.case_sensitive:
            return lambda v: flt.value in v
        needle = flt.value.lower()
        return lambda v: needle in v.lower()
    if isinstance(flt, F.JavaScriptFilter):
        return flt.predicate
    return None


# ---------------------------------------------------------------------------
# Planner
# ---------------------------------------------------------------------------

def plan_filter(flt: Optional[F.DimFilter], segment: Segment,
                virtual_columns: Sequence = (),
                device_bitmap: Optional[bool] = None) -> Optional[FilterNode]:
    """device_bitmap: compile bitmap-eligible subtrees to DeviceBitmapNodes
    (None → the process default). Every execution path — per-segment,
    batched, and the sharded mesh — keeps resident bitmap words: the
    sharded stack carries them as per-segment word slots on the mapped
    axis. Filtered aggregators follow the process default
    (kernels.make_kernel), riding resident words / the fused megakernel
    like the query filter."""
    if flt is None:
        return None
    flt = flt.optimize()
    vc_types = {v.name: v.output_type for v in virtual_columns}
    use_bitmap = device_bitmap_enabled() if device_bitmap is None \
        else device_bitmap
    node = _plan(flt, segment, vc_types, use_bitmap)
    for slot, bn in enumerate(collect_bitmap_nodes(node)):
        bn.slot = slot
    return node


def _bitmap_compilable(flt: F.DimFilter, segment: Segment) -> bool:
    """Whole subtree is bitmap-algebra material AND touches at least one
    real indexed dimension (pure-constant subtrees fold to ConstNodes —
    cheaper than words)."""
    if not can_use_bitmap(flt, segment):
        return False

    def has_leaf(f):
        if isinstance(f, (F.AndFilter, F.OrFilter)):
            return any(has_leaf(x) for x in f.fields)
        if isinstance(f, F.NotFilter):
            return has_leaf(f.field)
        return getattr(f, "dimension", None) in segment.dims
    return has_leaf(flt)


def _plan(flt: F.DimFilter, segment: Segment,
          vc_types: Optional[Dict[str, str]] = None,
          use_bitmap: bool = False) -> FilterNode:
    vc_types = vc_types or {}
    if isinstance(flt, F.TrueFilter):
        return ConstNode(True)
    if isinstance(flt, F.FalseFilter):
        return ConstNode(False)
    if use_bitmap and _bitmap_compilable(flt, segment):
        # maximal eligible subtree → resident device bitmap words; partial
        # trees recurse and wrap their eligible branches below
        return DeviceBitmapNode(flt, segment)
    if isinstance(flt, F.AndFilter):
        return AndNode([_plan(f, segment, vc_types, use_bitmap)
                        for f in flt.fields])
    if isinstance(flt, F.OrFilter):
        return OrNode([_plan(f, segment, vc_types, use_bitmap)
                       for f in flt.fields])
    if isinstance(flt, F.NotFilter):
        return NotNode(_plan(flt.field, segment, vc_types, use_bitmap))
    if isinstance(flt, F.IntervalFilter):
        if flt.dimension != "__time":
            raise ValueError("interval filter supported on __time only")
        t0 = segment.interval.start
        offs = np.asarray(
            [[max(iv.start - t0, -(2**31) + 1), min(iv.end - t0, 2**31 - 1)]
             for iv in flt.intervals], dtype=np.int64).clip(-(2**31) + 1, 2**31 - 1)
        return TimeIntervalsNode(offs.astype(np.int32))
    if isinstance(flt, F.ColumnComparisonFilter):
        dicts = []
        for d in flt.dimensions:
            col = segment.dims.get(d)
            if col is None:
                raise ValueError(f"columnComparison on non-string dim {d!r}")
            dicts.append(col.dictionary)
        _, remaps = merge_dictionaries(dicts)
        return ColumnCompareNode(flt.dimensions, remaps)
    if isinstance(flt, F.ExpressionFilter):
        return ExpressionNode(flt.expression, segment.interval.start, segment)

    # single-column leaf filters
    dim = getattr(flt, "dimension", None)
    if dim is None:
        raise ValueError(f"cannot plan filter {flt!r}")
    if dim in segment.dims:
        d = segment.dims[dim].dictionary
        pred = _string_predicate(flt)
        if pred is None:
            raise ValueError(f"cannot plan string filter {flt!r}")
        # bound filters on sorted dictionaries could use id ranges
        # (Dictionary.id_range); the LUT is equally one gather so we keep
        # the uniform mechanism.
        return LutNode(dim, _dictionary_lut(d, pred))
    if getattr(flt, "extraction_fn", None) is not None:
        # numeric/time columns have no dictionary to transform
        raise ValueError(
            f"extractionFn filter on non-string column [{dim}]")
    # numeric column (metric) or __time
    if dim == "__time":
        dtype, colname = np.int32, "__time_offset"
        # clip to the int32 offset range (bounds far outside the segment's
        # interval still compare correctly after clipping)
        conv = lambda s: min(max(int(s) - segment.interval.start,
                                 -(2**31) + 1), 2**31 - 2)
    elif dim in segment.metrics:
        vt = segment.metrics[dim].type
        # compare in the column's STAGED dtype — an int64 constant against
        # an int32-narrowed column would promote the whole compare to
        # emulated 64-bit ops on device
        dtype, colname = segment.staged_dtype(dim), dim
        conv = (int if vt == ValueType.LONG else float)
        if vt == ValueType.LONG and dtype == np.int32:
            # constants outside int32 have constant outcomes (every value
            # fits int32 — that is why the column staged narrow)
            return _plan_narrow_long(flt, colname)
    elif dim in vc_types:
        t = vc_types[dim]
        dtype = {"long": np.int64, "float": np.float32}.get(t, np.float64)
        colname = dim
        conv = (int if t == "long" else float)
    else:
        # missing column: selector of null matches all rows, else none
        if isinstance(flt, F.SelectorFilter) and (flt.value is None or flt.value == ""):
            return ConstNode(True)
        return ConstNode(False)

    if isinstance(flt, F.SelectorFilter):
        if flt.value is None:
            return ConstNode(False)
        return NumericEqNode(colname, conv(flt.value), dtype)
    if isinstance(flt, F.InFilter):
        vals = np.asarray([conv(v) for v in flt.values if v is not None], dtype=dtype)
        return NumericInNode(colname, vals)
    if isinstance(flt, F.BoundFilter):
        lo = conv(flt.lower) if flt.lower is not None else None
        hi = conv(flt.upper) if flt.upper is not None else None
        return NumericCmpNode(colname, lo, hi, flt.lower_strict, flt.upper_strict,
                              dtype)
    raise ValueError(f"cannot plan filter {type(flt).__name__} on numeric column")


_I32_MIN, _I32_MAX = -(2**31), 2**31 - 1


def _plan_narrow_long(flt: F.DimFilter, colname: str) -> FilterNode:
    """Numeric filters over int32-staged long columns: in-range constants
    compare in int32; out-of-range constants fold to constants."""
    if isinstance(flt, F.SelectorFilter):
        if flt.value is None:
            return ConstNode(False)
        v = int(flt.value)
        if not (_I32_MIN <= v <= _I32_MAX):
            return ConstNode(False)
        return NumericEqNode(colname, v, np.int32)
    if isinstance(flt, F.InFilter):
        vals = [int(v) for v in flt.values if v is not None]
        vals = [v for v in vals if _I32_MIN <= v <= _I32_MAX]
        if not vals:
            return ConstNode(False)
        return NumericInNode(colname, np.asarray(vals, dtype=np.int32))
    if isinstance(flt, F.BoundFilter):
        lo = int(flt.lower) if flt.lower is not None else None
        hi = int(flt.upper) if flt.upper is not None else None
        if lo is not None and lo > _I32_MAX:
            return ConstNode(False)       # nothing is that large
        if hi is not None and hi < _I32_MIN:
            return ConstNode(False)
        if lo is not None and lo < _I32_MIN:
            lo = None                      # everything passes the lower bound
        if hi is not None and hi > _I32_MAX:
            hi = None
        if lo is None and hi is None:
            return ConstNode(True)
        return NumericCmpNode(colname, lo, hi, flt.lower_strict,
                              flt.upper_strict, np.int32)
    raise ValueError(f"cannot plan filter {type(flt).__name__} on numeric column")


# ---------------------------------------------------------------------------
# Host bitmap-index path (reference: Filter.getBitmapIndex)
# ---------------------------------------------------------------------------

def can_use_bitmap(flt: F.DimFilter, segment: Segment) -> bool:
    if isinstance(flt, (F.TrueFilter, F.FalseFilter)):
        return True
    if isinstance(flt, (F.AndFilter, F.OrFilter)):
        return all(can_use_bitmap(f, segment) for f in flt.fields)
    if isinstance(flt, F.NotFilter):
        return can_use_bitmap(flt.field, segment)
    dim = getattr(flt, "dimension", None)
    return dim in segment.dims and _string_predicate(flt) is not None


def bitmap_of(flt: F.DimFilter, segment: Segment) -> AnyBitmap:
    """Evaluate an indexable filter purely via bitmap algebra. Results are
    density-adaptive (data/bitmap.py): low-density operands stay sparse id
    lists through AND/OR/XOR — a SparseBitmap is never densified except by
    complement, whose result is inherently dense."""
    n = segment.n_rows
    if isinstance(flt, F.TrueFilter):
        return Bitmap.full(n)
    if isinstance(flt, F.FalseFilter):
        return SparseBitmap(np.zeros(0, dtype=np.int32), n)
    if isinstance(flt, F.AndFilter):
        parts = [bitmap_of(f, segment) for f in flt.fields]
        return functools.reduce(bitmap_and, parts) if parts \
            else Bitmap.full(n)
    if isinstance(flt, F.OrFilter):
        parts = [bitmap_of(f, segment) for f in flt.fields]
        return functools.reduce(bitmap_or, parts) if parts \
            else SparseBitmap(np.zeros(0, dtype=np.int32), n)
    if isinstance(flt, F.NotFilter):
        return ~bitmap_of(flt.field, segment)
    dim = flt.dimension
    col = segment.dims[dim]
    pred = _string_predicate(flt)
    lut = _dictionary_lut(col.dictionary, pred)
    matching = np.flatnonzero(lut)
    index = col.bitmap_index()
    return index.union_of(matching)


def filter_cardinality(flt: F.DimFilter, segment: Segment) -> int:
    """EXACT matching-row count of a bitmap-eligible filter. NOT computes
    as n - |child| — the complement bitmap is never materialized, so
    NOT-of-sparse costs the sparse child only."""
    n = segment.n_rows
    if isinstance(flt, F.TrueFilter):
        return n
    if isinstance(flt, F.FalseFilter):
        return 0
    if isinstance(flt, F.NotFilter):
        return n - filter_cardinality(flt.field, segment)
    return bitmap_of(flt, segment).cardinality()


def estimate_selectivity(flt: Optional[F.DimFilter], segment: Segment) -> float:
    """Fraction of rows expected to match (reference:
    Filter.estimateSelectivity); exact when bitmap-indexable."""
    if flt is None:
        return 1.0
    if segment.n_rows == 0:
        return 0.0
    if can_use_bitmap(flt, segment):
        return filter_cardinality(flt, segment) / segment.n_rows
    return 1.0


# ---------------------------------------------------------------------------
# Device bitmap staging + the filter-result cache
# ---------------------------------------------------------------------------

class FilterBitmapStats:
    """Filter-cache counters behind query/filter/* (FilterBitmapMonitor).
    hits/misses count RESULT-words pool probes (a hit skips leaf staging
    and the algebra fill entirely); built_bytes are the device bitmap bytes
    materialized on misses."""

    def __init__(self):
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.built_bytes = 0

    def record(self, hit: bool, nbytes: int = 0) -> None:
        with self._lock:
            if hit:
                self.hits += 1
            else:
                self.misses += 1
                self.built_bytes += nbytes

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return {"hits": self.hits, "misses": self.misses,
                    "builtBytes": self.built_bytes}

    @property
    def hit_rate(self) -> float:
        with self._lock:
            total = self.hits + self.misses
            return self.hits / total if total else 0.0


_FBMP_STATS = FilterBitmapStats()


def filter_bitmap_stats() -> FilterBitmapStats:
    return _FBMP_STATS


class FilterBitmapMonitor(Monitor):
    """Emits query/filter/{deviceBitmapHits,deviceBitmapMisses,bytes} per
    tick (deltas over the tick window, the DevicePoolMonitor discipline)."""

    def __init__(self, source: Optional[FilterBitmapStats] = None):
        self.source = source or _FBMP_STATS
        self._last = self.source.snapshot()

    def do_monitor(self, emitter):
        s = self.source.snapshot()
        last, self._last = self._last, s
        emitter.metric("query/filter/deviceBitmapHits",
                       s["hits"] - last["hits"])
        emitter.metric("query/filter/deviceBitmapMisses",
                       s["misses"] - last["misses"])
        emitter.metric("query/filter/bytes",
                       s["builtBytes"] - last["builtBytes"])


# Jitted bitmap-algebra fill programs, keyed on (structure, leaf reprs, Rw):
# LRU-bounded + locked like grouping._JIT_CACHE (broker thread-pool fan-out).
# Leaf reprs/rungs are pow2-quantized (device_repr), so the key space stays
# coarse the way pack descriptors do.
_FBMP_JIT_CACHE: "collections.OrderedDict[Tuple, object]" = \
    collections.OrderedDict()
_FBMP_JIT_CACHE_CAP = 64
_FBMP_JIT_CACHE_LOCK = threading.Lock()


def combine_structure_words(structure, leaf_words, const_words):
    """THE word-domain algebra evaluator: AND/OR/NOT/XOR over whatever
    `leaf_words(index)` / `const_words(bool)` return. Shared by the fill
    program below AND the megakernel's inline path
    (engine/megakernel.MegaBitmapNode.words_traced), so the staged and
    fused paths cannot drift — their bit-parity contract is structural."""
    def ev(node):
        op = node[0]
        if op == "leaf":
            return leaf_words(node[1])
        if op == "const":
            return const_words(node[1])
        if op == "not":
            return ~ev(node[1])
        kids = [ev(c) for c in node[1]]
        out = kids[0]
        for k in kids[1:]:
            out = (out & k) if op == "and" else \
                (out | k) if op == "or" else (out ^ k)
        return out

    return ev(structure)


def _eval_structure(structure, kinds: Tuple, leaves: Tuple, Rw: int):
    """Traced word-wise bitmap algebra: leaves arrive as device arrays
    (dense uint32 words, sparse int32 id lists scattered into words
    in-program — distinct ids set distinct bits, so scatter-add IS
    bitwise-or; padding ids equal padded_rows and drop out of bounds — or
    RLE run tables whose per-RUN match bit was decided host-side once per
    run and expands to rows by a searchsorted over run ends), and
    AND/OR/NOT/XOR combine word-wise on the VPU. Output: uint32 [Rw]."""
    import jax.numpy as jnp

    def leaf_words(i):
        if kinds[i][0] == "dense":
            return leaves[i]
        if kinds[i][0] == "runs":
            # RLE-run-aware leaf (data/cascade.py run tables): column 0 =
            # EXCLUSIVE run ends (+ a 2^31-1 sentinel run covering
            # padding, match 0), column 1 = the per-run match decided ONCE
            # per run. Ships 8 bytes/run instead of 1 bit/row.
            ends = leaves[i][:, 0]
            match = leaves[i][:, 1]
            iota = jnp.arange(Rw * 32, dtype=jnp.int32)
            idx = jnp.clip(jnp.searchsorted(ends, iota, side="right"),
                           0, ends.shape[0] - 1)
            bits = (match[idx] > 0).astype(jnp.uint32).reshape(-1, 32)
            w = bits[:, 0]
            for s in range(1, 32):
                w = w | (bits[:, s] << jnp.uint32(s))
            return w
        ids = leaves[i]
        bit = jnp.uint32(1) << (ids & 31).astype(jnp.uint32)
        return jnp.zeros((Rw,), jnp.uint32).at[ids >> 5].add(bit, mode="drop")

    def const_words(value):
        fill = np.uint32(0xFFFFFFFF) if value else np.uint32(0)
        return jnp.full((Rw,), fill, jnp.uint32)

    return combine_structure_words(structure, leaf_words, const_words)


def _build_fill_fn(structure, kinds: Tuple, Rw: int):
    """One filter's fill program (unit-testable single case)."""
    import jax
    return jax.jit(lambda leaves: _eval_structure(structure, kinds, leaves,
                                                  Rw))


def _build_fill_multi(structures: Tuple, kinds_per: Tuple, Rw: int):
    """The BATCHED fill program: every cold (segment, filter) pair of a
    staging wave computes its words inside ONE dispatch — the same
    unroll-don't-loop discipline as engine/batching.py (a per-miss fill
    dispatch would hand the host-mask path back its dispatch advantage on
    cold dashboards)."""
    import jax

    def fn(leaves_per: Tuple):
        return tuple(_eval_structure(s, k, l, Rw)
                     for s, k, l in zip(structures, kinds_per, leaves_per))

    return jax.jit(fn)


def _leaf_digest(lut: np.ndarray) -> str:
    return hashlib.sha1(lut.tobytes()).hexdigest()[:16]


def _permuted_bitmap(segment: Segment, bm: AnyBitmap,
                     perm: np.ndarray, perm_key) -> AnyBitmap:
    """Reorder a row bitmap into a permuted (projection) row layout. Sparse
    bitmaps stay sparse: ids map through the cached inverse permutation."""
    if isinstance(bm, SparseBitmap):
        inv = segment.aux_cached(
            ("perm_inv", perm_digest(perm_key)),
            lambda: np.argsort(perm, kind="stable").astype(np.int32))
        return SparseBitmap(np.sort(inv[bm.ids]), bm.n_rows)
    return Bitmap.from_bool(bm.to_bool()[perm])


def _run_leaf_payload(segment: Segment, dim: str, lut: np.ndarray,
                      padded_rows: int) -> Optional[np.ndarray]:
    """RLE-run-aware leaf payload: int32 [Rpad, 2] of (EXCLUSIVE run end —
    start-of-next-run index — and per-run match) when `dim` is run-compressible enough that the run
    table undercuts both bitmap representations (data/cascade.py run
    info), else None. The match bit is decided ONCE PER RUN (one LUT
    gather over run values) instead of once per row; a 2^31-1-end
    sentinel run covers padding rows with match 0."""
    from druid_tpu.data import cascade as cascade_mod
    if not cascade_mod.enabled():
        return None
    # beat the dense words (padded_rows/32 uint32) with clear margin
    info = cascade_mod.column_run_info(segment, dim,
                                       max_runs=padded_rows // 256)
    if info is None:
        return None
    values, ends, nr = info
    rpad = cascade_mod.pad_pow2(nr + 1)
    payload = np.zeros((rpad, 2), dtype=np.int32)
    payload[:, 0] = 2**31 - 1            # sentinel tail (match 0)
    payload[:nr, 0] = ends
    payload[:nr, 1] = lut[values]
    return payload


def _leaf_arrays(segment: Segment, node: DeviceBitmapNode,
                 padded_rows: int, perm: Optional[np.ndarray] = None,
                 perm_key=None) -> Tuple[Tuple, Tuple]:
    """(kinds, device leaf payloads) for one node: leaf bitmaps come from
    the host index and ship density-adaptively — RLE run tables when the
    dim is run-compressed (match decided once per run, data/cascade.py),
    else sparse ids or dense words — pool-resident per leaf.
    `perm` reorders rows into a projection layout before packing; the
    permutation digest keys those entries separately."""
    import jax

    pdg = perm_digest(perm_key)
    kinds: List[Tuple] = []
    arrays = []
    for dim, lut in node.leaves:
        payload = None
        if perm is None:
            payload = _run_leaf_payload(segment, dim, lut, padded_rows)
        if payload is not None:
            kind = "runs"
        else:
            col = segment.dims[dim]
            bm = col.bitmap_index().union_of(np.flatnonzero(lut))
            if perm is not None:
                bm = _permuted_bitmap(segment, bm, perm, perm_key)
            kind, payload = device_repr(bm, padded_rows)
        kinds.append((kind, payload.shape[0]))
        lkey = ("fbmpleaf", dim, _leaf_digest(lut), padded_rows, kind,
                payload.shape[0], pdg)
        arrays.append(segment.device_cached(
            lkey, lambda p=payload: jax.device_put(p)))
    return tuple(kinds), tuple(arrays)


def _item_nodes(filter_node: Optional[FilterNode],
                kernels: Sequence) -> List[DeviceBitmapNode]:
    """One item's stageable nodes: the query filter's plus every filtered
    aggregator's (kernels plan bitmap words too — AggKernel.filter_trees)."""
    nodes = collect_bitmap_nodes(filter_node)
    for k in kernels:
        for tree in k.filter_trees():
            nodes.extend(collect_bitmap_nodes(tree))
    return nodes


def stage_device_bitmaps_multi(items: Sequence[Tuple],
                               padded_rows: int) -> List[Dict[str, object]]:
    """Resident filter-bitmap words for a whole staging wave: one
    {node.col: uint32 words [padded_rows/32]} dict per item, to merge into
    each slot's arrays. Items are (segment, filter_node) or (segment,
    filter_node, kernels) — filtered aggregators' trees stage exactly like
    the query filter's. Results live in the byte-budgeted device pool
    keyed (filter structural signature, aux digest, padded rows,
    permutation digest) per segment — warm probes skip leaf
    materialization AND the algebra (query/filter/deviceBitmapHits); ALL
    of the wave's cold misses fill in a single batched dispatch."""
    out: List[Dict[str, object]] = [{} for _ in items]
    pending = []          # (slot, segment, node, pool key)
    # identical (segment, key) pairs within one wave — N fused copies of
    # the same dashboard query — build ONCE and fan out (the duplicates
    # count as hits: they are served without leaf work or algebra)
    wave_dups: Dict[Tuple, List[Tuple[int, str]]] = {}
    for i, item in enumerate(items):
        segment, filter_node = item[0], item[1]
        kernels = item[2] if len(item) > 2 else ()
        for node in _item_nodes(filter_node, kernels):
            key = bitmap_pool_key(node, padded_rows)
            wkey = (id(segment), key)
            if wkey in wave_dups:
                _FBMP_STATS.record(True)
                wave_dups[wkey].append((i, node.col))
                continue
            hit = segment.device_contains(key)
            _FBMP_STATS.record(hit, 0 if hit else padded_rows // 8)
            if hit:
                # the build lambda never runs on a hit; a racing eviction
                # just lands this entry in the cold wave's semantics
                out[i][node.col] = segment.device_cached(
                    key, lambda s=segment, n=node: _fill_single(
                        s, n, padded_rows))
            else:
                wave_dups[wkey] = []
                pending.append((i, segment, node, key))
    if not pending:
        return out

    from druid_tpu.obs import dispatch as dispatch_mod
    Rw = padded_rows // 32
    kinds_per, leaves_per = [], []
    for _, segment, node, _ in pending:
        kinds, arrays = _leaf_arrays(segment, node, padded_rows)
        kinds_per.append(kinds)
        leaves_per.append(arrays)
    structures = tuple(node.structure for _, _, node, _ in pending)
    jkey = (structures, tuple(kinds_per), Rw)
    with _FBMP_JIT_CACHE_LOCK:
        fn = _FBMP_JIT_CACHE.get(jkey)
        if fn is None:
            fn = _build_fill_multi(structures, tuple(kinds_per), Rw)
            _FBMP_JIT_CACHE[jkey] = fn
            while len(_FBMP_JIT_CACHE) > _FBMP_JIT_CACHE_CAP:
                _FBMP_JIT_CACHE.popitem(last=False)
        else:
            _FBMP_JIT_CACHE.move_to_end(jkey)
    words_per = fn(tuple(leaves_per))
    dispatch_mod.record("filterFill")    # successful dispatches only
    for (i, segment, node, key), words in zip(pending, words_per):
        resident = segment.device_cached(key, lambda w=words: w)
        out[i][node.col] = resident
        for j, col in wave_dups.get((id(segment), key), ()):
            out[j][col] = resident
    return out


def _fill_single(segment: Segment, node: DeviceBitmapNode,
                 padded_rows: int, perm: Optional[np.ndarray] = None,
                 perm_key=None):
    """One (segment, filter) fill — the pool-miss build path when a probe
    said hit but the entry was evicted before device_cached re-read it,
    and the permuted-layout (projection) staging path."""
    from druid_tpu.obs import dispatch as dispatch_mod
    kinds, arrays = _leaf_arrays(segment, node, padded_rows, perm=perm,
                                 perm_key=perm_key)
    key = (node.structure, kinds, padded_rows // 32)
    with _FBMP_JIT_CACHE_LOCK:
        fn = _FBMP_JIT_CACHE.get(key)
        if fn is None:
            fn = _build_fill_fn(node.structure, kinds, padded_rows // 32)
            _FBMP_JIT_CACHE[key] = fn
            while len(_FBMP_JIT_CACHE) > _FBMP_JIT_CACHE_CAP:
                _FBMP_JIT_CACHE.popitem(last=False)
        else:
            _FBMP_JIT_CACHE.move_to_end(key)
    words = fn(arrays)
    dispatch_mod.record("filterFill")    # successful dispatches only
    return words


def stage_device_bitmaps(segment: Segment,
                         filter_node: Optional[FilterNode],
                         padded_rows: int, kernels: Sequence = (),
                         perm: Optional[np.ndarray] = None,
                         perm_key=None) -> Dict[str, object]:
    """Single-segment staging. Without a permutation this is the wave path
    for one item; with one (the projection layout), every node stages
    PERMUTED words under its own (key, permutation digest) pool entries —
    the projection path hits its cache instead of falling back to the
    column path."""
    if perm is None:
        return stage_device_bitmaps_multi(
            [(segment, filter_node, kernels)], padded_rows)[0]
    pdg = perm_digest(perm_key)
    out: Dict[str, object] = {}
    for node in _item_nodes(filter_node, kernels):
        key = bitmap_pool_key(node, padded_rows, pdg)
        hit = segment.device_contains(key)
        _FBMP_STATS.record(hit, 0 if hit else padded_rows // 8)
        out[node.col] = segment.device_cached(
            key, lambda s=segment, n=node: _fill_single(
                s, n, padded_rows, perm=perm, perm_key=perm_key))
    return out


# ---------------------------------------------------------------------------
# Row-level evaluation (having specs over result rows)
# ---------------------------------------------------------------------------

def evaluate_filter_on_row(flt: F.DimFilter, row: Dict[str, object]) -> bool:
    if isinstance(flt, F.TrueFilter):
        return True
    if isinstance(flt, F.FalseFilter):
        return False
    if isinstance(flt, F.AndFilter):
        return all(evaluate_filter_on_row(f, row) for f in flt.fields)
    if isinstance(flt, F.OrFilter):
        return any(evaluate_filter_on_row(f, row) for f in flt.fields)
    if isinstance(flt, F.NotFilter):
        return not evaluate_filter_on_row(flt.field, row)
    pred = _string_predicate(flt)
    if pred is None:
        raise ValueError(f"cannot row-evaluate {flt!r}")
    v = row.get(flt.dimension)
    return pred("" if v is None else str(v))


# ---------------------------------------------------------------------------
# Host-side full mask evaluation (scan / search / timeBoundary paths)
# ---------------------------------------------------------------------------

def _bind_string_dims(expr, segment: Segment, bindings: Dict) -> None:
    """Bind every string dim an expression references as a DECODED value
    array — host-path numpy string comparison matches the reference's
    lexicographic semantics directly."""
    for c in expr.required_columns():
        if c in segment.dims and c not in bindings:
            col = segment.dims[c]
            vals = np.asarray(list(col.dictionary.values), dtype=object)
            # bindings is a per-call accumulator scoped to ONE segment —
            # the caller builds it fresh for each host_mask evaluation
            bindings[c] = vals[col.ids]  # druidlint: disable=unkeyed-trace-input


def host_mask(flt: Optional[F.DimFilter], segment: Segment,
              virtual_columns: Sequence = ()) -> np.ndarray:
    """Evaluate a filter to a host boolean row mask with vectorized numpy —
    used by the row-export engines (scan/select), search, and timeBoundary,
    where results are host-side anyway."""
    n = segment.n_rows
    if flt is None:
        return np.ones(n, dtype=bool)
    flt = flt.optimize()
    vc_arrays = {}
    if virtual_columns:
        bindings = {"__time": segment.time_ms}
        for name, m in segment.metrics.items():
            bindings[name] = m.values
        for v in virtual_columns:
            expr = parse_expression(v.expression)
            _bind_string_dims(expr, segment, bindings)
            arr = expr.evaluate(bindings)
            vc_arrays[v.name] = np.broadcast_to(np.asarray(arr), (n,))
            bindings[v.name] = vc_arrays[v.name]
    return _host_mask(flt, segment, vc_arrays)


def _host_mask(flt: F.DimFilter, segment: Segment,
               vc_arrays: Optional[Dict[str, np.ndarray]] = None) -> np.ndarray:
    vc_arrays = vc_arrays or {}
    n = segment.n_rows
    if isinstance(flt, F.TrueFilter):
        return np.ones(n, dtype=bool)
    if isinstance(flt, F.FalseFilter):
        return np.zeros(n, dtype=bool)
    if isinstance(flt, F.AndFilter):
        out = np.ones(n, dtype=bool)
        for f in flt.fields:
            out &= _host_mask(f, segment, vc_arrays)
        return out
    if isinstance(flt, F.OrFilter):
        out = np.zeros(n, dtype=bool)
        for f in flt.fields:
            out |= _host_mask(f, segment, vc_arrays)
        return out
    if isinstance(flt, F.NotFilter):
        return ~_host_mask(flt.field, segment, vc_arrays)
    if isinstance(flt, F.IntervalFilter):
        t = segment.time_ms
        out = np.zeros(n, dtype=bool)
        for iv in flt.intervals:
            out |= (t >= iv.start) & (t < iv.end)
        return out
    if isinstance(flt, F.ColumnComparisonFilter):
        dicts = [segment.dims[d].dictionary for d in flt.dimensions]
        _, remaps = merge_dictionaries(dicts)
        first = remaps[0][segment.dims[flt.dimensions[0]].ids]
        out = np.ones(n, dtype=bool)
        for d, remap in zip(flt.dimensions[1:], remaps[1:]):
            out &= first == remap[segment.dims[d].ids]
        return out
    if isinstance(flt, F.ExpressionFilter):
        expr = parse_expression(flt.expression)
        bindings = {"__time": segment.time_ms}
        for name, m in segment.metrics.items():
            bindings[name] = m.values
        _bind_string_dims(expr, segment, bindings)
        bindings.update(vc_arrays)
        out = expr.evaluate(bindings)
        return np.broadcast_to(np.asarray(out, dtype=bool), (n,)).copy()

    dim = getattr(flt, "dimension", None)
    if dim in segment.dims:
        col = segment.dims[dim]
        pred = _string_predicate(flt)
        lut = _dictionary_lut(col.dictionary, pred)
        return lut[col.ids]
    if dim == "__time" or dim in segment.metrics or dim in vc_arrays:
        if dim == "__time":
            vals = segment.time_ms
        elif dim in segment.metrics:
            vals = segment.metrics[dim].values
        else:
            vals = vc_arrays[dim]
        conv = int if (dim == "__time"
                       or (dim in segment.metrics
                           and segment.metrics[dim].type == ValueType.LONG)
                       or (dim in vc_arrays
                           and np.issubdtype(vals.dtype, np.integer))) else float
        if isinstance(flt, F.SelectorFilter):
            if flt.value is None:
                return np.zeros(n, dtype=bool)
            return vals == conv(flt.value)
        if isinstance(flt, F.InFilter):
            targets = np.asarray([conv(v) for v in flt.values if v is not None])
            return np.isin(vals, targets)
        if isinstance(flt, F.BoundFilter):
            out = np.ones(n, dtype=bool)
            if flt.lower is not None:
                lo = conv(flt.lower)
                out &= (vals > lo) if flt.lower_strict else (vals >= lo)
            if flt.upper is not None:
                hi = conv(flt.upper)
                out &= (vals < hi) if flt.upper_strict else (vals <= hi)
            return out
        raise ValueError(f"cannot host-evaluate {type(flt).__name__} on numeric")
    # missing column
    if isinstance(flt, F.SelectorFilter) and (flt.value is None or flt.value == ""):
        return np.ones(n, dtype=bool)
    return np.zeros(n, dtype=bool)


def simplify_node(node: Optional[FilterNode]) -> Optional[FilterNode]:
    """Fold ConstNodes out of a planned tree. Returns None (no filter),
    a ConstNode(False) root (caller short-circuits without a device call —
    constant-mask programs also crash some TPU compiler backends), or a
    const-free tree."""
    if node is None:
        return None
    node = _simplify(node)
    if isinstance(node, ConstNode) and node.value:
        return None
    return node


def _simplify(node: FilterNode) -> FilterNode:
    if isinstance(node, AndNode):
        kids = []
        for c in node.children:
            c = _simplify(c)
            if isinstance(c, ConstNode):
                if not c.value:
                    return ConstNode(False)
                continue
            kids.append(c)
        if not kids:
            return ConstNode(True)
        return kids[0] if len(kids) == 1 else AndNode(kids)
    if isinstance(node, OrNode):
        kids = []
        for c in node.children:
            c = _simplify(c)
            if isinstance(c, ConstNode):
                if c.value:
                    return ConstNode(True)
                continue
            kids.append(c)
        if not kids:
            return ConstNode(False)
        return kids[0] if len(kids) == 1 else OrNode(kids)
    if isinstance(node, NotNode):
        c = _simplify(node.child)
        if isinstance(c, ConstNode):
            return ConstNode(not c.value)
        return NotNode(c)
    return node


# ---------------------------------------------------------------------------
# Row-level evaluation (host): used by ingest-time TransformSpec filters and
# having specs — the analog of the reference's ValueMatcher path
# (query/filter/ValueMatcher.java) for rows that are not yet columnar.
# ---------------------------------------------------------------------------

def make_row_matcher(flt: F.DimFilter):
    """Compile a DimFilter into row(dict)->bool over raw (pre-dictionary)
    values. Dims are strings (None ≡ ""), metrics numeric, __time millis."""
    if isinstance(flt, F.TrueFilter):
        return lambda row: True
    if isinstance(flt, F.FalseFilter):
        return lambda row: False
    if isinstance(flt, F.AndFilter):
        subs = [make_row_matcher(f) for f in flt.fields]
        return lambda row: all(m(row) for m in subs)
    if isinstance(flt, F.OrFilter):
        subs = [make_row_matcher(f) for f in flt.fields]
        return lambda row: any(m(row) for m in subs)
    if isinstance(flt, F.NotFilter):
        sub = make_row_matcher(flt.field)
        return lambda row: not sub(row)
    if isinstance(flt, F.IntervalFilter):
        ivs = flt.intervals
        col = flt.dimension

        def iv_match(row):
            v = row.get(col)
            if v is None:
                return False
            try:
                ms = int(float(v))
            except (TypeError, ValueError):
                return False
            return any(iv.contains(ms) for iv in ivs)
        return iv_match
    if isinstance(flt, F.ColumnComparisonFilter):
        dims = flt.dimensions

        def cc_match(row):
            vals = [("" if row.get(d) is None else str(row.get(d)))
                    for d in dims]
            return all(v == vals[0] for v in vals)
        return cc_match
    if isinstance(flt, F.ExpressionFilter):
        expr = parse_expression(flt.expression)

        def ex_match(row):
            # None ≡ "" — the same null contract as every other row matcher.
            # A numeric expr over a null-bound column raises (e.g. "" > 2);
            # such rows simply don't match, as in the reference.
            try:
                out = expr.evaluate({k: ("" if v is None else v)
                                     for k, v in row.items()})
            except (TypeError, ValueError):
                return False
            try:
                return bool(float(out))
            except (TypeError, ValueError):
                return bool(out)
        return ex_match
    pred = _string_predicate(flt)
    if pred is not None:
        dim = flt.dimension

        def s_match(row):
            v = row.get(dim)
            return pred("" if v is None else str(v))
        return s_match
    raise ValueError(f"cannot row-match filter {type(flt).__name__}")
