"""Per-host query executor: dispatch queries over local segments.

Reference analog: the historical's ServerManager + QueryRunnerFactory stack
(server/src/main/java/org/apache/druid/server/coordination/ServerManager.java:207
— timeline lookup, per-segment runners, mergeRunners on the processing pool).

TPU-first: no thread-pool of per-segment runners — each segment executes as
one device program (already internally parallel on the chip), results merge
vectorized on host (druid_tpu/engine/merge.py) or via collectives
(druid_tpu/parallel/). The executor owns the jit cache implicitly via
grouping._JIT_CACHE (specialization-by-shape, the reference's
SpecializationService analog).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from dataclasses import replace

from druid_tpu.data.segment import Segment
from druid_tpu.engine import engines
from druid_tpu.query.model import (DataSourceMetadataQuery, GroupByQuery, Query,
                                   ScanQuery, SearchQuery, SegmentMetadataQuery,
                                   SelectQuery, TimeBoundaryQuery,
                                   TimeseriesQuery, TopNQuery, query_from_json)
from druid_tpu.utils.intervals import (condense, parse_period_ms,
                                       split_by_period)


def apply_interval_chunking(query: Query) -> Query:
    """Honor the `chunkPeriod` query context: split long intervals into
    aligned per-period chunks (IntervalChunkingQueryRunner.java:67-133).
    The engine evaluates every interval in ONE device program — the time
    mask is a fused elementwise op over the chunk list — so chunking here
    is a semantics/caching surface, not the parallelism vehicle it is on
    the reference's processing pools."""
    p = query.context_map.get("chunkPeriod")
    if not p:
        return query
    period = parse_period_ms(p)
    chunks: list = []
    for iv in condense(query.intervals):
        chunks.extend(split_by_period(iv, period))
    if tuple(chunks) == tuple(query.intervals):
        return query
    return replace(query, intervals=tuple(chunks))


class QueryExecutor:
    """Runs queries over an in-process set of segments, grouped by datasource."""

    def __init__(self, segments: Optional[Sequence[Segment]] = None,
                 mesh=None, device_pool_bytes: Optional[int] = None):
        """`mesh`: optional jax.sharding.Mesh — when set, eligible grouped
        aggregations run as one sharded device program over it (the
        processing-pool analog, DruidProcessingModule.java:115). Without a
        mesh, shape-compatible segments batch into one device dispatch per
        shape bucket (engine/batching.py; disable per query with context
        {"batchSegments": false}).

        `device_pool_bytes`: optional HBM budget for the process-wide
        device segment pool (staged blocks LRU-evict by actual bytes past
        it); None keeps the current/default budget."""
        self._by_ds: Dict[str, List[Segment]] = {}
        self.mesh = mesh
        if device_pool_bytes is not None:
            from druid_tpu.data.devicepool import device_pool
            device_pool().configure(device_pool_bytes)
        for s in segments or ():
            self.add_segment(s)

    # ---- segment management (ServerManager.loadSegment/dropSegment analog)
    def add_segment(self, segment: Segment):
        self._by_ds.setdefault(segment.id.datasource, []).append(segment)

    def drop_segment(self, segment_id) -> bool:
        for ds, segs in self._by_ds.items():
            for s in list(segs):
                if s.id == segment_id or str(s.id) == str(segment_id):
                    segs.remove(s)
                    return True
        return False

    def segments_of(self, datasource: str) -> List[Segment]:
        return list(self._by_ds.get(datasource, ()))

    @property
    def datasources(self) -> List[str]:
        return sorted(self._by_ds)

    # ---- execution -----------------------------------------------------
    def run(self, query: Query, segments: Optional[Sequence[Segment]] = None):
        query = apply_interval_chunking(query)
        if segments is not None:
            segs = list(segments)
        elif query.inner_query is not None:
            # subquery: materialize inner results as a segment (the analog
            # of GroupByStrategyV2.processSubqueryResult re-grouping inner
            # rows through an in-memory index)
            inner_rows = self.run(query.inner_query)
            segs = [subquery_segment(query.inner_query, inner_rows)]
        elif query.union_datasources:
            segs = []
            for d in query.union_datasources:
                segs.extend(self._by_ds.get(d, []))
        else:
            segs = self._by_ds.get(query.datasource, [])
        if self.mesh is not None:
            from druid_tpu.parallel import use_mesh
            with use_mesh(self.mesh):
                return self._dispatch(query, segs)
        return self._dispatch(query, segs)

    def run_streaming(self, query: Query,
                      segments: Optional[Sequence[Segment]] = None):
        """Iterator of result batches. Scan queries stream lazily — a
        segment is only scanned when its batch is pulled, so limits
        short-circuit and callers (HTTP chunked responses) emit rows
        before the scan finishes. Other query types are aggregates whose
        results only exist after the merge: they yield their (already
        computed) rows one batch at a time (reference: every QueryRunner
        returns a lazy Sequence; scan is the type where laziness pays)."""
        if isinstance(query, ScanQuery) and query.inner_query is None:
            query = apply_interval_chunking(query)
            if segments is not None:
                segs = list(segments)
            elif query.union_datasources:
                segs = []
                for d in query.union_datasources:
                    segs.extend(self._by_ds.get(d, []))
            else:
                segs = self._by_ds.get(query.datasource, [])
            return engines.iter_scan(query, segs)
        return iter(self.run(query, segments))

    def _dispatch(self, query: Query, segs: List[Segment]):
        if isinstance(query, (TimeseriesQuery, TopNQuery, GroupByQuery)) \
                and query.context_map.get("bySegment"):
            return engines.run_by_segment(query, segs)
        if isinstance(query, TimeseriesQuery):
            return engines.run_timeseries(query, segs)
        if isinstance(query, TopNQuery):
            return engines.run_topn(query, segs)
        if isinstance(query, GroupByQuery):
            return engines.run_groupby(query, segs)
        if isinstance(query, ScanQuery):
            return engines.run_scan(query, segs)
        if isinstance(query, SelectQuery):
            return engines.run_select(query, segs)
        if isinstance(query, SearchQuery):
            return engines.run_search(query, segs)
        if isinstance(query, TimeBoundaryQuery):
            return engines.run_time_boundary(query, segs)
        if isinstance(query, SegmentMetadataQuery):
            return engines.run_segment_metadata(query, segs)
        if isinstance(query, DataSourceMetadataQuery):
            return engines.run_datasource_metadata(query, segs)
        raise ValueError(f"unsupported query type {type(query).__name__}")

    def run_json(self, query_json: dict):
        """Execute a reference-wire-format JSON query."""
        return self.run(query_from_json(query_json))


def subquery_segment(inner_query: Query, rows) -> Segment:
    """Materialize inner groupBy results as an in-memory segment so the
    outer query runs through the ordinary engines (the reference re-groups
    subquery rows through an IncrementalIndex —
    GroupByStrategyV2.processSubqueryResult :322)."""
    from druid_tpu.data.segment import SegmentBuilder
    from druid_tpu.utils.intervals import Interval, condense

    if not isinstance(inner_query, GroupByQuery):
        raise ValueError("query dataSource requires a groupBy inner query")
    dim_names = [d.output_name for d in inner_query.dimensions]
    ivs = condense(inner_query.intervals)
    interval = Interval(min(iv.start for iv in ivs),
                        max(iv.end for iv in ivs)) if ivs \
        else Interval.eternity()
    # NUMERIC inner dimensions (expression/numeric dims) materialize as
    # numeric columns, not stringified dims — the outer query's schema
    # types them numeric and aggregating str(value) would be silently wrong.
    # Type is sniffed from the first non-None value; NULLs in a numeric
    # inner dim become 0 in the outer segment, matching the reference's
    # default null-handling mode (NullHandling.defaultValue → 0)
    numeric_dims = set()
    for d in dim_names:
        for r in rows:
            v = r["event"].get(d)
            if v is None:
                continue
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                numeric_dims.add(d)
            break
    b = SegmentBuilder("__subquery__", interval, version="sub")
    for r in rows:
        event = r["event"]
        dims = {d: (None if event.get(d) is None else str(event.get(d)))
                for d in dim_names if d not in numeric_dims}
        metrics = {k: v for k, v in event.items()
                   if k not in dims and isinstance(v, (int, float))
                   and not isinstance(v, bool)}
        b.add_row(int(r["timestamp"]), dims, metrics)
    return b.build()
