from druid_tpu.storage.codec import (compress_array, decompress_array,
                                     default_codec, LZ4, NONE, ZLIB)
from druid_tpu.storage.format import (load_segment, persist_segment,
                                      read_format_version, read_segment_meta)
from druid_tpu.storage.format_v2 import (persist_segment_auto,
                                         persist_segment_v2)
from druid_tpu.storage.smoosh import (CorruptSegmentError, FileSmoosher,
                                      SmooshedFileMapper)

__all__ = [
    "compress_array", "decompress_array", "default_codec", "LZ4", "NONE",
    "ZLIB", "load_segment", "persist_segment", "persist_segment_auto",
    "persist_segment_v2", "read_format_version", "read_segment_meta",
    "CorruptSegmentError", "FileSmoosher", "SmooshedFileMapper",
]
