from druid_tpu.storage.codec import (compress_array, decompress_array,
                                     default_codec, LZ4, NONE, ZLIB)
from druid_tpu.storage.format import (load_segment, persist_segment,
                                      read_segment_meta)
from druid_tpu.storage.smoosh import FileSmoosher, SmooshedFileMapper

__all__ = [
    "compress_array", "decompress_array", "default_codec", "LZ4", "NONE",
    "ZLIB", "load_segment", "persist_segment", "read_segment_meta",
    "FileSmoosher", "SmooshedFileMapper",
]
