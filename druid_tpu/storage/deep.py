"""Deep storage SPI: where immutable segment files live.

Reference analog: api/.../segment/loading/DataSegmentPusher + DataSegmentPuller
and their impls (LocalDataSegmentPuller/Pusher; s3/hdfs in extensions).
Segment files use the on-disk format from druid_tpu/storage/format.py
(smoosh container + LZ4 columns), so a pulled segment mmaps straight back.
"""
from __future__ import annotations

import os
import shutil
import threading
from typing import Dict, Optional

from druid_tpu.cluster.metadata import SegmentDescriptor
from druid_tpu.data.segment import Segment


class DeepStorage:
    def push(self, segment: Segment, descriptor: SegmentDescriptor
             ) -> SegmentDescriptor:
        """Store the segment; returns the descriptor with its loadSpec set."""
        raise NotImplementedError

    def pull(self, descriptor: SegmentDescriptor) -> Optional[Segment]:
        raise NotImplementedError

    def kill(self, descriptor: SegmentDescriptor) -> bool:
        """Delete the stored segment file (KillTask's storage step)."""
        raise NotImplementedError


class InMemoryDeepStorage(DeepStorage):
    """Test/local double — the role S3 plays in production."""

    def __init__(self):
        self._store: Dict[str, Segment] = {}
        self._lock = threading.Lock()

    def push(self, segment, descriptor):
        with self._lock:
            self._store[descriptor.id] = segment
        return SegmentDescriptor(
            descriptor.datasource, descriptor.interval, descriptor.version,
            descriptor.partition, descriptor.shard_spec,
            descriptor.size_bytes, descriptor.num_rows,
            {"type": "memory", "key": descriptor.id})

    def pull(self, descriptor):
        with self._lock:
            return self._store.get(descriptor.id)

    def kill(self, descriptor):
        with self._lock:
            return self._store.pop(descriptor.id, None) is not None


class LocalDeepStorage(DeepStorage):
    """Directory-per-segment local deep storage using the V9-analog on-disk
    format (smoosh + LZ4) — LocalDataSegmentPusher/Puller."""

    def __init__(self, base_dir: str):
        self.base_dir = base_dir
        os.makedirs(base_dir, exist_ok=True)

    def _dir(self, descriptor: SegmentDescriptor) -> str:
        safe = descriptor.id.replace("/", "_")
        return os.path.join(self.base_dir, descriptor.datasource, safe)

    def push(self, segment, descriptor):
        from druid_tpu.storage.format import persist_segment
        d = self._dir(descriptor)
        os.makedirs(d, exist_ok=True)
        persist_segment(segment, d)
        size = sum(os.path.getsize(os.path.join(d, f)) for f in os.listdir(d))
        return SegmentDescriptor(
            descriptor.datasource, descriptor.interval, descriptor.version,
            descriptor.partition, descriptor.shard_spec, size,
            descriptor.num_rows, {"type": "local", "path": d})

    def pull(self, descriptor):
        from druid_tpu.storage.format import load_segment
        d = (descriptor.load_spec or {}).get("path") or self._dir(descriptor)
        if not os.path.isdir(d):
            return None
        return load_segment(d)

    def kill(self, descriptor):
        d = (descriptor.load_spec or {}).get("path") or self._dir(descriptor)
        if os.path.isdir(d):
            shutil.rmtree(d)
            return True
        return False
