"""Deep storage SPI: where immutable segment files live.

Reference analog: api/.../segment/loading/DataSegmentPusher + DataSegmentPuller
and their impls (LocalDataSegmentPuller/Pusher; s3/hdfs in extensions).
Segment files use the on-disk format from druid_tpu/storage/format.py
(smoosh container + LZ4 columns), so a pulled segment mmaps straight back.
"""
from __future__ import annotations

import os
import shutil
import threading
from typing import Dict, Optional

from druid_tpu.cluster.metadata import SegmentDescriptor
from druid_tpu.data.segment import Segment


class DeepStorage:
    def push(self, segment: Segment, descriptor: SegmentDescriptor
             ) -> SegmentDescriptor:
        """Store the segment; returns the descriptor with its loadSpec set."""
        raise NotImplementedError

    def pull(self, descriptor: SegmentDescriptor) -> Optional[Segment]:
        raise NotImplementedError

    def kill(self, descriptor: SegmentDescriptor) -> bool:
        """Delete the stored segment file (KillTask's storage step)."""
        raise NotImplementedError

    #: the live storage location segments restore back into
    BASE_LOCATION = "base"

    def move(self, descriptor: SegmentDescriptor,
             location: str) -> Optional[SegmentDescriptor]:
        """Relocate the stored files to a named location ("archive", a
        custom target, or BASE_LOCATION to restore) and return the
        descriptor with its loadSpec updated, or None if the segment is
        absent (reference: DataSegmentArchiver / MoveTask's storage step)."""
        raise NotImplementedError


class InMemoryDeepStorage(DeepStorage):
    """Test/local double — the role S3 plays in production."""

    def __init__(self):
        self._store: Dict[str, Segment] = {}
        self._lock = threading.Lock()

    def push(self, segment, descriptor):
        with self._lock:
            self._store[descriptor.id] = segment
        return SegmentDescriptor(
            descriptor.datasource, descriptor.interval, descriptor.version,
            descriptor.partition, descriptor.shard_spec,
            descriptor.size_bytes, descriptor.num_rows,
            {"type": "memory", "key": descriptor.id})

    def pull(self, descriptor):
        with self._lock:
            return self._store.get(descriptor.id)

    def kill(self, descriptor):
        with self._lock:
            return self._store.pop(descriptor.id, None) is not None

    def move(self, descriptor, location):
        # one shared dict: a move only re-tags the loadSpec location
        with self._lock:
            if descriptor.id not in self._store:
                return None
        spec = {"type": "memory", "key": descriptor.id}
        if location != self.BASE_LOCATION:
            spec["location"] = location
        return SegmentDescriptor(
            descriptor.datasource, descriptor.interval, descriptor.version,
            descriptor.partition, descriptor.shard_spec,
            descriptor.size_bytes, descriptor.num_rows, spec)


class LocalDeepStorage(DeepStorage):
    """Directory-per-segment local deep storage using the V9-analog on-disk
    format (smoosh + LZ4) — LocalDataSegmentPusher/Puller."""

    def __init__(self, base_dir: str):
        self.base_dir = base_dir
        os.makedirs(base_dir, exist_ok=True)

    def _dir(self, descriptor: SegmentDescriptor) -> str:
        safe = descriptor.id.replace("/", "_")
        return os.path.join(self.base_dir, descriptor.datasource, safe)

    def push(self, segment, descriptor):
        # format V2 by default (DRUID_TPU_SEGMENT_FORMAT=1 pins V1): the
        # pushed files keep their cascade form from disk to wire to HBM
        from druid_tpu.storage.format_v2 import persist_segment_auto
        d = self._dir(descriptor)
        os.makedirs(d, exist_ok=True)
        persist_segment_auto(segment, d)
        size = sum(os.path.getsize(os.path.join(d, f)) for f in os.listdir(d))
        return SegmentDescriptor(
            descriptor.datasource, descriptor.interval, descriptor.version,
            descriptor.partition, descriptor.shard_spec, size,
            descriptor.num_rows, {"type": "local", "path": d})

    def pull(self, descriptor):
        from druid_tpu.storage.format import load_segment
        d = (descriptor.load_spec or {}).get("path") or self._dir(descriptor)
        if not os.path.isdir(d):
            return None
        return load_segment(d)

    def kill(self, descriptor):
        d = (descriptor.load_spec or {}).get("path") or self._dir(descriptor)
        if os.path.isdir(d):
            shutil.rmtree(d)
            return True
        return False

    def move(self, descriptor, location):
        src = (descriptor.load_spec or {}).get("path") or \
            self._dir(descriptor)
        if location == self.BASE_LOCATION:
            dst = self._dir(descriptor)
        else:
            root = location if os.path.isabs(location) \
                else f"{self.base_dir.rstrip(os.sep)}_{location}"
            dst = os.path.join(root, descriptor.datasource,
                               os.path.basename(src.rstrip(os.sep)))
        if not os.path.isdir(src):
            # crash-idempotency: a prior run may have moved the files and
            # died before the metadata update — finding them already at
            # the destination completes that move instead of stranding it
            if not os.path.isdir(dst):
                return None
        elif os.path.abspath(src) != os.path.abspath(dst):
            os.makedirs(os.path.dirname(dst), exist_ok=True)
            if os.path.isdir(dst):
                shutil.rmtree(dst)   # re-run of a partially-copied move
            shutil.move(src, dst)
        return SegmentDescriptor(
            descriptor.datasource, descriptor.interval, descriptor.version,
            descriptor.partition, descriptor.shard_spec,
            descriptor.size_bytes, descriptor.num_rows,
            {"type": "local", "path": dst})
