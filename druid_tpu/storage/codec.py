"""Block compression codecs for columnar storage.

Capability parity with the reference's CompressionStrategy
(processing/.../segment/data/CompressionStrategy.java:48-108 — LZF=0x0,
LZ4=0x1 default, UNCOMPRESSED=0xFF) and its 64KB block layout
(BlockLayoutColumnarLongsSupplier.java). LZ4 runs in native C++
(native/druid_native.cpp) with multi-threaded batch decompression for
segment→HBM staging; zlib (stdlib) is the fallback codec; NONE is for
incompressible data.
"""
from __future__ import annotations

import os
import struct
import zlib
from typing import Tuple

import numpy as np

from druid_tpu import native

BLOCK_SIZE = 1 << 16  # 64KB, matching the reference's default block size

LZ4 = 0x1
ZLIB = 0x2
NONE = 0xFF


def default_codec() -> int:
    return LZ4 if native.available() else ZLIB


def compress_block(codec: int, data: bytes) -> bytes:
    if codec == LZ4:
        return native.lz4_compress(data)
    if codec == ZLIB:
        return zlib.compress(data, 1)
    if codec == NONE:
        return data
    raise ValueError(f"unknown codec {codec}")


def decompress_block(codec: int, data, out_size: int) -> bytes:
    if codec == LZ4:
        return native.lz4_decompress(data, out_size).tobytes()
    if codec == ZLIB:
        return zlib.decompress(bytes(data))
    if codec == NONE:
        return bytes(data)
    raise ValueError(f"unknown codec {codec}")


# ---------------------------------------------------------------------------
# Value encodings applied BEFORE block compression (reference: the
# CompressionFactory long encodings — delta/table — in
# processing/.../segment/data/CompressionFactory.java). Delta stores
# element[0] followed by wrapped differences in the SAME dtype; the decoder
# reconstructs with a wrapping cumulative sum. A sorted time column's small
# deltas compress dramatically better than raw epoch millis.
# ---------------------------------------------------------------------------

ENC_NONE = 0
ENC_DELTA = 1
ENC_VSIZE8 = 2     # byte-packed non-negative ints (VSizeLongSerde)
ENC_VSIZE16 = 3
ENC_VSIZE32 = 4
ENC_TABLE = 5      # ≤256 distinct values: table in header + u8 indexes

_VSIZE_DTYPE = {ENC_VSIZE8: np.dtype(np.uint8),
                ENC_VSIZE16: np.dtype(np.uint16),
                ENC_VSIZE32: np.dtype(np.uint32)}


def _stream_dtype(dtype: np.dtype, encoding_id: int) -> np.dtype:
    """dtype of the ENCODED value stream (what the blocks actually hold)."""
    if encoding_id in _VSIZE_DTYPE:
        return _VSIZE_DTYPE[encoding_id]
    if encoding_id == ENC_TABLE:
        return np.dtype(np.uint8)
    return dtype


def _vsize_id(arr: np.ndarray) -> int:
    """Narrowest byte-packing for a non-negative int array, or ENC_NONE
    when packing wouldn't shrink the stream."""
    mx = int(arr.max())
    if int(arr.min()) < 0:
        return ENC_NONE
    for enc in (ENC_VSIZE8, ENC_VSIZE16, ENC_VSIZE32):
        dt = _VSIZE_DTYPE[enc]
        if mx <= np.iinfo(dt).max:
            return enc if dt.itemsize < arr.dtype.itemsize else ENC_NONE
    return ENC_NONE


def _pick_encoding(arr: np.ndarray, encoding: str) -> int:
    """Resolve the requested encoding to an id. 'auto' picks delta for
    NON-DECREASING 1-D integer arrays (element comparison — wrapped deltas
    of unsigned/overflowing data would look falsely monotonic), else VSize
    byte-packing when the value range allows a narrower width; 'table'
    (explicit only — the distinct-scan costs a pass) stores ≤256 distinct
    values once and u8 indexes per row (CompressionFactory TABLE)."""
    if encoding == "none":
        return ENC_NONE
    if encoding not in ("auto", "delta", "vsize", "table"):
        raise ValueError(f"unknown value encoding {encoding!r}")
    if arr.ndim != 1 or arr.size < 2 \
            or not np.issubdtype(arr.dtype, np.integer):
        return ENC_NONE
    if encoding == "table":
        return _pick_encoding_ex(arr, "table")[0]
    if encoding == "vsize":
        return _vsize_id(arr)
    if encoding == "auto" and not bool((arr[1:] >= arr[:-1]).all()):
        return _vsize_id(arr)
    return ENC_DELTA


def _pick_encoding_ex(arr: np.ndarray, encoding: str):
    """(encoding id, table or None) — computes the TABLE distinct scan
    once for both eligibility and serialization."""
    if encoding == "table" and arr.ndim == 1 and arr.size >= 2 \
            and np.issubdtype(arr.dtype, np.integer):
        table = np.unique(arr)
        return (ENC_TABLE, table) if table.size <= 256 else (ENC_NONE, None)
    return _pick_encoding(arr, encoding), None


def _value_chunks(arr: np.ndarray, encoding_id: int,
                  table: "np.ndarray | None" = None):
    """Yield the ENCODED value stream as BLOCK_SIZE uint8 chunks with
    O(block) peak memory — delta encodes per chunk carrying one element
    across the boundary, vsize/table re-pack per chunk (the writeout
    path's memory guarantee holds)."""
    if encoding_id == ENC_NONE:
        raw = np.ascontiguousarray(arr).reshape(-1).view(np.uint8)
        for i in range(0, raw.shape[0], BLOCK_SIZE):
            yield raw[i:i + BLOCK_SIZE]
        return
    if encoding_id in _VSIZE_DTYPE:
        dt = _VSIZE_DTYPE[encoding_id]
        epb = BLOCK_SIZE // dt.itemsize
        for i in range(0, arr.shape[0], epb):
            yield np.ascontiguousarray(
                arr[i:i + epb].astype(dt)).view(np.uint8)
        return
    if encoding_id == ENC_TABLE:
        epb = BLOCK_SIZE
        for i in range(0, arr.shape[0], epb):
            ix = np.searchsorted(table, arr[i:i + epb]).astype(np.uint8)
            yield np.ascontiguousarray(ix).view(np.uint8)
        return
    epb = BLOCK_SIZE // arr.dtype.itemsize
    prev = None
    with np.errstate(over="ignore"):
        for i in range(0, arr.shape[0], epb):
            chunk = arr[i:i + epb]
            enc = np.empty_like(chunk)
            enc[0] = chunk[0] if prev is None else chunk[0] - prev
            np.subtract(chunk[1:], chunk[:-1], out=enc[1:])
            prev = chunk[-1]
            yield np.ascontiguousarray(enc).view(np.uint8)


def _decode_values(arr: np.ndarray, encoding_id: int,
                   dtype: "np.dtype | None" = None,
                   table: "np.ndarray | None" = None) -> np.ndarray:
    if encoding_id == ENC_NONE:
        return arr
    if encoding_id == ENC_DELTA:
        # wrapping cumsum restores the original exactly (two's complement)
        wide = np.cumsum(arr.astype(np.int64))
        return wide.astype(arr.dtype)
    if encoding_id in _VSIZE_DTYPE:
        return arr.astype(dtype)
    if encoding_id == ENC_TABLE:
        return table[arr]
    raise ValueError(f"unknown value encoding {encoding_id}")


def _array_blocks(chunks, codec: int):
    """Yield (block_codec, compressed_bytes) per value chunk — the ONE
    definition of the block layout both the in-memory and writeout-file
    writers share."""
    for c in chunks:
        chunk = c.tobytes()
        comp = compress_block(codec, chunk)
        if len(comp) >= len(chunk):  # incompressible block — store raw
            yield NONE, compress_block(NONE, chunk)
        else:
            yield codec, comp


def _array_header(arr: np.ndarray, codec: int,
                  block_meta: "list[Tuple[int, int]]",
                  encoding_id: int = ENC_NONE,
                  table: "np.ndarray | None" = None) -> bytes:
    """[codec u8][dtype_len u8][dtype str][ndim u8][shape i64 * ndim]
       [encoding u8][table: n u16 + values (ENC_TABLE only)]
       [block_size i32][n_blocks i32][(size i32, codec u8) * n_blocks]"""
    dtype_s = arr.dtype.str.encode()
    header = struct.pack("<BB", codec, len(dtype_s)) + dtype_s
    header += struct.pack("<B", arr.ndim)
    header += struct.pack(f"<{arr.ndim}q", *arr.shape)
    header += struct.pack("<B", encoding_id)
    if encoding_id == ENC_TABLE:
        header += struct.pack("<H", table.size)
        header += np.ascontiguousarray(table).tobytes()
    header += struct.pack("<ii", BLOCK_SIZE, len(block_meta))
    header += b"".join(struct.pack("<iB", sz, bc) for bc, sz in block_meta)
    return header


def compress_array(arr: np.ndarray, codec: int | None = None,
                   encoding: str = "auto") -> bytes:
    """Serialize a numpy array (any rank) as a block-compressed column part
    (layout: _array_header + blocks); `encoding` applies a value transform
    first ('auto' = delta for monotonic int columns)."""
    if codec is None:
        codec = default_codec()
    arr = np.ascontiguousarray(arr)
    enc_id, table = _pick_encoding_ex(arr, encoding)
    blocks = list(_array_blocks(_value_chunks(arr, enc_id, table), codec))
    header = _array_header(arr, codec, [(bc, len(c)) for bc, c in blocks],
                           enc_id, table)
    return header + b"".join(c for _, c in blocks)


def _copy_file_into(dst, path: str, copy_chunk: int = 1 << 20) -> None:
    with open(path, "rb") as src:
        while True:
            buf = src.read(copy_chunk)
            if not buf:
                break
            dst.write(buf)


def compress_array_to_file(arr: np.ndarray, out_path: str,
                           codec: int | None = None,
                           encoding: str = "auto") -> None:
    """compress_array with O(block) peak memory: blocks stream to a temp
    writeout file while sizes accumulate, then the final part file is
    header + streamed blocks (the WriteOutMedium capability —
    processing/.../segment/writeout/FileWriteOutMedium.java). Byte-
    identical output by construction: both writers share _value_chunks /
    _array_blocks / _array_header."""
    if codec is None:
        codec = default_codec()
    arr = np.ascontiguousarray(arr)
    enc_id, table = _pick_encoding_ex(arr, encoding)
    blocks_path = out_path + ".blocks"
    meta: list = []
    with open(blocks_path, "wb") as bf:
        for bc, comp in _array_blocks(_value_chunks(arr, enc_id, table),
                                      codec):
            meta.append((bc, len(comp)))
            bf.write(comp)
    with open(out_path, "wb") as f:
        f.write(_array_header(arr, codec, meta, enc_id, table))
        _copy_file_into(f, blocks_path)
    os.remove(blocks_path)


def decompress_array(buf) -> np.ndarray:
    """Inverse of compress_array; uses native multi-threaded batch
    decompression when every block is LZ4."""
    buf = memoryview(buf)
    codec, dlen = struct.unpack_from("<BB", buf, 0)
    dtype = np.dtype(bytes(buf[2:2 + dlen]).decode())
    off = 2 + dlen
    (ndim,) = struct.unpack_from("<B", buf, off)
    off += 1
    shape = struct.unpack_from(f"<{ndim}q", buf, off)
    off += 8 * ndim
    (encoding_id,) = struct.unpack_from("<B", buf, off)
    off += 1
    table = None
    if encoding_id == ENC_TABLE:
        (n_table,) = struct.unpack_from("<H", buf, off)
        off += 2
        table = np.frombuffer(buf, dtype=dtype, count=n_table,
                              offset=off).copy()
        off += n_table * dtype.itemsize
    n_elems = int(np.prod(shape)) if ndim else 1
    block_size, n_blocks = struct.unpack_from("<ii", buf, off)
    off += 8
    sizes = np.zeros(n_blocks, dtype=np.int64)
    codecs = np.zeros(n_blocks, dtype=np.uint8)
    for i in range(n_blocks):
        sizes[i], codecs[i] = struct.unpack_from("<iB", buf, off)
        off += 5
    sdtype = _stream_dtype(dtype, encoding_id)
    total = n_elems * sdtype.itemsize
    src_offsets = np.concatenate([[0], np.cumsum(sizes)[:-1]]) if n_blocks else np.zeros(0, np.int64)
    dst_sizes = np.full(n_blocks, block_size, dtype=np.int64)
    if n_blocks:
        dst_sizes[-1] = total - block_size * (n_blocks - 1)
    dst_offsets = np.arange(n_blocks, dtype=np.int64) * block_size
    blob = buf[off:off + int(sizes.sum())]
    if n_blocks and (codecs == LZ4).all() and native.available():
        out = native.lz4_decompress_batch(blob, src_offsets, sizes,
                                          dst_offsets, dst_sizes, total)
        return _decode_values(out.view(sdtype)[:n_elems], encoding_id,
                              dtype, table).reshape(shape)
    out = np.empty(total, dtype=np.uint8)
    for i in range(n_blocks):
        chunk = decompress_block(
            int(codecs[i]), blob[int(src_offsets[i]):int(src_offsets[i] + sizes[i])],
            int(dst_sizes[i]))
        out[int(dst_offsets[i]):int(dst_offsets[i] + dst_sizes[i])] = \
            np.frombuffer(chunk, dtype=np.uint8)
    return _decode_values(out.view(sdtype)[:n_elems], encoding_id,
                          dtype, table).reshape(shape)
