"""Segment format V2: the cascade form IS the on-disk column format.

V1 (storage/format.py) persists decoded columns behind a block codec and
eagerly decodes every part at load — historical cold start is decode-bound
and the device pool re-derives the cascade encodings (data/cascade.py) it
already paid for at ingest. V2 inverts that, per *GPU Acceleration of SQL
Analytics on Compressed Data* (PAPERS.md): eligible columns persist their
cascade/pack form directly —

  col.<name>.rle.values / .rle.ends   int32 run tables, raw little-endian
  col.<name>.pack                     tile-planar packed words (int32), raw
  col.<name>.lz4                      LZ4-block blob (float columns)

— with the `(col, codec, width, base, …)` descriptors in index.json, so
`load_segment` is mmap + zero-copy descriptor reconstruction: run/word
tables are `np.frombuffer` views over the page cache, decoded rows exist
only as LAZY columns that materialize (and count a `host:<kind>` decode)
if a host path ever asks. Device staging uploads the persisted tables
as-is — one bulk H2D copy of already-compressed bytes, trace-time decode
counter at zero for run-domain-eligible shapes. Ineligible columns keep
the V1 block-codec part (`dim.<name>.ids` / `met.<name>`) and load eagerly;
V1 segments keep loading byte-for-byte via the version.bin route in
storage/format.load_segment.

Version/back-compat matrix and the `DRUID_TPU_SEGMENT_FORMAT=1` opt-out
are documented in README "Segment format V2 & storage tiering".
"""
from __future__ import annotations

import json
import os
import struct
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from druid_tpu.data import cascade as cascade_mod
from druid_tpu.data import packed as packed_mod
from druid_tpu.data.segment import (DEFAULT_ROW_ALIGN, ComplexColumn,
                                    NumericColumn, Segment, SegmentId,
                                    StringDimColumn, ValueType)
from druid_tpu.storage import codec as codecs
from druid_tpu.storage.format import (FORMAT_VERSION_V2, LazyBitmapIndex,
                                      _decode_dictionary, _encode_bitmap_index,
                                      _encode_dictionary)
from druid_tpu.storage.smoosh import (CorruptSegmentError, FileSmoosher,
                                      SmooshedFileMapper)
from druid_tpu.utils.emitter import Monitor
from druid_tpu.utils.intervals import Interval


def default_format_version() -> int:
    """2 unless DRUID_TPU_SEGMENT_FORMAT=1 pins the V1 writer (the opt-out
    lever for mixed-version fleets still running pre-V2 readers)."""
    return 1 if os.environ.get("DRUID_TPU_SEGMENT_FORMAT", "").strip() == "1" \
        else 2


def persist_segment_auto(segment: Segment, directory: str, **kw) -> int:
    """The product persist entry point (deep-storage push, ingest persist):
    V2 by default, V1 when DRUID_TPU_SEGMENT_FORMAT=1."""
    if default_format_version() == 1:
        from druid_tpu.storage.format import persist_segment
        return persist_segment(segment, directory, **kw)
    return persist_segment_v2(segment, directory, **kw)


# ---------------------------------------------------------------------------
# Load metrics (segment/load/* — wired as a dataserver monitor)
# ---------------------------------------------------------------------------

class SegmentLoadStats:
    """Cumulative segment-load accounting: wall time, logical (decoded)
    bytes served, and on-disk (compressed) bytes mapped."""

    def __init__(self):
        self._lock = threading.Lock()
        self.time_ms = 0.0
        self.bytes = 0
        self.compressed_bytes = 0

    def record(self, seconds: float, logical: int, on_disk: int) -> None:
        with self._lock:
            self.time_ms += seconds * 1000.0
            self.bytes += int(logical)
            self.compressed_bytes += int(on_disk)

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return {"time_ms": self.time_ms, "bytes": self.bytes,
                    "compressedBytes": self.compressed_bytes}


_LOAD_STATS = SegmentLoadStats()


def segment_load_stats() -> SegmentLoadStats:
    return _LOAD_STATS


class SegmentLoadMonitor(Monitor):
    """Emits segment/load/{time,bytes,compressedBytes} per tick (deltas
    over the tick window, the CodeDomainMonitor discipline)."""

    def __init__(self, source: Optional[SegmentLoadStats] = None):
        self.source = source or _LOAD_STATS
        self._last = self.source.snapshot()

    def do_monitor(self, emitter):
        s = self.source.snapshot()
        last, self._last = self._last, s
        emitter.metric("segment/load/time",
                       int(s["time_ms"] - last["time_ms"]))
        emitter.metric("segment/load/bytes",
                       int(s["bytes"] - last["bytes"]))
        emitter.metric("segment/load/compressedBytes",
                       int(s["compressedBytes"] - last["compressedBytes"]))


# ---------------------------------------------------------------------------
# Lazy columns: descriptors now, rows only if a host path asks
# ---------------------------------------------------------------------------

class LazyStringDimColumn(StringDimColumn):
    """StringDimColumn whose ids materialize on first host access from the
    persisted cascade form (the V1-compat slow path — device staging never
    takes it for rle/pack columns). Materialization counts a host decode
    in cascade.decode_stats, the witness the zero-decode tests assert on."""

    # no __slots__: the `ids` property shadows the parent slot descriptor
    # and the instance dict carries the lazy state

    def __init__(self, n_rows: int, dictionary, decoder, kind: str):
        # parent __init__ bypassed: it asserts on a materialized ids array
        self.dictionary = dictionary
        self._bitmap_index = None
        self._lock = threading.Lock()
        self._n_rows = int(n_rows)
        self._decoder = decoder
        self._kind = kind
        self._mat_lock = threading.Lock()  # separate from _lock: the lazy
        self._ids = None                   # bitmap build holds _lock while
        #                                    reading .ids

    @property
    def ids(self) -> np.ndarray:
        with self._mat_lock:
            if self._ids is None:
                cascade_mod.record_decode(f"host:{self._kind}")
                self._ids = self._decoder()
            return self._ids

    @property
    def logical_nbytes(self) -> int:
        return self._n_rows * 4

    def materialized(self) -> bool:
        with self._mat_lock:
            return self._ids is not None


class LazyNumericColumn(NumericColumn):
    """NumericColumn twin of LazyStringDimColumn (rle longs, packed longs,
    lz4 floats)."""

    def __init__(self, n_rows: int, vtype: ValueType, decoder, kind: str):
        self.type = vtype
        self._n_rows = int(n_rows)
        self._decoder = decoder
        self._kind = kind
        self._mat_lock = threading.Lock()
        self._values = None

    @property
    def values(self) -> np.ndarray:
        with self._mat_lock:
            if self._values is None:
                cascade_mod.record_decode(f"host:{self._kind}")
                self._values = self._decoder()
            return self._values

    @property
    def logical_nbytes(self) -> int:
        return self._n_rows * np.dtype(self.type.numpy_dtype).itemsize

    def materialized(self) -> bool:
        with self._mat_lock:
            return self._values is not None


# ---------------------------------------------------------------------------
# Persist
# ---------------------------------------------------------------------------

def _padded_rows(n_rows: int, row_align: int = DEFAULT_ROW_ALIGN) -> int:
    return max(row_align,
               ((n_rows + row_align - 1) // row_align) * row_align)


def _pack_words(values: np.ndarray, width: int, base: int,
                pad_n: int) -> np.ndarray:
    out = np.zeros(pad_n, dtype=values.dtype)
    out[: values.shape[0]] = values
    return packed_mod.pack_padded(out, width, base)


def persist_segment_v2(segment: Segment, directory: str,
                       codec: Optional[int] = None,
                       build_bitmaps: bool = True,
                       chunk_size: int = 1 << 31) -> int:
    """Write a segment in format V2; returns total bytes written.

    Column encodings mirror EXACTLY what device staging would derive
    (cascade.plan_pair over all columns) so the load-time plans — pure
    functions of the seeded stats — reproduce the persisted descriptors:
      rle   -> raw int32 run tables (col.<name>.rle.values/.rle.ends)
      pack  -> raw tile-planar words at DEFAULT_ROW_ALIGN padding
      lz4   -> the LZ4-block blob itself (col.<name>.lz4)
      else  -> the V1 block-codec part (dim.<name>.ids / met.<name>)
    Dictionary and bitmap parts are byte-identical to V1."""
    if codec is None:
        codec = codecs.default_codec()
    os.makedirs(directory, exist_ok=True)
    with open(os.path.join(directory, "version.bin"), "wb") as f:
        f.write(struct.pack("<I", FORMAT_VERSION_V2))

    cols = list(segment.dims.keys()) + list(segment.metrics.keys())
    cascades, packs = cascade_mod.plan_pair(segment, cols)
    cascade_for = {e[0]: e for e in cascades}
    pack_for = {name: (w, base) for name, w, base in packs}
    pad_n = _padded_rows(segment.n_rows)
    _, _, max_delta = cascade_mod._time_stats(segment)

    specs: Dict[str, dict] = {}
    meta = {
        "datasource": segment.id.datasource,
        "interval": [segment.id.interval.start, segment.id.interval.end],
        "version": segment.id.version,
        "partition": segment.id.partition,
        "n_rows": segment.n_rows,
        "dimensions": list(segment.dims.keys()),
        "metrics": {k: (f"complex:{v.type_name}"
                        if v.type is ValueType.COMPLEX else v.type.value)
                    for k, v in segment.metrics.items()},
        "min_time": segment.min_time,
        "max_time": segment.max_time,
        "codec": codec,
        "format": 2,
        "row_align": DEFAULT_ROW_ALIGN,
    }

    with FileSmoosher(directory, chunk_size) as sm:
        def add_rle(name: str):
            values, ends = cascade_mod._rle_encoded(segment, name)
            sm.add(f"col.{name}.rle.values", values.tobytes())
            sm.add(f"col.{name}.rle.ends", ends.tobytes())
            return {"enc": "rle", "runs": int(values.shape[0])}

        def add_pack(name: str, values: np.ndarray, w: int, base: int):
            words = _pack_words(values, w, base, pad_n)
            sm.add(f"col.{name}.pack", words.tobytes())
            return {"enc": "pack", "width": w, "base": base, "rows": pad_n}

        for name, col in segment.dims.items():
            sm.add(f"dim.{name}.dict", _encode_dictionary(col.dictionary))
            c = cascade_for.get(name)
            if c is not None and c[1] == "rle":
                spec = add_rle(name)
            elif name in pack_for:
                w, base = pack_for[name]
                spec = add_pack(name, col.ids, w, base)
            else:
                sm.add(f"dim.{name}.ids",
                       codecs.compress_array(col.ids, codec))
                spec = {"enc": "block"}
            spec["dtype"] = "int32"
            # raw run count for EVERY column: load-time planning asks
            # column_run_count even for pack/block columns, and without the
            # seed that read would materialize a lazy column
            spec["raw_runs"] = int(cascade_mod.column_run_count(segment,
                                                                name))
            specs[name] = spec
            if build_bitmaps:
                sm.add(f"dim.{name}.bitmaps",
                       _encode_bitmap_index(col.bitmap_index(), codec))

        for name, m in segment.metrics.items():
            spec: dict = {"enc": "block"}
            c = cascade_for.get(name)
            if m.type is ValueType.LONG:
                lo, hi = segment.column_minmax(name)
                if c is not None and c[1] == "rle":
                    spec = add_rle(name)
                elif name in pack_for:
                    w, base = pack_for[name]
                    spec = add_pack(name, m.values.astype(np.int32),
                                    w, base)
                spec["min"], spec["max"] = int(lo), int(hi)
                spec["raw_runs"] = int(
                    cascade_mod.column_run_count(segment, name))
            elif m.type in (ValueType.FLOAT, ValueType.DOUBLE) \
                    and c is not None and c[1] in ("lz4", "lz4host"):
                from druid_tpu.native import lz4block
                raw = np.ascontiguousarray(m.values).tobytes()
                blob = lz4block.compress(raw)
                if lz4block.decompress(blob, len(raw)) == raw:
                    sm.add(f"col.{name}.lz4", blob)
                    spec = {"enc": "lz4", "raw": len(raw),
                            "comp": len(blob), "n": segment.n_rows,
                            "finite": segment.column_finite(name)}
            if spec["enc"] == "block":
                sm.add(f"met.{name}", codecs.compress_array(m.values, codec))
            spec["dtype"] = str(m.values.dtype) \
                if m.type is ValueType.COMPLEX else \
                str(np.dtype(m.type.numpy_dtype))
            specs[name] = spec

        sm.add("__time", codecs.compress_array(segment.time_ms, codec))
        meta["v2"] = {
            "columns": specs,
            "time": {"max_delta": int(max_delta)},
            "staging": {
                "cascades": cascade_mod.descriptor_to_json(cascades),
                "packs": cascade_mod.descriptor_to_json(packs),
            },
        }
        sm.add("index.json", json.dumps(meta).encode())
    total = 0
    for fn in os.listdir(directory):
        total += os.path.getsize(os.path.join(directory, fn))
    return total


# ---------------------------------------------------------------------------
# Load: mmap + zero-copy descriptor reconstruction
# ---------------------------------------------------------------------------

def _raw_part(mapper: SmooshedFileMapper, directory: str, name: str,
              dtype, count: int) -> np.ndarray:
    """Zero-copy typed view of a raw little-endian part (mmap-backed,
    read-only); size-validated so a truncated part fails typed, not with a
    frombuffer ValueError deep in staging."""
    buf = mapper.part(name)
    need = int(count) * np.dtype(dtype).itemsize
    if len(buf) != need:
        raise CorruptSegmentError(
            directory, f"part is {len(buf)} bytes, descriptor needs {need}",
            part=name)
    return np.frombuffer(buf, dtype=dtype)


def _rle_decoder(values: np.ndarray, ends: np.ndarray, dtype_str: str):
    def decode():
        lengths = np.diff(ends, prepend=np.int32(0))
        return np.repeat(values, lengths).astype(dtype_str)
    return decode


def _pack_decoder(words: np.ndarray, width: int, base: int, rows: int,
                  n_rows: int, dtype_str: str):
    def decode():
        full = packed_mod.unpack_host(words, width, base, rows,
                                      dtype=dtype_str)
        return full[:n_rows].copy()
    return decode


def _lz4_decoder(blob, raw_len: int, n: int, dtype_str: str):
    def decode():
        from druid_tpu.native import lz4block
        raw = lz4block.decompress(bytes(blob), raw_len)
        return np.frombuffer(raw, dtype=dtype_str)[:n].copy()
    return decode


def load_segment_v2(directory: str,
                    columns: Optional[Sequence[str]] = None) -> Segment:
    """mmap a V2 segment: run/word tables become zero-copy frombuffer views
    over the page cache, decoded rows become lazy columns, and the cascade
    stat caches (run counts, rle tables, min/max, lz4 stats, time deltas)
    seed from the persisted descriptors — so staging plans reproduce the
    persisted encodings without touching a single decoded row."""
    t_start = time.perf_counter()
    mapper = SmooshedFileMapper(directory)
    try:
        meta = json.loads(bytes(mapper.part("index.json")))
    except (ValueError, KeyError) as e:
        if isinstance(e, CorruptSegmentError):
            raise
        raise CorruptSegmentError(directory, f"bad index.json: {e}",
                                  part="index.json") from None
    v2 = meta.get("v2")
    if not isinstance(v2, dict) or "columns" not in v2:
        raise CorruptSegmentError(directory,
                                  "format-V2 segment missing v2 metadata",
                                  part="index.json")
    specs = v2["columns"]
    n_rows = int(meta["n_rows"])
    seg_id = SegmentId(meta["datasource"],
                       Interval(meta["interval"][0], meta["interval"][1]),
                       meta["version"], meta["partition"])
    time_ms = codecs.decompress_array(mapper.part("__time")).copy()
    # aux seeds applied after Segment construction (key -> value)
    seeds: List[Tuple[Tuple, object]] = []

    def load_rle(name: str, spec: dict):
        nr = int(spec["runs"])
        rv = _raw_part(mapper, directory, f"col.{name}.rle.values",
                       np.int32, nr)
        re_ = _raw_part(mapper, directory, f"col.{name}.rle.ends",
                        np.int32, nr)
        if nr and int(re_[-1]) != n_rows:
            raise CorruptSegmentError(
                directory, f"rle ends terminate at {int(re_[-1])}, "
                f"segment has {n_rows} rows", part=f"col.{name}.rle.ends")
        seeds.append((("cascade_runs", name), nr))
        seeds.append((("cascade_rleenc", name), (rv, re_)))
        return _rle_decoder(rv, re_, spec["dtype"])

    def load_pack(name: str, spec: dict):
        w, base = int(spec["width"]), int(spec["base"])
        rows = int(spec["rows"])
        vpw = packed_mod._word_bits() // w
        words = _raw_part(mapper, directory, f"col.{name}.pack",
                          np.int32, rows // vpw)
        return (_pack_decoder(words, w, base, rows, n_rows, spec["dtype"]),
                (words, w, base, rows))

    dims: Dict[str, StringDimColumn] = {}
    for name in meta["dimensions"]:
        if columns is not None and name not in columns:
            continue
        d = _decode_dictionary(mapper.part(f"dim.{name}.dict"))
        spec = specs.get(name, {"enc": "block", "dtype": "int32"})
        enc = spec["enc"]
        if enc == "rle":
            col = LazyStringDimColumn(n_rows, d, load_rle(name, spec),
                                      "rle")
        elif enc == "pack":
            decoder, hint = load_pack(name, spec)
            col = LazyStringDimColumn(n_rows, d, decoder, "packed")
            col._v2_pack = hint
        else:
            ids = codecs.decompress_array(
                mapper.part(f"dim.{name}.ids")).copy()
            col = StringDimColumn(ids, d)
        bm_part = f"dim.{name}.bitmaps"
        if mapper.has(bm_part):
            col.set_bitmap_index(LazyBitmapIndex(mapper.part(bm_part)))
        if "raw_runs" in spec and enc != "rle":
            seeds.append((("cascade_runs", name), int(spec["raw_runs"])))
        dims[name] = col

    metrics: Dict[str, object] = {}
    for name, tname in meta["metrics"].items():
        if columns is not None and name not in columns:
            continue
        if tname.startswith("complex:"):
            vals = codecs.decompress_array(mapper.part(f"met.{name}")).copy()
            metrics[name] = ComplexColumn(vals, tname.split(":", 1)[1])
            continue
        vtype = ValueType(tname)
        spec = specs.get(name, {"enc": "block",
                                "dtype": str(np.dtype(vtype.numpy_dtype))})
        enc = spec["enc"]
        if enc == "rle":
            m = LazyNumericColumn(n_rows, vtype, load_rle(name, spec),
                                  "rle")
        elif enc == "pack":
            decoder, hint = load_pack(name, spec)
            m = LazyNumericColumn(n_rows, vtype, decoder, "packed")
            m._v2_pack = hint
        elif enc == "lz4":
            blob = mapper.part(f"col.{name}.lz4")
            raw_len, comp_len = int(spec["raw"]), int(spec["comp"])
            if len(blob) != comp_len:
                raise CorruptSegmentError(
                    directory, f"lz4 blob is {len(blob)} bytes, "
                    f"descriptor says {comp_len}", part=f"col.{name}.lz4")
            m = LazyNumericColumn(
                n_rows, vtype,
                _lz4_decoder(blob, raw_len, n_rows, spec["dtype"]), "lz4")
            seeds.append((("finite", name), bool(spec.get("finite", True))))
            seeds.extend(_seed_lz4(name, blob, raw_len, comp_len,
                                   int(spec["n"])))
        else:
            vals = codecs.decompress_array(mapper.part(f"met.{name}")).copy()
            m = NumericColumn(vals, vtype)
        if "min" in spec:
            seeds.append((("minmax", name),
                          (int(spec["min"]), int(spec["max"]))))
        if "raw_runs" in spec and enc != "rle":
            seeds.append((("cascade_runs", name), int(spec["raw_runs"])))
        metrics[name] = m

    seg = Segment(seg_id, time_ms, dims, metrics, sorted_by_time=True)
    md = int(v2.get("time", {}).get("max_delta", -1))
    seeds.append((("cascade_tdelta",), md))
    for key, value in seeds:
        seg.aux_cached(key, lambda v=value: v)
    # loader-local publish (the V1 loader's rule): no other referent yet
    seg._mapper = mapper  # druidlint: disable=unguarded-shared-write  # keep mmaps alive for the zero-copy views
    on_disk = sum(os.path.getsize(os.path.join(directory, f))
                  for f in os.listdir(directory))
    _LOAD_STATS.record(time.perf_counter() - t_start, seg.size_bytes(),
                       on_disk)
    return seg


def _seed_lz4(name: str, blob, raw_len: int, comp_len: int,
              n_values: int) -> List[Tuple[Tuple, object]]:
    """Token arrays for the device LZ4 decoder, parsed straight from the
    persisted blob (token STRUCTURE parsing over compressed bytes — no row
    is decoded). Seeds both caches cascade._lz4_stat/_lz4_encoded would
    otherwise fill by recompressing the materialized column."""
    from druid_tpu.native import lz4block
    lits, ll, ml, off = lz4block.tokenize(bytes(blob))
    tp = cascade_mod.pad_pow2(ll.shape[0])
    lp = cascade_mod.pad_pow2(max(lits.shape[0], 1))

    def padto(a, n, dt):
        out = np.zeros(n, dtype=dt)
        out[: a.shape[0]] = a
        return out
    enc = (padto(lits, lp, np.uint8), padto(ll, tp, np.int32),
           padto(ml, tp, np.int32), padto(off, tp, np.int32), int(n_values))
    return [(("cascade_lz4stat", name), (raw_len, comp_len, tp)),
            (("cascade_lz4enc", name), enc)]


def logical_column_bytes(segment: Segment) -> int:
    """Decoded-equivalent bytes of a segment's columns (inspect/bench)."""
    return segment.size_bytes()
