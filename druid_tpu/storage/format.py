"""On-disk segment format (V1): columnar parts in a smoosh container.

Capability parity with the reference's V9 segment format
(processing/.../segment/IndexIO.java:86-116 — version.bin, meta.smoosh,
index.drd, per-column ColumnDescriptor parts;
segment/serde/DictionaryEncodedColumnPartSerde.java:57). TPU-first layout
decisions:
  * every physical column part is a dense block-compressed array (native LZ4)
    that decodes straight into the numpy array device staging expects —
    no per-row varint decoding on the critical path;
  * string dims store (sorted dictionary blob, int32 id column, per-value
    bitmap index), exactly the planning structures the host filter planner
    uses; the device never sees strings;
  * bitmaps load lazily (the reference mmaps them on demand too).

Layout: <dir>/version.bin (u32=1), meta.smoosh + chunk files. Parts:
  index.json                segment identity + schema + row count
  __time                    int64 millis, block-compressed
  dim.<name>.dict           utf8 dictionary (n, offsets[n+1], bytes)
  dim.<name>.ids            int32 ids, block-compressed
  dim.<name>.bitmaps        per-value packed-word bitmaps, LZ4 per value
  met.<name>                numeric column, block-compressed
"""
from __future__ import annotations

import json
import os
import struct
from typing import Dict, List, Optional, Sequence

import numpy as np

from druid_tpu.data.bitmap import Bitmap, BitmapIndex
from druid_tpu.data.dictionary import Dictionary
from druid_tpu.data.segment import (ComplexColumn, NumericColumn, Segment,
                                    SegmentId, StringDimColumn, ValueType)
from druid_tpu.storage import codec as codecs
from druid_tpu.storage.smoosh import (CorruptSegmentError, FileSmoosher,
                                      SmooshedFileMapper)
from druid_tpu.utils.intervals import Interval

FORMAT_VERSION = 3  # v3: value-encoding byte in column parts (delta longs)
FORMAT_VERSION_V2 = 4  # "segment format V2": cascade-form column parts


def read_format_version(directory: str) -> int:
    """The version.bin tag that routes load_segment between V1 (block-codec
    columns, eager decode) and V2 (cascade-form parts, lazy columns)."""
    path = os.path.join(directory, "version.bin")
    if not os.path.exists(path):
        raise CorruptSegmentError(directory, "missing version.bin")
    with open(path, "rb") as f:
        raw = f.read(4)
    if len(raw) != 4:
        raise CorruptSegmentError(directory, "truncated version.bin")
    (version,) = struct.unpack("<I", raw)
    return version


def _encode_dictionary(d: Dictionary) -> bytes:
    blobs = [v.encode("utf-8") for v in d.values]
    offsets = np.zeros(len(blobs) + 1, dtype=np.int32)
    np.cumsum([len(b) for b in blobs], out=offsets[1:])
    return (struct.pack("<i", len(blobs)) + offsets.tobytes()
            + b"".join(blobs))


def _decode_dictionary(buf) -> Dictionary:
    buf = memoryview(buf)
    (n,) = struct.unpack_from("<i", buf, 0)
    offsets = np.frombuffer(buf, dtype=np.int32, count=n + 1, offset=4)
    base = 4 + (n + 1) * 4
    blob = bytes(buf[base:base + int(offsets[-1])])
    values = [blob[offsets[i]:offsets[i + 1]].decode("utf-8")
              for i in range(n)]
    return Dictionary(values)


def _bitmap_parts(index: BitmapIndex, codec: int):
    """Per-value compressed bitmap parts — the one layout definition the
    in-memory and writeout-file encoders share."""
    for vid in range(index.cardinality):
        yield codecs.compress_block(codec, index.bitmap(vid).words.tobytes())


def _bitmap_header(index: BitmapIndex, codec: int,
                   sizes: Sequence[int]) -> bytes:
    offsets = np.zeros(index.cardinality + 1, dtype=np.int64)
    np.cumsum(np.asarray(sizes, dtype=np.int64), out=offsets[1:])
    return (struct.pack("<qiB", index.n_rows, index.cardinality, codec)
            + offsets.tobytes())


def _encode_bitmap_index(index: BitmapIndex, codec: int) -> bytes:
    parts = list(_bitmap_parts(index, codec))
    return _bitmap_header(index, codec, [len(p) for p in parts]) \
        + b"".join(parts)


def _encode_bitmap_index_to_file(index: BitmapIndex, codec: int,
                                 out_path: str) -> None:
    """Byte-identical to _encode_bitmap_index (shared _bitmap_parts /
    _bitmap_header) with O(one bitmap) peak memory."""
    from druid_tpu.storage.codec import _copy_file_into
    blocks_path = out_path + ".blocks"
    sizes: list = []
    with open(blocks_path, "wb") as bf:
        for part in _bitmap_parts(index, codec):
            sizes.append(len(part))
            bf.write(part)
    with open(out_path, "wb") as f:
        f.write(_bitmap_header(index, codec, sizes))
        _copy_file_into(f, blocks_path)
    os.remove(blocks_path)


class LazyBitmapIndex(BitmapIndex):
    """BitmapIndex that decompresses per-value bitmaps on first access —
    the analog of the reference mmapping bitmap parts on demand."""

    def __init__(self, buf):
        buf = memoryview(buf)
        n_rows, cardinality, codec = struct.unpack_from("<qiB", buf, 0)
        off = 13
        self._offsets = np.frombuffer(buf, dtype=np.int64,
                                      count=cardinality + 1, offset=off)
        self._blob = buf[off + (cardinality + 1) * 8:]
        self._codec = codec
        self._word_bytes = (n_rows + 7) // 8
        super().__init__(n_rows, cardinality,
                         [None] * cardinality)  # type: ignore[list-item]

    def bitmap(self, value_id: int) -> Bitmap:
        if value_id < 0 or value_id >= self.cardinality:
            return Bitmap.empty(self.n_rows)
        with self._lock:
            b = self._bitmaps[value_id]
            if b is None:
                lo, hi = (int(self._offsets[value_id]),
                          int(self._offsets[value_id + 1]))
                words = np.frombuffer(
                    codecs.decompress_block(self._codec, self._blob[lo:hi],
                                            self._word_bytes), dtype=np.uint8)
                b = Bitmap(words.copy(), self.n_rows)
                # decompressed bitmaps live under the index's LRU byte
                # budget exactly like lazily-built ones
                self._cache_put(value_id, b)
            elif value_id in self._lru:
                self._lru.move_to_end(value_id)
            return b

    def union_of(self, value_ids: np.ndarray) -> Bitmap:
        """Stream the OR into one accumulator: a wide IN/regex union over
        thousands of values must neither hold every decompressed bitmap at
        once nor thrash the LRU cache."""
        valid = [int(v) for v in value_ids if 0 <= v < self.cardinality]
        if not valid:
            return Bitmap.empty(self.n_rows)
        acc = np.zeros(self._word_bytes, dtype=np.uint8)
        for v in valid:
            with self._lock:
                cached = self._bitmaps[v]
            if cached is not None:
                words = cached.words
            else:
                lo, hi = int(self._offsets[v]), int(self._offsets[v + 1])
                words = np.frombuffer(
                    codecs.decompress_block(self._codec, self._blob[lo:hi],
                                            self._word_bytes),
                    dtype=np.uint8)
            np.bitwise_or(acc, words, out=acc)
        return Bitmap(acc, self.n_rows)

    def size_bytes(self) -> int:
        return int(self._offsets[-1])


def persist_segment(segment: Segment, directory: str,
                    codec: Optional[int] = None,
                    build_bitmaps: bool = True,
                    chunk_size: int = 1 << 31,
                    writeout: str = "memory") -> int:
    """Write a segment to `directory`; returns total bytes written.

    writeout="tmpfile" streams every compressed part through temp writeout
    files (peak extra memory O(64KB block) instead of O(largest compressed
    part)) — the reference's FileWriteOutMedium vs OnHeapMemory
    WriteOutMedium choice (processing/.../segment/writeout/). The on-disk
    result is byte-identical.

    Reference analog: IndexMergerV9.persist
    (processing/.../segment/IndexMergerV9.java:729)."""
    if codec is None:
        codec = codecs.default_codec()
    os.makedirs(directory, exist_ok=True)
    with open(os.path.join(directory, "version.bin"), "wb") as f:
        f.write(struct.pack("<I", FORMAT_VERSION))

    meta = {
        "datasource": segment.id.datasource,
        "interval": [segment.id.interval.start, segment.id.interval.end],
        "version": segment.id.version,
        "partition": segment.id.partition,
        "n_rows": segment.n_rows,
        "dimensions": list(segment.dims.keys()),
        "metrics": {k: (f"complex:{v.type_name}"
                        if v.type is ValueType.COMPLEX else v.type.value)
                    for k, v in segment.metrics.items()},
        "min_time": segment.min_time,
        "max_time": segment.max_time,
        "codec": codec,
    }
    with FileSmoosher(directory, chunk_size) as sm:
        if writeout == "tmpfile":
            import tempfile
            wo_dir = tempfile.mkdtemp(prefix="writeout_", dir=directory)

            def add_array(name, arr):
                path = os.path.join(wo_dir, "part")
                codecs.compress_array_to_file(arr, path, codec)
                sm.add_from_file(name, path)
                os.remove(path)

            def add_bitmaps(name, index):
                path = os.path.join(wo_dir, "part")
                _encode_bitmap_index_to_file(index, codec, path)
                sm.add_from_file(name, path)
                os.remove(path)
        else:
            def add_array(name, arr):
                sm.add(name, codecs.compress_array(arr, codec))

            def add_bitmaps(name, index):
                sm.add(name, _encode_bitmap_index(index, codec))

        sm.add("index.json", json.dumps(meta).encode())
        add_array("__time", segment.time_ms)
        for name, col in segment.dims.items():
            sm.add(f"dim.{name}.dict", _encode_dictionary(col.dictionary))
            add_array(f"dim.{name}.ids", col.ids)
            if build_bitmaps:
                add_bitmaps(f"dim.{name}.bitmaps", col.bitmap_index())
        for name, m in segment.metrics.items():
            add_array(f"met.{name}", m.values)
        if writeout == "tmpfile":
            os.rmdir(wo_dir)
    total = 0
    for fn in os.listdir(directory):
        total += os.path.getsize(os.path.join(directory, fn))
    return total


def load_segment(directory: str,
                 columns: Optional[Sequence[str]] = None) -> Segment:
    """mmap + decode a persisted segment. Column values decode eagerly via
    native batch LZ4 (multi-threaded); bitmap indexes attach lazily.

    Reference analog: IndexIO.loadIndex (segment/IndexIO.java:116)."""
    version = read_format_version(directory)
    if version == FORMAT_VERSION_V2:
        from druid_tpu.storage.format_v2 import load_segment_v2
        return load_segment_v2(directory, columns=columns)
    if version != FORMAT_VERSION:
        raise CorruptSegmentError(
            directory, f"unknown segment format version {version}")
    mapper = SmooshedFileMapper(directory)
    try:
        meta = json.loads(bytes(mapper.part("index.json")))
    except (ValueError, KeyError) as e:
        if isinstance(e, CorruptSegmentError):
            raise
        raise CorruptSegmentError(directory, f"bad index.json: {e}",
                                  part="index.json") from None
    seg_id = SegmentId(meta["datasource"],
                       Interval(meta["interval"][0], meta["interval"][1]),
                       meta["version"], meta["partition"])
    time_ms = decompress_part(mapper, "__time")
    dims: Dict[str, StringDimColumn] = {}
    for name in meta["dimensions"]:
        if columns is not None and name not in columns:
            continue
        d = _decode_dictionary(mapper.part(f"dim.{name}.dict"))
        ids = decompress_part(mapper, f"dim.{name}.ids").copy()
        col = StringDimColumn(ids, d)
        bm_part = f"dim.{name}.bitmaps"
        if mapper.has(bm_part):
            col.set_bitmap_index(LazyBitmapIndex(mapper.part(bm_part)))
        dims[name] = col
    metrics: Dict[str, object] = {}
    for name, tname in meta["metrics"].items():
        if columns is not None and name not in columns:
            continue
        vals = decompress_part(mapper, f"met.{name}").copy()
        if tname.startswith("complex:"):
            metrics[name] = ComplexColumn(vals, tname.split(":", 1)[1])
        else:
            metrics[name] = NumericColumn(vals, ValueType(tname))
    seg = Segment(seg_id, time_ms.copy(), dims, metrics, sorted_by_time=True)
    # loader-local publish: `seg` has no other referent until this return,
    # so the post-construction write cannot race (same-safety as __init__)
    seg._mapper = mapper  # druidlint: disable=unguarded-shared-write  # keep mmaps alive for lazy bitmap loads
    return seg


def decompress_part(mapper: SmooshedFileMapper, name: str) -> np.ndarray:
    return codecs.decompress_array(mapper.part(name))


def read_segment_meta(directory: str) -> dict:
    """index.json of a persisted segment — both V1 and V2 carry the same
    identity/schema keys (V2 adds a "v2" section with the cascade
    descriptors). Raises CorruptSegmentError on any structural damage."""
    with SmooshedFileMapper(directory) as mapper:
        try:
            return json.loads(bytes(mapper.part("index.json")))
        except (ValueError, KeyError) as e:
            if isinstance(e, CorruptSegmentError):
                raise
            raise CorruptSegmentError(directory, f"bad index.json: {e}",
                                      part="index.json") from None
