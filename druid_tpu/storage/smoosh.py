"""Smoosh container: pack many named parts into few mmap-able chunk files.

Capability parity with the reference's smoosh format
(java-util/.../io/smoosh/FileSmoosher.java, SmooshedFileMapper.java): all
columns of a segment live in ≤chunk_size files `chunk_NNNNN.bin` plus a
`meta.smoosh` index of (name, chunk, start, end). Reading maps chunks with
mmap and hands out zero-copy memoryviews, so decompression (native LZ4)
reads straight from the page cache.
"""
from __future__ import annotations

import mmap
import os
from typing import Dict, List, Optional, Tuple

DEFAULT_CHUNK_SIZE = 1 << 31  # 2GB, like the reference's mmap limit
META_FILE = "meta.smoosh"


class CorruptSegmentError(ValueError):
    """A segment directory failed structural validation: bad magic/version,
    truncated chunk file, malformed meta line, or a part whose offsets fall
    outside its chunk. Carries the directory and (when known) the part name
    so historical load can log exactly what is broken and move on instead
    of dying on a raw ValueError/struct.error traceback."""

    def __init__(self, path: str, detail: str, part: Optional[str] = None):
        self.path = path
        self.part = part
        where = f"{path}[{part}]" if part else path
        super().__init__(f"corrupt segment {where}: {detail}")


def _chunk_name(i: int) -> str:
    return f"chunk_{i:05d}.bin"


class FileSmoosher:
    """Writer: add named byte parts; parts never span chunks."""

    def __init__(self, directory: str, chunk_size: int = DEFAULT_CHUNK_SIZE):
        self.directory = directory
        self.chunk_size = chunk_size
        os.makedirs(directory, exist_ok=True)
        self._entries: List[Tuple[str, int, int, int]] = []
        self._chunk_idx = 0
        self._chunk_pos = 0
        self._fh = None

    def _ensure_chunk(self, size: int):
        if self._fh is None or (self._chunk_pos + size > self.chunk_size
                                and self._chunk_pos > 0):
            if self._fh is not None:
                self._fh.close()
                self._chunk_idx += 1
            self._fh = open(os.path.join(
                self.directory, _chunk_name(self._chunk_idx)), "wb")
            self._chunk_pos = 0

    def add(self, name: str, data: bytes):
        if any(e[0] == name for e in self._entries):
            raise ValueError(f"duplicate smoosh part {name!r}")
        self._ensure_chunk(len(data))
        start = self._chunk_pos
        self._fh.write(data)
        self._chunk_pos += len(data)
        self._entries.append((name, self._chunk_idx, start, self._chunk_pos))

    def add_from_file(self, name: str, path: str,
                      copy_chunk: int = 1 << 20):
        """Stream a part in from a writeout file without materializing it
        (reference: FileWriteOutMedium — intermediate persist data lives in
        temp files, not heap)."""
        if any(e[0] == name for e in self._entries):
            raise ValueError(f"duplicate smoosh part {name!r}")
        size = os.path.getsize(path)
        self._ensure_chunk(size)
        start = self._chunk_pos
        with open(path, "rb") as src:
            while True:
                buf = src.read(copy_chunk)
                if not buf:
                    break
                self._fh.write(buf)
                self._chunk_pos += len(buf)
        self._entries.append((name, self._chunk_idx, start, self._chunk_pos))

    def close(self):
        if self._fh is not None:
            self._fh.close()
            self._fh = None
        with open(os.path.join(self.directory, META_FILE), "w") as f:
            f.write(f"v1,{self.chunk_size},{self._chunk_idx + 1}\n")
            for name, chunk, start, end in self._entries:
                f.write(f"{name},{chunk},{start},{end}\n")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class SmooshedFileMapper:
    """Reader: mmap chunk files, hand out zero-copy memoryviews per part."""

    def __init__(self, directory: str):
        self.directory = directory
        self._parts: Dict[str, Tuple[int, int, int]] = {}
        meta_path = os.path.join(directory, META_FILE)
        if not os.path.exists(meta_path):
            raise CorruptSegmentError(directory, f"missing {META_FILE}")
        with open(meta_path) as f:
            header = f.readline().strip().split(",")
            if len(header) != 3 or header[0] != "v1":
                raise CorruptSegmentError(
                    directory, f"bad smoosh header {','.join(header)!r}")
            try:
                n_chunks = int(header[2])
            except ValueError:
                raise CorruptSegmentError(
                    directory, f"bad smoosh chunk count {header[2]!r}") \
                    from None
            for line in f:
                if not line.strip():
                    continue
                try:
                    name, chunk, start, end = line.rsplit(",", 3)
                    chunk, start, end = int(chunk), int(start), int(end)
                except ValueError:
                    raise CorruptSegmentError(
                        directory,
                        f"malformed meta line {line.strip()!r}") from None
                if not (0 <= chunk < n_chunks and 0 <= start <= end):
                    raise CorruptSegmentError(
                        directory,
                        f"part offsets out of range ({chunk},{start},{end})",
                        part=name)
                self._parts[name] = (chunk, start, end)
        self._maps: List[Optional[mmap.mmap]] = [None] * n_chunks
        self._files: List[Optional[object]] = [None] * n_chunks

    def names(self) -> List[str]:
        return list(self._parts.keys())

    def has(self, name: str) -> bool:
        return name in self._parts

    def part(self, name: str) -> memoryview:
        if name not in self._parts:
            raise CorruptSegmentError(self.directory, "part missing from "
                                      f"{META_FILE}", part=name)
        chunk, start, end = self._parts[name]
        if self._maps[chunk] is None:
            path = os.path.join(self.directory, _chunk_name(chunk))
            try:
                fh = open(path, "rb")
            except OSError as e:
                raise CorruptSegmentError(
                    self.directory, f"missing chunk file: {e}",
                    part=name) from None
            try:
                m = mmap.mmap(fh.fileno(), 0, access=mmap.ACCESS_READ)
            except (OSError, ValueError) as e:   # zero-length/unmappable
                fh.close()
                raise CorruptSegmentError(
                    self.directory, f"unmappable chunk file {path}: {e}",
                    part=name) from None
            self._files[chunk] = fh
            self._maps[chunk] = m
        if end > len(self._maps[chunk]):
            raise CorruptSegmentError(
                self.directory,
                f"truncated chunk {chunk}: part needs bytes "
                f"[{start},{end}) of {len(self._maps[chunk])}", part=name)
        return memoryview(self._maps[chunk])[start:end]

    def part_size(self, name: str) -> int:
        chunk, start, end = self._parts[name]
        return end - start

    def close(self):
        for i, m in enumerate(self._maps):
            if m is not None:
                m.close()
                self._maps[i] = None
            if self._files[i] is not None:
                self._files[i].close()
                self._files[i] = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
