"""Smoosh container: pack many named parts into few mmap-able chunk files.

Capability parity with the reference's smoosh format
(java-util/.../io/smoosh/FileSmoosher.java, SmooshedFileMapper.java): all
columns of a segment live in ≤chunk_size files `chunk_NNNNN.bin` plus a
`meta.smoosh` index of (name, chunk, start, end). Reading maps chunks with
mmap and hands out zero-copy memoryviews, so decompression (native LZ4)
reads straight from the page cache.
"""
from __future__ import annotations

import mmap
import os
from typing import Dict, List, Optional, Tuple

DEFAULT_CHUNK_SIZE = 1 << 31  # 2GB, like the reference's mmap limit
META_FILE = "meta.smoosh"


def _chunk_name(i: int) -> str:
    return f"chunk_{i:05d}.bin"


class FileSmoosher:
    """Writer: add named byte parts; parts never span chunks."""

    def __init__(self, directory: str, chunk_size: int = DEFAULT_CHUNK_SIZE):
        self.directory = directory
        self.chunk_size = chunk_size
        os.makedirs(directory, exist_ok=True)
        self._entries: List[Tuple[str, int, int, int]] = []
        self._chunk_idx = 0
        self._chunk_pos = 0
        self._fh = None

    def _ensure_chunk(self, size: int):
        if self._fh is None or (self._chunk_pos + size > self.chunk_size
                                and self._chunk_pos > 0):
            if self._fh is not None:
                self._fh.close()
                self._chunk_idx += 1
            self._fh = open(os.path.join(
                self.directory, _chunk_name(self._chunk_idx)), "wb")
            self._chunk_pos = 0

    def add(self, name: str, data: bytes):
        if any(e[0] == name for e in self._entries):
            raise ValueError(f"duplicate smoosh part {name!r}")
        self._ensure_chunk(len(data))
        start = self._chunk_pos
        self._fh.write(data)
        self._chunk_pos += len(data)
        self._entries.append((name, self._chunk_idx, start, self._chunk_pos))

    def add_from_file(self, name: str, path: str,
                      copy_chunk: int = 1 << 20):
        """Stream a part in from a writeout file without materializing it
        (reference: FileWriteOutMedium — intermediate persist data lives in
        temp files, not heap)."""
        if any(e[0] == name for e in self._entries):
            raise ValueError(f"duplicate smoosh part {name!r}")
        size = os.path.getsize(path)
        self._ensure_chunk(size)
        start = self._chunk_pos
        with open(path, "rb") as src:
            while True:
                buf = src.read(copy_chunk)
                if not buf:
                    break
                self._fh.write(buf)
                self._chunk_pos += len(buf)
        self._entries.append((name, self._chunk_idx, start, self._chunk_pos))

    def close(self):
        if self._fh is not None:
            self._fh.close()
            self._fh = None
        with open(os.path.join(self.directory, META_FILE), "w") as f:
            f.write(f"v1,{self.chunk_size},{self._chunk_idx + 1}\n")
            for name, chunk, start, end in self._entries:
                f.write(f"{name},{chunk},{start},{end}\n")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class SmooshedFileMapper:
    """Reader: mmap chunk files, hand out zero-copy memoryviews per part."""

    def __init__(self, directory: str):
        self.directory = directory
        self._parts: Dict[str, Tuple[int, int, int]] = {}
        with open(os.path.join(directory, META_FILE)) as f:
            header = f.readline().strip().split(",")
            if header[0] != "v1":
                raise ValueError(f"unknown smoosh version {header[0]!r}")
            n_chunks = int(header[2])
            for line in f:
                if not line.strip():
                    continue
                name, chunk, start, end = line.rsplit(",", 3)
                self._parts[name] = (int(chunk), int(start), int(end))
        self._maps: List[Optional[mmap.mmap]] = [None] * n_chunks
        self._files: List[Optional[object]] = [None] * n_chunks

    def names(self) -> List[str]:
        return list(self._parts.keys())

    def has(self, name: str) -> bool:
        return name in self._parts

    def part(self, name: str) -> memoryview:
        chunk, start, end = self._parts[name]
        if self._maps[chunk] is None:
            fh = open(os.path.join(self.directory, _chunk_name(chunk)), "rb")
            self._files[chunk] = fh
            self._maps[chunk] = mmap.mmap(fh.fileno(), 0,
                                          access=mmap.ACCESS_READ)
        return memoryview(self._maps[chunk])[start:end]

    def part_size(self, name: str) -> int:
        chunk, start, end = self._parts[name]
        return end - start

    def close(self):
        for i, m in enumerate(self._maps):
            if m is not None:
                m.close()
                self._maps[i] = None
            if self._files[i] is not None:
                self._files[i].close()
                self._files[i] = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
