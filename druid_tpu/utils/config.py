"""Layered configuration.

Reference analog: runtime.properties per node → Guice JsonConfigProvider /
JsonConfigurator binding `druid.*` property subtrees onto validated config
objects (api/.../guice/JsonConfigProvider.java), `PolyBind` selecting
implementations by property value, and per-query `query.context` overrides.

Layers (later wins): defaults → config file (.json or .properties) →
environment (DRUID_TPU_x_y for property x.y) → programmatic overrides.
"""
from __future__ import annotations

import json
import os
from typing import Callable, Dict, Optional


class Config:
    """Property keys are case-insensitive (stored lowercased) so the
    env layer — where names arrive upper-snake — composes with camelCase
    file/code keys."""

    def __init__(self, properties: Optional[Dict[str, object]] = None):
        self._props: Dict[str, object] = {
            k.lower(): v for k, v in (properties or {}).items()}

    # ---- layering ------------------------------------------------------
    @staticmethod
    def load(path: Optional[str] = None,
             env: Optional[Dict[str, str]] = None,
             overrides: Optional[Dict[str, object]] = None,
             env_prefix: str = "DRUID_TPU_") -> "Config":
        props: Dict[str, object] = {}
        if path and os.path.exists(path):
            if path.endswith(".json"):
                with open(path) as f:
                    props.update(_flatten(json.load(f)))
            else:
                with open(path) as f:
                    for line in f:
                        line = line.strip()
                        if not line or line.startswith(("#", "!")):
                            continue
                        if "=" in line:
                            k, v = line.split("=", 1)
                            props[k.strip()] = v.strip()
        for k, v in (env if env is not None else os.environ).items():
            if k.startswith(env_prefix):
                prop = k[len(env_prefix):].lower().replace("_", ".")
                props[prop] = v
        props.update(overrides or {})
        return Config(props)

    def with_overrides(self, overrides: Dict[str, object]) -> "Config":
        out = dict(self._props)
        out.update({k.lower(): v for k, v in overrides.items()})
        return Config(out)

    # ---- typed access --------------------------------------------------
    def get(self, key: str, default=None):
        return self._props.get(key.lower(), default)

    def get_int(self, key: str, default: int = 0) -> int:
        v = self._props.get(key.lower())
        return default if v is None else int(v)

    def get_float(self, key: str, default: float = 0.0) -> float:
        v = self._props.get(key.lower())
        return default if v is None else float(v)

    def get_bool(self, key: str, default: bool = False) -> bool:
        v = self._props.get(key.lower())
        if v is None:
            return default
        if isinstance(v, bool):
            return v
        return str(v).lower() in ("true", "1", "yes")

    def subtree(self, prefix: str) -> Dict[str, object]:
        """All `prefix.x` properties as {x: value} (JsonConfigProvider's
        subtree binding)."""
        p = prefix.lower().rstrip(".") + "."
        return {k[len(p):]: v for k, v in self._props.items()
                if k.startswith(p)}

    def select(self, key: str, registry: Dict[str, Callable], default: str,
               **kw):
        """PolyBind: instantiate the implementation named by a property."""
        kind = str(self._props.get(key.lower(), default))
        if kind not in registry:
            raise ValueError(
                f"unknown {key}={kind!r}; options: {sorted(registry)}")
        return registry[kind](**kw)

    def to_dict(self) -> Dict[str, object]:
        return dict(self._props)


def _flatten(tree: dict, prefix: str = "") -> Dict[str, object]:
    out: Dict[str, object] = {}
    for k, v in tree.items():
        key = f"{prefix}{k}" if not prefix else f"{prefix}.{k}"
        if isinstance(v, dict):
            out.update(_flatten(v, key))
        else:
            out[key] = v
    return out
