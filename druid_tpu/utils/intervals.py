"""Time intervals in epoch milliseconds.

Equivalent role to org.joda.time.Interval as used throughout the reference
(e.g. common/src/main/java/org/apache/druid/timeline/VersionedIntervalTimeline.java).
All timestamps in the framework are UTC epoch millis (int64).
"""
from __future__ import annotations

import datetime as _dt
import re
from dataclasses import dataclass
from typing import Iterable, List, Optional, Sequence, Tuple

_EPOCH = _dt.datetime(1970, 1, 1, tzinfo=_dt.timezone.utc)

ETERNITY_START = -(2**62)
ETERNITY_END = 2**62


def parse_ts(value) -> int:
    """Parse a timestamp (ISO string / datetime / int millis) to epoch millis."""
    if isinstance(value, bool):
        raise TypeError("bool is not a timestamp")
    if isinstance(value, int):
        return value
    if isinstance(value, float):
        return int(value)
    if isinstance(value, _dt.datetime):
        if value.tzinfo is None:
            value = value.replace(tzinfo=_dt.timezone.utc)
        return int(value.timestamp() * 1000)
    if isinstance(value, str):
        s = value.strip()
        # eternity bounds round-trip through their own wire tokens —
        # an unbounded query serialized to a remote node must parse back
        if s == "-eternity":
            return ETERNITY_START
        if s in ("+eternity", "eternity"):
            return ETERNITY_END
        # Normalize bare date / missing tz
        m = re.match(r"^(\d{4})-(\d{2})-(\d{2})$", s)
        if m:
            d = _dt.datetime(int(m.group(1)), int(m.group(2)), int(m.group(3)),
                             tzinfo=_dt.timezone.utc)
            return int(d.timestamp() * 1000)
        if s.endswith("Z"):
            s = s[:-1] + "+00:00"
        d = _dt.datetime.fromisoformat(s)
        if d.tzinfo is None:
            d = d.replace(tzinfo=_dt.timezone.utc)
        return int(d.timestamp() * 1000)
    raise TypeError(f"cannot parse timestamp from {value!r}")


def ts_to_iso(ms: int) -> str:
    if ms <= ETERNITY_START:
        return "-eternity"
    if ms >= ETERNITY_END:
        return "+eternity"
    d = _EPOCH + _dt.timedelta(milliseconds=int(ms))
    return d.strftime("%Y-%m-%dT%H:%M:%S.") + f"{ms % 1000:03d}Z"


@dataclass(frozen=True, order=True)
class Interval:
    """Half-open [start, end) interval in epoch millis."""
    start: int
    end: int

    def __post_init__(self):
        if self.end < self.start:
            raise ValueError(f"end < start: {self}")

    @staticmethod
    def of(start, end) -> "Interval":
        return Interval(parse_ts(start), parse_ts(end))

    @staticmethod
    def parse(s: str) -> "Interval":
        a, b = s.split("/")
        return Interval.of(a, b)

    @staticmethod
    def eternity() -> "Interval":
        return Interval(ETERNITY_START, ETERNITY_END)

    def overlaps(self, other: "Interval") -> bool:
        return self.start < other.end and other.start < self.end

    def contains(self, ms: int) -> bool:
        return self.start <= ms < self.end

    def contains_interval(self, other: "Interval") -> bool:
        return self.start <= other.start and other.end <= self.end

    def intersect(self, other: "Interval") -> Optional["Interval"]:
        s, e = max(self.start, other.start), min(self.end, other.end)
        if s >= e:
            return None
        return Interval(s, e)

    @property
    def width(self) -> int:
        return self.end - self.start

    def __str__(self) -> str:
        return f"{ts_to_iso(self.start)}/{ts_to_iso(self.end)}"


def condense(intervals: Iterable[Interval]) -> List[Interval]:
    """Merge overlapping/abutting intervals (JodaUtils.condenseIntervals analog)."""
    out: List[Interval] = []
    for iv in sorted(intervals, key=lambda i: (i.start, i.end)):
        if out and iv.start <= out[-1].end:
            if iv.end > out[-1].end:
                out[-1] = Interval(out[-1].start, iv.end)
        else:
            out.append(Interval(iv.start, iv.end))
    return out


_PERIOD_RE = re.compile(
    r"^P(?:(?P<y>\d+)Y)?(?:(?P<mo>\d+)M)?(?:(?P<w>\d+)W)?(?:(?P<d>\d+)D)?"
    r"(?:T(?:(?P<h>\d+)H)?(?:(?P<m>\d+)M)?(?:(?P<s>\d+)S)?)?$")

#: chunk-count ceiling for split_by_period — beyond this, splitting is
#: pure overhead (and an eternity-scale interval would try ~10^11 edges)
MAX_PERIOD_CHUNKS = 4096


def parse_period_ms(period) -> int:
    """ISO-8601 duration ('P1D', 'PT6H', 'P1W', 'P1M') or plain millis →
    milliseconds. Calendar units approximate (month=30d, year=365d): the
    only consumer is chunk SIZING, where results are split-invariant —
    boundaries need not be calendar-exact."""
    if isinstance(period, bool):
        raise TypeError("bool is not a period")
    if isinstance(period, (int, float)):
        return int(period)
    m = _PERIOD_RE.match(str(period).strip().upper())
    if not m or not any(m.groups()):
        raise ValueError(f"cannot parse period {period!r}")
    g = {k: int(v) if v else 0 for k, v in m.groupdict().items()}
    days = g["y"] * 365 + g["mo"] * 30 + g["w"] * 7 + g["d"]
    return ((days * 24 + g["h"]) * 60 + g["m"]) * 60_000 + g["s"] * 1000


def split_by_period(interval: Interval, period_ms: int,
                    origin_ms: int = 0) -> List[Interval]:
    """Split one interval at period boundaries aligned to `origin_ms`
    (reference: IntervalChunkingQueryRunner.java:67-133 — long intervals
    become parallel per-period chunks; aligned edges keep per-chunk cache
    keys stable across queries). Intervals that would exceed
    MAX_PERIOD_CHUNKS (e.g. eternity) pass through unsplit."""
    if period_ms <= 0 or interval.width <= period_ms \
            or interval.width // period_ms > MAX_PERIOD_CHUNKS:
        return [interval]
    edges = [interval.start]
    b = ((interval.start - origin_ms) // period_ms + 1) * period_ms \
        + origin_ms
    while b < interval.end:
        edges.append(b)
        b += period_ms
    edges.append(interval.end)
    return [Interval(a, b) for a, b in zip(edges, edges[1:]) if b > a]


def normalize_intervals(spec) -> List[Interval]:
    """Accept an Interval, 'start/end' string, or sequence of either."""
    if spec is None:
        return [Interval.eternity()]
    if isinstance(spec, Interval):
        return [spec]
    if isinstance(spec, str):
        return [Interval.parse(spec)]
    if isinstance(spec, (list, tuple)):
        out = []
        for item in spec:
            out.extend(normalize_intervals(item))
        return out
    raise TypeError(f"cannot normalize interval spec {spec!r}")
