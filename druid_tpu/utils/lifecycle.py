"""Ordered service lifecycle.

Reference analog: java-util/src/main/java/org/apache/druid/java/util/
common/lifecycle/Lifecycle.java — services register in a stage
(INIT → NORMAL → SERVER → COORDINATION → ANNOUNCEMENTS), start runs stages
in order and registration order within a stage, stop runs the exact
reverse, and a failed start unwinds whatever already started.
ANNOUNCEMENTS last means a node only becomes discoverable once everything
beneath it is serving — the property the ad-hoc try/finally assemblies
could not guarantee. COORDINATION (leader-latch participation) sits after
SERVER so a node only competes for leadership once its advertised
endpoint is live, and before ANNOUNCEMENTS so a winning node is leading
by the time it is discoverable; on stop the reverse order steps down from
the latch (releasing the lease for fast standby promotion) before the
HTTP server goes away.
"""
from __future__ import annotations

import enum
import logging
import threading
from typing import Callable, List, Optional

log = logging.getLogger(__name__)


class Stage(enum.IntEnum):
    INIT = 0            # metadata stores, config, extension registries
    NORMAL = 1          # coordinators, overlords, monitors
    SERVER = 2          # HTTP/socket servers begin accepting
    COORDINATION = 3    # leader-latch participation (heartbeats begin)
    ANNOUNCEMENTS = 4   # node announces itself into the cluster


class Lifecycle:
    """start() brings handlers up stage by stage; stop() tears down in
    exact reverse; a mid-start failure unwinds the started prefix and
    re-raises. Usable as a context manager."""

    def __init__(self):
        self._handlers: List[tuple] = []   # (stage, seq, name, start, stop)
        self._seq = 0
        self._started: List[tuple] = []
        self._lock = threading.Lock()
        self._stop_event = threading.Event()
        self.running = False
        self._in_start = False
        self._stop_requested = False

    def add(self, obj=None, *, start: Optional[Callable] = None,
            stop: Optional[Callable] = None, stage: Stage = Stage.NORMAL,
            name: Optional[str] = None) -> "Lifecycle":
        """Register `obj` (anything with .start()/.stop()) or explicit
        start/stop callables. Registration after start() is rejected —
        the reference's Lifecycle likewise refuses late joiners outside
        managed stages."""
        with self._lock:
            if self.running:
                raise RuntimeError("lifecycle already started")
            s = start if start is not None else getattr(obj, "start", None)
            t = stop if stop is not None else getattr(obj, "stop", None)
            if s is None and t is None:
                raise ValueError("nothing to manage: no start or stop")
            label = name or type(obj).__name__ if obj is not None \
                else (name or getattr(s, "__name__", "handler"))
            self._handlers.append((stage, self._seq, label, s, t))
            self._seq += 1
        return self

    def start(self) -> "Lifecycle":
        with self._lock:
            if self.running:
                return self
            self.running = True
            self._in_start = True
            self._stop_requested = False
            # restart after stop(): join() must block again
            self._stop_event.clear()
        aborted = False
        try:
            for h in sorted(self._handlers, key=lambda h: (h[0], h[1])):
                with self._lock:
                    if self._stop_requested:
                        aborted = True
                        break
                stage, _, label, start_fn, _ = h
                try:
                    if start_fn is not None:
                        start_fn()
                except BaseException:
                    log.exception("start failed at %s (stage %s); unwinding",
                                  label, stage.name)
                    self._unwind()
                    with self._lock:
                        self.running = False
                    raise
                with self._lock:
                    self._started.append(h)
        finally:
            with self._lock:
                self._in_start = False
                aborted = aborted or self._stop_requested
        if aborted:
            # a concurrent stop() arrived mid-start: this thread owns the
            # unwind so no just-started handler can leak
            self._unwind()
            with self._lock:
                self.running = False
            self._stop_event.set()
        return self

    def stop(self) -> None:
        with self._lock:
            if not self.running:
                return
            self._stop_requested = True
            if self._in_start:
                # the starting thread sees the flag and unwinds everything
                # it started — stopping here would race its handler loop
                return
            self.running = False
        self._unwind()
        self._stop_event.set()

    def _unwind(self) -> None:
        while True:
            # pop under the lock: start() appends under it, and a start
            # thread racing a stop() must not tear a list resize
            with self._lock:
                if not self._started:
                    return
                stage, _, label, _, stop_fn = self._started.pop()
            if stop_fn is None:
                continue
            try:
                stop_fn()
            except Exception:
                # teardown keeps going: one bad stop must not leak the rest
                log.exception("stop failed at %s (stage %s)", label,
                              stage.name)

    def join(self, timeout: Optional[float] = None) -> bool:
        """Block until stop() (e.g. from a signal handler)."""
        return self._stop_event.wait(timeout)

    def __enter__(self) -> "Lifecycle":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
