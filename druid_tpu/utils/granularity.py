"""Query granularities: time bucketing.

Capability parity with the reference's Granularity/Granularities
(java-util/src/main/java/org/apache/druid/java/util/common/granularity/).
Design difference (TPU-first): a granularity compiles to *bucket ids* — an
int32 array mapping each row to a dense bucket index for a query interval —
so that on-device aggregation is one `segment_sum` with a static bucket count,
instead of the reference's per-bucket cursor
(processing/.../segment/QueryableIndexStorageAdapter.java makeCursors).

Uniform (fixed-period) granularities bucket on-device from the segment's
int32 time-offset column; calendar granularities (month/quarter/year) are
bucketed host-side with vectorized numpy datetime64 arithmetic.
"""
from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import List, Optional, Tuple

import numpy as np

from druid_tpu.utils.intervals import Interval

MS_SECOND = 1000
MS_MINUTE = 60 * MS_SECOND
MS_HOUR = 60 * MS_MINUTE
MS_DAY = 24 * MS_HOUR
MS_WEEK = 7 * MS_DAY
# 1969-12-29 was a Monday; weeks bucket to Monday boundaries like Joda/Druid.
WEEK_ORIGIN_MS = -3 * MS_DAY


class GranularityType(enum.Enum):
    ALL = "all"
    NONE = "none"  # millisecond granularity
    SECOND = "second"
    MINUTE = "minute"
    FIVE_MINUTE = "five_minute"
    TEN_MINUTE = "ten_minute"
    FIFTEEN_MINUTE = "fifteen_minute"
    THIRTY_MINUTE = "thirty_minute"
    HOUR = "hour"
    SIX_HOUR = "six_hour"
    DAY = "day"
    WEEK = "week"
    MONTH = "month"
    QUARTER = "quarter"
    YEAR = "year"


_UNIFORM_MS = {
    GranularityType.NONE: 1,
    GranularityType.SECOND: MS_SECOND,
    GranularityType.MINUTE: MS_MINUTE,
    GranularityType.FIVE_MINUTE: 5 * MS_MINUTE,
    GranularityType.TEN_MINUTE: 10 * MS_MINUTE,
    GranularityType.FIFTEEN_MINUTE: 15 * MS_MINUTE,
    GranularityType.THIRTY_MINUTE: 30 * MS_MINUTE,
    GranularityType.HOUR: MS_HOUR,
    GranularityType.SIX_HOUR: 6 * MS_HOUR,
    GranularityType.DAY: MS_DAY,
    GranularityType.WEEK: MS_WEEK,
}

_CALENDAR_UNIT = {
    GranularityType.MONTH: "M",
    GranularityType.QUARTER: "M",  # 3-month groups, handled specially
    GranularityType.YEAR: "Y",
}


def _floor_div(a, b):
    return a // b  # python/numpy ints already floor-divide


@dataclass(frozen=True)
class Granularity:
    kind: GranularityType

    # ---- constructors -------------------------------------------------
    @staticmethod
    def of(name) -> "Granularity":
        if isinstance(name, Granularity):
            return name
        if isinstance(name, GranularityType):
            return Granularity(name)
        return Granularity(GranularityType(str(name).lower()))

    ALL: "Granularity" = None  # set below
    DAY: "Granularity" = None
    HOUR: "Granularity" = None

    # ---- properties ---------------------------------------------------
    @property
    def is_all(self) -> bool:
        return self.kind is GranularityType.ALL

    @property
    def is_uniform(self) -> bool:
        """True when buckets are fixed-width in millis (device-bucketable)."""
        return self.kind in _UNIFORM_MS

    @property
    def period_ms(self) -> Optional[int]:
        return _UNIFORM_MS.get(self.kind)

    @property
    def origin_ms(self) -> int:
        return WEEK_ORIGIN_MS if self.kind is GranularityType.WEEK else 0

    # ---- scalar ops ---------------------------------------------------
    def bucket_start(self, ms: int) -> int:
        """Truncate a timestamp to its bucket start."""
        if self.is_all:
            return ms
        if self.is_uniform:
            p, o = self.period_ms, self.origin_ms
            return _floor_div(ms - o, p) * p + o
        return int(self.bucket_start_array(np.asarray([ms], dtype=np.int64))[0])

    def bucket_start_array(self, ms: np.ndarray) -> np.ndarray:
        """Vectorized truncation to bucket starts (host-side)."""
        ms = np.asarray(ms, dtype=np.int64)
        if self.is_all:
            return ms
        if self.is_uniform:
            p, o = self.period_ms, self.origin_ms
            return (ms - o) // p * p + o
        dt = ms.astype("datetime64[ms]")
        if self.kind is GranularityType.YEAR:
            return dt.astype("datetime64[Y]").astype("datetime64[ms]").astype(np.int64)
        months = dt.astype("datetime64[M]")
        if self.kind is GranularityType.QUARTER:
            mi = months.astype(np.int64)
            months = ((mi // 3) * 3).astype("datetime64[M]")
        return months.astype("datetime64[ms]").astype(np.int64)

    def next_bucket(self, bucket_start_ms: int) -> int:
        if self.is_all:
            raise ValueError("ALL granularity has one unbounded bucket")
        if self.is_uniform:
            return bucket_start_ms + self.period_ms
        dt = np.int64(bucket_start_ms).astype("datetime64[ms]")
        if self.kind is GranularityType.YEAR:
            nxt = (dt.astype("datetime64[Y]") + 1).astype("datetime64[ms]")
        elif self.kind is GranularityType.QUARTER:
            nxt = (dt.astype("datetime64[M]") + 3).astype("datetime64[ms]")
        else:
            nxt = (dt.astype("datetime64[M]") + 1).astype("datetime64[ms]")
        return int(nxt.astype(np.int64))

    # ---- bucket enumeration for a query interval ----------------------
    def bucket_starts(self, interval: Interval) -> np.ndarray:
        """All bucket start timestamps whose bucket overlaps `interval`.

        For ALL, returns a single entry = interval.start (one global bucket),
        mirroring the reference's AllGranularity cursor behavior.
        """
        if self.is_all:
            return np.asarray([interval.start], dtype=np.int64)
        first = self.bucket_start(interval.start)
        if self.is_uniform:
            p = self.period_ms
            n = (interval.end - first + p - 1) // p
            n = max(int(n), 0)
            return first + np.arange(n, dtype=np.int64) * p
        starts = []
        cur = first
        while cur < interval.end:
            starts.append(cur)
            cur = self.next_bucket(cur)
        return np.asarray(starts, dtype=np.int64)

    def num_buckets(self, interval: Interval) -> int:
        return int(len(self.bucket_starts(interval)))

    def bucket_ids(self, ms: np.ndarray, interval: Interval) -> np.ndarray:
        """Map timestamps to dense bucket indices within `interval` (host path).

        Out-of-interval rows map to -1 (they must be masked out anyway).
        """
        ms = np.asarray(ms, dtype=np.int64)
        if self.is_all:
            ids = np.zeros(ms.shape, dtype=np.int32)
        else:
            starts = self.bucket_starts(interval)
            trunc = self.bucket_start_array(ms)
            ids = np.searchsorted(starts, trunc).astype(np.int32)
            ids[(trunc < starts[0]) | (trunc > starts[-1])] = -1
        ids[(ms < interval.start) | (ms >= interval.end)] = -1
        return ids

    def __str__(self):
        return self.kind.value


# canonical instances
Granularity.ALL = Granularity(GranularityType.ALL)
Granularity.DAY = Granularity(GranularityType.DAY)
Granularity.HOUR = Granularity(GranularityType.HOUR)
