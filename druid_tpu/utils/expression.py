"""Druid-style scalar expression language: parser + vectorized evaluator.

Capability parity with the reference's math expression language
(common/src/main/java/org/apache/druid/math/expr/Parser.java, Expr.java,
Function.java — ANTLR grammar over typed long/double/string exprs, used by
expression virtual columns and expression filters).

TPU-first difference: instead of a per-row interpreter, an expression
evaluates over whole columns at once — numpy arrays host-side or jax.numpy
arrays on device (the evaluator is backend-agnostic; under jit it traces to
fused XLA elementwise ops, which is strictly better than the reference's
boxed per-row eval).

Grammar (precedence low→high):
  || ; && ; ==, != ; <, <=, >, >= ; +, - ; *, /, % ; ^ ; unary -, ! ;
  literals (long, double, 'string'), identifiers, function calls.
"""
from __future__ import annotations

import math
import re
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

_TOKEN_RE = re.compile(r"""
    \s*(?:
      (?P<num>\d+\.\d*(?:[eE][+-]?\d+)?|\.\d+(?:[eE][+-]?\d+)?|\d+[eE][+-]?\d+)
    | (?P<int>\d+)
    | (?P<str>'(?:[^'\\]|\\.)*')
    | (?P<id>[A-Za-z_][A-Za-z0-9_.$]*)
    | (?P<op>\|\||&&|==|!=|<=|>=|[-+*/%^()!<>,])
    )""", re.VERBOSE)


def _tokenize(s: str) -> List[Tuple[str, str]]:
    out, pos = [], 0
    while pos < len(s):
        m = _TOKEN_RE.match(s, pos)
        if not m or m.end() == pos:
            if s[pos:].strip() == "":
                break
            raise ValueError(f"bad token at {s[pos:]!r}")
        pos = m.end()
        for kind in ("num", "int", "str", "id", "op"):
            v = m.group(kind)
            if v is not None:
                out.append((kind, v))
                break
    out.append(("eof", ""))
    return out


class Expr:
    def evaluate(self, bindings: Dict[str, object]):
        raise NotImplementedError

    def required_columns(self) -> set:
        return set()


@dataclass(frozen=True)
class Literal(Expr):
    value: object

    def evaluate(self, bindings):
        return self.value


@dataclass(frozen=True)
class Identifier(Expr):
    name: str

    def evaluate(self, bindings):
        if self.name not in bindings:
            raise KeyError(f"unbound identifier {self.name!r}")
        return bindings[self.name]

    def required_columns(self):
        return {self.name}


def _xp(*vals):
    """Pick the array module (jnp if any input is a jax array, else numpy)."""
    for v in vals:
        if type(v).__module__.startswith("jax"):
            import jax.numpy as jnp
            return jnp
    return np


def _to_num(v):
    if isinstance(v, bool):
        return int(v)
    return v


@dataclass(frozen=True)
class BinaryOp(Expr):
    op: str
    left: Expr
    right: Expr

    def evaluate(self, b):
        l = _to_num(self.left.evaluate(b))
        r = _to_num(self.right.evaluate(b))
        op = self.op
        if op == "+":
            return l + r
        if op == "-":
            return l - r
        if op == "*":
            return l * r
        if op == "/":
            xp = _xp(l, r)
            if isinstance(l, (int, np.integer)) and isinstance(r, (int, np.integer)):
                return l // r if r else 0
            return xp.where(r != 0, l / xp.where(r != 0, r, 1), 0.0) \
                if not np.isscalar(r) or hasattr(r, "shape") else (l / r if r else 0.0)
        if op == "%":
            return l % r
        if op == "^":
            xp = _xp(l, r)
            return xp.power(l, r) if hasattr(l, "shape") or hasattr(r, "shape") \
                else l ** r
        if op == "==":
            return l == r
        if op == "!=":
            return l != r
        if op == "<":
            return l < r
        if op == "<=":
            return l <= r
        if op == ">":
            return l > r
        if op == ">=":
            return l >= r
        if op == "&&":
            xp = _xp(l, r)
            return xp.logical_and(xp.asarray(l, dtype=bool) if hasattr(l, "shape") else bool(l),
                                  xp.asarray(r, dtype=bool) if hasattr(r, "shape") else bool(r))
        if op == "||":
            xp = _xp(l, r)
            return xp.logical_or(xp.asarray(l, dtype=bool) if hasattr(l, "shape") else bool(l),
                                 xp.asarray(r, dtype=bool) if hasattr(r, "shape") else bool(r))
        raise ValueError(op)

    def required_columns(self):
        return self.left.required_columns() | self.right.required_columns()


@dataclass(frozen=True)
class UnaryOp(Expr):
    op: str
    operand: Expr

    def evaluate(self, b):
        v = _to_num(self.operand.evaluate(b))
        if self.op == "-":
            return -v
        xp = _xp(v)
        return xp.logical_not(v) if hasattr(v, "shape") else (not v)

    def required_columns(self):
        return self.operand.required_columns()


def _fn_if(cond, a, b):
    xp = _xp(cond, a, b)
    if hasattr(cond, "shape"):
        return xp.where(cond, a, b)
    return a if cond else b


_FUNCTIONS: Dict[str, Callable] = {
    "abs": lambda x: _xp(x).abs(x) if hasattr(x, "shape") else abs(x),
    "ceil": lambda x: _xp(x).ceil(x) if hasattr(x, "shape") else math.ceil(x),
    "floor": lambda x: _xp(x).floor(x) if hasattr(x, "shape") else math.floor(x),
    "exp": lambda x: _xp(x).exp(x) if hasattr(x, "shape") else math.exp(x),
    "log": lambda x: _xp(x).log(x) if hasattr(x, "shape") else math.log(x),
    "log10": lambda x: _xp(x).log10(x) if hasattr(x, "shape") else math.log10(x),
    "sqrt": lambda x: _xp(x).sqrt(x) if hasattr(x, "shape") else math.sqrt(x),
    "sin": lambda x: _xp(x).sin(x) if hasattr(x, "shape") else math.sin(x),
    "cos": lambda x: _xp(x).cos(x) if hasattr(x, "shape") else math.cos(x),
    "tan": lambda x: _xp(x).tan(x) if hasattr(x, "shape") else math.tan(x),
    "min": lambda a, b: _xp(a, b).minimum(a, b)
        if hasattr(a, "shape") or hasattr(b, "shape") else min(a, b),
    "max": lambda a, b: _xp(a, b).maximum(a, b)
        if hasattr(a, "shape") or hasattr(b, "shape") else max(a, b),
    "pow": lambda a, b: _xp(a, b).power(a, b)
        if hasattr(a, "shape") or hasattr(b, "shape") else a ** b,
    "if": _fn_if,
    "nvl": lambda a, b: b if a is None else a,
    "cast": lambda x, t: x,  # typing handled by output column dtype
}


@dataclass(frozen=True)
class FunctionCall(Expr):
    name: str
    args: Tuple[Expr, ...]

    def evaluate(self, b):
        fn = _FUNCTIONS.get(self.name)
        if fn is None:
            raise ValueError(f"unknown function {self.name!r}")
        return fn(*[a.evaluate(b) for a in self.args])

    def required_columns(self):
        out = set()
        for a in self.args:
            out |= a.required_columns()
        return out


@dataclass(frozen=True)
class DimLut(Expr):
    """A comparison over a STRING dimension, precomputed at plan time as a
    per-dictionary-id boolean LUT: device evaluation is one gather
    `lut[ids]`. This is how string semantics ride the TPU path — the device
    only ever sees integer ids; every string computation happens host-side
    over the (small) dictionary (reference: ExpressionVirtualColumn
    evaluates per row on the JVM; here per VALUE, once)."""
    dim: str
    index: int          # position in the bindings["__luts"] sequence

    def evaluate(self, b):
        return b["__luts"][self.index][b[self.dim]]

    def required_columns(self):
        return {self.dim}


_STR_CMP_FLIP = {"==": "==", "!=": "!=", "<": ">", "<=": ">=",
                 ">": "<", ">=": "<="}


def rewrite_string_sites(expr: Expr, string_dims) -> Tuple[Expr, List[tuple]]:
    """Replace (string dim ⋄ string literal) comparisons with DimLut
    gathers. Returns (rewritten expr, sites) where sites[i] = (dim, op,
    literal) defines LUT i; `lut_for_site` computes its contents from a
    concrete dictionary. Deterministic in expression structure, so the
    rewritten AST is shareable across segments while LUT contents ride the
    per-segment aux stream. Any OTHER use of a string dim in the expression
    raises — silently comparing dictionary ids would be wrong."""
    sites: List[tuple] = []

    def walk(e: Expr) -> Expr:
        if isinstance(e, BinaryOp):
            l, r = e.left, e.right
            if e.op in _STR_CMP_FLIP:
                if (isinstance(l, Identifier) and l.name in string_dims
                        and isinstance(r, Literal)
                        and isinstance(r.value, str)):
                    sites.append((l.name, e.op, r.value))
                    return DimLut(l.name, len(sites) - 1)
                if (isinstance(r, Identifier) and r.name in string_dims
                        and isinstance(l, Literal)
                        and isinstance(l.value, str)):
                    sites.append((r.name, _STR_CMP_FLIP[e.op], l.value))
                    return DimLut(r.name, len(sites) - 1)
            return BinaryOp(e.op, walk(l), walk(r))
        if isinstance(e, UnaryOp):
            return UnaryOp(e.op, walk(e.operand))
        if isinstance(e, FunctionCall):
            return FunctionCall(e.name, tuple(walk(a) for a in e.args))
        if isinstance(e, Identifier) and e.name in string_dims:
            raise ValueError(
                f"string dimension {e.name!r} used outside a "
                f"string-literal comparison — not expressible as a device "
                f"expression (wrap it in a LUT-able comparison)")
        return e

    return walk(expr), sites


def lut_for_site(site: tuple, values) -> np.ndarray:
    """Boolean per-dictionary-id LUT for one rewrite site (lexicographic
    ordering, matching the reference's StringComparators.LEXICOGRAPHIC)."""
    dim, op, lit = site
    vals = np.asarray(list(values), dtype=object)
    if op == "==":
        out = vals == lit
    elif op == "!=":
        out = vals != lit
    elif op == "<":
        out = vals < lit
    elif op == "<=":
        out = vals <= lit
    elif op == ">":
        out = vals > lit
    else:
        out = vals >= lit
    return np.asarray(out, dtype=bool)


class _Parser:
    _BINARY = [
        {"||"}, {"&&"}, {"==", "!="}, {"<", "<=", ">", ">="},
        {"+", "-"}, {"*", "/", "%"}, {"^"},
    ]

    def __init__(self, tokens):
        self.toks = tokens
        self.i = 0

    def peek(self):
        return self.toks[self.i]

    def next(self):
        t = self.toks[self.i]
        self.i += 1
        return t

    def expect(self, op):
        k, v = self.next()
        if k != "op" or v != op:
            raise ValueError(f"expected {op!r}, got {v!r}")

    def parse(self) -> Expr:
        e = self.parse_level(0)
        if self.peek()[0] != "eof":
            raise ValueError(f"trailing tokens: {self.toks[self.i:]}")
        return e

    def parse_level(self, level) -> Expr:
        if level >= len(self._BINARY):
            return self.parse_unary()
        left = self.parse_level(level + 1)
        while True:
            k, v = self.peek()
            if k == "op" and v in self._BINARY[level]:
                self.next()
                right = self.parse_level(level + 1)
                left = BinaryOp(v, left, right)
            else:
                return left

    def parse_unary(self) -> Expr:
        k, v = self.peek()
        if k == "op" and v in ("-", "!"):
            self.next()
            return UnaryOp(v, self.parse_unary())
        return self.parse_atom()

    def parse_atom(self) -> Expr:
        k, v = self.next()
        if k == "int":
            return Literal(int(v))
        if k == "num":
            return Literal(float(v))
        if k == "str":
            return Literal(v[1:-1].replace("\\'", "'"))
        if k == "id":
            if self.peek() == ("op", "("):
                self.next()
                args = []
                if self.peek() != ("op", ")"):
                    args.append(self.parse_level(0))
                    while self.peek() == ("op", ","):
                        self.next()
                        args.append(self.parse_level(0))
                self.expect(")")
                return FunctionCall(v, tuple(args))
            return Identifier(v)
        if k == "op" and v == "(":
            e = self.parse_level(0)
            self.expect(")")
            return e
        raise ValueError(f"unexpected token {v!r}")


_CACHE: Dict[str, Expr] = {}


def parse_expression(s: str) -> Expr:
    e = _CACHE.get(s)
    if e is None:
        e = _Parser(_tokenize(s)).parse()
        _CACHE[s] = e
    return e
