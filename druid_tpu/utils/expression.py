"""Druid-style scalar expression language: parser + vectorized evaluator.

Capability parity with the reference's math expression language
(common/src/main/java/org/apache/druid/math/expr/Parser.java, Expr.java,
Function.java — ANTLR grammar over typed long/double/string exprs, used by
expression virtual columns and expression filters).

TPU-first difference: instead of a per-row interpreter, an expression
evaluates over whole columns at once — numpy arrays host-side or jax.numpy
arrays on device (the evaluator is backend-agnostic; under jit it traces to
fused XLA elementwise ops, which is strictly better than the reference's
boxed per-row eval).

Grammar (precedence low→high):
  || ; && ; ==, != ; <, <=, >, >= ; +, - ; *, /, % ; ^ ; unary -, ! ;
  literals (long, double, 'string'), identifiers, function calls.
"""
from __future__ import annotations

import math
import re
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

_TOKEN_RE = re.compile(r"""
    \s*(?:
      (?P<num>\d+\.\d*(?:[eE][+-]?\d+)?|\.\d+(?:[eE][+-]?\d+)?|\d+[eE][+-]?\d+)
    | (?P<int>\d+)
    | (?P<str>'(?:[^'\\]|\\.)*')
    | (?P<id>[A-Za-z_][A-Za-z0-9_.$]*)
    | (?P<op>\|\||&&|==|!=|<=|>=|[-+*/%^()!<>,])
    )""", re.VERBOSE)


def _tokenize(s: str) -> List[Tuple[str, str]]:
    out, pos = [], 0
    while pos < len(s):
        m = _TOKEN_RE.match(s, pos)
        if not m or m.end() == pos:
            if s[pos:].strip() == "":
                break
            raise ValueError(f"bad token at {s[pos:]!r}")
        pos = m.end()
        for kind in ("num", "int", "str", "id", "op"):
            v = m.group(kind)
            if v is not None:
                out.append((kind, v))
                break
    out.append(("eof", ""))
    return out


class Expr:
    def evaluate(self, bindings: Dict[str, object]):
        raise NotImplementedError

    def required_columns(self) -> set:
        return set()


@dataclass(frozen=True)
class Literal(Expr):
    value: object

    def evaluate(self, bindings):
        return self.value


@dataclass(frozen=True)
class Identifier(Expr):
    name: str

    def evaluate(self, bindings):
        if self.name not in bindings:
            raise KeyError(f"unbound identifier {self.name!r}")
        return bindings[self.name]

    def required_columns(self):
        return {self.name}


def _xp(*vals):
    """Pick the array module (jnp if any input is a jax array, else numpy)."""
    for v in vals:
        if type(v).__module__.startswith("jax"):
            import jax.numpy as jnp
            return jnp
    return np


def _to_num(v):
    if isinstance(v, bool):
        return int(v)
    return v


@dataclass(frozen=True)
class BinaryOp(Expr):
    op: str
    left: Expr
    right: Expr

    def evaluate(self, b):
        l = _to_num(self.left.evaluate(b))
        r = _to_num(self.right.evaluate(b))
        op = self.op
        if op == "+":
            return l + r
        if op == "-":
            return l - r
        if op == "*":
            return l * r
        if op == "/":
            xp = _xp(l, r)
            if isinstance(l, (int, np.integer)) and isinstance(r, (int, np.integer)):
                return l // r if r else 0
            return xp.where(r != 0, l / xp.where(r != 0, r, 1), 0.0) \
                if not np.isscalar(r) or hasattr(r, "shape") else (l / r if r else 0.0)
        if op == "%":
            return l % r
        if op == "^":
            xp = _xp(l, r)
            return xp.power(l, r) if hasattr(l, "shape") or hasattr(r, "shape") \
                else l ** r
        if op == "==":
            return l == r
        if op == "!=":
            return l != r
        if op == "<":
            return l < r
        if op == "<=":
            return l <= r
        if op == ">":
            return l > r
        if op == ">=":
            return l >= r
        if op == "&&":
            xp = _xp(l, r)
            return xp.logical_and(xp.asarray(l, dtype=bool) if hasattr(l, "shape") else bool(l),
                                  xp.asarray(r, dtype=bool) if hasattr(r, "shape") else bool(r))
        if op == "||":
            xp = _xp(l, r)
            return xp.logical_or(xp.asarray(l, dtype=bool) if hasattr(l, "shape") else bool(l),
                                 xp.asarray(r, dtype=bool) if hasattr(r, "shape") else bool(r))
        raise ValueError(op)

    def required_columns(self):
        return self.left.required_columns() | self.right.required_columns()


@dataclass(frozen=True)
class UnaryOp(Expr):
    op: str
    operand: Expr

    def evaluate(self, b):
        v = _to_num(self.operand.evaluate(b))
        if self.op == "-":
            return -v
        xp = _xp(v)
        return xp.logical_not(v) if hasattr(v, "shape") else (not v)

    def required_columns(self):
        return self.operand.required_columns()


def _str_fn_err(name: str):
    raise ValueError(
        f"{name}() over a non-dictionary operand is not expressible on "
        "the device path — apply it to a string dimension (LUT rewrite) "
        "or a string literal")


def _fn_if(cond, a, b):
    xp = _xp(cond, a, b)
    if hasattr(cond, "shape"):
        return xp.where(cond, a, b)
    return a if cond else b


_MS_DAY = 86_400_000


def _fdiv(a, b):
    """Floor division that works for np/jnp arrays and python ints."""
    xp = _xp(a, b)
    if hasattr(a, "shape") or hasattr(b, "shape"):
        return xp.floor_divide(a, b)
    return a // b


def _civil(t_ms):
    """(year, month, day, days-since-epoch) from epoch millis — Hinnant's
    civil-from-days in pure integer arithmetic, so it traces to XLA
    elementwise ops (no host calendar lookups on the device path)."""
    days = _fdiv(t_ms, _MS_DAY)
    z = days + 719468
    era = _fdiv(z, 146097)
    doe = z - era * 146097
    yoe = _fdiv(doe - _fdiv(doe, 1460) + _fdiv(doe, 36524)
                - _fdiv(doe, 146096), 365)
    y = yoe + era * 400
    doy = doe - (365 * yoe + _fdiv(yoe, 4) - _fdiv(yoe, 100))
    mp = _fdiv(5 * doy + 2, 153)
    d = doy - _fdiv(153 * mp + 2, 5) + 1
    m = mp + _where_num(mp < 10, 3, -9)
    y = y + _where_num(m <= 2, 1, 0)
    return y, m, d, days


def _days_from_civil(y, m, d):
    ya = y - _where_num(m <= 2, 1, 0)
    era = _fdiv(ya, 400)
    yoe = ya - era * 400
    doy = _fdiv(153 * (m + _where_num(m > 2, -3, 9)) + 2, 5) + d - 1
    doe = yoe * 365 + _fdiv(yoe, 4) - _fdiv(yoe, 100) + doy
    return era * 146097 + doe - 719468


def _where_num(cond, a, b):
    return _fn_if(cond, a, b)


#: units _fn_timestamp_extract understands (planners validate against this
#: so an unsupported unit is a plan-time error, not a runtime one)
EXTRACT_UNITS = frozenset({
    "EPOCH", "MILLISECOND", "SECOND", "MINUTE", "HOUR", "DAY", "DOW",
    "DOY", "MONTH", "QUARTER", "YEAR"})


def _fn_timestamp_extract(t, unit):
    """EXTRACT unit from epoch millis (reference: TimestampExtractExprMacro
    semantics; DOW ISO 1=Mon..7=Sun)."""
    u = str(unit).upper()
    msod = t - _fdiv(t, _MS_DAY) * _MS_DAY
    if u == "EPOCH":
        return _fdiv(t, 1000)
    if u == "MILLISECOND":
        return msod % 1000
    if u == "SECOND":
        return _fdiv(msod, 1000) % 60
    if u == "MINUTE":
        return _fdiv(msod, 60_000) % 60
    if u == "HOUR":
        return _fdiv(msod, 3_600_000)
    y, m, d, days = _civil(t)
    if u == "YEAR":
        return y
    if u == "QUARTER":
        return _fdiv(m + 2, 3)
    if u == "MONTH":
        return m
    if u == "DAY":
        return d
    if u == "DOW":
        return (days + 3) % 7 + 1
    if u == "DOY":
        return days - _days_from_civil(y, 1, 0)
    raise ValueError(f"unknown EXTRACT unit {unit!r}")


def _fn_timestamp_floor(t, period_ms, origin=0):
    return _fdiv(t - origin, period_ms) * period_ms + origin


def _fn_greatest(*vals):
    out = vals[0]
    for v in vals[1:]:
        out = _FUNCTIONS["max"](out, v)
    return out


def _fn_least(*vals):
    out = vals[0]
    for v in vals[1:]:
        out = _FUNCTIONS["min"](out, v)
    return out


def _fn_safe_div(a, b):
    xp = _xp(a, b)
    if hasattr(a, "shape") or hasattr(b, "shape"):
        return xp.where(b != 0, a / xp.where(b != 0, b, 1), 0.0)
    return a / b if b else 0.0


def _trunc_div_ints(a, b):
    """Exact truncated integer division (no float64 round-trip — longs
    above 2^53 must divide exactly)."""
    q = abs(a) // abs(b)
    return q if (a >= 0) == (b >= 0) else -q


def _fn_mod(a, b):
    """Truncated modulo — sign of the DIVIDEND, matching Druid/Calcite
    (Java %), not python's floored modulo. Exact for integers."""
    xp = _xp(a, b)
    if hasattr(a, "shape") or hasattr(b, "shape"):
        import numpy as _np
        if _np.issubdtype(getattr(a, "dtype", _np.float64), _np.integer) \
                and _np.issubdtype(getattr(b, "dtype", _np.int64),
                                   _np.integer):
            # integer-exact: a - trunc(a/b)*b in pure int arithmetic
            bb = xp.where(b != 0, b, 1)
            q = xp.where(b != 0, abs(a) // abs(bb), 0)
            q = xp.where((a >= 0) == (bb >= 0), q, -q)
            return a - q * bb
        return xp.fmod(a, b)
    if isinstance(a, int) and isinstance(b, int):
        return a - _trunc_div_ints(a, b) * b if b else a
    return math.fmod(a, b)


def _fn_int_div(a, b):
    """Druid expression div(): integer (long) division truncated toward
    zero; division by zero yields 0. Exact for integers (no float64
    round-trip)."""
    xp = _xp(a, b)
    if hasattr(a, "shape") or hasattr(b, "shape"):
        import numpy as _np
        if _np.issubdtype(getattr(a, "dtype", _np.float64), _np.integer) \
                and _np.issubdtype(getattr(b, "dtype", _np.int64),
                                   _np.integer):
            bb = xp.where(b != 0, b, 1)
            q = xp.where(b != 0, abs(a) // abs(bb), 0)
            return xp.where((a >= 0) == (bb >= 0), q, -q).astype("int64")
        q = xp.where(b != 0, a / xp.where(b != 0, b, 1), 0)
        return xp.trunc(q).astype("int64")
    if not b:
        return 0
    if isinstance(a, int) and isinstance(b, int):
        return _trunc_div_ints(a, b)
    return int(a / b)


def _fn_round(x, n=0):
    """ROUND half-AWAY-FROM-ZERO with optional decimal places (Druid
    semantics; numpy/python's default is banker's rounding). Integers with
    n >= 0 return unchanged — a float64 round-trip would corrupt longs
    above 2^53."""
    xp = _xp(x)
    import numpy as _np
    n = int(n)
    scale = 10 ** n if n >= 0 else 0
    if hasattr(x, "shape"):
        if _np.issubdtype(getattr(x, "dtype", _np.float64), _np.integer):
            if n >= 0:
                return x
            s = 10 ** (-n)   # exact integer rounding to tens/hundreds/...
            q = (abs(x) + s // 2) // s * s
            return xp.where(x >= 0, q, -q).astype(x.dtype)
        if n < 0:
            s = 10 ** (-n)
            return xp.sign(x) * xp.floor(xp.abs(x) / s + 0.5) * s
        return xp.sign(x) * xp.floor(xp.abs(x) * scale + 0.5) / scale
    if isinstance(x, int):
        if n >= 0:
            return x
        s = 10 ** (-n)
        q = (abs(x) + s // 2) // s * s
        return q if x >= 0 else -q
    if n < 0:
        s = 10 ** (-n)
        return math.copysign(math.floor(abs(x) / s + 0.5), x) * s
    return math.copysign(math.floor(abs(x) * scale + 0.5), x) / scale


_FUNCTIONS: Dict[str, Callable] = {
    "abs": lambda x: _xp(x).abs(x) if hasattr(x, "shape") else abs(x),
    "ceil": lambda x: _xp(x).ceil(x) if hasattr(x, "shape") else math.ceil(x),
    "floor": lambda x: _xp(x).floor(x) if hasattr(x, "shape") else math.floor(x),
    "exp": lambda x: _xp(x).exp(x) if hasattr(x, "shape") else math.exp(x),
    "log": lambda x: _xp(x).log(x) if hasattr(x, "shape") else math.log(x),
    "log10": lambda x: _xp(x).log10(x) if hasattr(x, "shape") else math.log10(x),
    "sqrt": lambda x: _xp(x).sqrt(x) if hasattr(x, "shape") else math.sqrt(x),
    "sin": lambda x: _xp(x).sin(x) if hasattr(x, "shape") else math.sin(x),
    "cos": lambda x: _xp(x).cos(x) if hasattr(x, "shape") else math.cos(x),
    "tan": lambda x: _xp(x).tan(x) if hasattr(x, "shape") else math.tan(x),
    "asin": lambda x: _xp(x).arcsin(x) if hasattr(x, "shape")
        else math.asin(x),
    "acos": lambda x: _xp(x).arccos(x) if hasattr(x, "shape")
        else math.acos(x),
    "atan": lambda x: _xp(x).arctan(x) if hasattr(x, "shape")
        else math.atan(x),
    "atan2": lambda y, x: _xp(y, x).arctan2(y, x)
        if hasattr(y, "shape") or hasattr(x, "shape") else math.atan2(y, x),
    "cot": lambda x: (1.0 / _xp(x).tan(x)) if hasattr(x, "shape")
        else (1.0 / math.tan(x)),
    "log10": lambda x: _xp(x).log10(x) if hasattr(x, "shape")
        else math.log10(x),
    "degrees": lambda x: _xp(x).degrees(x) if hasattr(x, "shape")
        else math.degrees(x),
    "radians": lambda x: _xp(x).radians(x) if hasattr(x, "shape")
        else math.radians(x),
    "pi": lambda: math.pi,
    # string fns evaluate host-side over python strings (literals); over
    # a string DIMENSION they are rewritten to LUT gathers BEFORE eval
    # (rewrite_string_sites) — reaching here with an array means the
    # rewrite didn't apply, and a clear error beats len() of a tracer
    "strlen": lambda x: len(x) if isinstance(x, str) else _str_fn_err(
        "strlen"),
    "strpos": lambda x, y: (x.find(y) if isinstance(x, str)
                            and isinstance(y, str)
                            else _str_fn_err("strpos")),
    "min": lambda a, b: _xp(a, b).minimum(a, b)
        if hasattr(a, "shape") or hasattr(b, "shape") else min(a, b),
    "max": lambda a, b: _xp(a, b).maximum(a, b)
        if hasattr(a, "shape") or hasattr(b, "shape") else max(a, b),
    "pow": lambda a, b: _xp(a, b).power(a, b)
        if hasattr(a, "shape") or hasattr(b, "shape") else a ** b,
    "if": _fn_if,
    "nvl": lambda a, b: b if a is None else a,
    "cast": lambda x, t: x,  # typing handled by output column dtype
    "round": _fn_round,
    "sign": lambda x: _xp(x).sign(x) if hasattr(x, "shape")
        else (0 if x == 0 else (1 if x > 0 else -1)),
    "trunc": lambda x: _xp(x).trunc(x) if hasattr(x, "shape")
        else math.trunc(x),
    "mod": _fn_mod,
    "greatest": _fn_greatest,
    "least": _fn_least,
    "div": _fn_int_div,
    "safe_divide": _fn_safe_div,
    "timestamp_floor": _fn_timestamp_floor,
    "timestamp_shift": lambda t, period_ms, n: t + period_ms * n,
    "timestamp_extract": _fn_timestamp_extract,
}


@dataclass(frozen=True)
class FunctionCall(Expr):
    name: str
    args: Tuple[Expr, ...]

    def evaluate(self, b):
        fn = _FUNCTIONS.get(self.name)
        if fn is None:
            raise ValueError(f"unknown function {self.name!r}")
        return fn(*[a.evaluate(b) for a in self.args])

    def required_columns(self):
        out = set()
        for a in self.args:
            out |= a.required_columns()
        return out


@dataclass(frozen=True)
class DimLut(Expr):
    """A comparison over a STRING dimension, precomputed at plan time as a
    per-dictionary-id boolean LUT: device evaluation is one gather
    `lut[ids]`. This is how string semantics ride the TPU path — the device
    only ever sees integer ids; every string computation happens host-side
    over the (small) dictionary (reference: ExpressionVirtualColumn
    evaluates per row on the JVM; here per VALUE, once)."""
    dim: str
    index: int          # position in the bindings["__luts"] sequence

    def evaluate(self, b):
        return b["__luts"][self.index][b[self.dim]]

    def required_columns(self):
        return {self.dim}


_STR_CMP_FLIP = {"==": "==", "!=": "!=", "<": ">", "<=": ">=",
                 ">": "<", ">=": "<="}

#: string→NUMERIC per-dictionary-value functions: like comparisons, they
#: precompute one numeric LUT per site and the device gathers `lut[ids]`
#: (strlen/strpos ride the same DimLut node; the gather result simply
#: carries the LUT's dtype)
_STR_NUM_FNS = {
    "strlen": lambda vals, _lit: np.asarray(
        [0 if v is None else len(v) for v in vals], dtype=np.int32),
    # DRUID-native semantics: 0-based index, -1 when absent (the SQL
    # layer emits strpos(...)+1 for SQL's 1-based STRPOS/POSITION)
    "strpos": lambda vals, lit: np.asarray(
        [-1 if v is None else v.find(lit) for v in vals],
        dtype=np.int32),
}
_STR_NUM_ARITY = {"strlen": 1, "strpos": 2}


def rewrite_string_sites(expr: Expr, string_dims) -> Tuple[Expr, List[tuple]]:
    """Replace (string dim ⋄ string literal) comparisons with DimLut
    gathers. Returns (rewritten expr, sites) where sites[i] = (dim, op,
    literal) defines LUT i; `lut_for_site` computes its contents from a
    concrete dictionary. Deterministic in expression structure, so the
    rewritten AST is shareable across segments while LUT contents ride the
    per-segment aux stream. Any OTHER use of a string dim in the expression
    raises — silently comparing dictionary ids would be wrong."""
    sites: List[tuple] = []

    def walk(e: Expr) -> Expr:
        if isinstance(e, BinaryOp):
            l, r = e.left, e.right
            if e.op in _STR_CMP_FLIP:
                if (isinstance(l, Identifier) and l.name in string_dims
                        and isinstance(r, Literal)
                        and isinstance(r.value, str)):
                    sites.append((l.name, e.op, r.value))
                    return DimLut(l.name, len(sites) - 1)
                if (isinstance(r, Identifier) and r.name in string_dims
                        and isinstance(l, Literal)
                        and isinstance(l.value, str)):
                    sites.append((r.name, _STR_CMP_FLIP[e.op], l.value))
                    return DimLut(r.name, len(sites) - 1)
            return BinaryOp(e.op, walk(l), walk(r))
        if isinstance(e, UnaryOp):
            return UnaryOp(e.op, walk(e.operand))
        if isinstance(e, FunctionCall):
            if e.name in _STR_NUM_FNS \
                    and len(e.args) == _STR_NUM_ARITY[e.name] \
                    and isinstance(e.args[0], Identifier) \
                    and e.args[0].name in string_dims \
                    and all(isinstance(a, Literal) and isinstance(a.value,
                                                                  str)
                            for a in e.args[1:]):
                lit = e.args[1].value if len(e.args) > 1 else None
                sites.append((e.args[0].name, e.name, lit))
                return DimLut(e.args[0].name, len(sites) - 1)
            return FunctionCall(e.name, tuple(walk(a) for a in e.args))
        if isinstance(e, Identifier) and e.name in string_dims:
            raise ValueError(
                f"string dimension {e.name!r} used outside a "
                f"string-literal comparison — not expressible as a device "
                f"expression (wrap it in a LUT-able comparison)")
        return e

    return walk(expr), sites


def lut_for_site(site: tuple, values) -> np.ndarray:
    """Per-dictionary-id LUT for one rewrite site: BOOLEAN for comparison
    sites (lexicographic ordering, matching the reference's
    StringComparators.LEXICOGRAPHIC), INT32 for string→numeric function
    sites (strlen/strpos)."""
    dim, op, lit = site
    if op in _STR_NUM_FNS:
        return _STR_NUM_FNS[op](list(values), lit)
    vals = np.asarray(list(values), dtype=object)
    if op == "==":
        out = vals == lit
    elif op == "!=":
        out = vals != lit
    elif op == "<":
        out = vals < lit
    elif op == "<=":
        out = vals <= lit
    elif op == ">":
        out = vals > lit
    else:
        out = vals >= lit
    return np.asarray(out, dtype=bool)


class _Parser:
    _BINARY = [
        {"||"}, {"&&"}, {"==", "!="}, {"<", "<=", ">", ">="},
        {"+", "-"}, {"*", "/", "%"}, {"^"},
    ]

    def __init__(self, tokens):
        self.toks = tokens
        self.i = 0

    def peek(self):
        return self.toks[self.i]

    def next(self):
        t = self.toks[self.i]
        self.i += 1
        return t

    def expect(self, op):
        k, v = self.next()
        if k != "op" or v != op:
            raise ValueError(f"expected {op!r}, got {v!r}")

    def parse(self) -> Expr:
        e = self.parse_level(0)
        if self.peek()[0] != "eof":
            raise ValueError(f"trailing tokens: {self.toks[self.i:]}")
        return e

    def parse_level(self, level) -> Expr:
        if level >= len(self._BINARY):
            return self.parse_unary()
        left = self.parse_level(level + 1)
        while True:
            k, v = self.peek()
            if k == "op" and v in self._BINARY[level]:
                self.next()
                right = self.parse_level(level + 1)
                left = BinaryOp(v, left, right)
            else:
                return left

    def parse_unary(self) -> Expr:
        k, v = self.peek()
        if k == "op" and v in ("-", "!"):
            self.next()
            return UnaryOp(v, self.parse_unary())
        return self.parse_atom()

    def parse_atom(self) -> Expr:
        k, v = self.next()
        if k == "int":
            return Literal(int(v))
        if k == "num":
            return Literal(float(v))
        if k == "str":
            return Literal(v[1:-1].replace("\\'", "'"))
        if k == "id":
            if self.peek() == ("op", "("):
                self.next()
                args = []
                if self.peek() != ("op", ")"):
                    args.append(self.parse_level(0))
                    while self.peek() == ("op", ","):
                        self.next()
                        args.append(self.parse_level(0))
                self.expect(")")
                return FunctionCall(v, tuple(args))
            return Identifier(v)
        if k == "op" and v == "(":
            e = self.parse_level(0)
            self.expect(")")
            return e
        raise ValueError(f"unexpected token {v!r}")


_CACHE: Dict[str, Expr] = {}


def parse_expression(s: str) -> Expr:
    e = _CACHE.get(s)
    if e is None:
        e = _Parser(_tokenize(s)).parse()
        _CACHE[s] = e
    return e
