"""Metric/alert emission + monitors.

Reference analogs:
  java-util/.../emitter/core/Emitter.java + HttpPostEmitter.java — batched
    async event emission with pluggable sinks
  emitter/service/ServiceEmitter.java — stamps service/host dims
  java-util/.../metrics/MonitorScheduler.java, JvmMonitor, SysMonitor,
    server/metrics/QueryCountStatsMonitor.java, CacheMonitor — periodic
    metric producers
  server/emitter/EmitterModule.java — sink selection by config

Python-host equivalents: /proc-based system metrics (the Sigar JNI role),
process RSS/CPU, cache hit rates, query counts.
"""
from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence


@dataclass
class Event:
    kind: str                    # "metric" | "alert"
    metric: str
    value: float
    timestamp_ms: int
    dims: Dict[str, object] = field(default_factory=dict)

    def to_json(self) -> dict:
        out = {"feed": "metrics" if self.kind == "metric" else "alerts",
               "timestamp": self.timestamp_ms, "metric": self.metric,
               "value": self.value}
        out.update(self.dims)
        return out


class Emitter:
    def emit(self, event: Event) -> None:
        raise NotImplementedError

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass


class NoopEmitter(Emitter):
    def emit(self, event):
        pass


class InMemoryEmitter(Emitter):
    """Test/inspection sink (the reference's stub emitters)."""

    def __init__(self):
        self.events: List[Event] = []
        self._lock = threading.Lock()

    def emit(self, event):
        with self._lock:
            self.events.append(event)

    def metrics(self, name: Optional[str] = None) -> List[Event]:
        with self._lock:
            return [e for e in self.events
                    if e.kind == "metric" and (name is None or e.metric == name)]


class LoggingEmitter(Emitter):
    def __init__(self, logger=None):
        import logging
        self.logger = logger or logging.getLogger("druid_tpu.emitter")

    def emit(self, event):
        self.logger.info("%s", json.dumps(event.to_json()))


class FileEmitter(Emitter):
    """Newline-delimited JSON events (the file request-logger pattern)."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._fh = open(path, "a")

    def emit(self, event):
        with self._lock:
            if self._fh.closed:
                return        # late tick racing shutdown: drop, don't raise
            self._fh.write(json.dumps(event.to_json()) + "\n")

    def flush(self):
        with self._lock:
            if not self._fh.closed:
                self._fh.flush()

    def close(self):
        with self._lock:
            self._fh.close()


class BatchingEmitter(Emitter):
    """Buffers events and hands batches to a sender callable — the
    HttpPostEmitter's batch/flush discipline with the transport abstracted
    (a real deployment posts JSON arrays over HTTP).

    A background flush timer (daemon, joined on close()) drains the buffer
    every `flush_seconds` even when NO further emit arrives — previously the
    time-based path only fired on the next emit, so a trickle of events
    could sit buffered forever. The timer thread acquires only self._lock
    (briefly, to swap the buffer) and sends outside it — witness-clean."""

    def __init__(self, send: Callable[[List[dict]], None],
                 batch_size: int = 500, flush_seconds: float = 60.0):
        self.send = send
        self.batch_size = batch_size
        self.flush_seconds = flush_seconds
        self._buf: List[dict] = []
        self._lock = threading.Lock()
        self._last_flush = time.monotonic()
        self._stop = threading.Event()
        self._flusher = threading.Thread(target=self._flush_loop,
                                         daemon=True,
                                         name="batching-emitter-flush")
        self._flusher.start()

    def _flush_loop(self):
        while not self._stop.wait(self.flush_seconds):
            self.flush()

    def emit(self, event):
        flush_now = False
        with self._lock:
            self._buf.append(event.to_json())
            if len(self._buf) >= self.batch_size \
                    or time.monotonic() - self._last_flush > self.flush_seconds:
                flush_now = True
        if flush_now:
            self.flush()

    def flush(self):
        with self._lock:
            buf, self._buf = self._buf, []
            self._last_flush = time.monotonic()
        if buf:
            self.send(buf)

    def close(self):
        """Stop AND join the flush timer before the final flush: a tick
        mid-send while the owner tears down its transport would race."""
        self._stop.set()
        t = self._flusher
        if t is not None and t.is_alive() \
                and t is not threading.current_thread():
            t.join(timeout=5.0)
        self.flush()


class ComposingEmitter(Emitter):
    def __init__(self, children: Sequence[Emitter]):
        self.children = list(children)

    def emit(self, event):
        for c in self.children:
            c.emit(event)

    def flush(self):
        for c in self.children:
            c.flush()

    def close(self):
        """Close children too — a composed FileEmitter's handle previously
        leaked because only flush() propagated."""
        for c in self.children:
            c.close()


class ServiceEmitter(Emitter):
    """Stamps service/host dimensions onto every event."""

    def __init__(self, service: str, host: str, sink: Emitter):
        self.service = service
        self.host = host
        self.sink = sink

    def emit(self, event):
        event.dims.setdefault("service", self.service)
        event.dims.setdefault("host", self.host)
        self.sink.emit(event)

    def metric(self, name: str, value: float, **dims) -> None:
        self.emit(Event("metric", name, value, int(time.time() * 1000),
                        dict(dims)))

    def alert(self, description: str, **dims) -> None:
        self.emit(Event("alert", description, 1.0, int(time.time() * 1000),
                        dict(dims)))

    def flush(self):
        self.sink.flush()


def emitter_from_config(kind: str, **kw) -> Emitter:
    """EmitterModule's sink selection (noop/logging/file/composing…)."""
    if kind in ("noop", "none"):
        return NoopEmitter()
    if kind == "logging":
        return LoggingEmitter()
    if kind == "file":
        return FileEmitter(kw["path"])
    if kind == "memory":
        return InMemoryEmitter()
    raise ValueError(f"unknown emitter {kind!r}")


# ---------------------------------------------------------------------------
# Monitors
# ---------------------------------------------------------------------------

class Monitor:
    def do_monitor(self, emitter: ServiceEmitter) -> None:
        raise NotImplementedError


class SysMonitor(Monitor):
    """Host cpu/mem/disk via /proc (the Sigar JNI role)."""

    def __init__(self):
        self._last_cpu: Optional[tuple] = None

    def do_monitor(self, emitter):
        try:
            with open("/proc/stat") as f:
                parts = f.readline().split()[1:8]
            vals = [int(x) for x in parts]
            total, idle = sum(vals), vals[3]
            if self._last_cpu is not None:
                dt = total - self._last_cpu[0]
                didle = idle - self._last_cpu[1]
                if dt > 0:
                    emitter.metric("sys/cpu", 100.0 * (dt - didle) / dt)
            self._last_cpu = (total, idle)
            with open("/proc/meminfo") as f:
                mem = {}
                for line in f:
                    k, v = line.split(":", 1)
                    mem[k] = int(v.strip().split()[0]) * 1024
            emitter.metric("sys/mem/used",
                           mem["MemTotal"] - mem["MemAvailable"])
            emitter.metric("sys/mem/max", mem["MemTotal"])
        except (OSError, KeyError, ValueError):
            pass


class ProcessMonitor(Monitor):
    """This process's RSS + cpu time (JvmMonitor's heap/GC role)."""

    def do_monitor(self, emitter):
        try:
            with open("/proc/self/statm") as f:
                rss_pages = int(f.read().split()[1])
            emitter.metric("proc/rss", rss_pages * os.sysconf("SC_PAGE_SIZE"))
            emitter.metric("proc/cpu", time.process_time())
        except (OSError, ValueError):
            pass


class CacheMonitor(Monitor):
    """Cache hit-rate metrics (client/cache/CacheMonitor.java)."""

    def __init__(self, cache):
        self.cache = cache

    def do_monitor(self, emitter):
        s = self.cache.stats
        emitter.metric("query/cache/total/hits", s.hits)
        emitter.metric("query/cache/total/misses", s.misses)
        emitter.metric("query/cache/total/evictions", s.evictions)
        emitter.metric("query/cache/total/entries", len(self.cache))


class QueryCountStatsMonitor(Monitor):
    """query success/failed counts (QueryCountStatsMonitor.java): emits the
    cumulative totals AND the per-period deltas since the last tick (the
    reference's KeyedDiff semantics — rate dashboards read the deltas,
    uptime counters the totals)."""

    def __init__(self):
        self.success = 0
        self.failed = 0
        self._last_success = 0
        self._last_failed = 0
        self._lock = threading.Lock()

    def on_query(self, ok: bool):
        with self._lock:
            if ok:
                self.success += 1
            else:
                self.failed += 1

    def do_monitor(self, emitter):
        with self._lock:
            succ, fail = self.success, self.failed
            d_succ = succ - self._last_success
            d_fail = fail - self._last_failed
            self._last_success, self._last_failed = succ, fail
        emitter.metric("query/count", succ + fail)
        emitter.metric("query/success/count", succ)
        emitter.metric("query/failed/count", fail)
        emitter.metric("query/count/delta", d_succ + d_fail)
        emitter.metric("query/success/count/delta", d_succ)
        emitter.metric("query/failed/count/delta", d_fail)


class MonitorScheduler:
    """Periodic monitor driver (MonitorScheduler.java). start() spawns a
    daemon thread; tick() drives manually (tests)."""

    def __init__(self, emitter: ServiceEmitter,
                 monitors: Sequence[Monitor], period_seconds: float = 60.0):
        self.emitter = emitter
        self.monitors = list(monitors)
        self.period = period_seconds
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def tick(self):
        for m in self.monitors:
            m.do_monitor(self.emitter)

    def start(self):
        def loop():
            while not self._stop.wait(self.period):
                self.tick()
        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self, join_timeout: float = 5.0):
        """Signal the loop AND wait for it: callers close their emitter
        right after stop(), and a tick still in flight would write to the
        closed sink (FileEmitter additionally drops late writes — belt and
        suspenders, since a tick may be mid-emit when stop() is called)."""
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=join_timeout)
