"""Catalog of every DRUID_TPU_* environment flag.

One declaration per flag: default, latch-vs-live semantics, and a doc
line. The scattered ``os.environ`` reads across engine/, data/ and
storage/ stay where they are — locality matters for the latches — but
each read must name a flag declared here. Two consumers parse this
module WITHOUT importing it (the ``FLAGS`` literal is kept statically
evaluable for that reason — string keys, ``Flag(...)`` values with
constant arguments only):

  * druidlint's `flag-name` rule rejects any ``os.environ`` read of a
    ``DRUID_TPU_*`` name not declared here (typo guard, the
    `metric-name` pattern), and keyguard's `env-flag-latch` rule uses
    the ``semantics`` field to decide whether an in-function read of a
    flag can alias a cached program.
  * tests regenerate the README flags table from
    :func:`flags_table_markdown` and diff it against the committed one.

Semantics vocabulary:

  * ``latch`` — read once at import/process start into a module global
    (possibly overridable later through an explicit setter, which is a
    deliberate API call, not an aliasing hazard). A latch read inside a
    plan/build function would let a mid-process flip alias a cached
    program, so keyguard flags it.
  * ``live`` — consulted at call time by design. A live flag read in
    plan/build code must be a key member (``key_member=True``) or be
    provably trace-irrelevant (capacity bounds, persistence format
    bytes), which the catalog documents per flag.
"""
from __future__ import annotations

from dataclasses import dataclass

__all__ = ["Flag", "FLAGS", "flags_table_markdown"]


@dataclass(frozen=True)
class Flag:
    default: str
    semantics: str            # "latch" | "live"
    doc: str
    #: live flags only: the read's effect joins every cache/plan key
    #: (so a mid-process flip cannot alias a cached program)
    key_member: bool = False

    def __post_init__(self):
        if self.semantics not in ("latch", "live"):
            raise ValueError(f"unknown semantics {self.semantics!r}")


#: every DRUID_TPU_* flag the package reads, keyed by full env name.
#: Keep this a plain dict literal of Flag(...) calls with constant
#: arguments — druidlint and keyguard evaluate it by AST, not import.
FLAGS = {
    "DRUID_TPU_BATCH": Flag(
        default="1", semantics="latch",
        doc="Cross-segment batching opt-out; 0 restores per-segment "
            "dispatch (engine/batching.py)."),
    "DRUID_TPU_CASCADE": Flag(
        default="1", semantics="latch",
        doc="Cascaded-encoding execution opt-out; 0 decodes to flat "
            "codes at staging time (data/cascade.py)."),
    "DRUID_TPU_COMPILE_CACHE": Flag(
        default="", semantics="latch",
        doc="XLA persistent compilation cache: 0 disables, a path "
            "overrides the default directory (engine/__init__.py)."),
    "DRUID_TPU_DEVICE_BITMAP": Flag(
        default="1", semantics="latch",
        doc="Device-side filter bitmap construction opt-out "
            "(engine/filters.py)."),
    "DRUID_TPU_DEVICE_POOL_BYTES": Flag(
        default="", semantics="live",
        doc="Device segment pool budget override in bytes. Capacity "
            "bound only — never a trace input (data/devicepool.py)."),
    "DRUID_TPU_DONATE": Flag(
        default="auto", semantics="live", key_member=True,
        doc="Carry-buffer donation tri-state: 'on' forces "
            "donate_argnums (the real-TPU bench lever), 'off' disables "
            "it, 'auto' detects by backend. Live by design — the "
            "decision joins the jit program signature's mk= field "
            "(engine/contracts.py donation_supported, "
            "engine/grouping.py)."),
    "DRUID_TPU_DONOR_WITNESS": Flag(
        default="", semantics="latch",
        doc="Test-only: 1 arms the suite-wide donation/ownership "
            "witness (tools/druidlint/donorwitness.py) from "
            "tests/conftest.py — pool takes, donating dispatches and "
            "re-parks are tracked by array identity, and a cached-entry "
            "donation, post-dispatch touch of a donated argument, or "
            "un-reparked take at teardown fails the session."),
    "DRUID_TPU_LZ4": Flag(
        default="device", semantics="latch",
        doc="LZ4 frame handling: device decode (default) or 'host' "
            "staging comparison fallback (data/cascade.py)."),
    "DRUID_TPU_MEGAKERNEL": Flag(
        default="1", semantics="latch",
        doc="Fused megakernel path opt-out (engine/megakernel.py)."),
    "DRUID_TPU_PACKED": Flag(
        default="1", semantics="latch",
        doc="Bit-packed column staging opt-out (data/packed.py)."),
    "DRUID_TPU_PALLAS": Flag(
        default="", semantics="live", key_member=True,
        doc="Pallas kernel mode: 0 disables, 'interpret' forces "
            "interpreter mode. Live by design — availability is probed "
            "per build and the chosen strategy joins the plan "
            "signature's strat= field (engine/pallas_agg.py)."),
    "DRUID_TPU_SEGMENT_FORMAT": Flag(
        default="", semantics="live",
        doc="Segment writer format pin: 1 pins the V1 writer. Live by "
            "design — the chosen version is persisted as the format "
            "byte readers negotiate on, never a trace input "
            "(storage/format_v2.py)."),
    "DRUID_TPU_STALL_WITNESS": Flag(
        default="", semantics="latch",
        doc="Test-only: 1 arms the suite-wide stall witness "
            "(tools/druidlint/stallwitness.py) from tests/conftest.py — "
            "every blocking park issued from a druid_tpu call site is "
            "timed, and an untimed park outside a shutdown scope fails "
            "the session."),
    "DRUID_TPU_STANDING": Flag(
        default="1", semantics="latch",
        doc="Standing-query incremental maintenance opt-out; 0 "
            "restores re-scan on every tick (engine/standing.py)."),
    "DRUID_TPU_STRATEGY": Flag(
        default="", semantics="latch",
        doc="Grouping strategy override for measurement runs "
            "(engine/grouping.py, tools/chip_suite.py)."),
    "DRUID_TPU_UNIDIM_TTL_S": Flag(
        default="900", semantics="latch",
        doc="Unidimensional result-cache TTL in seconds; <= 0 "
            "disables expiry (engine/engines.py)."),
}


def flags_table_markdown() -> str:
    """The README flags table, generated so it cannot drift from the
    catalog (tests diff this against the committed README section)."""
    lines = ["| Flag | Default | Semantics | Description |",
             "| --- | --- | --- | --- |"]
    for name in sorted(FLAGS):
        f = FLAGS[name]
        sem = f.semantics + (" (key member)" if f.key_member else "")
        default = f"`{f.default}`" if f.default else "(unset)"
        lines.append(f"| `{name}` | {default} | {sem} | {f.doc} |")
    return "\n".join(lines) + "\n"
