"""Single-source runtime configuration surface.

`druid_tpu.config.flags` is the catalog of every ``DRUID_TPU_*``
environment flag the package reads. Code keeps reading flags wherever it
needs them (a latch at import, a live probe in a version negotiation) —
but every such read must name a flag declared here, and druidlint's
`flag-name` rule enforces it the same way `metric-name` enforces the
metrics catalog.
"""
from druid_tpu.config.flags import FLAGS, Flag, flags_table_markdown

__all__ = ["FLAGS", "Flag", "flags_table_markdown"]
