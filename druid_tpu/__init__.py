"""druid_tpu — a TPU-native, column-oriented distributed OLAP analytics framework.

Brand-new design with the capabilities of Apache Druid (reference:
foamdino/incubator-druid, pre-0.13), re-architected TPU-first:

- Segments are blocks of dense device arrays (int32 dictionary ids, float32/
  int32 metrics), padded to static shapes so XLA compiles one program per
  (query shape, segment schema).
- Queries compile to jit-ted mask + segmented-reduction programs instead of the
  reference's per-row cursor hot loop (reference:
  processing/src/main/java/org/apache/druid/query/timeseries/TimeseriesQueryEngine.java:87).
- Broker "merge" becomes device collectives (psum/all_gather over ICI via
  shard_map) instead of Sequence n-way merge (reference:
  java-util/src/main/java/org/apache/druid/java/util/common/guava/MergeSequence.java).
- The control plane (timeline, coordinator, metadata) stays host-side,
  mirroring the reference's semantics (VersionedIntervalTimeline MVCC).
"""

__version__ = "0.1.0"

from druid_tpu.utils.intervals import Interval
from druid_tpu.utils.granularity import Granularity

__all__ = ["Interval", "Granularity", "__version__"]
