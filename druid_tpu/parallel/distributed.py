"""Sharded multi-segment execution: one device program for a whole query.

Reference analog, inverted for TPU:
  * ChainedExecutionQueryRunner.java (thread-pool per-segment runners) →
    segments stacked on a leading axis, `jax.vmap` over it;
  * CachingClusteredClient.java:253 scatter-gather + MergeSequence →
    `shard_map` over a mesh axis, partial states merged with
    psum/pmin/pmax/all_gather collectives over ICI;
  * epinephelinae/ParallelCombiner.java combining tree → the XLA collective
    is the combining tree.

The stacked blocks are COMPRESSED-RESIDENT: each shard carries per-segment
packed words (data/packed.py tile-planar layout), cascade columns (RLE run
tables, delta/FOR words — data/cascade.py), and resident filter-bitmap
words (engine/filters.py DeviceBitmapNode slots), and the program decodes
at its top through the same `cascade.split_resident` every other path
calls — one decode/filter story for per-segment, batched and sharded
execution. Every PartitionSpec comes from parallel/speclayout.py (the
canonical SpecLayout; lint-enforced single source), and partial grids are
merged ON DEVICE by the collectives — the broker-side host merge for this
path is gone; `host_from_device` below only converts the already-merged
replicated states to their host representation.

Eligibility (else callers fall back to per-segment host-merged execution):
dense key mode, "all"/"uniform" bucketing, and identical plan constants
(filter LUTs, kernel aux, dim remaps) across segments — true whenever
segments share dictionaries, which the ingestion path guarantees per
datasource generation (the analog of DimensionMergerV9's unified dictionary).
"""
from __future__ import annotations

import collections
import functools
import hashlib
import threading
import weakref
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from druid_tpu.data import cascade as cascade_mod
from druid_tpu.data import devicepool
from druid_tpu.data import packed as packed_mod
from druid_tpu.data.segment import Segment
from druid_tpu.engine import filters as filters_mod
from druid_tpu.engine.filters import ConstNode, plan_filter, simplify_node
from druid_tpu.engine import grouping
from druid_tpu.engine.grouping import (GroupSpec, KeyDim, SegmentPartial,
                                       assemble_stacked_aux, aux_equal,
                                       keydims_equal, make_group_spec,
                                       make_stacked_segment_fn,
                                       needed_columns, plan_virtual_columns,
                                       windowed_window)
from druid_tpu.engine.kernels import AggKernel, make_kernel
from druid_tpu.obs.trace import span as trace_span
from druid_tpu.obs.trace import span_when as trace_span_when
from druid_tpu.parallel import context, speclayout
from druid_tpu.query.aggregators import AggregatorSpec
from druid_tpu.utils.emitter import Monitor
from druid_tpu.utils.granularity import Granularity
from druid_tpu.utils.intervals import Interval

# Jitted sharded programs, LRU-bounded: entries capture kernel aux arrays in
# their closures, so an unbounded cache would pin host memory across segment
# generations. Locked: concurrent queries racing evict vs move_to_end would
# KeyError (shard_map/jit construction is lazy, so building under the lock
# is cheap).
_FN_CACHE: "collections.OrderedDict[Tuple, object]" = collections.OrderedDict()
_FN_CACHE_CAP = 64
_CACHE_LOCK = threading.Lock()


class _StackOwner:
    """Anchor object owning the stacked-shard entries in the device pool.

    Stacked blocks pin whole segment sets in HBM; instead of a private
    count-capped LRU they live in the process-wide DeviceSegmentPool under
    this owner, accounted at actual bytes against DEVICE_POOL_BUDGET_BYTES
    (satellite of the old `_STACK_CACHE`). The anchor is module-lived, so
    entries only leave through LRU pressure or clear_stack_cache()."""


_STACK_ANCHOR: Optional[_StackOwner] = None
_STACK_TOKEN: Optional[int] = None
_STACK_POOL: Optional["weakref.ref"] = None


def _stack_owner_token(pool: "devicepool.DeviceSegmentPool") -> int:
    """Lazily (re-)register the stack owner: purge_owner removes the
    registry slot, so after clear_stack_cache() the next stacking must
    register a fresh token or the pool would refuse its inserts. The
    token is only valid for the pool it was registered on — when the
    process pool is swapped (tests monkeypatch isolated pools), the old
    pool's stacked entries are purged and a fresh token registers on the
    new one, so there is always at most ONE live stack owner."""
    global _STACK_ANCHOR, _STACK_TOKEN, _STACK_POOL
    with _CACHE_LOCK:
        prev = _STACK_POOL() if _STACK_POOL is not None else None
        if _STACK_TOKEN is None or prev is not pool:
            if prev is not None and _STACK_TOKEN is not None:
                # _CACHE_LOCK -> pool lock is the documented order; the
                # pool never takes _CACHE_LOCK
                prev.purge_owner(_STACK_TOKEN)
            _STACK_ANCHOR = _StackOwner()
            _STACK_TOKEN = pool.register_owner(_STACK_ANCHOR)
            _STACK_POOL = weakref.ref(pool)
        return _STACK_TOKEN


# plan-constant equality + column planning now live in engine/grouping.py,
# shared with the batched (unrolled, engine/batching.py) multi-segment path
_aux_equal = aux_equal
_keydims_equal = keydims_equal
_needed_columns = needed_columns


def try_sharded(segments: Sequence[Segment], intervals: Sequence[Interval],
                granularity: Granularity,
                kds_per_seg: Sequence[Sequence[KeyDim]],
                aggs: Sequence[AggregatorSpec], flt,
                virtual_columns: Sequence = ()) -> Optional[SegmentPartial]:
    """Run the grouped aggregate for all segments as ONE sharded device
    program; returns a single merged SegmentPartial, or None if ineligible
    (caller falls back to the per-segment path)."""
    mesh = context.get_mesh()
    if mesh is None or not segments:
        return None
    import jax
    if any(d.process_index != jax.process_index()
           for d in mesh.devices.flat):
        # cross-process mesh: the stacked program would need every shard's
        # data process-addressable; host-level combine is the broker's job
        return None
    layout = speclayout.layout_for(mesh)
    axis = layout.seg_axis
    n_dev = mesh.shape[axis]

    kds = list(kds_per_seg[0])
    if any(d.host_ids is not None for d in kds):
        # numeric-dimension ids are per-segment query-time dictionaries —
        # a stacked program cannot share one id space; per-segment path
        # merges them host-side
        return None
    for other in kds_per_seg[1:]:
        if not _keydims_equal(kds, other):
            return None
    # raw (remap-free) key dims fuse dictionary ids directly, so the
    # dictionaries themselves must agree across segments — equal cardinality
    # is NOT enough (ids would decode through segments[0]'s values)
    for d in kds:
        if d.column is None:
            continue
        first = segments[0].dims[d.column].dictionary
        for s in segments[1:]:
            other = s.dims.get(d.column)
            if other is None:
                return None
            if other.dictionary is not first and \
                    list(other.dictionary.values) != list(first.values):
                return None

    spec0 = make_group_spec(segments[0], intervals, granularity, kds)
    if spec0.key_mode != "dense" or spec0.bucket_mode not in ("all", "uniform"):
        return None

    # plan filter + kernels + virtual columns per segment; constants must
    # agree across segments. Device-bitmap compilation follows the process
    # default (the stacked program reads resident `__fbmpN` word slots,
    # exactly like _build_device_fn) — slots are assigned per plan BEFORE
    # signatures are compared, so filtered-aggregator trees cannot collide
    # with the query filter's slot 0.
    filter_node = simplify_node(plan_filter(flt, segments[0],
                                            virtual_columns))
    kernels = [make_kernel(a, segments[0]) for a in aggs]
    n_slots = filters_mod.assign_bitmap_slots(filter_node, kernels)
    vc_plans, vc_luts = plan_virtual_columns(segments[0], virtual_columns)
    f_sig = filter_node.signature() if filter_node else "none"
    f_aux = filter_node.aux_arrays() if filter_node else []
    k_aux = [a for k in kernels for a in k.aux_arrays()]
    seg_filters: List[object] = [filter_node]
    seg_kernels: List[List[AggKernel]] = [kernels]
    for s in segments[1:]:
        fn_s = simplify_node(plan_filter(flt, s, virtual_columns))
        ks = [make_kernel(a, s) for a in aggs]
        filters_mod.assign_bitmap_slots(fn_s, ks)
        if (fn_s.signature() if fn_s else "none") != f_sig:
            return None
        if not _aux_equal(fn_s.aux_arrays() if fn_s else [], f_aux):
            return None
        if [k.signature() for k in ks] != [k.signature() for k in kernels]:
            return None
        if not _aux_equal([a for k in ks for a in k.aux_arrays()], k_aux):
            return None
        vp_s, vl_s = plan_virtual_columns(s, virtual_columns)
        if repr(vp_s) != repr(vc_plans) or not _aux_equal(vl_s, vc_luts):
            return None
        seg_filters.append(fn_s)
        seg_kernels.append(ks)
    # only after every segment agreed on the plan is a const-false filter a
    # whole-query zero (a column may exist in some segments only)
    if isinstance(filter_node, ConstNode) and not filter_node.value:
        return SegmentPartial(
            segment=segments[0], spec=spec0,
            counts=np.zeros(spec0.num_total, dtype=np.int64),
            states={k.name: k.empty_state(spec0.num_total) for k in kernels},
            kernels=kernels)

    # every needed column must have the same presence, kind AND dtype in all
    # segments: the plain path handles per-segment differences (missing
    # aggregates as zero), but one stacked program cannot — fall back rather
    # than KeyError, silently cast, or crash. Complex (2-D) metric columns
    # also fall back: the stacker allocates [K, R] only. Planned
    # filter/kernel trees are passed so bitmap-compiled subtrees stop
    # staging their columns (their data rides in the word slots).
    needed, columns = _needed_columns(segments[0], kds, aggs, flt,
                                      virtual_columns,
                                      filter_node=filter_node,
                                      kernels=kernels)
    for c in needed:
        in_dim0 = c in segments[0].dims
        met0 = segments[0].metrics.get(c)
        if met0 is not None and np.asarray(met0.values).ndim != 1:
            return None
        for s in segments[1:]:
            if (c in s.dims) != in_dim0:
                return None
            met = s.metrics.get(c)
            if (met is None) != (met0 is None):
                return None
            if met is not None and (met.type is not met0.type
                                    or met.values.dtype != met0.values.dtype
                                    or s.staged_dtype(c)
                                    != segments[0].staged_dtype(c)):
                return None

    # compressed slots: the descriptor pair every segment can agree on
    # (cascade entries + pack entries), plus RLE validity masks — the
    # descriptors join the stack pool key AND _sharded_sig below, so
    # chunk-mates agree and the cached program's treedef is pinned
    valid_rle = cascade_mod.enabled()
    cascades, packs = _common_descriptors(segments, columns)
    stacked, time0s, R, K = _stack_segments(mesh, segments, columns,
                                            cascades, packs, valid_rle,
                                            seg_filters, seg_kernels, layout)

    # reduction strategy must agree across the whole stacked program; the
    # windowed path needs every segment's host span check to pass
    col_dtypes = {"__time_offset": np.dtype(np.int32),
                  "__valid": np.dtype(bool)}
    for c in columns:
        if c in segments[0].dims:
            col_dtypes[c] = np.dtype(np.int32)
        else:
            col_dtypes[c] = np.dtype(segments[0].staged_dtype(c))

    def _windowed_all():
        w_all = 0
        for s in segments:
            w = windowed_window(s, intervals, granularity, spec0)
            if not w:
                return 0
            w_all = max(w_all, w)
        return w_all

    # via the module so tests forcing a strategy (monkeypatching
    # grouping.select_strategy) also steer the sharded path
    spec0.strategy, spec0.window = grouping.select_strategy(
        spec0, kernels, col_dtypes, R, _windowed_all)
    if spec0.strategy == "projection":
        # sorted projections are per-segment layouts the stacked program
        # cannot share. Falling back to per-segment pallas would also pay
        # per-call dispatch/merge overhead once per segment; ONE stacked
        # scatter-mixed program amortizes it across the whole set and
        # measured ~2x faster at bench scale (8x12.5M rows) on v5e — so
        # the stacked program overrides to mixed and the projection path
        # stays the meshless per-segment winner.
        spec0.strategy, spec0.window = "mixed", 0

    # per-segment RELATIVE interval bounds + bucket start offsets: the
    # device program stays in int32 offset space (64-bit elementwise time
    # math is limb-emulated on TPU)
    clip_lo, clip_hi = -(2**31) + 1, 2**31 - 1
    iv_rel = np.zeros((K, max(len(intervals), 1), 2), dtype=np.int32)
    bucket_off = np.zeros((K,), dtype=np.int32)
    for i, s in enumerate(segments):
        t0 = s.interval.start
        for j, ivl in enumerate(intervals):
            iv_rel[i, j, 0] = min(max(ivl.start - t0, clip_lo), clip_hi)
            iv_rel[i, j, 1] = min(max(ivl.end - t0, clip_lo), clip_hi)
        if spec0.bucket_mode == "uniform":
            bucket_off[i] = min(max(int(spec0.bucket_starts[0]) - t0,
                                    clip_lo), clip_hi)
    iv_rel = layout.put_interval_bounds(mesh, iv_rel)
    bucket_off = layout.put_bucket_offsets(mesh, bucket_off)

    aux = _assemble_aux(spec0, kds, f_aux, k_aux, granularity, vc_luts)

    sig = _sharded_sig(mesh, axis, spec0, kds, filter_node, kernels,
                       len(intervals), vc_plans, K, R, columns, cascades,
                       packs, n_slots, valid_rle, layout)
    with _CACHE_LOCK:
        fn = _FN_CACHE.get(sig)
        # the miss IS the compile event (shard_map traces/compiles on the
        # first call below) — timing stays at the existing dispatch boundary
        compiled = fn is None
        if fn is None:
            fn = _build_sharded_fn(mesh, axis, n_dev, spec0, kds, filter_node,
                                   kernels, vc_plans, layout, stacked)
            _FN_CACHE[sig] = fn
            while len(_FN_CACHE) > _FN_CACHE_CAP:
                _FN_CACHE.popitem(last=False)
        else:
            _FN_CACHE.move_to_end(sig)
    from druid_tpu.obs import dispatch as dispatch_mod
    dispatch_mod.record("sharded")
    with trace_span("engine/sharded/dispatch", segments=K, devices=n_dev,
                    compile=compiled), \
            trace_span_when(compiled, "engine/compile", kind="sharded"):
        counts, states = fn(stacked, time0s, iv_rel, bucket_off, aux)
    _SHARDED_STATS.record(len(segments))

    # NOT a host merge: counts/states left the program replicated and
    # already collective-merged; host_from_device only converts the merged
    # device representation (HLL registers, first/last packed pairs) to
    # the host one, exactly like the single-segment path does per segment
    host_states = {k.name: k.host_from_device(st)
                   for k, st in zip(kernels, states)}
    return SegmentPartial(segment=segments[0], spec=spec0,
                          counts=np.asarray(counts, dtype=np.int64),
                          states=host_states, kernels=kernels)


def _common_descriptors(segments: Sequence[Segment],
                        columns: Tuple[str, ...]) -> Tuple[Tuple, Tuple]:
    """The (cascade, pack) descriptor pair EVERY segment can stage under.

    Per-segment plans come from the one shared derivation
    (cascade.plan_pair); a column keeps its encoding only when all
    segments planned the same (name, kind) with stack-compatible params:
    RLE run-table lengths normalize to the max (pow2 stays pow2, and
    encode_column pads per entry[2]), delta/FOR widths+bases must match
    exactly (word shapes must stack), and `lz4host` drops out (it stages
    the exact host-roundtripped decoded rows anyway). Everything else
    falls back to decoded [K, R] slots — never to a fallback PATH."""
    per_seg = [cascade_mod.plan_pair(s, columns) for s in segments]
    casc0, packs0 = per_seg[0]
    cascades: List[Tuple] = []
    for entry in casc0:
        name, kind = entry[0], entry[1]
        if kind == "lz4host":
            continue
        mates = []
        for cs, _ in per_seg:
            mate = next((e for e in cs if e[0] == name), None)
            if mate is None or mate[1] != kind:
                mates = None
                break
            mates.append(mate)
        if mates is None:
            continue
        if kind == "rle":
            # run counts are per-segment data; the stacked run tables pad
            # to the widest (max of pow2 paddings is one of them)
            cascades.append((name, kind, max(m[2] for m in mates)))
        elif all(m == entry for m in mates):
            cascades.append(entry)
    claimed = {e[0] for e in cascades}
    packs = tuple(e for e in packs0
                  if e[0] not in claimed
                  and all(e in ps for _, ps in per_seg))
    return tuple(cascades), packs


def _bitmap_nodes(filter_node, kernels: Sequence[AggKernel]) -> List:
    """Every DeviceBitmapNode of one segment's plan, slot order (the query
    filter's tree first, then each kernel's filter trees — the same walk
    assign_bitmap_slots numbers)."""
    nodes = list(filters_mod.collect_bitmap_nodes(filter_node))
    for k in kernels:
        for tree in k.filter_trees():
            nodes.extend(filters_mod.collect_bitmap_nodes(tree))
    return nodes


def _bitmap_digest(seg_filters: Sequence, seg_kernels: Sequence) -> str:
    """Content digest of every segment's bitmap-node set for the stack pool
    key: bitmap LUTs ride the stacked WORDS (per-segment data, aux-free by
    the DeviceBitmapNode contract), so two plans that differ only in word
    content must stack under different keys."""
    h = hashlib.sha1()
    any_nodes = False
    for fn_s, ks in zip(seg_filters, seg_kernels):
        for node in _bitmap_nodes(fn_s, ks):
            any_nodes = True
            h.update(node.col.encode())
            h.update(b"|")
            h.update(node.structure_sig().encode())
            h.update(b"|")
            h.update(node.digest().encode())
        h.update(b"||")
    return h.hexdigest()[:16] if any_nodes else ""


def _stack_tree(cols: List, K: int):
    """Stack K per-segment column pytrees (decoded arrays, PackedColumn,
    RLE/FOR/delta columns) leaf-wise onto a leading segment axis. Padding
    segments are zeroed copies of the first: RLE zeros decode all-invalid
    (n_rows=0), packed/FOR zeros decode to the base — every consumer masks
    them through `__valid`. Descriptor agreement (_common_descriptors)
    guarantees equal treedefs, so per-segment row counts/firsts ride as
    stacked [K] scalar leaves, not aux."""
    import jax
    if len(cols) < K:
        pad = jax.tree.map(lambda leaf: np.zeros_like(np.asarray(leaf)),
                           cols[0])
        cols = list(cols) + [pad] * (K - len(cols))
    return jax.tree.map(
        lambda *leaves: np.stack([np.asarray(l) for l in leaves], axis=0),
        *cols)


def _stack_segments(mesh, segments: Sequence[Segment],
                    columns: Tuple[str, ...], cascades: Tuple, packs: Tuple,
                    valid_rle: bool, seg_filters: Sequence,
                    seg_kernels: Sequence,
                    layout: "speclayout.SpecLayout"):
    """Stack segments into COMPRESSED-RESIDENT [K, ...] slots sharded over
    the mesh axis: cascade columns (RLE run tables, delta/FOR words),
    packed words, resident filter-bitmap words, decoded rows for the rest —
    the sharded program decodes in-program through cascade.split_resident
    exactly like _build_device_fn.

    K pads to a multiple of the axis size with empty (all-invalid)
    segments; R pads rows to the max padded row count (1024-aligned — a
    multiple of every pack width's tile quantum). Stacks live in the
    process-wide device pool under the stack owner, accounted at actual
    bytes against the pool budget (PoolStats.stacked_*) — repeat queries
    reuse HBM-resident shards, the analog of the reference keeping
    segments mmapped across queries."""
    axis = layout.seg_axis
    n_dev = mesh.shape[axis]
    pool = devicepool.device_pool()
    # keyed by object identity, not segment-id strings: rebuilt segments can
    # legitimately reuse (datasource, interval, version, partition) and must
    # not be served stale stacked data. The cached value pins the segment
    # objects, so their id()s cannot be recycled while the entry lives. The
    # descriptors/bitmap digest join the key: latch flips (packed/cascade/
    # device-bitmap) and filter-word content changes restack.
    key = (devicepool.STACKED_KIND, tuple(id(s) for s in segments), columns,
           n_dev, tuple(int(d.id) for d in mesh.devices.flat), cascades,
           packs, int(valid_rle), _bitmap_digest(seg_filters, seg_kernels))

    def build():
        return _build_stack(mesh, segments, columns, cascades, packs,
                            valid_rle, seg_filters, seg_kernels, layout,
                            n_dev)

    value = pool.get_or_build(_stack_owner_token(pool), key, build)
    return value[:4]


def _build_stack(mesh, segments: Sequence[Segment], columns: Tuple[str, ...],
                 cascades: Tuple, packs: Tuple, valid_rle: bool,
                 seg_filters: Sequence, seg_kernels: Sequence,
                 layout: "speclayout.SpecLayout", n_dev: int):
    # 1024-aligned rows satisfy pack_padded's tile quantum (128 * values
    # per word) for every contract width, 4/8/16 alike
    align = 1024
    R = max(align, max(((s.n_rows + align - 1) // align) * align
                       for s in segments))
    K = ((len(segments) + n_dev - 1) // n_dev) * n_dev
    casc_by_name = {e[0]: e for e in cascades}
    pack_by_name = {e[0]: (e[1], e[2]) for e in packs}

    def padded_col(s: Segment, name: str) -> np.ndarray:
        if name == "__time_offset":
            off = s.time_ms - s.interval.start
            if off.size and (off.min() < 0 or off.max() >= 2**31):
                raise ValueError(f"segment {s.id} outside int32 offset range")
            a = off.astype(np.int32)
        elif name in s.dims:
            a = s.dims[name].ids
        else:
            m = s.metrics[name]
            dt = s.staged_dtype(name)   # int32-narrowed longs stay narrow
            a = m.values if m.values.dtype == dt else m.values.astype(dt)
        out = np.zeros(R, dtype=a.dtype)
        out[: a.shape[0]] = a
        return out

    def encoded_col(s: Segment, name: str):
        padded = padded_col(s, name)
        entry = casc_by_name.get(name)
        if entry is not None:
            # host identity `put`: device placement happens once for the
            # whole stack below, with the layout's shardings
            return cascade_mod.encode_column(s, name, entry, padded,
                                             lambda x: x)
        wb = pack_by_name.get(name)
        if wb is not None:
            w, base = wb
            return packed_mod.PackedColumn(
                packed_mod.pack_padded(padded, w, base), w, base, R,
                str(padded.dtype))
        return padded

    arrays: Dict[str, object] = {}
    for name in ("__time_offset",) + tuple(columns):
        arrays[name] = _stack_tree([encoded_col(s, name) for s in segments],
                                   K)

    # validity as an RLE run table (8 int32 pairs/segment instead of R
    # bools): rows < n_rows decode 1, pads 0 — bit-exact with the dense
    # mask. Dense [K, R] bools only when cascading is off.
    if valid_rle:
        valid_cols = []
        for s in segments:
            nr = int(s.n_rows)
            vals = np.zeros(8, dtype=np.int32)
            vals[0] = 1 if nr else 0
            ends = np.full(8, nr, dtype=np.int32)
            valid_cols.append(cascade_mod.RleColumn(
                vals, ends, np.asarray(nr, dtype=np.int32), R, "bool"))
        arrays["__valid"] = _stack_tree(valid_cols, K)
    else:
        valid = np.zeros((K, R), dtype=bool)
        for i, s in enumerate(segments):
            valid[i, : s.n_rows] = True
        arrays["__valid"] = valid

    # resident filter-bitmap words: stage per segment through the pooled
    # wave path (query/filter/* accounting included), then stack each
    # `__fbmpN` slot; padding segments keep zero words (no row passes)
    bitmap_cols: Dict[str, np.ndarray] = {}
    for i, (s, fn_s, ks) in enumerate(zip(segments, seg_filters,
                                          seg_kernels)):
        words = filters_mod.stage_device_bitmaps(s, fn_s, R, kernels=ks)
        for col, w in words.items():
            host = np.asarray(w)
            slot = bitmap_cols.get(col)
            if slot is None:
                slot = np.zeros((K,) + host.shape, dtype=host.dtype)
                bitmap_cols[col] = slot
            slot[i] = host
    arrays.update(bitmap_cols)

    time0s = np.zeros((K,), dtype=np.int64)
    for i, s in enumerate(segments):
        time0s[i] = s.interval.start

    dev_arrays = layout.put_stacked(mesh, arrays)
    dev_time0s = layout.put_time0s(mesh, time0s)
    # stacked column objects carry per-SEGMENT aux (the vmapped decode
    # slices one segment at a time), so their logical_nbytes describes one
    # segment while their leaves hold K — restore the missing (K-1) share
    # for the pool's decoded-equivalent accounting
    corr = sum((K - 1) * int(v.logical_nbytes)
               for v in dev_arrays.values()
               if getattr(v, "logical_nbytes", None) is not None)
    # the trailing segment tuple pins the objects (id()-recycling guard);
    # Segment carries no nbytes, so it counts 0 in the pool accounting
    return (dev_arrays, dev_time0s, R, K, tuple(segments),
            devicepool.LogicalBytes(corr))


def clear_stack_cache() -> int:
    """Release the HBM-resident stacked segment sets (and the segment
    objects each entry deliberately pins). Returns the entry count
    dropped. The ops analog of unloading segments to reclaim HBM without
    a restart — engine.release_device_caches() is the public surface."""
    global _STACK_TOKEN, _STACK_POOL
    with _CACHE_LOCK:
        token, _STACK_TOKEN = _STACK_TOKEN, None
        pool = _STACK_POOL() if _STACK_POOL is not None else None
        _STACK_POOL = None
    if token is None or pool is None:
        return 0
    n = pool.snapshot().stacked_entries
    pool.purge_owner(token)
    return n


def clear_fn_cache() -> int:
    """Drop the jitted sharded programs (their closures pin kernel aux
    arrays across segment generations)."""
    with _CACHE_LOCK:
        n = len(_FN_CACHE)
        _FN_CACHE.clear()
        return n


# aux layout shared with the batched path (engine/grouping.py)
_assemble_aux = assemble_stacked_aux


def _sharded_sig(mesh, axis, spec: GroupSpec, kds, filter_node, kernels,
                 n_intervals, vc_plans, K, R, columns, cascades, packs,
                 n_bitmap_slots, valid_rle, layout) -> Tuple:
    """Cache key of one sharded program. The compressed-slot inputs —
    staged column set, cascade/pack descriptors, bitmap slot count, RLE
    validity — pin the stacked pytree's treedef, so two queries share a
    cached program only when their stacks share a structure."""
    dims_sig = ",".join(
        f"{d.column}:{'remap' if d.remap is not None else 'raw'}" for d in kds)
    vc_sig = ";".join(f"{name}={expr!r}:{out_type}:l{n_luts}"
                      for name, expr, out_type, n_luts in vc_plans)
    return (speclayout.layout_sig(layout, mesh), axis, spec.bucket_mode,
            dims_sig, n_intervals, vc_sig,
            filter_node.signature() if filter_node else "none",
            ";".join(k.signature() for k in kernels), spec.num_total, K, R,
            spec.strategy, spec.window, columns, cascades, packs,
            n_bitmap_slots, int(valid_rle))


def _merge_states(kernel: AggKernel, stacked_state, axis: str, n_dev: int,
                  k_local: int):
    """Fold per-segment states over the local axis, then across the mesh."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    kind = kernel.reduce_kind
    # On a 1-device mesh every collective is the identity; use psum for all
    # kinds — it satisfies the replication (vma) check and is the one
    # collective every TPU transport lowers (some support only Sum
    # all-reduce). Bool states must go through int for psum.
    if n_dev == 1:
        if kind != "sum":
            if kind == "max":
                st = jax.tree.map(lambda x: x.max(axis=0), stacked_state)
            elif kind == "min":
                st = jax.tree.map(lambda x: x.min(axis=0), stacked_state)
            else:
                parts = [jax.tree.map(lambda x, i=i: x[i], stacked_state)
                         for i in range(k_local)]
                st = functools.reduce(kernel.device_combine, parts)
        else:
            # cross-segment integer sums widen to int64 before the fold —
            # exactness contract, x64 globally on (engine/__init__)
            st = jax.tree.map(
                lambda x: (x.astype(jnp.int64)  # druidlint: disable=x64-dtype
                           if jnp.issubdtype(x.dtype, jnp.integer)
                           else x).sum(axis=0), stacked_state)

        def ident_psum(x):
            if x.dtype == jnp.bool_:
                return lax.psum(x.astype(jnp.int32), axis) > 0
            return lax.psum(x, axis)
        return jax.tree.map(ident_psum, st)
    if kind == "sum":
        def local(x):
            if jnp.issubdtype(x.dtype, jnp.integer):
                # int64 before psum: exactness contract, x64 globally on
                x = x.astype(jnp.int64)  # druidlint: disable=x64-dtype
            return x.sum(axis=0)
        st = jax.tree.map(local, stacked_state)
        return jax.tree.map(lambda x: lax.psum(x, axis), st)
    if kind == "max":
        st = jax.tree.map(lambda x: x.max(axis=0), stacked_state)
        return jax.tree.map(lambda x: lax.pmax(x, axis), st)
    if kind == "min":
        st = jax.tree.map(lambda x: x.min(axis=0), stacked_state)
        return jax.tree.map(lambda x: lax.pmin(x, axis), st)
    # fold: pairwise device_combine locally, all_gather + fold across devices
    parts = [jax.tree.map(lambda x, i=i: x[i], stacked_state)
             for i in range(k_local)]
    st = functools.reduce(kernel.device_combine, parts)
    gathered = jax.tree.map(
        lambda x: lax.all_gather(x, axis, axis=0, tiled=False), st)
    parts = [jax.tree.map(lambda x, i=i: x[i], gathered) for i in range(n_dev)]
    return functools.reduce(kernel.device_combine, parts)


def _build_sharded_fn(mesh, axis: str, n_dev: int, spec: GroupSpec,
                      kds: Sequence[KeyDim], filter_node,
                      kernels: List[AggKernel], vc_plans: Tuple,
                      layout: "speclayout.SpecLayout", stacked):
    import jax
    import jax.numpy as jnp
    try:
        from jax import shard_map          # jax >= 0.5
        _check_kw = "check_vma"
    except ImportError:                    # 0.4.x: experimental home,
        from jax.experimental.shard_map import shard_map
        _check_kw = "check_rep"            # and the old replication-check kw

    seg_body = make_stacked_segment_fn(spec, kds, filter_node, kernels,
                                       vc_plans)

    def per_segment(arrays, time0, iv_rel, bucket_off, aux):
        counts, states = seg_body(arrays, time0, iv_rel, bucket_off, aux)
        states = tuple(k.device_post(s, time0)
                       for k, s in zip(kernels, states))
        return counts, states

    def body(stacked, time0s, iv_rel, bucket_off, aux):
        k_local = time0s.shape[0]
        counts, states = jax.vmap(
            lambda a, t0, ivr, boff: per_segment(a, t0, ivr, boff, aux))(
                stacked, time0s, iv_rel, bucket_off)
        # int64 count totals across devices: exactness, x64 globally on
        counts = jax.lax.psum(counts.astype(jnp.int64).sum(axis=0), axis)  # druidlint: disable=x64-dtype
        merged = tuple(
            _merge_states(k, st, axis, n_dev, k_local)
            for k, st in zip(kernels, states))
        return counts, merged

    # fold-merged states go through all_gather, whose output the vma system
    # conservatively marks varying even though it is replicated by
    # construction — turn the static replication check off for those.
    has_fold = any(k.reduce_kind == "fold" for k in kernels) and n_dev > 1
    f = shard_map(body, mesh=mesh,
                  in_specs=layout.in_specs(stacked),
                  out_specs=layout.out_specs(), **{_check_kw: not has_fold})
    return jax.jit(f)


# ---------------------------------------------------------------------------
# Observability: query/sharded/* metrics
# ---------------------------------------------------------------------------

class ShardedStats:
    """merged_device = sharded dispatches whose partials were merged by the
    in-program collectives (every dispatch since the host-merge tail was
    removed — the counter exists so its constancy is assertable);
    segments = segments those dispatches covered."""

    def __init__(self):
        self._lock = threading.Lock()
        self.merged_device = 0
        self.segments = 0

    def record(self, n_segments: int) -> None:
        with self._lock:
            self.merged_device += 1
            self.segments += n_segments

    def snapshot(self) -> Tuple[int, int]:
        with self._lock:
            return (self.merged_device, self.segments)


_SHARDED_STATS = ShardedStats()


def sharded_stats() -> ShardedStats:
    """The process-wide sharded-dispatch stats (tests + ShardedMonitor)."""
    return _SHARDED_STATS


class ShardedMonitor(Monitor):
    """Emits `query/sharded/*` per tick: device-merged dispatches over the
    tick window, and the stacked-shard residency gauges from the device
    pool's stacked accounting."""

    def __init__(self, stats: Optional[ShardedStats] = None,
                 pool: Optional["devicepool.DeviceSegmentPool"] = None):
        self.stats = stats or sharded_stats()
        self.pool = pool or devicepool.device_pool()
        self._last = (0, 0)

    def do_monitor(self, emitter) -> None:
        s = self.stats.snapshot()
        last, self._last = self._last, s
        emitter.metric("query/sharded/mergeDevice", s[0] - last[0])
        p = self.pool.snapshot()
        emitter.metric("query/sharded/stackBytes", p.stacked_bytes)
        emitter.metric("query/sharded/packedRatio", p.stacked_ratio)
