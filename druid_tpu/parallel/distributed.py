"""Sharded multi-segment execution: one device program for a whole query.

Reference analog, inverted for TPU:
  * ChainedExecutionQueryRunner.java (thread-pool per-segment runners) →
    segments stacked on a leading axis, `jax.vmap` over it;
  * CachingClusteredClient.java:253 scatter-gather + MergeSequence →
    `shard_map` over a mesh axis, partial states merged with
    psum/pmin/pmax/all_gather collectives over ICI;
  * epinephelinae/ParallelCombiner.java combining tree → the XLA collective
    is the combining tree.

Eligibility (else callers fall back to per-segment host-merged execution):
dense key mode, "all"/"uniform" bucketing, and identical plan constants
(filter LUTs, kernel aux, dim remaps) across segments — true whenever
segments share dictionaries, which the ingestion path guarantees per
datasource generation (the analog of DimensionMergerV9's unified dictionary).
"""
from __future__ import annotations

import collections
import functools
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from druid_tpu.data.segment import Segment
from druid_tpu.engine.filters import ConstNode, plan_filter, simplify_node
from druid_tpu.engine import grouping
from druid_tpu.engine.grouping import (GroupSpec, KeyDim, SegmentPartial,
                                       assemble_stacked_aux, aux_equal,
                                       keydims_equal, make_group_spec,
                                       make_stacked_segment_fn,
                                       needed_columns, plan_virtual_columns,
                                       windowed_window)
from druid_tpu.engine.kernels import AggKernel, make_kernel
from druid_tpu.obs.trace import span as trace_span
from druid_tpu.obs.trace import span_when as trace_span_when
from druid_tpu.parallel import context
from druid_tpu.query.aggregators import AggregatorSpec
from druid_tpu.utils.granularity import Granularity
from druid_tpu.utils.intervals import Interval

# Jitted sharded programs, LRU-bounded: entries capture kernel aux arrays in
# their closures, so an unbounded cache would pin host memory across segment
# generations. Locked: concurrent queries racing evict vs move_to_end would
# KeyError (shard_map/jit construction is lazy, so building under the lock
# is cheap).
_FN_CACHE: "collections.OrderedDict[Tuple, object]" = collections.OrderedDict()
_FN_CACHE_CAP = 64
_CACHE_LOCK = threading.Lock()

# Stacked device blocks pin whole segment sets in HBM — bound the cache (LRU)
# so dropped segment generations / varying column subsets free their memory.
_STACK_CACHE: "collections.OrderedDict[Tuple, object]" = collections.OrderedDict()
_STACK_CACHE_CAP = 4


# plan-constant equality + column planning now live in engine/grouping.py,
# shared with the batched (unrolled, engine/batching.py) multi-segment path
_aux_equal = aux_equal
_keydims_equal = keydims_equal
_needed_columns = needed_columns


def try_sharded(segments: Sequence[Segment], intervals: Sequence[Interval],
                granularity: Granularity,
                kds_per_seg: Sequence[Sequence[KeyDim]],
                aggs: Sequence[AggregatorSpec], flt,
                virtual_columns: Sequence = ()) -> Optional[SegmentPartial]:
    """Run the grouped aggregate for all segments as ONE sharded device
    program; returns a single merged SegmentPartial, or None if ineligible
    (caller falls back to the per-segment path)."""
    mesh = context.get_mesh()
    if mesh is None or not segments:
        return None
    import jax
    if any(d.process_index != jax.process_index()
           for d in mesh.devices.flat):
        # cross-process mesh: the stacked program would need every shard's
        # data process-addressable; host-level combine is the broker's job
        return None
    axis = mesh.axis_names[0]
    n_dev = mesh.shape[axis]

    kds = list(kds_per_seg[0])
    if any(d.host_ids is not None for d in kds):
        # numeric-dimension ids are per-segment query-time dictionaries —
        # a stacked program cannot share one id space; per-segment path
        # merges them host-side
        return None
    for other in kds_per_seg[1:]:
        if not _keydims_equal(kds, other):
            return None
    # raw (remap-free) key dims fuse dictionary ids directly, so the
    # dictionaries themselves must agree across segments — equal cardinality
    # is NOT enough (ids would decode through segments[0]'s values)
    for d in kds:
        if d.column is None:
            continue
        first = segments[0].dims[d.column].dictionary
        for s in segments[1:]:
            other = s.dims.get(d.column)
            if other is None:
                return None
            if other.dictionary is not first and \
                    list(other.dictionary.values) != list(first.values):
                return None

    spec0 = make_group_spec(segments[0], intervals, granularity, kds)
    if spec0.key_mode != "dense" or spec0.bucket_mode not in ("all", "uniform"):
        return None

    # plan filter + kernels + virtual columns per segment; constants must
    # agree across segments
    filter_node = simplify_node(plan_filter(flt, segments[0], virtual_columns,
                                            device_bitmap=False))
    kernels = [make_kernel(a, segments[0], device_bitmap=False) for a in aggs]
    vc_plans, vc_luts = plan_virtual_columns(segments[0], virtual_columns)
    f_sig = filter_node.signature() if filter_node else "none"
    f_aux = filter_node.aux_arrays() if filter_node else []
    k_aux = [a for k in kernels for a in k.aux_arrays()]
    for s in segments[1:]:
        fn_s = simplify_node(plan_filter(flt, s, virtual_columns,
                                         device_bitmap=False))
        if (fn_s.signature() if fn_s else "none") != f_sig:
            return None
        if not _aux_equal(fn_s.aux_arrays() if fn_s else [], f_aux):
            return None
        ks = [make_kernel(a, s, device_bitmap=False) for a in aggs]
        if [k.signature() for k in ks] != [k.signature() for k in kernels]:
            return None
        if not _aux_equal([a for k in ks for a in k.aux_arrays()], k_aux):
            return None
        vp_s, vl_s = plan_virtual_columns(s, virtual_columns)
        if repr(vp_s) != repr(vc_plans) or not _aux_equal(vl_s, vc_luts):
            return None
    # only after every segment agreed on the plan is a const-false filter a
    # whole-query zero (a column may exist in some segments only)
    if isinstance(filter_node, ConstNode) and not filter_node.value:
        return SegmentPartial(
            segment=segments[0], spec=spec0,
            counts=np.zeros(spec0.num_total, dtype=np.int64),
            states={k.name: k.empty_state(spec0.num_total) for k in kernels},
            kernels=kernels)

    # every needed column must have the same presence, kind AND dtype in all
    # segments: the plain path handles per-segment differences (missing
    # aggregates as zero), but one stacked program cannot — fall back rather
    # than KeyError, silently cast, or crash. Complex (2-D) metric columns
    # also fall back: the stacker allocates [K, R] only.
    needed, columns = _needed_columns(segments[0], kds, aggs, flt,
                                      virtual_columns)
    for c in needed:
        in_dim0 = c in segments[0].dims
        met0 = segments[0].metrics.get(c)
        if met0 is not None and np.asarray(met0.values).ndim != 1:
            return None
        for s in segments[1:]:
            if (c in s.dims) != in_dim0:
                return None
            met = s.metrics.get(c)
            if (met is None) != (met0 is None):
                return None
            if met is not None and (met.type is not met0.type
                                    or met.values.dtype != met0.values.dtype
                                    or s.staged_dtype(c)
                                    != segments[0].staged_dtype(c)):
                return None
    stacked, time0s, R, K = _stack_segments(mesh, axis, segments, columns)

    # reduction strategy must agree across the whole stacked program; the
    # windowed path needs every segment's host span check to pass
    col_dtypes = {"__time_offset": np.dtype(np.int32),
                  "__valid": np.dtype(bool)}
    for c in columns:
        if c in segments[0].dims:
            col_dtypes[c] = np.dtype(np.int32)
        else:
            col_dtypes[c] = np.dtype(segments[0].staged_dtype(c))

    def _windowed_all():
        w_all = 0
        for s in segments:
            w = windowed_window(s, intervals, granularity, spec0)
            if not w:
                return 0
            w_all = max(w_all, w)
        return w_all

    # via the module so tests forcing a strategy (monkeypatching
    # grouping.select_strategy) also steer the sharded path
    spec0.strategy, spec0.window = grouping.select_strategy(
        spec0, kernels, col_dtypes, R, _windowed_all)
    if spec0.strategy == "projection":
        # sorted projections are per-segment layouts the stacked program
        # cannot share. Falling back to per-segment pallas would also pay
        # per-call dispatch/merge overhead once per segment; ONE stacked
        # scatter-mixed program amortizes it across the whole set and
        # measured ~2x faster at bench scale (8x12.5M rows) on v5e — so
        # the stacked program overrides to mixed and the projection path
        # stays the meshless per-segment winner.
        spec0.strategy, spec0.window = "mixed", 0

    # per-segment RELATIVE interval bounds + bucket start offsets: the
    # device program stays in int32 offset space (64-bit elementwise time
    # math is limb-emulated on TPU)
    clip_lo, clip_hi = -(2**31) + 1, 2**31 - 1
    iv_rel = np.zeros((K, max(len(intervals), 1), 2), dtype=np.int32)
    bucket_off = np.zeros((K,), dtype=np.int32)
    for i, s in enumerate(segments):
        t0 = s.interval.start
        for j, ivl in enumerate(intervals):
            iv_rel[i, j, 0] = min(max(ivl.start - t0, clip_lo), clip_hi)
            iv_rel[i, j, 1] = min(max(ivl.end - t0, clip_lo), clip_hi)
        if spec0.bucket_mode == "uniform":
            bucket_off[i] = min(max(int(spec0.bucket_starts[0]) - t0,
                                    clip_lo), clip_hi)
    import jax as _jax
    from jax.sharding import NamedSharding as _NS, PartitionSpec as _P
    iv_rel = _jax.device_put(iv_rel, _NS(mesh, _P(axis, None, None)))
    bucket_off = _jax.device_put(bucket_off, _NS(mesh, _P(axis)))

    aux = _assemble_aux(spec0, kds, f_aux, k_aux, granularity, vc_luts)

    sig = _sharded_sig(mesh, axis, spec0, kds, filter_node, kernels,
                       len(intervals), vc_plans, K, R)
    with _CACHE_LOCK:
        fn = _FN_CACHE.get(sig)
        # the miss IS the compile event (shard_map traces/compiles on the
        # first call below) — timing stays at the existing dispatch boundary
        compiled = fn is None
        if fn is None:
            fn = _build_sharded_fn(mesh, axis, n_dev, spec0, kds, filter_node,
                                   kernels, vc_plans)
            _FN_CACHE[sig] = fn
            while len(_FN_CACHE) > _FN_CACHE_CAP:
                _FN_CACHE.popitem(last=False)
        else:
            _FN_CACHE.move_to_end(sig)
    from druid_tpu.obs import dispatch as dispatch_mod
    dispatch_mod.record("sharded")
    with trace_span("engine/sharded/dispatch", segments=K, devices=n_dev,
                    compile=compiled), \
            trace_span_when(compiled, "engine/compile", kind="sharded"):
        counts, states = fn(stacked, time0s, iv_rel, bucket_off, aux)

    host_states = {k.name: k.host_from_device(st)
                   for k, st in zip(kernels, states)}
    return SegmentPartial(segment=segments[0], spec=spec0,
                          counts=np.asarray(counts, dtype=np.int64),
                          states=host_states, kernels=kernels)


def _stack_segments(mesh, axis: str, segments: Sequence[Segment],
                    columns: Tuple[str, ...]):
    """Host-stack segments into [K, R] arrays sharded over the mesh axis.

    K pads to a multiple of the axis size with empty (all-invalid) segments;
    R pads rows to the max padded row count. Cached per (segment set,
    columns, mesh) — repeat queries reuse HBM-resident shards, the analog of
    the reference keeping segments mmapped across queries."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    n_dev = mesh.shape[axis]
    # keyed by object identity, not segment-id strings: rebuilt segments can
    # legitimately reuse (datasource, interval, version, partition) and must
    # not be served stale stacked data. The cached value pins the segment
    # objects, so their id()s cannot be recycled while the entry lives.
    key = (tuple(id(s) for s in segments), columns, n_dev,
           tuple(d.id for d in mesh.devices.flat))
    with _CACHE_LOCK:
        cached = _STACK_CACHE.get(key)
        if cached is not None:
            _STACK_CACHE.move_to_end(key)
            return cached[:4]

    align = 1024
    R = max(align, max(((s.n_rows + align - 1) // align) * align
                       for s in segments))
    K = ((len(segments) + n_dev - 1) // n_dev) * n_dev

    def col_array(s: Segment, name: str) -> Tuple[np.ndarray, object]:
        if name in s.dims:
            return s.dims[name].ids, np.int32(0)
        m = s.metrics[name]
        dt = s.staged_dtype(name)   # int32-narrowed longs stay narrow
        vals = m.values if m.values.dtype == dt else m.values.astype(dt)
        return vals, vals.dtype.type(0)

    arrays: Dict[str, np.ndarray] = {}
    names = ("__time_offset", "__valid") + columns
    for name in names:
        if name == "__time_offset":
            dt, fill = np.int32, 0
        elif name == "__valid":
            dt, fill = bool, False
        else:
            a0, fill = col_array(segments[0], name)
            dt = a0.dtype
        out = np.full((K, R), fill, dtype=dt)
        for i, s in enumerate(segments):
            if name == "__time_offset":
                off = s.time_ms - s.interval.start
                if off.size and (off.min() < 0 or off.max() >= 2**31):
                    raise ValueError(f"segment {s.id} outside int32 offset range")
                out[i, : s.n_rows] = off.astype(np.int32)
            elif name == "__valid":
                out[i, : s.n_rows] = True
            else:
                a, _ = col_array(s, name)
                out[i, : a.shape[0]] = a
        arrays[name] = out

    time0s = np.zeros((K,), dtype=np.int64)
    for i, s in enumerate(segments):
        time0s[i] = s.interval.start

    shard = NamedSharding(mesh, P(axis, None))
    shard1 = NamedSharding(mesh, P(axis))
    dev_arrays = {k: jax.device_put(v, shard) for k, v in arrays.items()}
    dev_time0s = jax.device_put(time0s, shard1)
    result = (dev_arrays, dev_time0s, R, K)
    # stacking (device_put of whole segment sets) stays outside the lock;
    # a concurrent duplicate build wastes work but cannot corrupt the LRU
    with _CACHE_LOCK:
        _STACK_CACHE[key] = result + (tuple(segments),)
        while len(_STACK_CACHE) > _STACK_CACHE_CAP:
            _STACK_CACHE.popitem(last=False)
    return result


def clear_stack_cache() -> int:
    """Release the HBM-resident stacked segment sets (and the segment
    objects each entry deliberately pins). Returns the entry count
    dropped. The ops analog of unloading segments to reclaim HBM without
    a restart — engine.release_device_caches() is the public surface."""
    with _CACHE_LOCK:
        n = len(_STACK_CACHE)
        _STACK_CACHE.clear()
        return n


def clear_fn_cache() -> int:
    """Drop the jitted sharded programs (their closures pin kernel aux
    arrays across segment generations)."""
    with _CACHE_LOCK:
        n = len(_FN_CACHE)
        _FN_CACHE.clear()
        return n


# aux layout shared with the batched path (engine/grouping.py)
_assemble_aux = assemble_stacked_aux


def _sharded_sig(mesh, axis, spec: GroupSpec, kds, filter_node, kernels,
                 n_intervals, vc_plans, K, R) -> Tuple:
    dims_sig = ",".join(
        f"{d.column}:{'remap' if d.remap is not None else 'raw'}" for d in kds)
    vc_sig = ";".join(f"{name}={expr!r}:{out_type}:l{n_luts}"
                      for name, expr, out_type, n_luts in vc_plans)
    mesh_key = (tuple(d.id for d in mesh.devices.flat), mesh.axis_names)
    return (mesh_key, axis, spec.bucket_mode, dims_sig, n_intervals, vc_sig,
            filter_node.signature() if filter_node else "none",
            ";".join(k.signature() for k in kernels), spec.num_total, K, R,
            spec.strategy, spec.window)


def _merge_states(kernel: AggKernel, stacked_state, axis: str, n_dev: int,
                  k_local: int):
    """Fold per-segment states over the local axis, then across the mesh."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    kind = kernel.reduce_kind
    # On a 1-device mesh every collective is the identity; use psum for all
    # kinds — it satisfies the replication (vma) check and is the one
    # collective every TPU transport lowers (some support only Sum
    # all-reduce). Bool states must go through int for psum.
    if n_dev == 1:
        if kind != "sum":
            if kind == "max":
                st = jax.tree.map(lambda x: x.max(axis=0), stacked_state)
            elif kind == "min":
                st = jax.tree.map(lambda x: x.min(axis=0), stacked_state)
            else:
                parts = [jax.tree.map(lambda x, i=i: x[i], stacked_state)
                         for i in range(k_local)]
                st = functools.reduce(kernel.device_combine, parts)
        else:
            # cross-segment integer sums widen to int64 before the fold —
            # exactness contract, x64 globally on (engine/__init__)
            st = jax.tree.map(
                lambda x: (x.astype(jnp.int64)  # druidlint: disable=x64-dtype
                           if jnp.issubdtype(x.dtype, jnp.integer)
                           else x).sum(axis=0), stacked_state)

        def ident_psum(x):
            if x.dtype == jnp.bool_:
                return lax.psum(x.astype(jnp.int32), axis) > 0
            return lax.psum(x, axis)
        return jax.tree.map(ident_psum, st)
    if kind == "sum":
        def local(x):
            if jnp.issubdtype(x.dtype, jnp.integer):
                # int64 before psum: exactness contract, x64 globally on
                x = x.astype(jnp.int64)  # druidlint: disable=x64-dtype
            return x.sum(axis=0)
        st = jax.tree.map(local, stacked_state)
        return jax.tree.map(lambda x: lax.psum(x, axis), st)
    if kind == "max":
        st = jax.tree.map(lambda x: x.max(axis=0), stacked_state)
        return jax.tree.map(lambda x: lax.pmax(x, axis), st)
    if kind == "min":
        st = jax.tree.map(lambda x: x.min(axis=0), stacked_state)
        return jax.tree.map(lambda x: lax.pmin(x, axis), st)
    # fold: pairwise device_combine locally, all_gather + fold across devices
    parts = [jax.tree.map(lambda x, i=i: x[i], stacked_state)
             for i in range(k_local)]
    st = functools.reduce(kernel.device_combine, parts)
    gathered = jax.tree.map(
        lambda x: lax.all_gather(x, axis, axis=0, tiled=False), st)
    parts = [jax.tree.map(lambda x, i=i: x[i], gathered) for i in range(n_dev)]
    return functools.reduce(kernel.device_combine, parts)


def _build_sharded_fn(mesh, axis: str, n_dev: int, spec: GroupSpec,
                      kds: Sequence[KeyDim], filter_node,
                      kernels: List[AggKernel], vc_plans: Tuple):
    import jax
    import jax.numpy as jnp
    try:
        from jax import shard_map          # jax >= 0.5
        _check_kw = "check_vma"
    except ImportError:                    # 0.4.x: experimental home,
        from jax.experimental.shard_map import shard_map
        _check_kw = "check_rep"            # and the old replication-check kw
    from jax.sharding import PartitionSpec as P

    seg_body = make_stacked_segment_fn(spec, kds, filter_node, kernels,
                                       vc_plans)

    def per_segment(arrays, time0, iv_rel, bucket_off, aux):
        counts, states = seg_body(arrays, time0, iv_rel, bucket_off, aux)
        states = tuple(k.device_post(s, time0)
                       for k, s in zip(kernels, states))
        return counts, states

    def body(stacked, time0s, iv_rel, bucket_off, aux):
        k_local = time0s.shape[0]
        counts, states = jax.vmap(
            lambda a, t0, ivr, boff: per_segment(a, t0, ivr, boff, aux))(
                stacked, time0s, iv_rel, bucket_off)
        # int64 count totals across devices: exactness, x64 globally on
        counts = jax.lax.psum(counts.astype(jnp.int64).sum(axis=0), axis)  # druidlint: disable=x64-dtype
        merged = tuple(
            _merge_states(k, st, axis, n_dev, k_local)
            for k, st in zip(kernels, states))
        return counts, merged

    # fold-merged states go through all_gather, whose output the vma system
    # conservatively marks varying even though it is replicated by
    # construction — turn the static replication check off for those.
    has_fold = any(k.reduce_kind == "fold" for k in kernels) and n_dev > 1
    f = shard_map(body, mesh=mesh,
                  in_specs=(P(axis, None), P(axis), P(axis, None, None),
                            P(axis), P()),
                  out_specs=(P(), P()), **{_check_kw: not has_fold})
    return jax.jit(f)
