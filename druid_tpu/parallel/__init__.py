"""Multi-chip execution: device meshes, sharded scatter-gather, collectives.

Reference analog: Druid's distribution layer — the broker scatter-gather
(client/CachingClusteredClient.java:253) + per-node parallel merge
(ChainedExecutionQueryRunner.java) + parallel combine
(epinephelinae/ParallelCombiner.java). TPU-first inversion: segments shard
over a jax.sharding.Mesh axis; per-segment partial aggregation states live in
HBM and merge with XLA collectives (psum/pmin/pmax/all_gather) over ICI
instead of shipping intermediate bytes over HTTP.
"""
from druid_tpu.parallel.context import (get_mesh, make_mesh, set_mesh,
                                        use_mesh)

__all__ = ["get_mesh", "make_mesh", "set_mesh", "use_mesh"]
