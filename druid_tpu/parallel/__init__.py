"""Multi-chip execution: device meshes, sharded scatter-gather, collectives.

Reference analog: Druid's distribution layer — the broker scatter-gather
(client/CachingClusteredClient.java:253) + per-node parallel merge
(ChainedExecutionQueryRunner.java) + parallel combine
(epinephelinae/ParallelCombiner.java). TPU-first inversion: segments shard
over a jax.sharding.Mesh axis; per-segment partial aggregation states live in
HBM and merge with XLA collectives (psum/pmin/pmax/all_gather) over ICI
instead of shipping intermediate bytes over HTTP.

Scaling axes, explicitly:
  * within a host/pod (ICI): the stacked sharded program — segments on the
    mesh axis, partials combined with collectives (distributed.py);
  * across hosts (DCN): the broker scatter over remote data nodes
    (cluster/broker.py + cluster/dataserver.py binary wire) — exactly the
    reference's host-level model, with each node running its own mesh.
    Segments are immutable and partials are tiny, so host-level scatter
    composes with chip-level collectives without a global mesh.
  * a jax-level multi-host mesh (initialize_multihost + make_mesh spanning
    processes) is available for pod-slice deployments; the stacked program
    requires process-addressable shards, so on a cross-process mesh it
    falls back to per-segment execution and the broker layer carries the
    cross-host combine (try_sharded guards this explicitly).
"""
from druid_tpu.parallel.context import (get_mesh, initialize_multihost,
                                        make_mesh, set_mesh, use_mesh)

__all__ = ["get_mesh", "initialize_multihost", "make_mesh", "set_mesh",
           "use_mesh"]
