"""Canonical mesh layout: the ONE source of PartitionSpecs in the tree.

The sharded execution path used to hand-roll PartitionSpecs at every call
site (stack staging, interval bounds, shard_map in/out specs), so a
resharding edit had to find and agree with every literal. This module is
the single authority instead: a frozen :class:`SpecLayout` names the mesh
axes once and exposes ONE METHOD PER ARRAY ROLE — stacked column words,
resident bitmap word slots, cascade run tables, per-segment time origins,
per-device partial grids — and every sharded producer/consumer asks it.
druidlint's `spec-literal-outside-layout` rule (tools/druidlint/
tracecheck.py) makes the invariant structural: a PartitionSpec or
NamedSharding constructed anywhere else in the tree is a lint failure.

Layout contract (the parallel/distributed.py execution model):

  * every STACKED leaf — decoded rows [K, R], packed/cascade words
    [K, W], run tables [K, runs], bitmap words [K, R/32], per-segment
    scalars [K] — carries the segment axis FIRST and shards over it;
    trailing dimensions are replicated within a shard;
  * plan constants (aux arrays) are replicated everywhere;
  * merged partial grids leave the program replicated — the collective
    merge (psum/pmin/pmax/all_gather+fold) already combined them, so the
    broker-side host merge for the sharded path is gone by construction.

jax imports stay lazy (function-local): the layout must be constructible
and hashable for cache keys without touching a backend.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Tuple

from druid_tpu.parallel import context


def _pspec():
    from jax.sharding import PartitionSpec
    return PartitionSpec


def _named_sharding():
    from jax.sharding import NamedSharding
    return NamedSharding


@dataclass(frozen=True)
class SpecLayout:
    """Frozen, canonical sharding layout over a 1-D segment mesh."""

    #: the mesh axis segments shard over (context.make_mesh's one axis)
    seg_axis: str = context.SEGMENT_AXIS

    # ---- one method per array role -----------------------------------
    def column_rows(self):
        """Stacked decoded column rows [K, R]: segment axis leads, rows
        replicated within the shard."""
        return _pspec()(self.seg_axis, None)

    def column_words(self):
        """Stacked packed/FOR/delta word slots [K, W] (data/packed.py
        tile-planar layout) — same story as decoded rows: the word axis
        is intra-segment."""
        return _pspec()(self.seg_axis, None)

    def bitmap_words(self):
        """Stacked resident filter-bitmap words [K, R/32]
        (engine/filters.py DeviceBitmapNode slots)."""
        return _pspec()(self.seg_axis, None)

    def run_tables(self):
        """Stacked RLE run values/ends [K, runs] (data/cascade.py)."""
        return _pspec()(self.seg_axis, None)

    def time0s(self):
        """Per-segment scalars [K]: time origins, delta-column firsts,
        RLE row counts, bucket offsets."""
        return _pspec()(self.seg_axis)

    def interval_bounds(self):
        """Per-segment relative interval bounds [K, n_intervals, 2]."""
        return _pspec()(self.seg_axis, None, None)

    def bucket_offsets(self):
        """Per-segment uniform-granularity bucket origins [K]."""
        return self.time0s()

    def replicated(self):
        """Plan constants (aux arrays): replicated on every device."""
        return _pspec()()

    def partial_grid(self):
        """Merged per-device partial grids: the collective merge already
        combined them, so they leave the program replicated."""
        return self.replicated()

    # ---- generic stacked-pytree mapping ------------------------------
    def stacked_leaf(self, ndim: int):
        """Spec for ONE stacked leaf by rank: axis 0 is always the
        segment axis ([K] scalars, [K, R] rows, [K, W] words alike);
        everything trailing is intra-segment."""
        if ndim < 1:
            raise ValueError("stacked leaves carry a leading segment axis")
        return _pspec()(self.seg_axis, *(None,) * (ndim - 1))

    def stacked_specs(self, tree):
        """The PartitionSpec tree matching a stacked pytree (compressed
        column objects included — their registered leaves map by rank)."""
        import jax
        return jax.tree.map(lambda leaf: self.stacked_leaf(leaf.ndim), tree)

    # ---- device placement (the only NamedSharding factory) -----------
    def sharding(self, mesh, spec):
        return _named_sharding()(mesh, spec)

    def put_stacked(self, mesh, tree):
        """device_put a stacked pytree with per-leaf rank-derived specs."""
        import jax
        shardings = jax.tree.map(
            lambda leaf: self.sharding(mesh, self.stacked_leaf(leaf.ndim)),
            tree)
        return jax.device_put(tree, shardings)

    def put_time0s(self, mesh, value):
        import jax
        return jax.device_put(value, self.sharding(mesh, self.time0s()))

    def put_interval_bounds(self, mesh, value):
        import jax
        return jax.device_put(value,
                              self.sharding(mesh, self.interval_bounds()))

    def put_bucket_offsets(self, mesh, value):
        import jax
        return jax.device_put(value,
                              self.sharding(mesh, self.bucket_offsets()))

    # ---- shard_map plumbing ------------------------------------------
    def in_specs(self, stacked) -> Tuple:
        """shard_map in_specs for the canonical sharded-program calling
        convention: (stacked tree, time0s, interval bounds, bucket
        offsets, replicated aux)."""
        return (self.stacked_specs(stacked), self.time0s(),
                self.interval_bounds(), self.bucket_offsets(),
                self.replicated())

    def out_specs(self) -> Tuple:
        """(counts, states): both pre-merged on device, both replicated."""
        return (self.partial_grid(), self.partial_grid())


def layout_for(mesh) -> "SpecLayout":
    """The layout for a mesh: its first axis is the segment axis (the
    parallel.context.make_mesh contract; user-built meshes keep their own
    leading axis name)."""
    axis = mesh.axis_names[0]
    return SpecLayout(seg_axis=axis)


def layout_sig(layout: "SpecLayout", mesh) -> Tuple:
    """Cache-key witness for everything a sharded program specializes on
    from the (layout, mesh) pair: segment axis, the exact device set in
    mesh order, the axis-name tuple, and the mesh shape. Joins
    distributed._sharded_sig; keyguard's `unkeyed-trace-input` rule
    (pyproject `keyguard-key-fns`) holds every parameter to dataflow into
    the return, so a mesh/layout input silently dropped from the key is a
    lint failure, not an aliased cached program."""
    return (layout.seg_axis,
            tuple(int(d.id) for d in mesh.devices.flat),
            tuple(mesh.axis_names),
            tuple(int(n) for n in mesh.devices.shape))
