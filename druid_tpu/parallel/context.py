"""Active device-mesh context for query execution.

The executor (and tests / the driver's multi-chip dry run) install a
`jax.sharding.Mesh` here; the engines then route eligible grouped
aggregations through the sharded path (druid_tpu/parallel/distributed.py)
instead of per-segment host-merged execution.

Reference analog: DruidProcessingConfig.java:30-72 selecting the processing
pool the per-segment runners execute on — here the "pool" is a device mesh.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Optional

SEGMENT_AXIS = "seg"

_state = threading.local()


def make_mesh(n_devices: Optional[int] = None, axis: str = SEGMENT_AXIS):
    """1-D mesh over the first `n_devices` local devices (all by default)."""
    import jax
    from jax.sharding import Mesh
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    import numpy as np
    return Mesh(np.asarray(devices), (axis,))


def set_mesh(mesh) -> None:
    _state.mesh = mesh


def get_mesh():
    return getattr(_state, "mesh", None)


@contextlib.contextmanager
def use_mesh(mesh):
    prev = get_mesh()
    _state.mesh = mesh
    try:
        yield mesh
    finally:
        _state.mesh = prev
