"""Active device-mesh context for query execution.

The executor (and tests / the driver's multi-chip dry run) install a
`jax.sharding.Mesh` here; the engines then route eligible grouped
aggregations through the sharded path (druid_tpu/parallel/distributed.py)
instead of per-segment host-merged execution.

Reference analog: DruidProcessingConfig.java:30-72 selecting the processing
pool the per-segment runners execute on — here the "pool" is a device mesh.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Optional

SEGMENT_AXIS = "seg"

_state = threading.local()


def initialize_multihost(coordinator_address: Optional[str] = None,
                         num_processes: Optional[int] = None,
                         process_id: Optional[int] = None) -> int:
    """Join a multi-host jax.distributed job (pod-slice deployments where
    one logical data node spans several hosts). After this, jax.devices()
    lists EVERY host's chips and make_mesh() builds a global mesh whose
    psum/pmax collectives ride ICI within a pod and DCN across pods —
    the role NCCL/MPI play for the reference's distribution layer.
    With no arguments, jax reads JAX_COORDINATOR_ADDRESS from the
    environment and auto-detects process count/id on recognized clusters
    (TPU pod metadata, SLURM, OMPI); elsewhere pass num_processes and
    process_id explicitly. Returns the process count. Idempotent."""
    import jax
    if getattr(initialize_multihost, "_done", False):
        return jax.process_count()
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    jax.distributed.initialize(**kwargs)
    initialize_multihost._done = True
    return jax.process_count()


def make_mesh(n_devices: Optional[int] = None, axis: str = SEGMENT_AXIS):
    """1-D mesh over the first `n_devices` devices (all by default). After
    initialize_multihost() the device list is global, so the mesh spans
    every process's chips."""
    import jax
    from jax.sharding import Mesh
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    import numpy as np
    return Mesh(np.asarray(devices), (axis,))


def set_mesh(mesh) -> None:
    _state.mesh = mesh


def get_mesh():
    return getattr(_state, "mesh", None)


@contextlib.contextmanager
def use_mesh(mesh):
    prev = get_mesh()
    _state.mesh = mesh
    try:
        yield mesh
    finally:
        _state.mesh = prev
