"""Per-query lifecycle: initialize → authorize → execute → emit logs/metrics.

Reference analogs:
  server/QueryLifecycle.java:61-69,120-133 — the four-phase lifecycle every
    query goes through, emitting query/time metrics and request logs
  processing/.../query/QueryMetrics.java + MetricsEmittingQueryRunner —
    per-query timing dims (query id, type, datasource, success)
  server/log/FileRequestLogger.java / EmittingRequestLogger — request logs
  server/security/Authenticator/Authorizer — pluggable auth SPI chain
    (allow-all default, like the reference's AllowAllAuthorizer)
"""
from __future__ import annotations

import json
import time
import uuid
from typing import Callable, List, Optional

from druid_tpu.obs import trace as qtrace
from druid_tpu.query.model import Query, query_from_json
from druid_tpu.utils.emitter import ServiceEmitter


class Unauthorized(PermissionError):
    pass


class RequestLogger:
    """NDJSON request log (FileRequestLogger pattern); None path = memory,
    bounded to the most recent `max_entries` so long-running servers don't
    grow without bound."""

    def __init__(self, path: Optional[str] = None, max_entries: int = 10_000):
        from collections import deque
        self.path = path
        self.entries = deque(maxlen=max_entries)
        self._fh = open(path, "a") if path else None

    def log(self, entry: dict) -> None:
        if self._fh is not None:
            self._fh.write(json.dumps(entry) + "\n")
            self._fh.flush()
        else:
            self.entries.append(entry)


class QueryLifecycle:
    """Wraps any runner (QueryExecutor / Broker) with auth, metrics,
    request logging, and query-id bookkeeping."""

    def __init__(self, runner,
                 emitter: Optional[ServiceEmitter] = None,
                 request_logger: Optional[RequestLogger] = None,
                 authorizer: Optional[Callable[[Optional[str], Query], bool]] = None,
                 on_result: Optional[Callable[[bool], None]] = None,
                 query_manager=None, scheduler=None,
                 slow_query_ms: Optional[float] = None):
        """slow_query_ms: queries slower than this emit an ALERT carrying
        the full qtrace phase breakdown (the slow-query log); None = off."""
        self.runner = runner
        self.emitter = emitter
        self.request_logger = request_logger
        self.authorizer = authorizer          # (identity, query) → allowed
        self.on_result = on_result            # QueryCountStatsMonitor hook
        self.slow_query_ms = slow_query_ms
        #: optional QueryScheduler: bounded priority-ordered admission
        #: (the PrioritizedExecutorService role, per query not per segment)
        self.scheduler = scheduler
        # share the runner's manager so a DELETE at this resource trips the
        # same token the broker's scatter is checking
        self.query_manager = query_manager \
            if query_manager is not None \
            else getattr(runner, "query_manager", None)

    def _admit(self, query: Query, qid: str):
        """Acquire a scheduler slot (priority/lane from the query context).
        Returns (query, release): the context timeout is rewritten to the
        budget REMAINING after the queue wait — timeout means total query
        time, not per-phase — and a DELETE on the queued id aborts the
        wait via the token. Without a scheduler: (query, no-op)."""
        if self.scheduler is None:
            return query, (lambda: None)
        from druid_tpu.server.querymanager import (QueryTimeoutError,
                                                   context_priority,
                                                   context_timeout_ms)
        lane = query.context_map.get("lane")
        tmo = context_timeout_ms(query)
        token = self.query_manager.token(qid) \
            if self.query_manager is not None else None
        t0 = time.monotonic()
        with qtrace.span("queue/wait", lane=lane or "",
                         priority=context_priority(query)):
            ok = self.scheduler.acquire(
                priority=context_priority(query), lane=lane,
                timeout=None if tmo is None else tmo / 1000.0,
                should_abort=token.check if token is not None else None)
        if not ok:
            raise QueryTimeoutError(
                "query timed out waiting for an execution slot")
        waited_ms = (time.monotonic() - t0) * 1000
        if self.emitter is not None:
            # time queued before execution (reference: query/wait/time)
            self.emitter.metric("query/wait/time", waited_ms,
                                dataSource=query.datasource,
                                type=query.query_type, id=qid)
        if tmo is not None and waited_ms > 1.0:
            from dataclasses import replace
            remaining = max(1, int(tmo - waited_ms))
            query = replace(query, context=tuple(sorted(
                {**query.context_map, "timeout": remaining}.items())))
        return query, (lambda: self.scheduler.release(lane))

    def cancel(self, query_id: str) -> bool:
        """DELETE /druid/v2/{id} (QueryResource.cancelQuery)."""
        if self.query_manager is None:
            return False
        return self.query_manager.cancel(query_id)

    def run_json(self, payload: dict, identity: Optional[str] = None):
        try:
            query = query_from_json(payload)
        except (ValueError, KeyError, TypeError):
            # malformed queries count as failures at the resource layer
            if self.on_result:
                self.on_result(False)
            raise
        return self.run(query, identity)

    def _prepare(self, query: Query, identity):
        """Shared security-sensitive prologue of run()/run_streaming:
        authorize, stamp the queryId so cancel/timeout plumbing sees it,
        register with the query manager. Returns (query, qid)."""
        qid = query.context_map.get("queryId") or str(uuid.uuid4())
        if self.authorizer is not None \
                and not self.authorizer(identity, query):
            self._log(query, qid, 0.0, False, error="unauthorized")
            raise Unauthorized(f"identity {identity!r} denied on "
                               f"[{query.datasource}]")
        if qid != query.context_map.get("queryId"):
            from dataclasses import replace
            query = replace(query, context=tuple(sorted(
                {**query.context_map, "queryId": qid}.items())))
        if self.query_manager is not None:
            self.query_manager.register(qid)
        return query, qid

    def etag(self, query: Query, identity: Optional[str] = None):
        """Authorization-gated result-set identity (X-Druid-ETag): raises
        Unauthorized exactly like run() would — a 304 must never leak
        whether forbidden data changed. None when the runner has no etag
        surface or the query has none."""
        if self.authorizer is not None \
                and not self.authorizer(identity, query):
            raise Unauthorized(f"identity {identity!r} denied on "
                               f"[{query.datasource}]")
        fn = getattr(self.runner, "etag", None)
        return fn(query) if fn is not None else None

    def log_conditional_hit(self, query: Query, etag: str) -> None:
        """A 304 served off If-None-Match still counts: request log entry
        and success tick, zero rows."""
        self._log(query, f"etag:{etag[:12]}", 0.0, True, n_rows=0)
        if self.on_result:
            self.on_result(True)

    def run(self, query: Query, identity: Optional[str] = None):
        query, qid = self._prepare(query, identity)
        t0 = time.monotonic()
        release = lambda: None
        root = None
        try:
            # the trace root (trace id = queryId): queue wait, broker
            # phases, engine dispatches, and remote nodes' spans all
            # assemble under it; {"trace": false} makes it a no-op
            with qtrace.root_span(
                    "query", query,
                    service=self.emitter.service if self.emitter is not None
                    else "druid/query") as root:
                query, release = self._admit(query, qid)
                rows = self.runner.run(query)
        except Exception as e:
            ms = (time.monotonic() - t0) * 1000
            self._log(query, qid, ms, False, error=str(e))
            self._finish_trace(query, qid, ms, root)
            if self.on_result:
                self.on_result(False)
            raise
        finally:
            release()
            if self.query_manager is not None:
                self.query_manager.unregister(qid)
        ms = (time.monotonic() - t0) * 1000
        self._log(query, qid, ms, True, n_rows=_count_rows(rows))
        self._finish_trace(query, qid, ms, root)
        if self.on_result:
            self.on_result(True)
        return rows

    def _finish_trace(self, query: Query, qid: str, ms: float,
                      root) -> None:
        """Phase-attributed per-query metrics from the assembled trace
        (query/compile/time, query/stage/h2d/time, query/node/time) and the
        slow-query log: a threshold breach emits an alert with the full
        phase breakdown, so 'where did the 40 ms go' is answerable from the
        metrics stream alone."""
        if self.emitter is None:
            return
        # restrict to THIS run's subtree: a client-reused queryId lands
        # several runs in one store entry, and summing across them would
        # report phantom compile/node time on a cache-hit rerun
        spans = qtrace.spans_under(root._store.spans(root.trace_id),
                                   root.span_id) \
            if root is not None and root._store is not None else []
        if root is not None:
            qtrace.emit_trace_metrics(self.emitter, query, qid, spans)
        # the slow-query alert fires from the wall clock alone — a query
        # opting out of TRACING ({"trace": false}) still breaches the
        # threshold, it just alerts with an empty phase breakdown
        if self.slow_query_ms is not None and ms > self.slow_query_ms:
            self.emitter.alert(
                "slow query: query/time above threshold",
                queryId=qid, dataSource=query.datasource,
                type=query.query_type, durationMs=round(ms, 3),
                thresholdMs=self.slow_query_ms,
                breakdown=qtrace.phase_breakdown(spans))

    def run_streaming(self, query: Query, identity: Optional[str] = None):
        """Streaming variant: authorize up front, yield result batches as
        the runner produces them, emit the request log/metrics when the
        stream completes, fails, OR is abandoned (client disconnect →
        GeneratorExit). Falls back to the materialized path for runners
        without run_streaming."""
        runner_stream = getattr(self.runner, "run_streaming", None)
        if runner_stream is None:
            yield from self.run(query, identity)
            return
        query, qid = self._prepare(query, identity)
        t0 = time.monotonic()
        n = 0
        release = lambda: None
        try:
            query, release = self._admit(query, qid)
            for batch in runner_stream(query):
                n += 1    # top-level results (scan batches), like run()'s
                yield batch   # len(rows) over the materialized batch list
            self._log(query, qid, (time.monotonic() - t0) * 1000, True,
                      n_rows=n)
            if self.on_result:
                self.on_result(True)
        except GeneratorExit:
            # consumer walked away mid-stream — the query still happened
            self._log(query, qid, (time.monotonic() - t0) * 1000, False,
                      error="stream abandoned", n_rows=n)
            if self.on_result:
                self.on_result(False)
            raise
        except Exception as e:
            self._log(query, qid, (time.monotonic() - t0) * 1000, False,
                      error=str(e))
            if self.on_result:
                self.on_result(False)
            raise
        finally:
            release()
            if self.query_manager is not None:
                self.query_manager.unregister(qid)

    def _log(self, query: Query, qid: str, ms: float, ok: bool,
             error: Optional[str] = None, n_rows: int = 0) -> None:
        if self.emitter is not None:
            from druid_tpu.server.querymanager import context_priority
            self.emitter.metric("query/time", ms, dataSource=query.datasource,
                                type=query.query_type, id=qid,
                                priority=context_priority(query),
                                success=str(ok).lower())
        if self.request_logger is not None:
            self.request_logger.log({
                "timestamp": int(time.time() * 1000), "queryId": qid,
                "queryType": query.query_type,
                "dataSource": query.datasource, "query/time": ms,
                "success": ok, "error": error, "rows": n_rows})


def _count_rows(rows) -> int:
    try:
        return len(rows)
    except TypeError:
        return 0
