"""Security SPI chain: Authenticator → Authorizer (+ Escalator).

Reference analogs (server/src/main/java/org/apache/druid/server/security/):
  Authenticator.java / AuthenticatorMapper — ordered credential checkers;
    the first one that recognizes the request wins
  Authorizer.java / AuthorizationUtils.authorizeAllResourceActions — maps an
    authenticated identity to per-(resource, action) decisions
  Escalator.java — the internal identity services use for
    service-to-service calls (so cluster-internal fan-out is never blocked
    by user-level ACLs)
  Resource.java / Action.java / ResourceAction.java — the resource model

The chain plugs into QueryLifecycle via `authorizer_for_query` and into the
HTTP layer via `AuthChain.authenticate(headers)`.
"""
from __future__ import annotations

import base64
import fnmatch
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

READ = "READ"
WRITE = "WRITE"

DATASOURCE = "DATASOURCE"
CONFIG = "CONFIG"
STATE = "STATE"


@dataclass(frozen=True)
class Resource:
    name: str
    type: str = DATASOURCE


@dataclass(frozen=True)
class ResourceAction:
    resource: Resource
    action: str


@dataclass(frozen=True)
class AuthenticationResult:
    """Who the caller is and which authorizer decides for them
    (reference: AuthenticationResult.java)."""
    identity: str
    authorizer_name: str = "allowAll"
    context: Tuple = ()


class AuthenticationFailed(Exception):
    """Credentials were PRESENT for this authenticator but invalid — the
    chain must deny the request, not fall through to a weaker
    authenticator (reference BasicHTTPAuthenticator skipOnFailure=false)."""


class Authenticator:
    """SPI: inspect request headers, return an AuthenticationResult, None
    ('not mine'; the chain moves to the next authenticator), or raise
    AuthenticationFailed (mine, and wrong — terminal deny)."""

    name = "base"

    def authenticate(self, headers: Dict[str, str]
                     ) -> Optional[AuthenticationResult]:
        raise NotImplementedError


class AllowAllAuthenticator(Authenticator):
    name = "allowAll"

    def __init__(self, authorizer_name: str = "allowAll"):
        self.authorizer_name = authorizer_name

    def authenticate(self, headers):
        return AuthenticationResult("allowAll", self.authorizer_name)


class BasicHTTPAuthenticator(Authenticator):
    """HTTP Basic credentials against a user→password map (the capability
    of extensions-core/druid-basic-security's BasicHTTPAuthenticator)."""

    name = "basic"

    def __init__(self, users: Dict[str, str],
                 authorizer_name: str = "allowAll"):
        self.users = dict(users)
        self.authorizer_name = authorizer_name

    def authenticate(self, headers):
        auth = headers.get("Authorization") or headers.get("authorization")
        if not auth or not auth.startswith("Basic "):
            return None
        try:
            user, _, pw = base64.b64decode(auth[6:]).decode().partition(":")
        except Exception:
            raise AuthenticationFailed("malformed Basic credentials")
        if self.users.get(user) == pw:
            return AuthenticationResult(user, self.authorizer_name)
        # present-but-wrong credentials must not launder into a weaker
        # authenticator downstream
        raise AuthenticationFailed(f"bad credentials for {user!r}")


class Authorizer:
    """SPI: one (identity, resource, action) decision."""

    def authorize(self, auth: AuthenticationResult, resource: Resource,
                  action: str) -> bool:
        raise NotImplementedError


class AllowAllAuthorizer(Authorizer):
    def authorize(self, auth, resource, action):
        return True


@dataclass
class Permission:
    resource_pattern: str       # fnmatch over resource name
    resource_type: str = DATASOURCE
    actions: Tuple[str, ...] = (READ, WRITE)

    def grants(self, resource: Resource, action: str) -> bool:
        return (resource.type == self.resource_type
                and action in self.actions
                and fnmatch.fnmatchcase(resource.name, self.resource_pattern))


class RoleBasedAuthorizer(Authorizer):
    """identity → roles → permissions (basic-security RBAC capability)."""

    def __init__(self, role_permissions: Dict[str, Sequence[Permission]],
                 user_roles: Dict[str, Sequence[str]]):
        self.role_permissions = {r: list(p)
                                 for r, p in role_permissions.items()}
        self.user_roles = {u: list(r) for u, r in user_roles.items()}

    def authorize(self, auth, resource, action):
        for role in self.user_roles.get(auth.identity, ()):
            for perm in self.role_permissions.get(role, ()):
                if perm.grants(resource, action):
                    return True
        return False


class Escalator:
    """Internal service-to-service identity (reference Escalator.java):
    cluster-internal calls run as this identity, never as the end user."""

    def __init__(self, identity: str = "druid_internal",
                 authorizer_name: str = "allowAll"):
        self._result = AuthenticationResult(identity, authorizer_name)

    def escalate(self) -> AuthenticationResult:
        return self._result


class AuthChain:
    """Ordered authenticators + named authorizers — the AuthenticatorMapper
    / AuthorizerMapper pair."""

    def __init__(self, authenticators: Sequence[Authenticator] = (),
                 authorizers: Optional[Dict[str, Authorizer]] = None,
                 escalator: Optional[Escalator] = None):
        self.authenticators = list(authenticators) or [AllowAllAuthenticator()]
        self.authorizers = dict(authorizers or {"allowAll": AllowAllAuthorizer()})
        self.escalator = escalator or Escalator()

    def authenticate(self, headers: Dict[str, str]
                     ) -> Optional[AuthenticationResult]:
        for a in self.authenticators:
            try:
                result = a.authenticate(headers)
            except AuthenticationFailed:
                return None      # terminal deny: no fall-through
            if result is not None:
                return result
        return None

    def authorize_all(self, auth: AuthenticationResult,
                      resource_actions: Sequence[ResourceAction]) -> bool:
        zer = self.authorizers.get(auth.authorizer_name)
        if zer is None:
            return False
        return all(zer.authorize(auth, ra.resource, ra.action)
                   for ra in resource_actions)


def resource_actions_for_query(query) -> List[ResourceAction]:
    """The datasources a query reads (incl. unions and nested inner
    queries) as READ resource-actions
    (AuthorizationUtils.authorizeAllResourceActions inputs)."""
    out: List[ResourceAction] = []
    seen = set()

    def add(q):
        for ds in (q.union_datasources or (q.datasource,)):
            # the synthetic nested-query datasource is not a resource;
            # the INNER query's real tables are what gets authorized
            if ds and ds != "__subquery__" and ds not in seen:
                seen.add(ds)
                out.append(ResourceAction(Resource(ds, DATASOURCE), READ))
        if q.inner_query is not None:
            add(q.inner_query)

    add(query)
    return out


def authorizer_for_query(chain: AuthChain):
    """Adapter to QueryLifecycle's (identity, query) -> bool hook: looks the
    identity back up through the chain's authenticated results by treating
    identity as pre-authenticated (the HTTP layer authenticates; this
    authorizes)."""
    def check(auth: Optional[AuthenticationResult], query) -> bool:
        if auth is None:
            return False
        if isinstance(auth, str):
            # pre-chain callers pass a bare identity: authorize it under
            # the default authorizer
            auth = AuthenticationResult(auth, "allowAll")
        return chain.authorize_all(auth, resource_actions_for_query(query))
    return check
