"""Router: query forwarding to tiered brokers.

Reference analogs (server/src/main/java/org/apache/druid/server/):
  AsyncQueryForwardingServlet.java — the router process: parses just enough
    of the request (datasource, context) to pick a broker, then proxies the
    raw request/response
  router/TieredBrokerHostSelector.java + rule-based / priority / manual
    strategies — which broker tier serves a query: explicit
    context.brokerService wins, then priority thresholds, then the
    datasource's load rules mapped through tierToBrokerMap, else default
  router/AvaticaConnectionBalancer — (JDBC; out of scope)

In-process brokers (cluster.Broker) and remote broker base-URLs are both
valid targets; the HTTP front proxies to remote targets byte-for-byte.
"""
from __future__ import annotations

import itertools
import json
import logging
import threading
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Sequence, Tuple

from druid_tpu.utils.intervals import parse_period_ms


class TieredBrokerSelector:
    """Pick a broker tier for one query payload."""

    def __init__(self, tier_to_brokers: Dict[str, Sequence[object]],
                 default_tier: str,
                 rules: Optional[Dict[str, List[dict]]] = None,
                 min_priority: Optional[int] = None,
                 max_priority: Optional[int] = None,
                 priority_tier: Optional[str] = None):
        """tier_to_brokers: tier name → broker targets (round-robin within).
        rules: datasource → [{"periodMs"|"period":..., "tier": ...}] — a
        query whose FIRST interval starts within the period routes to that
        tier (the rule-based strategy over load rules).
        min/max_priority + priority_tier: queries with context.priority
        outside [min, max] route to priority_tier (PriorityTieredBroker
        SelectorStrategy pair)."""
        self.tiers = {t: list(bs) for t, bs in tier_to_brokers.items()}
        self.default_tier = default_tier
        self.rules = rules or {}
        self.min_priority = min_priority
        self.max_priority = max_priority
        self.priority_tier = priority_tier
        self._rr = {t: itertools.cycle(range(max(len(b), 1)))
                    for t, b in self.tiers.items()}
        self._lock = threading.Lock()

    def select_tier(self, payload: dict, now_ms: Optional[int] = None) -> str:
        ctx = payload.get("context") or {}
        # 1. manual: context.brokerService
        manual = ctx.get("brokerService")
        if manual in self.tiers:
            return manual
        # 2. priority thresholds
        if self.priority_tier is not None:
            try:
                pri = int(ctx.get("priority", 0))
            except (TypeError, ValueError):
                pri = 0
            if (self.min_priority is not None and pri < self.min_priority) \
                    or (self.max_priority is not None
                        and pri > self.max_priority):
                return self.priority_tier
        # 3. datasource rules (hot/cold tiering by interval recency)
        ds = payload.get("dataSource")
        if isinstance(ds, dict):
            ds = ds.get("name")
        for rule in self.rules.get(str(ds), ()):
            tier = rule.get("tier")
            if tier not in self.tiers:
                continue
            period = rule.get("periodMs", rule.get("period"))
            if period is None:
                return tier
            import time
            now = int(time.time() * 1000) if now_ms is None else now_ms
            horizon = now - parse_period_ms(period)
            for iv in payload.get("intervals") or ():
                try:
                    start = str(iv).split("/", 1)[0]
                    from druid_tpu.utils.intervals import parse_ts
                    if parse_ts(start) >= horizon:
                        return tier
                except (ValueError, TypeError):
                    continue
        return self.default_tier

    def pick(self, payload: dict, now_ms: Optional[int] = None,
             affinity_key: Optional[str] = None):
        """(tier, broker target) for one query payload. A selected tier
        with no brokers falls back to the default tier. affinity_key pins
        a key to ONE broker in the tier (Avatica connections are broker-
        local state — the AvaticaConnectionBalancer's job)."""
        tier = self.select_tier(payload, now_ms)
        if not self.tiers.get(tier):
            tier = self.default_tier
        brokers = self.tiers.get(tier)
        if not brokers:
            raise ValueError(f"no brokers in tier {tier!r}")
        if affinity_key is not None:
            import hashlib
            h = int(hashlib.md5(affinity_key.encode()).hexdigest()[:8], 16)
            return tier, brokers[h % len(brokers)]
        with self._lock:
            i = next(self._rr[tier]) % len(brokers)
        return tier, brokers[i]


class Router:
    """In-process router facade: run_json forwards to the selected broker
    (duck-typed: anything with run_json, or a base-URL string proxied over
    HTTP)."""

    def __init__(self, selector: TieredBrokerSelector):
        self.selector = selector

    def run_json(self, payload: dict):
        tier, target = self.selector.pick(payload)
        if isinstance(target, str):
            body = json.dumps(payload).encode()
            req = urllib.request.Request(
                target.rstrip("/") + "/druid/v2", data=body,
                headers={"Content-Type": "application/json"}, method="POST")
            with urllib.request.urlopen(req, timeout=300.0) as r:
                return json.loads(r.read())
        return target.run_json(payload)


class RouterHttpServer:
    """HTTP front that proxies /druid/v2 and /druid/v2/sql to the selected
    broker's HTTP endpoint (AsyncQueryForwardingServlet)."""

    def __init__(self, selector: TieredBrokerSelector,
                 host: str = "127.0.0.1", port: int = 0,
                 leader_clients=None):
        """leader_clients: optional {"coordinator"|"overlord":
        coordination.LeaderClient} — the router then also fronts the
        control plane: /druid/coordinator/* and /druid/indexer/* proxy to
        the CURRENT leader of that service (resolved from the lease row,
        re-resolved on failure), so clients keep one stable URL across
        failovers (AsyncQueryForwardingServlet does the same via its
        /proxy/coordinator paths)."""
        outer_selector = selector
        outer_leaders = leader_clients or {}

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def _leader_service(self):
                for prefix, svc in (("/druid/coordinator", "coordinator"),
                                    ("/druid/indexer", "overlord")):
                    if self.path.startswith(prefix + "/") \
                            and svc in outer_leaders:
                        return svc
                return None

            def _proxy_leader(self, svc: str) -> None:
                """Forward the raw request to the service's current
                leader; one same-request retry after invalidating the
                cached leader (it may have just been deposed)."""
                client = outer_leaders[svc]
                n = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(n) if n else None
                for attempt in (0, 1):
                    url = client.leader_url(use_cache=(attempt == 0))
                    if url is None:
                        continue
                    # credentials travel with the proxied request, same as
                    # the broker proxy path below
                    fwd = {"Content-Type": self.headers.get(
                        "Content-Type", "application/json")}
                    for h in ("Authorization", "X-Druid-Identity"):
                        if self.headers.get(h):
                            fwd[h] = self.headers[h]
                    req = urllib.request.Request(
                        url.rstrip("/") + self.path, data=raw,
                        headers=fwd, method=self.command)
                    try:
                        with urllib.request.urlopen(req, timeout=60.0) as r:
                            self._send(r.status, r.read())
                            return
                    except urllib.error.HTTPError as e:
                        self._send(e.code, e.read())
                        return
                    except Exception:
                        # re-resolve the leader and retry the next attempt
                        logging.getLogger(__name__).debug(
                            "control-plane proxy attempt for [%s] failed; "
                            "invalidating cached leader", svc, exc_info=True)
                        client.invalidate()
                self._send(503, json.dumps(
                    {"error": f"no reachable leader for [{svc}]"}).encode())

            def _proxy(self):
                n = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(n)
                try:
                    payload = json.loads(raw or b"{}")
                except ValueError:
                    payload = {}
                affinity = None
                if self.path.rstrip("/").endswith("/avatica"):
                    # Avatica connections are broker-local state: every
                    # request of one connection must land on one broker
                    affinity = payload.get("connectionId") or \
                        (payload.get("statementHandle") or {}).get(
                            "connectionId")
                try:
                    _, target = outer_selector.pick(
                        payload, affinity_key=affinity)
                except Exception as e:
                    self._send(500, json.dumps(
                        {"error": str(e)}).encode())
                    return
                url = str(target).rstrip("/") + self.path
                # credentials travel with the proxied request (the
                # reference servlet forwards headers; the broker behind the
                # router does its own authentication)
                fwd = {"Content-Type": self.headers.get(
                    "Content-Type", "application/json")}
                for h in ("Authorization", "X-Druid-Identity"):
                    if self.headers.get(h):
                        fwd[h] = self.headers[h]
                req = urllib.request.Request(url, data=raw, headers=fwd,
                                             method="POST")
                try:
                    with urllib.request.urlopen(req, timeout=300.0) as r:
                        self._send(r.status, r.read())
                except urllib.error.HTTPError as e:
                    self._send(e.code, e.read())
                except Exception as e:
                    self._send(502, json.dumps(
                        {"error": f"broker unreachable: {e}"}).encode())

            def _send(self, code: int, data: bytes):
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                try:
                    self.wfile.write(data)
                except (BrokenPipeError, ConnectionResetError):
                    pass

            def do_POST(self):
                svc = self._leader_service()
                if svc is not None:
                    self._proxy_leader(svc)
                elif self.path.rstrip("/") in ("/druid/v2", "/druid/v2/sql",
                                               "/druid/v2/sql/avatica"):
                    self._proxy()
                else:
                    self._send(404, b'{"error": "unknown path"}')

            def do_GET(self):
                svc = self._leader_service()
                if svc is not None:
                    self._proxy_leader(svc)
                elif self.path == "/status":
                    self._send(200, b'{"service": "router"}')
                else:
                    self._send(404, b'{"error": "unknown path"}')

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def start(self) -> "RouterHttpServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=5.0)
