"""Broker-side subscription fan-out over standing queries.

A million dashboards watching the same handful of queries must cost a
handful of device programs, not a million re-scans. The `SubscriptionHub`
dedupes structurally identical subscriptions (the existing query-structure
signature, cluster/cache.query_cache_key — the query minus context) onto
ONE `StandingQuery` (engine/standing.py) per structure, and fans results
out via long-poll:

  * subscribe(query) -> (subscription id, etag). N identical dashboards
    share one refcounted standing program; the Nth subscribe is a dict
    bump, not a compile.
  * poll(sub_id, etag, timeout_s): blocks the caller (the HTTP handler
    thread — ThreadingHTTPServer's per-connection threads ARE the fan-out
    pool) until the program's version moves past the presented etag or
    the timeout lapses — the long-poll twin of the server's existing
    If-None-Match machinery (server/http.py): an unchanged window is a
    304, a changed one ships rows + the new X-Druid-ETag.
  * unsubscribe (or a client that silently disconnected and stopped
    polling, swept after `idle_timeout_s`) decrements the refcount; the
    last reference tears the standing program down — listeners detach,
    folded state drops, waiters wake.

Ticking: `drive_with(scheduler)` hangs the hub's tick on the data-node
scheduler's flush loop (server/scheduler.py tick hooks — the natural tick
driver, PR 7); `start()` runs a dedicated daemon tick thread instead
(joined in stop()) for broker-only deployments.
"""
from __future__ import annotations

import logging
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from druid_tpu.engine.standing import StandingQuery
from druid_tpu.query.model import Query
from druid_tpu.server.deadline import Deadline
from druid_tpu.utils.emitter import Monitor

log = logging.getLogger(__name__)


class UnknownSubscriptionError(KeyError):
    """The subscription id is not (or no longer) registered — the client
    re-subscribes (its state may have been swept as idle)."""


class SubscriptionStats:
    """Counters behind subscription/{active,fanout,ticks}."""

    def __init__(self):
        self._lock = threading.Lock()
        self.ticks = 0
        self.fanout = 0
        self.subscribed = 0
        self.unsubscribed = 0

    def record_tick(self) -> None:
        with self._lock:
            self.ticks += 1

    def record_fanout(self) -> None:
        with self._lock:
            self.fanout += 1

    def record_subscribe(self) -> None:
        with self._lock:
            self.subscribed += 1

    def record_unsubscribe(self) -> None:
        with self._lock:
            self.unsubscribed += 1

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return {"ticks": self.ticks, "fanout": self.fanout,
                    "subscribed": self.subscribed,
                    "unsubscribed": self.unsubscribed}


@dataclass
class _Program:
    """One standing program + its subscriber refcount."""
    standing: StandingQuery
    refs: int = 0


@dataclass
class _Subscription:
    """One client's handle onto a shared program."""
    sub_id: str
    sig: str
    program: _Program
    last_poll: float = field(default_factory=time.monotonic)


class SubscriptionHub:
    """Refcounted dedupe of dashboard subscriptions onto standing
    programs, with long-poll fan-out (see module docstring)."""

    def __init__(self, emitter=None, idle_timeout_s: float = 300.0,
                 tick_period_s: float = 0.05):
        self.stats = SubscriptionStats()
        self.emitter = emitter
        self.idle_timeout_s = float(idle_timeout_s)
        self.tick_period_s = float(tick_period_s)
        self._cond = threading.Condition(threading.Lock())
        self._programs: Dict[str, _Program] = {}
        self._subs: Dict[str, _Subscription] = {}
        self._apps: List[object] = []
        self._scheduler = None
        self._thread: Optional[threading.Thread] = None
        self._stopping = False

    # ---- wiring --------------------------------------------------------
    def attach(self, appenderator) -> None:
        """Register a live datasource; existing programs on the same
        datasource start standing over it too."""
        with self._cond:
            self._apps.append(appenderator)
            progs = list(self._programs.values())
        for p in progs:
            if p.standing.query.datasource == appenderator.datasource:
                p.standing.attach(appenderator)

    def drive_with(self, scheduler) -> "SubscriptionHub":
        """Tick on the data-node scheduler's flush loop instead of an own
        thread (the PR 7 batching loop is the natural tick driver)."""
        with self._cond:
            self._scheduler = scheduler
        scheduler.add_tick_hook(self.tick)
        return self

    def start(self) -> "SubscriptionHub":
        with self._cond:
            self._stopping = False
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._tick_loop, daemon=True,
                    name="subscription-hub")
                self._thread.start()
        return self

    def stop(self) -> None:
        with self._cond:
            self._stopping = True
            sched, self._scheduler = self._scheduler, None
            t = self._thread
            self._cond.notify_all()
        if sched is not None:
            sched.remove_tick_hook(self.tick)
        if t is not None and t.is_alive() \
                and t is not threading.current_thread():
            t.join(timeout=5.0)
        # tear down every program: waiters wake, listeners detach
        with self._cond:
            subs = list(self._subs)
        for sid in subs:
            self.unsubscribe(sid)

    def _tick_loop(self) -> None:
        while True:
            with self._cond:
                if self._stopping:
                    return
            self.tick()
            with self._cond:
                if self._stopping:
                    return
                self._cond.wait(self.tick_period_s)

    # ---- subscription lifecycle ----------------------------------------
    def subscribe(self, query: Query) -> Tuple[str, str]:
        """Register one subscriber; returns (subscription id, etag of the
        program's current version). Structurally identical queries share
        one standing program — the dedupe key is the structure signature
        (query minus context) PLUS the resolved emission policy, since
        standingEmit lives in the context but changes what a program
        delivers (StandingIneligible propagates for shapes that cannot
        stand)."""
        from druid_tpu.cluster.cache import query_cache_key
        from druid_tpu.engine.standing import resolve_emit
        sig = f"{query_cache_key(query)}|emit={resolve_emit(query)}"
        while True:
            with self._cond:
                if self._stopping:
                    raise RuntimeError("subscription hub stopped")
                prog = self._programs.get(sig)
                apps = [a for a in self._apps
                        if a.datasource == query.datasource]
            if prog is None:
                # build OUTSIDE the lock (attaches listeners); a
                # concurrent duplicate build loses the insert race and is
                # closed below
                built = _Program(standing=StandingQuery(query, apps))
                missing = []
                with self._cond:
                    prog = self._programs.get(sig)
                    if prog is None:
                        # the program key is query structure by design;
                        # an attach() that races the build retro-wires
                        # through the missing re-check below, so the
                        # apps snapshot cannot alias a subscriber set
                        prog = self._programs[sig] = built  # druidlint: disable=unkeyed-trace-input
                        built = None
                        # an attach() that raced the build (retro-wiring
                        # ran before our insert) would leave this program
                        # permanently blind to that datasource — re-check
                        missing = [a for a in self._apps
                                   if a.datasource == query.datasource
                                   and a not in apps]
                if built is not None:
                    built.standing.close()
                for a in missing:
                    prog.standing.attach(a)
            sub_id = uuid.uuid4().hex
            with self._cond:
                # the program may have been torn down between the lookup
                # and here (last unsubscribe raced us): registering
                # against the closed, unmapped program would long-poll a
                # dead world forever — retry against the live registry
                if self._programs.get(sig) is not prog:
                    continue
                prog.refs += 1
                self._subs[sub_id] = _Subscription(sub_id=sub_id, sig=sig,
                                                   program=prog)
            self.stats.record_subscribe()
            return sub_id, prog.standing.etag()

    def unsubscribe(self, sub_id: str) -> bool:
        """Drop one subscriber; the last reference tears the standing
        program down. Returns whether the id was registered."""
        with self._cond:
            sub = self._subs.pop(sub_id, None)
            if sub is None:
                return False
            sub.program.refs -= 1
            dead = None
            if sub.program.refs <= 0 \
                    and self._programs.get(sub.sig) is sub.program:
                dead = self._programs.pop(sub.sig)
            self._cond.notify_all()       # wake this client's poll waiters
        if dead is not None:
            dead.standing.close()
        self.stats.record_unsubscribe()
        return True

    def active_subscriptions(self) -> int:
        with self._cond:
            return len(self._subs)

    def active_programs(self) -> int:
        with self._cond:
            return len(self._programs)

    #: server-side ceiling on one long-poll hold: a client-supplied
    #: timeout (timeoutMs=inf, or merely huge) must never park a handler
    #: thread indefinitely — the parked poll refreshes the idle clock, so
    #: an unbounded hold would also defeat the idle sweep forever
    MAX_POLL_TIMEOUT_S = 60.0

    # ---- fan-out -------------------------------------------------------
    def poll(self, sub_id: str, etag: Optional[str] = None,
             timeout_s: float = 0.0):
        """Long-poll one subscription. Returns (rows, etag, changed):
        changed=False (rows None) when the program's version still matches
        the presented etag after `timeout_s` (clamped to
        MAX_POLL_TIMEOUT_S — clients re-poll) — the 304 path. Touches the
        subscription's idle clock."""
        timeout_s = float(timeout_s)
        if not (timeout_s > 0):             # NaN/negative -> immediate
            timeout_s = 0.0
        timeout_s = min(timeout_s, self.MAX_POLL_TIMEOUT_S)
        deadline = Deadline.after_s(timeout_s)
        while True:
            with self._cond:
                sub = self._subs.get(sub_id)
                if sub is None:
                    raise UnknownSubscriptionError(sub_id)
                sub.last_poll = time.monotonic()
                prog = sub.program
                current = prog.standing.etag()
                if etag is not None and current == etag:
                    if deadline.expired():
                        return None, current, False
                    self._cond.wait(deadline.clamp(0.25))
                    continue
            # changed (or unconditional): the merge runs OUTSIDE the hub
            # lock; rows/etag are read as one consistent snapshot
            snap = prog.standing.snapshot()
            self.stats.record_fanout()
            return snap.rows, snap.etag, True

    # ---- the tick ------------------------------------------------------
    def tick(self) -> int:
        """Advance every standing program one tick and wake waiters whose
        program emitted; sweeps idle subscriptions. Returns the number of
        programs that emitted."""
        with self._cond:
            if self._stopping:
                return 0
            progs = list(self._programs.values())
        emitted = 0
        for p in progs:
            try:
                if p.standing.tick() is not None:
                    emitted += 1
            except Exception:
                log.exception("standing tick failed")
        if emitted:
            with self._cond:
                self._cond.notify_all()
        self._sweep_idle()
        self.stats.record_tick()
        return emitted

    def _sweep_idle(self) -> None:
        """Tear down subscriptions whose client stopped polling (silent
        disconnects must not pin standing programs forever)."""
        if self.idle_timeout_s <= 0:
            return
        cutoff = time.monotonic() - self.idle_timeout_s
        with self._cond:
            idle = [s.sub_id for s in self._subs.values()
                    if s.last_poll < cutoff]
        for sid in idle:
            self.unsubscribe(sid)


class SubscriptionMetricsMonitor(Monitor):
    """subscription/active gauge + per-tick fanout/ticks deltas."""

    def __init__(self, hub: SubscriptionHub):
        self.hub = hub
        self._last = hub.stats.snapshot()

    def do_monitor(self, emitter):
        s = self.hub.stats.snapshot()
        last, self._last = self._last, s
        emitter.metric("subscription/active",
                       self.hub.active_subscriptions())
        emitter.metric("subscription/fanout", s["fanout"] - last["fanout"])
        emitter.metric("subscription/ticks", s["ticks"] - last["ticks"])
