"""HTTP query endpoints.

Reference analogs:
  server/QueryResource.java:77,126,153-156 — POST /druid/v2/ (native JSON),
    DELETE /druid/v2/{id} cancel, datasource listing
  sql/.../http/SqlResource.java:58,75-78 — POST /druid/v2/sql
  /status — the common status endpoint every node serves

stdlib ThreadingHTTPServer stands in for Jetty; the wire format (JSON
payloads/results) matches the reference so existing Druid HTTP clients map
1:1. Streaming chunked responses collapse to one JSON body — results are
materialized host-side anyway after device execution.
"""
from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from druid_tpu.server.lifecycle import QueryLifecycle, Unauthorized
from druid_tpu.server.querymanager import (QueryCapacityError,
                                           QueryInterruptedError,
                                           QueryTimeoutError)


def _json_value(obj):
    """Render extension values (sketches, histograms, bloom filters) the way
    the reference serializes complex agg results: structured JSON where the
    type defines one (histogram), base64 where it's opaque bits (bloom),
    estimates for sketches."""
    if hasattr(obj, "serialize"):
        return obj.serialize()
    if hasattr(obj, "to_json"):
        return obj.to_json()
    if hasattr(obj, "estimate"):
        return obj.estimate
    import numpy as np
    if isinstance(obj, np.generic):
        return obj.item()
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    raise TypeError(f"not JSON serializable: {type(obj).__name__}")


class QueryHttpServer:
    """Serves a QueryLifecycle (+ optional SqlExecutor) over HTTP."""

    def __init__(self, lifecycle: QueryLifecycle, sql_executor=None,
                 host: str = "127.0.0.1", port: int = 0,
                 auth_chain=None, coordination=None, overlord=None,
                 monitor_period_seconds: float = 60.0,
                 subscription_hub=None):
        """auth_chain: optional server.security.AuthChain — requests
        authenticate at the HTTP boundary (401 on failure) and the
        resulting AuthenticationResult flows into the lifecycle, whose
        authorizer makes the per-datasource decision (403).

        Observability: a MetricRegistry always backs GET /metrics (the
        lifecycle emitter's sink is composed with it, or a registry-only
        ServiceEmitter is created), GET /druid/v2/trace/<queryId> serves
        the assembled qtrace trace, and a QueryCountStatsMonitor is wired
        into the lifecycle's on_result hook (chained with any existing
        hook) so query success/failure counts emit per monitor tick.

        coordination: optional {"coordinator"|"overlord":
        LeaderParticipant} — adds the leader discovery endpoints
        (/druid/coordinator/v1/leader, .../isLeader and the indexer
        equivalents) and the DruidLeaderClient redirect contract: any
        other coordinator/overlord API request on a NON-leader answers
        307 with Location on the current leader (503 while no leader is
        live). overlord: the local Overlord — leader-only task submission
        (POST /druid/indexer/v1/task) and status reads serve from it.

        subscription_hub: optional server.subscriptions.SubscriptionHub —
        adds the standing-query subscription surface (POST/GET/DELETE
        /druid/v2/subscriptions[/<id>]): long-poll fan-out composing with
        the same ETag/If-None-Match contract the one-shot query path
        speaks, so an unchanged window is a 304."""
        self.lifecycle = lifecycle
        self.subscription_hub = subscription_hub
        self.sql_executor = sql_executor
        self.auth_chain = auth_chain
        self.coordination = coordination or {}
        self.overlord = overlord
        # one lease-liveness reader per hosted service — the SAME
        # expiry/None semantics clients use (no duplicated logic here)
        self._leader_clients = {}
        if self.coordination:
            from druid_tpu.coordination.discovery import LeaderClient
            self._leader_clients = {
                svc: LeaderClient(p.store, p.service, clock=p.clock)
                for svc, p in self.coordination.items()}
        self.avatica = None
        if sql_executor is not None:
            from druid_tpu.server.avatica import AvaticaServer
            self.avatica = AvaticaServer(sql_executor)

        # ---- observability: /metrics registry + query-count monitor ----
        from druid_tpu.obs.prometheus import MetricRegistry, compose_sink
        from druid_tpu.utils.emitter import (MonitorScheduler,
                                             QueryCountStatsMonitor,
                                             ServiceEmitter)
        self.registry = MetricRegistry()
        # the sink rewrap + on_result chain below mutate the caller-owned
        # lifecycle IN PLACE; stop() undoes both (guarded by identity) so
        # a lifecycle reused across server generations doesn't accumulate
        # dead registries and double-counting monitors
        self._restore_sink = lambda: None
        if lifecycle.emitter is not None:
            self._restore_sink = compose_sink(lifecycle.emitter,
                                              self.registry)
            scrape_emitter = lifecycle.emitter
        else:
            scrape_emitter = ServiceEmitter("druid/broker", host,
                                            self.registry)
        self.query_counts = QueryCountStatsMonitor()
        self._prev_on_result = prev_on_result = lifecycle.on_result
        if prev_on_result is None:
            lifecycle.on_result = self.query_counts.on_query
        else:
            def _chained(ok, _prev=prev_on_result,
                         _qc=self.query_counts):
                _prev(ok)
                _qc.on_query(ok)
            lifecycle.on_result = _chained
        self._installed_on_result = lifecycle.on_result
        monitors = [self.query_counts]
        if subscription_hub is not None:
            from druid_tpu.engine.standing import StandingMetricsMonitor
            from druid_tpu.server.subscriptions import \
                SubscriptionMetricsMonitor
            monitors.append(SubscriptionMetricsMonitor(subscription_hub))
            monitors.append(StandingMetricsMonitor())
        resilience = getattr(lifecycle.runner, "resilience", None)
        if resilience is not None:
            # broker-backed lifecycles surface the fault-tolerance layer
            # (broker/circuit/*, query/hedge/*, query/partial/*)
            from druid_tpu.cluster.resilience import \
                ResilienceMetricsMonitor
            monitors.append(ResilienceMetricsMonitor(resilience))
        self._monitors = MonitorScheduler(
            scrape_emitter, monitors,
            period_seconds=monitor_period_seconds)
        outer = self

        class Handler(BaseHTTPRequestHandler):
            # chunked streaming requires 1.1; every non-streaming reply
            # sends Content-Length so keep-alive works unchanged
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):   # quiet
                pass

            def _reply(self, code: int, body: dict | list,
                       extra_headers: dict | None = None):
                data = json.dumps(body, default=_json_value).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                for k, v in (extra_headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(data)

            def _body(self) -> dict:
                n = int(self.headers.get("Content-Length", 0))
                return json.loads(self.rfile.read(n) or b"{}")

            def _authenticated(self) -> bool:
                """Non-POST paths also sit behind the chain (the reference
                wraps EVERY resource in the auth filter); /status stays
                open for load-balancer health checks."""
                if outer.auth_chain is None:
                    return True
                if outer.auth_chain.authenticate(dict(self.headers)) is None:
                    self._reply(401, {"error": "unauthenticated"})
                    return False
                return True

            # ---- coordination (leader discovery + redirect) ------------
            def _leader_lease(self, service: str):
                """The current UNEXPIRED lease, or None (mid-election /
                store unreachable) — read through the same LeaderClient
                semantics redirecting clients use."""
                return outer._leader_clients[service].leader()

            def _redirect_to_leader(self, service: str) -> None:
                """307 on the live leader (DruidLeaderClient contract);
                503 while no leader is live — clients retry, they never
                get a non-leader's answer."""
                lease = self._leader_lease(service)
                if lease is None or not lease.url:
                    self._reply(503, {"error": "no live leader for "
                                      f"[{service}]"})
                    return
                self.send_response(307)
                self.send_header("Location",
                                 lease.url.rstrip("/") + self.path)
                self.send_header("Content-Length", "0")
                self.end_headers()

            def _handle_coordination(self, service: str, payload) -> None:
                """One coordinator/overlord API request (payload None for
                GET). Leader/isLeader serve everywhere; everything else
                redirects off non-leaders."""
                p = outer.coordination[service]
                prefix = ("/druid/coordinator/v1" if service == "coordinator"
                          else "/druid/indexer/v1")
                sub = self.path.rstrip("/")[len(prefix):]
                if sub == "/leader":
                    lease = self._leader_lease(service)
                    if lease is None:
                        self._reply(503, {"error": "no live leader for "
                                          f"[{p.service}]"})
                    else:
                        self._reply(200, {"leader": lease.url,
                                          "term": lease.term,
                                          "holder": lease.holder})
                    return
                if sub == "/isLeader":
                    # Druid's semantics: 200 on the leader, 404 elsewhere
                    code = 200 if p.is_leader() else 404
                    self._reply(code, {"leader": p.is_leader()})
                    return
                if not p.is_leader():
                    self._redirect_to_leader(service)
                    return
                if service == "overlord" and outer.overlord is not None:
                    from druid_tpu.coordination.latch import NotLeaderError
                    if sub == "/task" and payload is not None:
                        from druid_tpu.indexing.task import task_from_json
                        try:
                            tid = outer.overlord.submit(
                                task_from_json(payload))
                        except NotLeaderError:
                            # deposed between is_leader() and submit()
                            self._redirect_to_leader(service)
                            return
                        self._reply(200, {"task": tid})
                        return
                    if sub.startswith("/task/") and sub.endswith("/status") \
                            and payload is None:
                        tid = sub[len("/task/"):-len("/status")]
                        st = outer.overlord.status(tid)
                        if st is None:
                            self._reply(404,
                                        {"error": f"unknown task {tid!r}"})
                        else:
                            self._reply(200, {"task": tid,
                                              "status": st.state})
                        return
                self._reply(404, {"error": "unknown path", "leader": True,
                                  "term": p.term, "node": p.node_id})

            def do_GET(self):
                if self.path == "/status":
                    self._reply(200, {"version": "druid-tpu-0.1",
                                      "modules": []})
                elif self.path.rstrip("/") == "/metrics":
                    # scrape surface: open like /status (Prometheus
                    # scrapers do not carry Druid credentials)
                    from druid_tpu.obs.prometheus import \
                        CONTENT_TYPE as PROM_CTYPE
                    data = outer.registry.exposition().encode()
                    self.send_response(200)
                    self.send_header("Content-Type", PROM_CTYPE)
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    self.wfile.write(data)
                elif self.path.startswith("/druid/v2/trace/"):
                    if self._authenticated():
                        import urllib.parse
                        from druid_tpu.obs.trace import trace_store
                        qid = urllib.parse.unquote(
                            self.path[len("/druid/v2/trace/"):].rstrip("/"))
                        got = trace_store().get(qid)
                        if got is None:
                            self._reply(404, {"error": "unknown trace",
                                              "queryId": qid})
                        else:
                            self._reply(200, got)
                elif self.path.startswith("/druid/v2/subscriptions/"):
                    # long-poll fan-out: the handler thread parks in the
                    # hub until the standing program's version moves past
                    # the presented If-None-Match etag (or the timeout
                    # lapses → 304, the unchanged-window contract)
                    if outer.subscription_hub is None:
                        self._reply(404, {"error": "subscriptions not "
                                          "enabled"})
                    elif self._authenticated():
                        self._poll_subscription()
                elif self.path in ("/druid/v2/datasources",
                                   "/druid/v2/datasources/"):
                    if self._authenticated():
                        self._reply(200, outer._datasources())
                elif outer._coord_service(self.path) is not None:
                    if self._authenticated():
                        try:
                            self._handle_coordination(
                                outer._coord_service(self.path), None)
                        except Exception as e:
                            self._reply(500,
                                        {"error": f"{type(e).__name__}: {e}"})
                else:
                    self._reply(404, {"error": "unknown path"})

            def do_POST(self):
                try:
                    # read the body BEFORE any early reply: on a keep-alive
                    # (HTTP/1.1) connection an unread body would be parsed
                    # as the next request line, desyncing the stream
                    payload = self._body()
                    identity = self.headers.get("X-Druid-Identity")
                    if outer.auth_chain is not None:
                        auth = outer.auth_chain.authenticate(
                            dict(self.headers))
                        if auth is None:
                            self._reply(401, {"error": "unauthenticated"})
                            return
                        identity = auth
                    svc = outer._coord_service(self.path)
                    if svc is not None:
                        self._handle_coordination(svc, payload)
                        return
                    if self.path.rstrip("/") == "/druid/v2/subscriptions":
                        if outer.subscription_hub is None:
                            self._reply(404, {"error": "subscriptions not "
                                              "enabled"})
                        else:
                            self._subscribe(payload, identity)
                        return
                    if self.path.rstrip("/") == "/druid/v2/sql/avatica":
                        if outer.avatica is None:
                            self._reply(404, {"error": "SQL not enabled"})
                            return
                        authorize = None
                        if outer.auth_chain is not None:
                            def authorize(stmt, params=(), _id=identity):
                                return outer._authorize_sql(_id, stmt,
                                                            params)
                        self._reply(200, outer.avatica.handle(
                            payload, authorize, identity=identity))
                        return
                    if self.path.rstrip("/") == "/druid/v2/sql":
                        if outer.sql_executor is None:
                            self._reply(404, {"error": "SQL not enabled"})
                            return
                        if outer.auth_chain is not None and not \
                                outer._authorize_sql(
                                    identity, payload["query"],
                                    payload.get("parameters") or ()):
                            self._reply(403, {"error": "unauthorized"})
                            return
                        cols, rows = outer.sql_executor.execute(
                            payload["query"],
                            payload.get("parameters") or (),
                            payload.get("context") or None)
                        # SQL surface of the partial-result contract:
                        # the shaped rows stay typed through the executor
                        missing = getattr(rows, "missing_segments", None)
                        headers = None if missing is None else {
                            "X-Druid-Response-Context": json.dumps(
                                {"partial": True,
                                 "missingSegments": missing})}
                        fmt = payload.get("resultFormat", "object")
                        if fmt == "array":
                            self._reply(200, list(rows), headers)
                        else:
                            self._reply(200, [dict(zip(cols, r))
                                              for r in rows], headers)
                    elif self.path.rstrip("/") == "/druid/v2":
                        if payload.get("queryType") == "scan" and \
                                "application/x-ndjson" in (
                                    self.headers.get("Accept") or ""):
                            self._stream_scan(payload, identity)
                            return
                        # ETag over the (query, exact segment set) identity
                        # (QueryResource's If-None-Match / X-Druid-ETag).
                        # Parsed ONCE; lifecycle.etag authorizes before any
                        # 304 so a match never leaks forbidden data's state
                        from druid_tpu.query.model import query_from_json
                        try:
                            query = query_from_json(payload)
                        except (ValueError, KeyError, TypeError):
                            # malformed queries count as failures, like
                            # run_json's resource-layer accounting
                            if outer.lifecycle.on_result:
                                outer.lifecycle.on_result(False)
                            raise
                        etag = outer.lifecycle.etag(query,
                                                    identity=identity)
                        if etag is not None and \
                                self.headers.get("If-None-Match") == etag:
                            outer.lifecycle.log_conditional_hit(query, etag)
                            self.send_response(304)
                            self.send_header("X-Druid-ETag", etag)
                            self.send_header("Content-Length", "0")
                            self.end_headers()
                            return
                        rows = outer.lifecycle.run(query,
                                                   identity=identity)
                        headers = {}
                        # a degraded result (allowPartialResults) stamps
                        # its missing-segments report on the response
                        # context header — the contract is EXPLICIT,
                        # exactly once, never a silent hole in the rows.
                        # It must NOT carry the ETag: the etag names the
                        # COMPLETE result over this segment set, and a
                        # client caching the partial body against it
                        # would be confirmed 304-fresh forever after the
                        # cluster heals — the conditional-request twin of
                        # 'partials never populate the result cache'
                        missing = getattr(rows, "missing_segments", None)
                        if missing is not None:
                            headers["X-Druid-Response-Context"] = \
                                json.dumps({"partial": True,
                                            "missingSegments": missing})
                        elif etag:
                            headers["X-Druid-ETag"] = etag
                        self._reply(200, rows, headers or None)
                    else:
                        self._reply(404, {"error": "unknown path"})
                except Unauthorized as e:
                    self._reply(403, {"error": str(e)})
                except QueryTimeoutError as e:
                    self._reply(504, {"error": "Query timed out",
                                      "errorMessage": str(e)})
                except QueryCapacityError as e:
                    # a saturated data tier shed the query (scheduler
                    # admission): surface the same 429 + Retry-After
                    # contract to the original client
                    self._reply(429, {"error": "Query capacity exceeded",
                                      "errorMessage": str(e)},
                                {"Retry-After": e.retry_after_header()})
                except QueryInterruptedError as e:
                    self._reply(500, {"error": "Query cancelled",
                                      "errorMessage": str(e)})
                except (ValueError, KeyError) as e:
                    # bad query = client error (QueryResource's
                    # BadJsonQueryException handling)
                    self._reply(400, {"error": f"{type(e).__name__}: {e}"})
                except Exception as e:
                    self._reply(500, {"error": f"{type(e).__name__}: {e}"})

            def _stream_scan(self, payload: dict, identity) -> None:
                """Chunked NDJSON scan results: one batch per line, written
                as the engine produces it — rows reach the client before
                the scan finishes (the Sequence-streaming surface of
                QueryResource). A failure after the first chunk can only
                truncate: the missing terminal chunk tells the client."""
                from druid_tpu.query.model import query_from_json
                try:
                    query = query_from_json(payload)
                except (ValueError, KeyError, TypeError):
                    # malformed queries count as failures here too, like
                    # run_json's resource-layer accounting
                    if outer.lifecycle.on_result:
                        outer.lifecycle.on_result(False)
                    raise
                gen = outer.lifecycle.run_streaming(query,
                                                    identity=identity)
                # pull the first batch BEFORE sending headers so pre-stream
                # failures (auth, planning) take the normal error path
                first = next(gen, None)
                self.send_response(200)
                self.send_header("Content-Type", "application/x-ndjson")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()

                def chunk(b: dict) -> None:
                    line = json.dumps(
                        b, default=_json_value).encode() + b"\n"
                    self.wfile.write(f"{len(line):X}\r\n".encode()
                                     + line + b"\r\n")

                try:
                    if first is not None:
                        chunk(first)
                    for batch in gen:
                        chunk(batch)
                    self.wfile.write(b"0\r\n\r\n")
                except Exception:
                    # client gone: close the generator NOW so the
                    # lifecycle's abandoned-stream accounting fires
                    # deterministically, then drop the connection (the
                    # missing terminal chunk marks truncation)
                    logging.getLogger(__name__).debug(
                        "result stream aborted mid-flight", exc_info=True)
                    gen.close()
                    self.close_connection = True

            # ---- standing-query subscriptions (server/subscriptions.py)
            def _poll_subscription(self) -> None:
                import urllib.parse
                from druid_tpu.server.subscriptions import \
                    UnknownSubscriptionError
                parsed = urllib.parse.urlparse(self.path)
                sub_id = parsed.path[len("/druid/v2/subscriptions/"):] \
                    .rstrip("/")
                params = urllib.parse.parse_qs(parsed.query)
                try:
                    timeout_s = float(params.get("timeoutMs",
                                                 ["0"])[0]) / 1000.0
                except ValueError:
                    timeout_s = 0.0
                etag = self.headers.get("If-None-Match")
                try:
                    rows, new_etag, changed = outer.subscription_hub.poll(
                        sub_id, etag=etag, timeout_s=timeout_s)
                except UnknownSubscriptionError:
                    # swept as idle or never registered: the client
                    # re-subscribes
                    self._reply(404, {"error": "unknown subscription",
                                      "subscriptionId": sub_id})
                    return
                if not changed:
                    self.send_response(304)
                    self.send_header("X-Druid-ETag", new_etag)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                self._reply(200, rows, {"X-Druid-ETag": new_etag})

            def _subscribe(self, payload, identity) -> None:
                """POST /druid/v2/subscriptions: body = a native aggregate
                query; authorizes (with the identity do_POST already
                authenticated) exactly like a one-shot run of it."""
                from druid_tpu.engine.standing import StandingIneligible
                from druid_tpu.query.model import query_from_json
                query = query_from_json(payload)
                authorizer = getattr(outer.lifecycle, "authorizer", None)
                if authorizer is not None \
                        and not authorizer(identity, query):
                    self._reply(403, {"error": "unauthorized"})
                    return
                try:
                    sub_id, etag = outer.subscription_hub.subscribe(query)
                except StandingIneligible as e:
                    self._reply(400, {"error": f"StandingIneligible: {e}"})
                    return
                self._reply(200, {"subscriptionId": sub_id, "etag": etag},
                            {"X-Druid-ETag": etag})

            def do_DELETE(self):
                # DELETE /druid/v2/{id} — QueryResource.cancelQuery:
                # 202 accepted whether or not the id was in flight
                from druid_tpu.server.querymanager import cancel_path_id
                if not self._authenticated():
                    return
                if self.path.startswith("/druid/v2/subscriptions/"):
                    if outer.subscription_hub is None:
                        self._reply(404, {"error": "subscriptions not "
                                          "enabled"})
                        return
                    sub_id = self.path[
                        len("/druid/v2/subscriptions/"):].rstrip("/")
                    found = outer.subscription_hub.unsubscribe(sub_id)
                    self._reply(202, {"subscriptionId": sub_id,
                                      "active": bool(found)})
                    return
                qid = cancel_path_id(self.path)
                if qid is not None:
                    found = outer.lifecycle.cancel(qid)
                    self._reply(202, {"queryId": qid,
                                      "inFlight": bool(found)})
                else:
                    self._reply(404, {"error": "unknown path"})

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def _coord_service(self, path: str) -> Optional[str]:
        """Which coordination service a path addresses (None when it is
        not a coordination path or that service is not hosted here)."""
        for prefix, svc in (("/druid/coordinator/v1", "coordinator"),
                            ("/druid/indexer/v1", "overlord")):
            if (path == prefix or path.startswith(prefix + "/")) \
                    and svc in self.coordination:
                return svc
        return None

    def _datasources(self):
        r = self.lifecycle.runner
        return list(getattr(r, "datasources", []) or [])

    def _authorize_sql(self, identity, statement: str,
                       parameters=()) -> bool:
        """Per-table READ authorization for a SQL statement — shared by
        the plain SQL resource and the Avatica endpoint (SqlResource's
        resource-action collection)."""
        from druid_tpu.server.security import (READ, Resource,
                                               ResourceAction)
        tables, is_meta = self.sql_executor.tables_of(statement, parameters)
        # INFORMATION_SCHEMA itself needs no table grant, but a statement
        # mixing it with real tables (UNION ALL arm, IN-subquery) must still
        # pass the real tables' READ checks — is_meta alone is not a bypass
        if is_meta and not tables:
            return True
        return self.auth_chain.authorize_all(
            identity, [ResourceAction(Resource(t), READ) for t in tables])

    def metrics_tick(self) -> None:
        """Drive the query-count monitor once (tests; the scheduler drives
        it periodically after start())."""
        self._monitors.tick()

    def start(self):
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        self._monitors.start()
        return self

    def stop(self):
        self._monitors.stop()
        # un-chain what __init__ installed on the shared lifecycle — only
        # if still ours (a later server generation may have re-chained)
        if self.lifecycle.on_result is self._installed_on_result:
            self.lifecycle.on_result = self._prev_on_result
        self._restore_sink()
        self._httpd.shutdown()
        self._httpd.server_close()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=5.0)
