"""Data-node query scheduler: cross-query batched execution behind
admission control.

Production traffic is thousands of small concurrent queries hammering the
same hot datasource — and each one used to pay its own device dispatch even
when its program was identical to its neighbor's. This module is the
batching/admission/fallback triad of the Tailwind query-accelerator design
(PAPERS.md) at the data node:

  * BATCHING — arriving queries are held for a short window
    (`batch_window_ms`, a few ms) and flushed as ONE group through
    DataNode.run_partials_group, where plan-compatible segment work fuses
    across queries into shared device dispatches
    (engine/batching.run_multi_with_batching). While a flush executes,
    new arrivals accumulate — the batch size self-tunes to the service
    rate, the window only pays off when the node is idle.
  * ADMISSION — a bounded queue (`max_queue_depth`) with priority lanes:
    context `lane` (or derived from context `priority`: < 0 means
    "background") caps how much of the queue background work may occupy
    (`lane_depths`), so a background flood sheds background queries while
    interactive admission — and hence interactive p99 — stays bounded.
    Per-query cost (segment row counts) feeds an EWMA service rate; a
    query whose context deadline the queue provably cannot meet is shed
    immediately rather than timed out late.
  * FALLBACK — shedding raises QueryCapacityError (HTTP 429 + Retry-After
    at DataNodeServer); mesh/cached/row work routes through the normal
    per-query path inside the same flush, so nothing changes semantics.

Observability: the request thread wraps its hold in a `queue/wait` qtrace
span (nested under the per-request `datanode/query` root) and emits
`query/queue/wait` directly — metrics flow even for {"trace": false}
queries. The dispatcher attaches the flush leader's span so engine
dispatch/compile spans land in a real request trace. SchedulerMetricsMonitor
emits `query/queue/depth`, `query/shed/count`, and per-fused-dispatch
`query/crossBatch/{queries,segments,fillRatio}` (declared in obs/catalog.py,
enforced by the druidlint metric-name rule).
"""
from __future__ import annotations

import collections
import logging
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from druid_tpu.obs import trace as qtrace
from druid_tpu.server.querymanager import (Deadline, QueryCapacityError,
                                           context_priority,
                                           context_timeout_ms)
from druid_tpu.utils.emitter import Monitor

log = logging.getLogger(__name__)

#: lane assigned when the context names none and priority >= 0
INTERACTIVE_LANE = "interactive"
#: the low-priority lane (context {"lane": "background"} or priority < 0)
BACKGROUND_LANE = "background"


def lane_of(query) -> str:
    """The query's priority lane: explicit context `lane`, else derived
    from context `priority` (< 0 = background, the reference's HiLo laning
    convention)."""
    lane = query.context_map.get("lane")
    if lane:
        return str(lane)
    return BACKGROUND_LANE if context_priority(query) < 0 \
        else INTERACTIVE_LANE


@dataclass
class SchedulerConfig:
    """Admission/batching knobs (see README 'Cross-query batching &
    admission control')."""
    #: how long the dispatcher holds the first arrival for batch-mates
    batch_window_ms: float = 3.0
    #: bounded queue: arrivals beyond this depth shed with 429
    max_queue_depth: int = 64
    #: per-lane queue-depth caps; None derives {background: depth // 4} so
    #: a background flood can never occupy the whole queue
    lane_depths: Optional[Dict[str, int]] = None
    #: at most this many queries per flush group
    max_batch_queries: int = 64
    #: Retry-After seconds when no service-rate estimate exists yet
    retry_after_s: float = 1.0
    #: shed queries whose context deadline the queue provably cannot meet
    shed_on_deadline: bool = True

    def effective_lane_depths(self) -> Dict[str, int]:
        if self.lane_depths is not None:
            return dict(self.lane_depths)
        return {BACKGROUND_LANE: max(1, self.max_queue_depth // 4)}


class SchedulerStats:
    """Counters + bounded per-dispatch event queue the monitor drains
    (the BatchStats discipline)."""

    EVENT_CAP = 4096

    def __init__(self):
        self._lock = threading.Lock()
        self.submitted = 0
        self.shed = 0
        self.executed = 0
        self.flushes = 0
        self.cross_batches = 0
        self._shed_since_drain = 0
        self.dropped_events = 0
        self._events: "collections.deque[Tuple[int, int, float]]" = \
            collections.deque(maxlen=self.EVENT_CAP)

    def record_submit(self) -> None:
        with self._lock:
            self.submitted += 1

    def record_shed(self) -> None:
        with self._lock:
            self.shed += 1
            self._shed_since_drain += 1

    def record_flush(self, n_items: int) -> None:
        with self._lock:
            self.flushes += 1
            self.executed += n_items

    def record_cross_batch(self, n_queries: int, n_segments: int,
                           fill: float) -> None:
        """on_batch hook: one event per fused device dispatch."""
        with self._lock:
            if n_queries > 1:
                self.cross_batches += 1
            if len(self._events) == self.EVENT_CAP:
                self.dropped_events += 1
            self._events.append((n_queries, n_segments, fill))

    def drain_events(self):
        """Returns (events, shed-since-last-drain, dropped-since-drain)."""
        with self._lock:
            out = list(self._events)
            self._events.clear()
            shed, self._shed_since_drain = self._shed_since_drain, 0
            dropped, self.dropped_events = self.dropped_events, 0
            return out, shed, dropped

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return {"submitted": self.submitted, "shed": self.shed,
                    "executed": self.executed, "flushes": self.flushes,
                    "crossBatches": self.cross_batches}


class SchedulerMetricsMonitor(Monitor):
    """query/queue/depth gauge + query/shed/count delta + one
    query/crossBatch/{queries,segments,fillRatio} triple per fused
    dispatch recorded since the last tick."""

    def __init__(self, scheduler: "DataNodeScheduler"):
        self.scheduler = scheduler

    def do_monitor(self, emitter):
        emitter.metric("query/queue/depth", self.scheduler.depth())
        events, shed, dropped = self.scheduler.stats.drain_events()
        emitter.metric("query/shed/count", shed)
        for n_queries, n_segments, fill in events:
            emitter.metric("query/crossBatch/queries", n_queries)
            emitter.metric("query/crossBatch/segments", n_segments)
            emitter.metric("query/crossBatch/fillRatio", fill)
        if dropped:
            # no silent caps: >EVENT_CAP dispatches between ticks means
            # the crossBatch series above undercounts — say by how much
            emitter.metric("query/crossBatch/droppedEvents", dropped)


class _Item:
    """One queued query. `result`/`error` are written by the dispatcher and
    read by the submitting thread, both under the scheduler lock; `done`
    orders the handoff."""

    __slots__ = ("query", "segment_ids", "check", "lane", "priority",
                 "cost_rows", "seq", "enq_t", "started", "done", "result",
                 "error", "abandoned", "parent_span")

    def __init__(self, query, segment_ids, check, lane, priority,
                 cost_rows, seq):
        self.query = query
        self.segment_ids = list(segment_ids)
        self.check = check
        self.lane = lane
        self.priority = priority
        self.cost_rows = cost_rows
        self.seq = seq
        self.enq_t = time.monotonic()
        self.started = threading.Event()   # left the queue, flush running
        self.done = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None
        self.abandoned = False
        self.parent_span = qtrace.current_span()


class DataNodeScheduler:
    """The admission-controlled batching scheduler fronting one DataNode's
    aggregate-partials path. submit() blocks the (HTTP handler) request
    thread until its query's flush completes; a dedicated dispatcher
    thread drains the queue in priority order and executes each group via
    DataNode.run_partials_group."""

    def __init__(self, node, config: Optional[SchedulerConfig] = None,
                 emitter=None):
        self.node = node
        self.config = config or SchedulerConfig()
        self.emitter = emitter
        self.stats = SchedulerStats()
        self._lane_depths = self.config.effective_lane_depths()
        self._cond = threading.Condition(threading.Lock())
        self._queue: List[_Item] = []
        self._seq = 0
        self._stopping = False
        self._thread: Optional[threading.Thread] = None
        #: EWMA service rate (rows/s) measured over completed flushes;
        #: None until the first flush lands
        self._rate_rows_per_s: Optional[float] = None
        #: tick hooks the flush loop drives between flushes (standing-query
        #: / subscription-hub ticks — server/subscriptions.py); fired
        #: OUTSIDE the lock, exception-isolated
        self._tick_hooks: List = []

    # ---- lifecycle -----------------------------------------------------
    def start(self) -> "DataNodeScheduler":
        with self._cond:
            if self._thread is None or not self._thread.is_alive():
                self._stopping = False
                self._thread = threading.Thread(
                    target=self._loop, daemon=True,
                    name="datanode-scheduler")
                self._thread.start()
        return self

    def stop(self) -> None:
        with self._cond:
            self._stopping = True
            # fail waiters HERE, not only in the dispatcher loop: a
            # submit that raced stop() when no dispatcher is alive (e.g.
            # constructed but never started) has nothing else to fail it
            # and would strand its waiter until the query's own timeout
            self._fail_queued_locked(RuntimeError("scheduler stopped"))
            self._cond.notify_all()
            t = self._thread
        if t is not None and t.is_alive() \
                and t is not threading.current_thread():
            t.join(timeout=5.0)

    def depth(self) -> int:
        with self._cond:
            return len(self._queue)

    # ---- tick hooks (the standing-query tick driver) --------------------
    def add_tick_hook(self, fn) -> None:
        """Register a callable the dispatcher loop invokes between flushes
        (and roughly every wait period when idle). Hooks run on the
        dispatcher thread, outside the scheduler lock; exceptions are
        logged, never fatal."""
        with self._cond:
            if fn not in self._tick_hooks:
                self._tick_hooks.append(fn)

    def remove_tick_hook(self, fn) -> None:
        with self._cond:
            try:
                self._tick_hooks.remove(fn)
            except ValueError:
                pass

    def _fire_tick_hooks(self) -> None:
        with self._cond:
            hooks = list(self._tick_hooks)
        for fn in hooks:
            try:
                fn()
            except Exception:
                log.exception("scheduler tick hook failed")

    # ---- admission + hold (request thread) -----------------------------
    def submit(self, query, segment_ids, check=None):
        """Admit, queue, and wait for this query's flush. Returns
        (AggregatePartials, served); raises QueryCapacityError when shed,
        or whatever the query's own cancel/timeout probe raised."""
        self.stats.record_submit()
        lane = lane_of(query)
        priority = context_priority(query)
        cost = self._estimate_rows(segment_ids)
        deadline = Deadline.for_query(query)
        with self._cond:
            if self._stopping:
                raise RuntimeError("scheduler stopped")
            self._admit_locked(query, lane, cost)
            self._seq += 1
            item = _Item(query, segment_ids, check, lane, priority, cost,
                         self._seq)
            self._queue.append(item)
            depth = len(self._queue)
            self._cond.notify_all()
        self._ensure_dispatcher()
        # phase 1 — the HOLD: queued until the dispatcher starts our
        # flush. This is what queue/wait (span AND metric) measures;
        # execution time shows up as engine spans, not queue time. The
        # metric emits even when tracing is off ({"trace": false}).
        t0 = time.monotonic()
        try:
            with qtrace.span("queue/wait", lane=lane, depth=depth,
                             priority=priority):
                self._await(item, deadline, item.started)
        finally:
            waited_ms = (time.monotonic() - t0) * 1000.0
            if self.emitter is not None:
                self.emitter.metric(
                    "query/queue/wait", waited_ms,
                    dataSource=query.datasource, type=query.query_type,
                    id=query.context_map.get("queryId", ""), lane=lane)
        # phase 2 — the flush itself
        self._await(item, deadline, item.done)
        with self._cond:
            if item.error is not None:
                raise item.error
            return item.result

    def _estimate_rows(self, segment_ids) -> int:
        try:
            segs, _ = self.node._select(segment_ids)
        except Exception:
            log.debug("cost estimate failed; admitting at zero cost",
                      exc_info=True)
            return 0
        return sum(s.n_rows for s in segs)

    def _admit_locked(self, query, lane: str, cost_rows: int) -> None:
        """Shed checks, called with the lock held. Raising here is the
        429: bounded total depth, per-lane depth, and (when a service-rate
        estimate exists) a deadline the queue provably cannot meet."""
        cfg = self.config
        depth = len(self._queue)
        if depth >= cfg.max_queue_depth:
            self.stats.record_shed()
            raise QueryCapacityError(
                f"query queue full ({depth}/{cfg.max_queue_depth})",
                retry_after_s=self._drain_estimate_s(),
                server=getattr(self.node, "name", ""))
        cap = self._lane_depths.get(lane)
        if cap is not None \
                and sum(1 for it in self._queue if it.lane == lane) >= cap:
            self.stats.record_shed()
            raise QueryCapacityError(
                f"lane [{lane}] queue full ({cap})",
                retry_after_s=self._drain_estimate_s(),
                server=getattr(self.node, "name", ""))
        if cfg.shed_on_deadline and self._rate_rows_per_s:
            tmo = context_timeout_ms(query)
            if tmo is not None:
                queued = sum(it.cost_rows for it in self._queue) + cost_rows
                est_ms = queued / self._rate_rows_per_s * 1000.0
                if est_ms > tmo:
                    self.stats.record_shed()
                    raise QueryCapacityError(
                        f"deadline infeasible: ~{est_ms:.0f}ms of queued "
                        f"work against a {tmo:.0f}ms timeout",
                        retry_after_s=max(est_ms / 1000.0,
                                          cfg.retry_after_s),
                        server=getattr(self.node, "name", ""))

    def _drain_estimate_s(self) -> float:
        """Retry-After: the time the current queue needs to drain at the
        measured service rate (floor: the configured default)."""
        rate = self._rate_rows_per_s
        if not rate:
            return self.config.retry_after_s
        queued = sum(it.cost_rows for it in self._queue)
        return max(queued / rate, self.config.retry_after_s)

    def _await(self, item: _Item, deadline: Deadline,
               event: threading.Event) -> None:
        """Block until `event` fires; polls the query's cancel/timeout
        probe (no notification reaches a queued waiter on cancel) and
        abandons the slot on abort so the dispatcher skips still-queued
        dead work (an already-running flush is uninterruptible — its
        late result is simply discarded)."""
        while True:
            if event.wait(0.05):
                return
            try:
                if item.check is not None:
                    item.check()
                deadline.check()
            except BaseException:
                with self._cond:
                    item.abandoned = True
                    if item in self._queue:
                        self._queue.remove(item)
                raise

    def _ensure_dispatcher(self) -> None:
        with self._cond:
            if self._stopping:
                # a submit racing stop(): the item was (or will be)
                # failed by _fail_queued_locked — do NOT resurrect the
                # dispatcher; only an explicit start() restarts
                return
            t = self._thread
        if t is None or not t.is_alive():
            self.start()

    # ---- dispatch (scheduler thread) -----------------------------------
    def _loop(self) -> None:
        while True:
            with self._cond:
                if not self._queue and not self._stopping:
                    # single-shot wait (submit notifies): the loop exits
                    # the lock each period so tick hooks fire while idle
                    self._cond.wait(0.2)
                if self._stopping:
                    self._fail_queued_locked(
                        RuntimeError("scheduler stopped"))
                    return
                oldest = min((it.enq_t for it in self._queue), default=None)
            # the flush loop doubles as the standing-query tick driver:
            # hooks fire between flushes, outside the lock
            self._fire_tick_hooks()
            if oldest is None:
                continue
            # the batching window: give the oldest arrival's batch-mates
            # time to land before flushing (outside the lock; stop() stays
            # responsive via the post-sleep re-check). The window anchors
            # at the oldest enqueue, so the hold is its remaining budget.
            window = Deadline.until(
                oldest + self.config.batch_window_ms / 1000.0)
            hold = window.remaining()
            if hold > 0:
                time.sleep(hold)
            with self._cond:
                if self._stopping:
                    self._fail_queued_locked(
                        RuntimeError("scheduler stopped"))
                    return
                group = self._drain_locked()
            if group:
                self._execute(group)

    def _drain_locked(self) -> List[_Item]:
        """Priority-ordered flush group: interactive lanes ahead of
        background, higher context priority first, FIFO within — capped at
        max_batch_queries (the rest stays queued for the next flush)."""
        live = [it for it in self._queue if not it.abandoned]
        live.sort(key=lambda it: (it.lane == BACKGROUND_LANE,
                                  -it.priority, it.seq))
        group = live[:self.config.max_batch_queries]
        taken = set(map(id, group))
        self._queue = [it for it in self._queue if id(it) not in taken
                       and not it.abandoned]
        return group

    def _fail_queued_locked(self, err: BaseException) -> None:
        for it in self._queue:
            it.error = err
            it.started.set()
            it.done.set()
        self._queue.clear()

    def _execute(self, group: List[_Item]) -> None:
        """Run one flush group through the node's cross-query path. Engine
        spans land under the flush leader's request trace (the other
        queries' traces still carry their own queue/wait hold)."""
        leader = next((it.parent_span for it in group
                       if it.parent_span is not None), None)
        for it in group:
            it.started.set()             # ends every member's queue/wait
        t0 = time.monotonic()
        rows = sum(it.cost_rows for it in group)
        try:
            from druid_tpu.obs import dispatch as dispatch_mod
            d0 = dispatch_mod.count()
            with qtrace.attach(leader), \
                    qtrace.span("sched/flush", queries=len(group),
                                segments=sum(len(it.segment_ids)
                                             for it in group)) as fsp:
                results = self.node.run_partials_group(
                    [(it.query, it.segment_ids, it.check) for it in group],
                    on_batch=self.stats.record_cross_batch)
                if fsp is not None:
                    # the flush's whole-group dispatch bill: the megakernel
                    # + cross-query fusion story in one span attribute
                    fsp.attrs["dispatches"] = dispatch_mod.count() - d0
        except Exception as e:
            # run_partials_group isolates per-query failures; reaching
            # here is a scheduler-level defect — fail the group, keep
            # serving
            log.exception("scheduler flush failed")
            results = [e] * len(group)
        self.stats.record_flush(len(group))
        dt = time.monotonic() - t0
        if rows and dt > 0:
            inst = rows / dt
            with self._cond:
                self._rate_rows_per_s = inst if self._rate_rows_per_s \
                    is None else 0.7 * self._rate_rows_per_s + 0.3 * inst
        with self._cond:
            for it, res in zip(group, results):
                if isinstance(res, BaseException):
                    it.error = res
                else:
                    it.result = res
                it.done.set()
