from druid_tpu.server.lifecycle import QueryLifecycle, RequestLogger
from druid_tpu.server.http import QueryHttpServer
from druid_tpu.server.querymanager import (Deadline, QueryInterruptedError,
                                           QueryManager, QueryTimeoutError)
from druid_tpu.server.router import (Router, RouterHttpServer,
                                     TieredBrokerSelector)
from druid_tpu.server.security import (AllowAllAuthenticator,
                                       AllowAllAuthorizer, AuthChain,
                                       AuthenticationResult,
                                       BasicHTTPAuthenticator, Escalator,
                                       Permission, RoleBasedAuthorizer,
                                       authorizer_for_query)
from druid_tpu.server.subscriptions import (SubscriptionHub,
                                            UnknownSubscriptionError)

__all__ = ["QueryLifecycle", "RequestLogger", "QueryHttpServer",
           "QueryManager", "Deadline", "QueryInterruptedError",
           "QueryTimeoutError", "Router", "RouterHttpServer",
           "TieredBrokerSelector", "AuthChain", "AuthenticationResult",
           "AllowAllAuthenticator", "BasicHTTPAuthenticator",
           "AllowAllAuthorizer", "RoleBasedAuthorizer", "Permission",
           "Escalator", "authorizer_for_query", "SubscriptionHub",
           "UnknownSubscriptionError"]
