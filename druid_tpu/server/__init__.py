from druid_tpu.server.lifecycle import QueryLifecycle, RequestLogger
from druid_tpu.server.http import QueryHttpServer

__all__ = ["QueryLifecycle", "RequestLogger", "QueryHttpServer"]
