from druid_tpu.server.lifecycle import QueryLifecycle, RequestLogger
from druid_tpu.server.http import QueryHttpServer
from druid_tpu.server.querymanager import (Deadline, QueryInterruptedError,
                                           QueryManager, QueryTimeoutError)

__all__ = ["QueryLifecycle", "RequestLogger", "QueryHttpServer",
           "QueryManager", "Deadline", "QueryInterruptedError",
           "QueryTimeoutError"]
