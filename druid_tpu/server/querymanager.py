"""Query cancellation + timeout bookkeeping.

Reference analogs:
  server/QueryResource.java:126 — DELETE /druid/v2/{id} → QueryManager.cancel
  query/QueryContexts.java — timeout / priority context keys and defaults
  query/QueryInterruptedException.java — the wire-visible cancel/timeout error

A QueryToken is registered per running query id; cancel() trips the token and
fans out to any registered remote-cancel hooks (the broker propagates the
DELETE to data nodes it has in-flight requests on, like DirectDruidClient
does). Execution layers call token.check() at their natural yield points
(between scatter rounds, between segment batches) — device programs
themselves are uninterruptible once launched, exactly like a Java hot loop
between two Yielder steps.
"""
from __future__ import annotations

import logging
import threading
from typing import Callable, Dict, List, Optional

from druid_tpu.server.deadline import (Deadline,  # noqa: F401 (re-export)
                                       context_timeout_ms)


class QueryInterruptedError(RuntimeError):
    """Query was cancelled (reference: QueryInterruptedException CANCELLED)."""


class QueryTimeoutError(RuntimeError):
    """Query exceeded its context timeout (QueryInterruptedException
    TIMED_OUT; HTTP 504 at the resource layer)."""


class QueryCapacityError(RuntimeError):
    """The query was shed at admission — bounded scheduler queue, lane cap,
    or a deadline the queue cannot meet (reference:
    QueryCapacityExceededException). HTTP 429 with a Retry-After header at
    the resource layer; the broker surfaces it as a clear shed error
    instead of an opaque per-segment failure."""

    def __init__(self, message: str, retry_after_s: float = 1.0,
                 server: str = ""):
        super().__init__(message)
        self.retry_after_s = retry_after_s
        self.server = server

    def retry_after_header(self) -> str:
        """The Retry-After header value (whole seconds, floor 1) — the one
        place the wire contract's rounding lives; the broker resource and
        the data-node handler must answer identically."""
        return str(max(1, round(self.retry_after_s)))


DEFAULT_TIMEOUT_MS = 300_000


def cancel_path_id(path: str) -> Optional[str]:
    """The query id from an exact DELETE /druid/v2/{id} path, else None.
    Reserved sub-resources (datasources, sql, partials, rows) and bare
    /druid/v2 are not query ids."""
    parts = path.rstrip("/").split("/")
    if len(parts) != 4 or parts[:3] != ["", "druid", "v2"]:
        return None
    qid = parts[3]
    return qid if qid and qid not in ("datasources", "sql", "partials",
                                      "rows") else None


def context_priority(query) -> int:
    """Context "priority" (QueryContexts.getPriority) — tagged on query
    metrics/request logs; lane scheduling can build on it."""
    try:
        return int(query.context_map.get("priority", 0))
    except (TypeError, ValueError):
        return 0


class QueryToken:
    def __init__(self, query_id: str):
        self.query_id = query_id
        self.refcount = 1
        self._cancelled = threading.Event()
        self._remote_cancels: Dict[object, Callable[[], None]] = {}
        self._lock = threading.Lock()

    def cancelled(self) -> bool:
        return self._cancelled.is_set()

    def check(self) -> None:
        if self.cancelled():
            raise QueryInterruptedError(
                f"query [{self.query_id}] was cancelled")

    def add_remote_cancel(self, fn: Callable[[], None],
                          key: object = None) -> None:
        """Register a propagation hook (e.g. DELETE to a data node), one per
        key — re-registering the same server across retry rounds is a no-op.
        Runs immediately (in the background) if the token already tripped."""
        run_now = False
        with self._lock:
            if self._cancelled.is_set():
                run_now = True
            else:
                # one hook per key by contract: re-registering the same
                # server across retry rounds is an equivalent no-op
                self._remote_cancels.setdefault(  # druidlint: disable=unkeyed-trace-input
                    key if key is not None else object(), fn)
        if run_now:
            self._fire([fn])

    @staticmethod
    def _fire(hooks: List[Callable[[], None]]) -> None:
        """Best-effort propagation off the caller's thread: a DELETE at the
        resource layer must answer 202 immediately, not block on slow or
        dead data nodes (each hook has its own connect timeout)."""
        def run():
            for fn in hooks:
                try:
                    fn()
                except Exception:
                    logging.getLogger(__name__).exception(
                        "cancel propagation hook failed")
        threading.Thread(target=run, daemon=True).start()

    def cancel(self) -> None:
        with self._lock:
            self._cancelled.set()
            hooks = list(self._remote_cancels.values())
            self._remote_cancels = {}
        if hooks:
            self._fire(hooks)


class QueryScheduler:
    """Bounded, priority-ordered admission of queries.

    Reference analog: query/PrioritizedExecutorService.java (per-segment
    work ordered by query priority on a bounded pool) + the laning idea of
    DruidProcessingConfig — here admission happens once per query, because
    a query is ONE fused device program, not thousands of per-segment
    tasks. `total_slots` bounds concurrent queries; waiting queries are
    admitted highest-priority-first (FIFO within a priority); an optional
    per-lane cap (context "lane") keeps one class of queries from
    saturating the node."""

    def __init__(self, total_slots: int = 8,
                 lanes: Optional[Dict[str, int]] = None):
        self.total_slots = total_slots
        self.lane_caps = dict(lanes or {})
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._running = 0
        self._lane_running: Dict[str, int] = {}
        self._waiters: List[tuple] = []   # (-priority, seq, event, lane)
        self._seq = 0

    #: longest single park while queued without a caller timeout: the wait
    #: re-arms after each quantum, so a lost wakeup degrades to one poll
    #: period instead of a handler thread parked forever
    MAX_ADMISSION_POLL_S = 30.0

    def _admissible(self, lane: Optional[str]) -> bool:
        if self._running >= self.total_slots:
            return False
        if lane is not None and lane in self.lane_caps:
            return self._lane_running.get(lane, 0) < self.lane_caps[lane]
        return True

    def acquire(self, priority: int = 0, lane: Optional[str] = None,
                timeout: Optional[float] = None,
                should_abort: Optional[Callable[[], None]] = None) -> bool:
        """Block until admitted (priority order). False on timeout.
        `should_abort` (e.g. QueryToken.check) is polled while queued and
        may raise to abandon the wait — a DELETE on a queued query must
        free the waiter, not let it run later."""
        deadline = Deadline.after_s(timeout)
        with self._cond:
            if not self._waiters and self._admissible(lane):
                self._admit(lane)
                return True
            ev = threading.Event()
            entry = (-priority, self._seq, ev, lane)
            self._seq += 1
            self._waiters.append(entry)
            self._waiters.sort(key=lambda w: (w[0], w[1]))
            # a lane-blocked head must not stall an admissible newcomer
            self._wake_admissible()
            got_slot = False
            try:
                # the caller's timeout IS the query's own admitted budget
                # (context timeoutMs, already defaulted/validated at the
                # edge), not a raw wire value; each park re-arms within
                # MAX_ADMISSION_POLL_S and the cancel token is polled, so
                # an unlimited budget still cannot orphan the waiter
                while True:  # druidlint: disable=unclamped-external-timeout
                    if should_abort is not None:
                        # BEFORE honoring admission: a cancel that raced a
                        # release must win, or the cancelled query runs
                        should_abort()
                    if ev.is_set():
                        got_slot = True
                        return True
                    if deadline.expired():
                        return False
                    if should_abort is not None:
                        # no notification on cancel: poll the token
                        self._cond.wait(deadline.clamp(0.1))
                    else:
                        self._cond.wait(
                            deadline.clamp(self.MAX_ADMISSION_POLL_S))
            finally:
                if entry in self._waiters:
                    self._waiters.remove(entry)
                if ev.is_set() and not got_slot:
                    # admitted concurrently with a timeout/abort: give the
                    # slot back or it leaks forever, and wake the waiter
                    # it now belongs to (it may be in an untimed wait)
                    self._running -= 1
                    if lane is not None and lane in self._lane_running:
                        self._lane_running[lane] -= 1
                    self._wake_admissible()
                    self._cond.notify_all()

    def _admit(self, lane: Optional[str]) -> None:
        self._running += 1
        if lane is not None:
            self._lane_running[lane] = self._lane_running.get(lane, 0) + 1

    def _wake_admissible(self) -> None:
        # admit the best-priority waiters whose lane has room
        admitted = []
        for entry in self._waiters:
            _, _, ev, lane = entry
            if self._running >= self.total_slots:
                break
            if lane is not None and lane in self.lane_caps and \
                    self._lane_running.get(lane, 0) >= self.lane_caps[lane]:
                continue          # lane full: try the next waiter
            self._admit(lane)
            ev.set()
            admitted.append(entry)
        for entry in admitted:
            self._waiters.remove(entry)

    def release(self, lane: Optional[str] = None) -> None:
        with self._cond:
            self._running -= 1
            if lane is not None and lane in self._lane_running:
                self._lane_running[lane] -= 1
            self._wake_admissible()
            self._cond.notify_all()

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"running": self._running,
                    "waiting": len(self._waiters)}


class QueryManager:
    """Registry of in-flight queries (server/QueryManager analog)."""

    def __init__(self):
        self._tokens: Dict[str, QueryToken] = {}
        self._lock = threading.Lock()

    def register(self, query_id: str) -> QueryToken:
        """Refcounted: two in-flight queries reusing one id share a token
        that survives until the LAST unregister (a retry reusing its
        queryId stays cancellable after the first attempt finishes)."""
        with self._lock:
            tok = self._tokens.get(query_id)
            if tok is None:
                tok = self._tokens[query_id] = QueryToken(query_id)
            else:
                tok.refcount += 1
            return tok

    def unregister(self, query_id: str) -> None:
        with self._lock:
            tok = self._tokens.get(query_id)
            if tok is None:
                return
            tok.refcount -= 1
            if tok.refcount <= 0:
                del self._tokens[query_id]

    def token(self, query_id: Optional[str]) -> Optional[QueryToken]:
        if query_id is None:
            return None
        with self._lock:
            return self._tokens.get(query_id)

    def cancel(self, query_id: str) -> bool:
        """True if the query was in flight. Cancelling an unknown id is a
        no-op success=false (the reference returns 202 either way)."""
        tok = self.token(query_id)
        if tok is None:
            return False
        tok.cancel()
        return True

    def active_ids(self) -> List[str]:
        with self._lock:
            return sorted(self._tokens)
