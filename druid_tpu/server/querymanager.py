"""Query cancellation + timeout bookkeeping.

Reference analogs:
  server/QueryResource.java:126 — DELETE /druid/v2/{id} → QueryManager.cancel
  query/QueryContexts.java — timeout / priority context keys and defaults
  query/QueryInterruptedException.java — the wire-visible cancel/timeout error

A QueryToken is registered per running query id; cancel() trips the token and
fans out to any registered remote-cancel hooks (the broker propagates the
DELETE to data nodes it has in-flight requests on, like DirectDruidClient
does). Execution layers call token.check() at their natural yield points
(between scatter rounds, between segment batches) — device programs
themselves are uninterruptible once launched, exactly like a Java hot loop
between two Yielder steps.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional


class QueryInterruptedError(RuntimeError):
    """Query was cancelled (reference: QueryInterruptedException CANCELLED)."""


class QueryTimeoutError(RuntimeError):
    """Query exceeded its context timeout (QueryInterruptedException
    TIMED_OUT; HTTP 504 at the resource layer)."""


DEFAULT_TIMEOUT_MS = 300_000


def cancel_path_id(path: str) -> Optional[str]:
    """The query id from an exact DELETE /druid/v2/{id} path, else None.
    Reserved sub-resources (datasources, sql, partials, rows) and bare
    /druid/v2 are not query ids."""
    parts = path.rstrip("/").split("/")
    if len(parts) != 4 or parts[:3] != ["", "druid", "v2"]:
        return None
    qid = parts[3]
    return qid if qid and qid not in ("datasources", "sql", "partials",
                                      "rows") else None


def context_timeout_ms(query) -> Optional[float]:
    """The query's timeout in ms (context key "timeout"; 0 = unlimited)."""
    t = query.context_map.get("timeout")
    if t is None:
        return None
    t = float(t)
    return None if t <= 0 else t


def context_priority(query) -> int:
    """Context "priority" (QueryContexts.getPriority) — tagged on query
    metrics/request logs; lane scheduling can build on it."""
    try:
        return int(query.context_map.get("priority", 0))
    except (TypeError, ValueError):
        return 0


class Deadline:
    """Monotonic deadline; None = unlimited."""

    def __init__(self, timeout_ms: Optional[float]):
        self._end = None if timeout_ms is None \
            else time.monotonic() + timeout_ms / 1000.0

    @staticmethod
    def for_query(query) -> "Deadline":
        return Deadline(context_timeout_ms(query))

    def remaining_ms(self) -> Optional[float]:
        if self._end is None:
            return None
        return max(0.0, (self._end - time.monotonic()) * 1000.0)

    def expired(self) -> bool:
        return self._end is not None and time.monotonic() >= self._end

    def check(self) -> None:
        if self.expired():
            raise QueryTimeoutError("query timed out")


class QueryToken:
    def __init__(self, query_id: str):
        self.query_id = query_id
        self.refcount = 1
        self._cancelled = threading.Event()
        self._remote_cancels: Dict[object, Callable[[], None]] = {}
        self._lock = threading.Lock()

    def cancelled(self) -> bool:
        return self._cancelled.is_set()

    def check(self) -> None:
        if self.cancelled():
            raise QueryInterruptedError(
                f"query [{self.query_id}] was cancelled")

    def add_remote_cancel(self, fn: Callable[[], None],
                          key: object = None) -> None:
        """Register a propagation hook (e.g. DELETE to a data node), one per
        key — re-registering the same server across retry rounds is a no-op.
        Runs immediately (in the background) if the token already tripped."""
        run_now = False
        with self._lock:
            if self._cancelled.is_set():
                run_now = True
            else:
                self._remote_cancels.setdefault(
                    key if key is not None else object(), fn)
        if run_now:
            self._fire([fn])

    @staticmethod
    def _fire(hooks: List[Callable[[], None]]) -> None:
        """Best-effort propagation off the caller's thread: a DELETE at the
        resource layer must answer 202 immediately, not block on slow or
        dead data nodes (each hook has its own connect timeout)."""
        def run():
            for fn in hooks:
                try:
                    fn()
                except Exception:
                    pass
        threading.Thread(target=run, daemon=True).start()

    def cancel(self) -> None:
        with self._lock:
            self._cancelled.set()
            hooks = list(self._remote_cancels.values())
            self._remote_cancels = {}
        if hooks:
            self._fire(hooks)


class QueryManager:
    """Registry of in-flight queries (server/QueryManager analog)."""

    def __init__(self):
        self._tokens: Dict[str, QueryToken] = {}
        self._lock = threading.Lock()

    def register(self, query_id: str) -> QueryToken:
        """Refcounted: two in-flight queries reusing one id share a token
        that survives until the LAST unregister (a retry reusing its
        queryId stays cancellable after the first attempt finishes)."""
        with self._lock:
            tok = self._tokens.get(query_id)
            if tok is None:
                tok = self._tokens[query_id] = QueryToken(query_id)
            else:
                tok.refcount += 1
            return tok

    def unregister(self, query_id: str) -> None:
        with self._lock:
            tok = self._tokens.get(query_id)
            if tok is None:
                return
            tok.refcount -= 1
            if tok.refcount <= 0:
                del self._tokens[query_id]

    def token(self, query_id: Optional[str]) -> Optional[QueryToken]:
        if query_id is None:
            return None
        with self._lock:
            return self._tokens.get(query_id)

    def cancel(self, query_id: str) -> bool:
        """True if the query was in flight. Cancelling an unknown id is a
        no-op success=false (the reference returns 202 either way)."""
        tok = self.token(query_id)
        if tok is None:
            return False
        tok.cancel()
        return True

    def active_ids(self) -> List[str]:
        with self._lock:
            return sorted(self._tokens)
