"""One shared monotonic Deadline for every remaining-budget computation.

Reference analogs:
  query/QueryContexts.java — the "timeout" context key the budget comes from
  server/QueryResource + DirectDruidClient — the same budget threads from
  the HTTP edge through the scatter to every remote call

Before this module, five call sites (query admission, the long-poll hub,
the scatter wave, the data-node scheduler's batch window, the remote
client's shed retry) each hand-rolled `end = time.monotonic() + t` /
`remaining = end - time.monotonic()` arithmetic — and the PR 14 review
caught one of them parking a handler thread forever on a wire-supplied
timeout. Deadline is the single carrier for "how long may I still block":
construct it once where the budget enters, pass the OBJECT down, and bound
every park with `clamp()`. stallguard's `deadline-not-propagated` rule
keys on this type, and `unbounded-retry` accepts its consults as a loop
bound.
"""
from __future__ import annotations

import time
from typing import Optional


def context_timeout_ms(query) -> Optional[float]:
    """The query's timeout in ms (context key "timeout"; 0 = unlimited)."""
    t = query.context_map.get("timeout")
    if t is None:
        return None
    t = float(t)
    return None if t <= 0 else t


class Deadline:
    """Monotonic deadline; None = unlimited."""

    __slots__ = ("_end",)

    def __init__(self, timeout_ms: Optional[float]):
        self._end = None if timeout_ms is None \
            else time.monotonic() + timeout_ms / 1000.0

    @staticmethod
    def for_query(query) -> "Deadline":
        return Deadline(context_timeout_ms(query))

    @staticmethod
    def after_s(timeout_s: Optional[float]) -> "Deadline":
        """A deadline `timeout_s` seconds out (None = unlimited)."""
        return Deadline(None if timeout_s is None else timeout_s * 1000.0)

    @staticmethod
    def until(end_monotonic_s: Optional[float]) -> "Deadline":
        """A deadline at an absolute time.monotonic() instant — for budgets
        anchored to an event that already happened (the batch window opens
        at the oldest enqueue, not at the wait)."""
        d = Deadline(None)
        d._end = end_monotonic_s
        return d

    def remaining_ms(self) -> Optional[float]:
        if self._end is None:
            return None
        return max(0.0, (self._end - time.monotonic()) * 1000.0)

    def remaining(self) -> Optional[float]:
        """Remaining budget in seconds (None = unlimited), floored at 0."""
        if self._end is None:
            return None
        return max(0.0, self._end - time.monotonic())

    def clamp(self, value_s: Optional[float]) -> Optional[float]:
        """`value_s` bounded by the remaining budget — the one idiom a park
        under a deadline should use for its timeout argument. value None
        means "the whole remaining budget"; an unlimited deadline leaves
        `value_s` unchanged (so a poll quantum stays the bound)."""
        rem = self.remaining()
        if rem is None:
            return value_s
        if value_s is None:
            return rem
        return min(value_s, rem)

    def expired(self) -> bool:
        return self._end is not None and time.monotonic() >= self._end

    def check(self) -> None:
        if self.expired():
            # local import: querymanager imports Deadline from here
            from druid_tpu.server.querymanager import QueryTimeoutError
            raise QueryTimeoutError("query timed out")
