"""Avatica JSON-RPC endpoint: the JDBC entry point.

Reference analog: sql/src/main/java/org/apache/druid/sql/avatica/
DruidMeta.java + DruidAvaticaJsonHandler (POST /druid/v2/sql/avatica/) —
the Calcite Avatica remote-driver wire protocol (JSON flavor). The subset
implemented here covers what the Avatica JDBC driver issues for plain
statement execution: openConnection / createStatement / prepareAndExecute
/ prepare / execute / fetch / closeStatement / closeConnection /
connectionSync / databaseProperty.
"""
from __future__ import annotations

import threading
import time
import uuid
from typing import Dict, List, Optional, Sequence, Tuple

_SQL_TYPE = {"string": ("VARCHAR", 12), "long": ("BIGINT", -5),
             "double": ("DOUBLE", 8), "float": ("FLOAT", 6),
             "timestamp": ("TIMESTAMP", 93)}


def _ident_key(identity) -> Optional[str]:
    """Normalize an identity (AuthenticationResult | str | None) to the
    comparable key connections bind to."""
    if identity is None:
        return None
    return getattr(identity, "identity", str(identity))


def _signature(columns: Sequence[str], rows: Sequence[list]) -> dict:
    """Column signature inferred from the result values (the executor
    shapes types; Avatica needs JDBC type codes)."""
    cols = []
    for i, name in enumerate(columns):
        kind = "string"
        for r in rows:
            v = r[i] if i < len(r) else None
            if isinstance(v, bool) or v is None:
                continue
            if isinstance(v, int):
                kind = "long"
                break
            if isinstance(v, float):
                kind = "double"
                break
            kind = "string"
            break
        tname, tid = _SQL_TYPE[kind]
        cols.append({
            "ordinal": i, "columnName": name, "label": name,
            "type": {"type": "scalar", "name": tname, "id": tid,
                     "rep": "OBJECT"},
            "nullable": 1,
        })
    return {"columns": cols, "sql": None, "parameters": [],
            "cursorFactory": {"style": "LIST"}, "statementType": "SELECT"}


class _Statement:
    def __init__(self, statement_id: int):
        self.id = statement_id
        self.columns: List[str] = []
        self.rows: List[list] = []
        self.sql: Optional[str] = None     # set by prepare


class _Connection:
    def __init__(self, connection_id: str, identity: Optional[str] = None):
        self.id = connection_id
        self.identity = identity     # bound at open; all requests must match
        self.statements: Dict[int, _Statement] = {}
        self.next_statement = 0
        self.last_used = time.monotonic()


class AvaticaServer:
    """Protocol state + request dispatch; mount under the query HTTP
    server at /druid/v2/sql/avatica/."""

    def __init__(self, sql_executor, max_connections: int = 50,
                 max_rows_per_frame: int = 5000):
        self.sql = sql_executor
        self.max_connections = max_connections
        self.max_rows_per_frame = max_rows_per_frame
        self._conns: Dict[str, _Connection] = {}
        self._lock = threading.Lock()

    # ---- dispatch -------------------------------------------------------
    def handle(self, payload: dict, authorize=None,
               identity: Optional[str] = None) -> dict:
        """authorize: optional (sql, params) -> bool — the same per-table
        decision the plain SQL resource makes; execution requests run it
        first. identity: the authenticated caller — connections BIND to
        the identity that opened them, so one user cannot fetch another's
        buffered rows by guessing a connection id (DruidMeta ties
        connections to the authenticated user)."""
        req = payload.get("request")
        fn = getattr(self, f"_req_{req}", None)
        if fn is None:
            return self._error(f"unsupported avatica request {req!r}")
        # request-scoped copy: identity rides the payload (instance state
        # would race across concurrent handler threads)
        payload = dict(payload)
        payload["__identity__"] = _ident_key(identity)
        try:
            if req in ("prepareAndExecute", "execute"):
                return fn(payload, authorize)
            return fn(payload)
        except KeyError as e:
            return self._error(f"missing field {e}")
        except PermissionError as e:
            return self._error(str(e))
        except Exception as e:
            return self._error(f"{type(e).__name__}: {e}")

    @staticmethod
    def _error(msg: str) -> dict:
        return {"response": "error", "errorMessage": msg,
                "errorCode": -1, "sqlState": "00000",
                "severity": "ERROR"}

    def _conn(self, payload: dict) -> _Connection:
        cid = payload["connectionId"]
        with self._lock:
            conn = self._conns.get(cid)
            if conn is None:
                raise ValueError(f"unknown connection {cid}")
            if conn.identity != payload.get("__identity__"):
                raise PermissionError(
                    "connection belongs to another identity")
            conn.last_used = time.monotonic()
            return conn

    # ---- connection lifecycle ------------------------------------------
    def _req_openConnection(self, payload: dict) -> dict:
        # reap abandoned connections on every open: a crashed JDBC client
        # must not permanently consume a slot (DruidMeta's timeout reaper)
        self.expire_idle()
        cid = payload.get("connectionId") or str(uuid.uuid4())
        identity = payload.get("__identity__")
        with self._lock:
            existing = self._conns.get(cid)
            if existing is not None:
                if existing.identity != identity:
                    return self._error(
                        "connection belongs to another identity")
                return {"response": "openConnection", "connectionId": cid}
            if len(self._conns) >= self.max_connections:
                return self._error("too many connections")
            self._conns[cid] = _Connection(cid, identity)
        return {"response": "openConnection", "connectionId": cid}

    def _req_closeConnection(self, payload: dict) -> dict:
        try:
            self._conn(payload)      # identity must match to close
        except ValueError:
            return {"response": "closeConnection"}   # already gone: idempotent
        with self._lock:
            self._conns.pop(payload["connectionId"], None)
        return {"response": "closeConnection"}

    def _req_connectionSync(self, payload: dict) -> dict:
        self._conn(payload)
        return {"response": "connectionSync", "connProps": {
            "connProps": "connPropsImpl", "autoCommit": True,
            "readOnly": True, "dirty": False}}

    def _req_databaseProperty(self, payload: dict) -> dict:
        return {"response": "databaseProperty", "map": {
            "GET_S_Q_L_KEYWORDS": "", "GET_DRIVER_NAME": "druid-tpu",
            "GET_DRIVER_VERSION": "0.1",
            "GET_DATABASE_PRODUCT_NAME": "druid-tpu",
            "GET_DATABASE_PRODUCT_VERSION": "0.1"}}

    # ---- statements -----------------------------------------------------
    def _req_createStatement(self, payload: dict) -> dict:
        conn = self._conn(payload)
        with self._lock:
            sid = conn.next_statement
            conn.next_statement += 1
            conn.statements[sid] = _Statement(sid)
        return {"response": "createStatement",
                "connectionId": conn.id, "statementId": sid}

    def _req_closeStatement(self, payload: dict) -> dict:
        conn = self._conn(payload)
        with self._lock:
            conn.statements.pop(payload["statementId"], None)
        return {"response": "closeStatement"}

    def _req_prepare(self, payload: dict) -> dict:
        conn = self._conn(payload)
        sql = payload["sql"]
        with self._lock:
            sid = conn.next_statement
            conn.next_statement += 1
            st = conn.statements[sid] = _Statement(sid)
            st.sql = sql
        return {"response": "prepare", "statement": {
            "connectionId": conn.id, "id": sid,
            "signature": {"columns": [], "sql": sql, "parameters": [],
                          "cursorFactory": {"style": "LIST"},
                          "statementType": "SELECT"}}}

    def _execute_sql(self, conn: _Connection, sid: int, sql: str,
                     parameters: Sequence = (),
                     max_rows: int = -1, authorize=None) -> dict:
        if authorize is not None and not authorize(sql, parameters):
            raise PermissionError("unauthorized")
        cols, rows = self.sql.execute(sql, parameters)
        if max_rows is not None and max_rows >= 0:
            rows = rows[:max_rows]
        # statement registry is mutated under the server lock everywhere
        # else; concurrent requests on one connection race the dict insert
        with self._lock:
            st = conn.statements.setdefault(sid, _Statement(sid))
            st.columns, st.rows = list(cols), [list(r) for r in rows]
        first = st.rows[: self.max_rows_per_frame]
        done = len(first) == len(st.rows)
        return {
            "response": "resultSet", "connectionId": conn.id,
            "statementId": sid, "ownStatement": True,
            "signature": _signature(st.columns, st.rows),
            "firstFrame": {"offset": 0, "done": done, "rows": first},
            "updateCount": -1,
        }

    def _req_prepareAndExecute(self, payload: dict, authorize=None) -> dict:
        conn = self._conn(payload)
        rs = self._execute_sql(conn, payload["statementId"],
                               payload["sql"], (),
                               payload.get("maxRowCount", -1), authorize)
        return {"response": "executeResults", "missingStatement": False,
                "connectionId": conn.id,
                "statementId": payload["statementId"], "results": [rs]}

    def _req_execute(self, payload: dict, authorize=None) -> dict:
        handle = payload["statementHandle"]
        conn = self._conn({**payload,
                           "connectionId": handle["connectionId"]})
        st = conn.statements.get(handle["id"])
        if st is None or st.sql is None:
            return self._error("statement not prepared")
        params = [p.get("value") for p in
                  payload.get("parameterValues", [])]
        rs = self._execute_sql(conn, st.id, st.sql, params,
                               payload.get("maxRowCount", -1), authorize)
        return {"response": "executeResults", "missingStatement": False,
                "connectionId": conn.id, "statementId": st.id,
                "results": [rs]}

    def _req_fetch(self, payload: dict) -> dict:
        conn = self._conn(payload)
        st = conn.statements.get(payload["statementId"])
        if st is None:
            return self._error("unknown statement")
        offset = int(payload.get("offset", 0))
        n = int(payload.get("fetchMaxRowCount",
                            self.max_rows_per_frame))
        if n < 0:
            n = self.max_rows_per_frame
        rows = st.rows[offset:offset + n]
        done = offset + len(rows) >= len(st.rows)
        return {"response": "fetch", "connectionId": conn.id,
                "statementId": st.id,
                "frame": {"offset": offset, "done": done, "rows": rows}}

    # ---- maintenance ----------------------------------------------------
    def expire_idle(self, ttl_seconds: float = 300.0) -> int:
        """Drop connections idle past the ttl (DruidMeta's connection
        timeout reaper)."""
        now = time.monotonic()
        with self._lock:
            dead = [cid for cid, c in self._conns.items()
                    if now - c.last_used > ttl_seconds]
            for cid in dead:
                del self._conns[cid]
        return len(dead)
