"""On-chip check: projection + pallas strategy compiles and matches, + rate."""
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ROWS = int(os.environ.get("PROF_ROWS", 12_500_000))


def main():
    import jax
    print(f"devices: {jax.devices()}", flush=True)

    import bench
    from druid_tpu.engine import QueryExecutor, grouping

    t0 = time.time()
    segments = bench.headline_segments(ROWS, 1)   # the gated headline shape
    print(f"gen {time.time()-t0:.1f}s", flush=True)
    q = bench.headline_groupby()

    picks = []
    orig = grouping.select_strategy

    def spy(*a, **kw):
        r = orig(*a, **kw)
        picks.append(r)
        return r
    grouping.select_strategy = spy

    ex = QueryExecutor(segments)

    # baseline: mixed (projection off)
    grouping.PROJECTION_MIN_ROWS = 1 << 62
    t0 = time.time()
    base = ex.run(q)
    print(f"mixed warm+run {time.time()-t0:.1f}s picks={picks}", flush=True)
    picks.clear()
    times = []
    for _ in range(3):
        t0 = time.time()
        ex.run(q)
        times.append(time.time() - t0)
    t_mixed = min(times)
    print(f"mixed best {t_mixed*1e3:.0f}ms -> {ROWS/t_mixed/1e6:.0f}M rows/s",
          flush=True)

    # projection + pallas
    grouping.PROJECTION_MIN_ROWS = 1 << 20
    t0 = time.time()
    got = ex.run(q)
    print(f"projection warm (sort+compile) {time.time()-t0:.1f}s "
          f"picks={picks}", flush=True)
    inner = grouping._projection_strategy
    times = []
    for _ in range(5):
        t0 = time.time()
        ex.run(q)
        times.append(time.time() - t0)
    t_proj = min(times)
    print(f"projection best {t_proj*1e3:.0f}ms -> "
          f"{ROWS/t_proj/1e6:.0f}M rows/s", flush=True)

    def norm(rows):
        return {(r["event"]["dimA"], r["event"]["dimB"]):
                (r["event"]["rows"], r["event"]["lsum"],
                 round(r["event"]["fmax"], 2)) for r in rows}
    a, b = norm(base), norm(got)
    diffs = [(k, a[k], b[k]) for k in a if a[k] != b.get(k)]
    print(f"nkeys {len(a)} vs {len(b)}; ndiffs {len(diffs)}", flush=True)
    for d in diffs[:5]:
        print(" ", d)
    assert not diffs and len(a) == len(b), "MISMATCH"
    print("MATCH", flush=True)


if __name__ == "__main__":
    main()
