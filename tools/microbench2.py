"""Round 2 of primitive probing: BLK sweep, int8 vs bf16 matmul rate,
pallas availability, fused pallas one-hot matmul prototype."""
import time
import sys
import functools

import numpy as np


def _sync(r):
    import jax
    for leaf in jax.tree.leaves(r):
        np.asarray(jax.device_get(leaf)).ravel()[:1]


def t(fn, *args, iters=3, warmup=1):
    for _ in range(warmup):
        _sync(fn(*args))
    best = float("inf")
    for _ in range(iters):
        t0 = time.perf_counter()
        _sync(fn(*args))
        best = min(best, time.perf_counter() - t0)
    return best


def main():
    import jax
    import jax.numpy as jnp

    N = 12_500_000
    A, B = 128, 1024
    rng = np.random.default_rng(0)
    a_ids = jnp.asarray(rng.integers(0, 100, N, dtype=np.int32))
    b_ids = jnp.asarray(rng.integers(0, 1000, N, dtype=np.int32))
    vals = jnp.asarray(rng.integers(0, 10_000, N, dtype=np.int32))

    results = {}

    # raw matmul rate probe: [M, K] @ [K, 1024] int8 and bf16
    for dt, acc_dt, name in [(jnp.int8, jnp.int32, "int8"),
                             (jnp.bfloat16, jnp.float32, "bf16")]:
        M, K = 384, 8192
        lhs = jnp.ones((K, M), dt)
        rhs = jnp.ones((K, B), dt)

        @jax.jit
        def mm(l, r):
            def body(acc, _):
                out = jax.lax.dot_general(
                    l, r, (((0,), (0,)), ((), ())),
                    preferred_element_type=acc_dt)
                return acc + out, None
            acc, _ = jax.lax.scan(body, jnp.zeros((M, B), acc_dt), None,
                                  length=256)
            return acc
        sec = t(mm, lhs, rhs)
        flops = 2 * M * K * B * 256
        results[f"raw_matmul_{name}_384x8192x1024"] = (
            sec, f"{flops/sec/1e12:8.1f} Tops")

    # XLA 2-level one-hot with BLK sweep, 3 int8 cols (RHS-value packing)
    for BLK in (1024, 2048, 4096):
        nblk = N // BLK

        @jax.jit
        def onehot2(ka, kb_, v):
            kaa = ka[: nblk * BLK].reshape(nblk, BLK)
            kbb = kb_[: nblk * BLK].reshape(nblk, BLK)
            v0 = (v[: nblk * BLK] & 127).astype(jnp.int8).reshape(nblk, BLK)
            v1 = ((v[: nblk * BLK] >> 7) & 127).astype(jnp.int8).reshape(
                nblk, BLK)
            iota_a = jnp.arange(A, dtype=jnp.int32)
            iota_b = jnp.arange(B, dtype=jnp.int32)

            def body(acc, xs):
                kk_a, kk_b, l0, l1 = xs
                oh_a = (kk_a[:, None] == iota_a[None, :]).astype(jnp.int8)
                oh_b = (kk_b[:, None] == iota_b[None, :])
                rhs = jnp.concatenate([
                    oh_b.astype(jnp.int8),
                    jnp.where(oh_b, l0[:, None], 0).astype(jnp.int8),
                    jnp.where(oh_b, l1[:, None], 0).astype(jnp.int8),
                ], axis=1)  # [BLK, 3B]
                out = jax.lax.dot_general(
                    oh_a, rhs, (((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.int32)  # [A, 3B]
                return acc + out, None

            acc0 = jnp.zeros((A, 3 * B), jnp.int32)
            acc, _ = jax.lax.scan(body, acc0, (kaa, kbb, v0, v1))
            return acc
        sec = t(onehot2, a_ids, b_ids, vals)
        results[f"xla_2level_rhs_blk{BLK}"] = (
            sec, f"{N/sec/1e6:8.0f} M rows/s")

    # pallas fused: one-hot built in VMEM scratch, matmul, accumulate
    try:
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        BLK = 2048

        def kernel(ka_ref, kb_ref, v0_ref, v1_ref, out_ref, acc_ref):
            i = pl.program_id(0)

            @pl.when(i == 0)
            def _():
                acc_ref[:] = jnp.zeros_like(acc_ref)

            ka = ka_ref[:]  # [BLK]
            kb = kb_ref[:]
            iota_a = jax.lax.broadcasted_iota(jnp.int32, (BLK, A), 1)
            iota_b = jax.lax.broadcasted_iota(jnp.int32, (BLK, B), 1)
            oh_a = (ka[:, None] == iota_a).astype(jnp.int8)
            oh_b = (kb[:, None] == iota_b)
            rhs = jnp.concatenate([
                oh_b.astype(jnp.int8),
                jnp.where(oh_b, v0_ref[:][:, None], 0).astype(jnp.int8),
                jnp.where(oh_b, v1_ref[:][:, None], 0).astype(jnp.int8),
            ], axis=1)
            acc_ref[:] += jax.lax.dot_general(
                oh_a, rhs, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.int32)

            @pl.when(i == pl.num_programs(0) - 1)
            def _():
                out_ref[:] = acc_ref[:]

        nblk = N // BLK

        @jax.jit
        def pallas_fused(ka, kb_, v):
            n = nblk * BLK
            v0 = (v[:n] & 127).astype(jnp.int8)
            v1 = ((v[:n] >> 7) & 127).astype(jnp.int8)
            grid = (nblk,)
            return pl.pallas_call(
                kernel,
                out_shape=jax.ShapeDtypeStruct((A, 3 * B), jnp.int32),
                grid=grid,
                in_specs=[
                    pl.BlockSpec((BLK,), lambda i: (i,),
                                 memory_space=pltpu.VMEM),
                    pl.BlockSpec((BLK,), lambda i: (i,),
                                 memory_space=pltpu.VMEM),
                    pl.BlockSpec((BLK,), lambda i: (i,),
                                 memory_space=pltpu.VMEM),
                    pl.BlockSpec((BLK,), lambda i: (i,),
                                 memory_space=pltpu.VMEM),
                ],
                out_specs=pl.BlockSpec((A, 3 * B), lambda i: (0, 0),
                                       memory_space=pltpu.VMEM),
                scratch_shapes=[pltpu.VMEM((A, 3 * B), jnp.int32)],
            )(ka[:n], kb_[:n], v0, v1)

        sec = t(pallas_fused, a_ids, b_ids, vals)
        results["pallas_fused_2level_blk2048"] = (
            sec, f"{N/sec/1e6:8.0f} M rows/s")
    except Exception as e:
        results["pallas_fused_2level_blk2048"] = (0.0, f"FAILED: {e!r:.200}")

    for k, (sec, extra) in results.items():
        print(f"{k:38s} {sec*1e3:9.2f} ms   {extra}")


if __name__ == "__main__":
    main()
