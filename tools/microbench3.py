"""Probe peak matmul rate + pallas error detail."""
import time
import sys
import numpy as np


def _sync(r):
    import jax
    for leaf in jax.tree.leaves(r):
        np.asarray(jax.device_get(leaf)).ravel()[:1]


def t(fn, *args, iters=3, warmup=1):
    for _ in range(warmup):
        _sync(fn(*args))
    best = float("inf")
    for _ in range(iters):
        t0 = time.perf_counter()
        _sync(fn(*args))
        best = min(best, time.perf_counter() - t0)
    return best


def main():
    import jax
    import jax.numpy as jnp

    # peak probe: chained square matmuls (data-dependent, can't be hoisted)
    for dt, acc, name in [(jnp.bfloat16, jnp.bfloat16, "bf16"),
                          (jnp.int8, jnp.int32, "int8")]:
        M = 4096

        @jax.jit
        def chain(x, w):
            def body(c, _):
                c = jax.lax.dot_general(
                    c, w, (((1,), (0,)), ((), ())),
                    preferred_element_type=acc)
                if acc != dt:
                    c = (c & 1).astype(dt) if name == "int8" else c.astype(dt)
                return c, None
            c, _ = jax.lax.scan(body, x, None, length=64)
            return c

        x = jnp.ones((M, M), dt)
        w = jnp.ones((M, M), dt) if name == "bf16" else jnp.ones(
            (M, M), dt)
        sec = t(chain, x, w)
        flops = 2 * M * M * M * 64
        print(f"peak_chain_{name}_4096^3 x64   {sec*1e3:9.2f} ms  "
              f"{flops/sec/1e12:8.1f} Tops")

    # pallas minimal test with full traceback
    try:
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        def kernel(x_ref, o_ref):
            o_ref[:] = x_ref[:] * 2.0

        @jax.jit
        def double(x):
            return pl.pallas_call(
                kernel,
                out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
                in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
                out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            )(x)

        r = double(jnp.ones((256, 256), jnp.float32))
        _sync(r)
        print("pallas_minimal OK", float(np.asarray(jax.device_get(r))[0, 0]))
    except Exception as e:
        import traceback
        traceback.print_exc()
        print("pallas_minimal FAILED")


if __name__ == "__main__":
    main()
