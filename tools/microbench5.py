"""Accurate timings: repeat each op R times inside ONE jit (data-dependent so
XLA can't hoist), fetch one scalar. Separately probe RPC latency + H2D rates."""
import time
import numpy as np

REPS = 10


def main():
    import jax
    import jax.numpy as jnp

    N = 12_500_000
    rng = np.random.default_rng(0)
    a_np = rng.integers(0, 100, N, dtype=np.int32)
    b_np = rng.integers(0, 1000, N, dtype=np.int32)
    v_np = rng.integers(0, 10_000, N, dtype=np.int32)
    f_np = rng.normal(100, 25, N).astype(np.float32)
    order = np.lexsort((b_np, a_np))

    print("staging inputs...", flush=True)
    t0 = time.perf_counter()
    a_ids = jax.device_put(a_np)
    b_ids = jax.device_put(b_np)
    vals = jax.device_put(v_np)
    fvals = jax.device_put(f_np)
    key_sorted = jax.device_put((a_np * 1000 + b_np)[order])
    v_sorted = jax.device_put(v_np[order])
    f_sorted = jax.device_put(f_np[order])
    key_fused = jax.device_put(a_np * 1000 + b_np)
    for x in (a_ids, b_ids, vals, fvals, key_sorted, v_sorted, f_sorted,
              key_fused):
        x.block_until_ready()
    print(f"staged 8 x 50MB in {time.perf_counter()-t0:.1f}s", flush=True)

    # RPC latency: device_get of a scalar
    s = jnp.float32(1.0) + 0
    lat = []
    for _ in range(5):
        t0 = time.perf_counter()
        np.asarray(jax.device_get(s))
        lat.append(time.perf_counter() - t0)
    rpc = min(lat)
    print(f"RPC scalar fetch latency: {rpc*1e3:.1f} ms", flush=True)

    def timed(name, fn, *args):
        """fn(i, *args) -> array; summed over REPS in-jit, one fetch."""
        @jax.jit
        def run(*a):
            def body(acc, i):
                out = fn(i, *a)
                leaves = jax.tree.leaves(out)
                r = sum(jnp.sum(l, dtype=jnp.float32) if l.dtype != jnp.bool_
                        else jnp.sum(l.astype(jnp.int32), dtype=jnp.float32)
                        for l in leaves)
                return acc + r, None
            acc, _ = jax.lax.scan(body, jnp.float32(0),
                                  jnp.arange(REPS, dtype=jnp.int32))
            return acc
        r = run(*args)
        np.asarray(jax.device_get(r))  # compile+warm
        t0 = time.perf_counter()
        r = run(*args)
        np.asarray(jax.device_get(r))
        per = (time.perf_counter() - t0 - rpc) / REPS
        print(f"{name:44s} {per*1e3:9.2f} ms  {N/per/1e6:9.0f} M rows/s",
              flush=True)
        return per

    # 1. timeseries 3agg
    def ts(i, v, f):
        v = v + i  # data dependence; cheap
        m = (v >= 100) & (v <= 9900)
        return (m.sum(dtype=jnp.int32), jnp.where(m, v, 0).sum(),
                jnp.where(m, f, -3.4e38).max())
    timed("timeseries_G1_3agg", ts, vals, fvals)

    # 2. one-hot int8 matmul G=1024, 3col (scan over 8192-blocks)
    BLK = 8192
    nblk = N // BLK
    n = nblk * BLK

    def onehot1024(i, bk, v):
        kb = (bk[:n] % 1024).reshape(nblk, BLK)
        v = v + i
        v0 = (v[:n] & 127).astype(jnp.int8).reshape(nblk, BLK)
        v1 = ((v[:n] >> 7) & 127).astype(jnp.int8).reshape(nblk, BLK)
        iota = jnp.arange(1024, dtype=jnp.int32)

        def body(acc, xs):
            kk, l0, l1 = xs
            oh = (kk[:, None] == iota[None, :]).astype(jnp.int8)
            lhs = jnp.stack([jnp.ones((BLK,), jnp.int8), l0, l1], 0)
            return acc + jax.lax.dot_general(
                lhs, oh, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32), None
        acc, _ = jax.lax.scan(body, jnp.zeros((3, 1024), jnp.int32),
                              (kb, v0, v1))
        return acc
    timed("onehot_int8_G1024_3col", onehot1024, b_ids, vals)

    # 3. one-hot int8 G=4096 3col
    def onehot4096(i, k, v):
        kb = (k[:n] % 4096).reshape(nblk, BLK)
        v = v + i
        v0 = (v[:n] & 127).astype(jnp.int8).reshape(nblk, BLK)
        v1 = ((v[:n] >> 7) & 127).astype(jnp.int8).reshape(nblk, BLK)
        iota = jnp.arange(4096, dtype=jnp.int32)

        def body(acc, xs):
            kk, l0, l1 = xs
            oh = (kk[:, None] == iota[None, :]).astype(jnp.int8)
            lhs = jnp.stack([jnp.ones((BLK,), jnp.int8), l0, l1], 0)
            return acc + jax.lax.dot_general(
                lhs, oh, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32), None
        acc, _ = jax.lax.scan(body, jnp.zeros((3, 4096), jnp.int32),
                              (kb, v0, v1))
        return acc
    timed("onehot_int8_G4096_3col", onehot4096, key_fused, vals)

    # 4. windowed local-dense W=128 on sorted keys, scan form, 3 aggs
    W = 128
    SUB = 16384  # rows per scan step
    nstep = n // SUB

    def windowed(i, key, v, f):
        ks = key[: nstep * SUB].reshape(nstep, SUB)
        vs = (v + i)[: nstep * SUB].reshape(nstep, SUB)
        fs = f[: nstep * SUB].reshape(nstep, SUB)
        iota = jnp.arange(W, dtype=jnp.int32)

        def body(carry, xs):
            kk, vv, ff = xs      # [SUB]
            kb = kk.reshape(-1, 2048)           # [8, 2048]
            base = kb[:, :1]
            local = kb - base
            ok = (local >= 0) & (local < W)
            oh = (local[:, :, None] == iota[None, None, :]) & ok[:, :, None]
            cnt = oh.sum(1, dtype=jnp.int32)                    # [8, W]
            sm = jnp.where(oh, vv.reshape(-1, 2048)[:, :, None], 0).sum(1)
            mx = jnp.where(oh, ff.reshape(-1, 2048)[:, :, None],
                           -3.4e38).max(1)
            return carry, (base[:, 0], cnt, sm, mx, ok.any())
        _, outs = jax.lax.scan(body, 0, (ks, vs, fs))
        return outs[1:]  # grids (keep on device; L2 combine separate)
    timed("windowed_sorted_W128_L1_scan", windowed, key_sorted, v_sorted,
          f_sorted)

    # 5. blocked VPU G=1024 3agg (current engine) for comparison
    def blocked(i, bk, v, f):
        kb = (bk[:n] % 1024).reshape(nblk, BLK)
        vs = (v + i)[:n].reshape(nblk, BLK)
        fs = f[:n].reshape(nblk, BLK)
        iota = jnp.arange(1024, dtype=jnp.int32)

        def body(acc, xs):
            kk, vv, ff = xs
            valid = kk[:, None] == iota[None, :]
            c = acc[0] + valid.astype(jnp.int32).sum(0, dtype=jnp.int32)
            s = acc[1] + jnp.where(valid, vv[:, None], 0).sum(
                0, dtype=jnp.int32)
            m = jnp.maximum(acc[2], jnp.where(valid, ff[:, None],
                                              -3.4e38).max(0))
            return (c, s, m), None
        acc, _ = jax.lax.scan(body, (jnp.zeros(1024, jnp.int32),
                                     jnp.zeros(1024, jnp.int32),
                                     jnp.full(1024, -3.4e38, jnp.float32)),
                              (kb, vs, fs))
        return acc
    timed("blocked_vpu_G1024_3agg", blocked, b_ids, vals, fvals)

    # 6. segment_sum 1 col G=131072
    def seg(i, k, v):
        return jax.ops.segment_sum(v + i, k, num_segments=131072)
    timed("segment_sum_G131072", seg, key_fused, vals)

    # 7. windowed L2 combine cost: scatter of [nblk8=763x8, W] grids
    grids = jnp.ones((6103, W), jnp.int32)
    bases = jnp.asarray((np.arange(6103) * 17).astype(np.int32))

    def l2(i, g, b):
        keys2 = jnp.clip(b[:, None] + jnp.arange(W, dtype=jnp.int32) + i * 0,
                         0, 131071).ravel()
        return jax.ops.segment_sum(g.ravel(), keys2, num_segments=131072)
    timed("windowed_L2_scatter_781k", l2, grids, bases)

    # H2D size sweep
    for mb in (1, 8, 50):
        arr = np.ones(mb * 262144, np.float32)
        jax.device_put(arr[:16]).block_until_ready()
        t0 = time.perf_counter()
        jax.device_put(arr).block_until_ready()
        dt = time.perf_counter() - t0
        print(f"H2D {mb:3d}MB: {dt*1e3:8.1f} ms   {mb/dt:7.1f} MB/s",
              flush=True)


if __name__ == "__main__":
    main()
