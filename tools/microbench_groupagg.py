"""Microbenchmark of candidate TPU primitives for grouped aggregation.

Decides the round-3 engine strategy: one-hot MXU matmul vs sort vs gather
partition vs scatter. Run on the real chip:  python tools/microbench_groupagg.py
"""
import time
import sys

import numpy as np


def _sync(r):
    # block_until_ready is a no-op through the axon tunnel; force a host
    # read of one element of every output to really synchronize
    import jax
    import numpy as _np
    for leaf in jax.tree.leaves(r):
        _np.asarray(jax.device_get(leaf)).ravel()[:1]


def t(fn, *args, iters=3, warmup=1):
    for _ in range(warmup):
        _sync(fn(*args))
    best = float("inf")
    for _ in range(iters):
        t0 = time.perf_counter()
        _sync(fn(*args))
        best = min(best, time.perf_counter() - t0)
    return best


def main():
    import jax
    import jax.numpy as jnp

    print(f"devices: {jax.devices()}", file=sys.stderr)
    N = 12_500_000          # rows per segment in the headline bench
    A, B = 128, 1024        # padded major/minor cardinality (100 x 1000)
    G = A * B               # 131072 dense group space
    BLK = 8192

    rng = np.random.default_rng(0)
    a_ids = jnp.asarray(rng.integers(0, 100, N, dtype=np.int32))
    b_ids = jnp.asarray(rng.integers(0, 1000, N, dtype=np.int32))
    key = a_ids * 1000 + b_ids
    vals = jnp.asarray(rng.integers(0, 10_000, N, dtype=np.int32))
    fvals = jnp.asarray(rng.normal(100, 25, N).astype(np.float32))

    results = {}

    # 1. segment_sum scatter at G=131072
    @jax.jit
    def seg_sum(k, v):
        return jax.ops.segment_sum(v, k, num_segments=G)
    results["segment_sum_scatter_G131072"] = t(seg_sum, key, vals)

    # 2. segment_max scatter
    @jax.jit
    def seg_max(k, v):
        return jax.ops.segment_max(v, k, num_segments=G)
    results["segment_max_scatter_G131072"] = t(seg_max, key, fvals)

    # 3. blocked VPU broadcast (current engine path) at G=1024
    @jax.jit
    def blocked_vpu(k, v):
        nblk = N // BLK
        kb = k[: nblk * BLK].reshape(nblk, BLK)
        vb = v[: nblk * BLK].reshape(nblk, BLK)
        iota = jnp.arange(1024, dtype=jnp.int32)

        def body(acc, xs):
            kk, vv = xs
            valid = (kk[:, None] % 1024) == iota[None, :]
            acc = (acc[0] + valid.astype(jnp.int32).sum(0, dtype=jnp.int32),
                   acc[1] + jnp.where(valid, vv[:, None], 0).sum(
                       0, dtype=jnp.int32))
            return acc, None

        init = (jnp.zeros(1024, jnp.int32), jnp.zeros(1024, jnp.int32))
        (c, s), _ = jax.lax.scan(body, init, (kb, vb))
        return c, s
    results["blocked_vpu_count+sum_G1024"] = t(blocked_vpu, key, vals)

    # 4. one-hot int8 matmul, G=1024 (minor only): count+2 limb cols
    @jax.jit
    def onehot_matmul_small(bk, v):
        nblk = N // BLK
        kb = (bk[: nblk * BLK] % 1024).reshape(nblk, BLK)
        v0 = (v[: nblk * BLK] & 127).astype(jnp.int8).reshape(nblk, BLK)
        v1 = ((v[: nblk * BLK] >> 7) & 127).astype(jnp.int8).reshape(nblk, BLK)
        iota = jnp.arange(1024, dtype=jnp.int32)

        def body(acc, xs):
            kk, l0, l1 = xs
            oh = (kk[:, None] == iota[None, :]).astype(jnp.int8)
            lhs = jnp.stack([jnp.ones((BLK,), jnp.int8), l0, l1], 0)  # [3,BLK]
            out = jax.lax.dot_general(
                lhs, oh, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32)  # [3, 1024]
            return acc + out, None

        acc0 = jnp.zeros((3, 1024), jnp.int32)
        acc, _ = jax.lax.scan(body, acc0, (kb, v0, v1))
        return acc
    results["onehot_int8_matmul_G1024_3col"] = t(onehot_matmul_small, b_ids, vals)

    # 5. two-level one-hot int8 matmul, G=131072: lhs=[3*A, BLK] @ [BLK, B]
    @jax.jit
    def onehot_matmul_2level(ka, kb_, v):
        nblk = N // BLK
        kaa = ka[: nblk * BLK].reshape(nblk, BLK)
        kbb = kb_[: nblk * BLK].reshape(nblk, BLK)
        v0 = (v[: nblk * BLK] & 127).astype(jnp.int8).reshape(nblk, BLK)
        v1 = ((v[: nblk * BLK] >> 7) & 127).astype(jnp.int8).reshape(nblk, BLK)
        iota_a = jnp.arange(A, dtype=jnp.int32)
        iota_b = jnp.arange(B, dtype=jnp.int32)

        def body(acc, xs):
            kk_a, kk_b, l0, l1 = xs
            oh_a = (kk_a[:, None] == iota_a[None, :])  # [BLK, A] bool
            oh_b = (kk_b[:, None] == iota_b[None, :]).astype(jnp.int8)
            lhs = jnp.concatenate([
                oh_a.astype(jnp.int8),
                jnp.where(oh_a, l0[:, None], 0).astype(jnp.int8),
                jnp.where(oh_a, l1[:, None], 0).astype(jnp.int8),
            ], axis=1)  # [BLK, 3A]
            out = jax.lax.dot_general(
                lhs, oh_b, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.int32)  # [3A, B]
            return acc + out, None

        acc0 = jnp.zeros((3 * A, B), jnp.int32)
        acc, _ = jax.lax.scan(body, acc0, (kaa, kbb, v0, v1))
        return acc
    results["onehot_int8_2level_G131072_3col"] = t(
        onehot_matmul_2level, a_ids, b_ids, vals)

    # 5b. bf16 variant of two-level (f32 accum)
    @jax.jit
    def onehot_matmul_2level_bf16(ka, kb_, v):
        nblk = N // BLK
        kaa = ka[: nblk * BLK].reshape(nblk, BLK)
        kbb = kb_[: nblk * BLK].reshape(nblk, BLK)
        vv = v[: nblk * BLK].astype(jnp.bfloat16).reshape(nblk, BLK)
        iota_a = jnp.arange(A, dtype=jnp.int32)
        iota_b = jnp.arange(B, dtype=jnp.int32)

        def body(acc, xs):
            kk_a, kk_b, x = xs
            oh_a = (kk_a[:, None] == iota_a[None, :])
            oh_b = (kk_b[:, None] == iota_b[None, :]).astype(jnp.bfloat16)
            lhs = jnp.concatenate([
                oh_a.astype(jnp.bfloat16),
                jnp.where(oh_a, x[:, None], 0).astype(jnp.bfloat16),
            ], axis=1)
            out = jax.lax.dot_general(
                lhs, oh_b, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            return acc + out, None

        acc0 = jnp.zeros((2 * A, B), jnp.float32)
        acc, _ = jax.lax.scan(body, acc0, (kaa, kbb, vv))
        return acc
    results["onehot_bf16_2level_G131072_2col"] = t(
        onehot_matmul_2level_bf16, a_ids, b_ids, fvals)

    # 6. sort: key+1 payload / key+3 payloads
    @jax.jit
    def sort1(k, v):
        return jax.lax.sort_key_val(k, v)
    results["sort_key_1payload_12.5M"] = t(sort1, key, vals)

    @jax.jit
    def sort3(k, v1, v2, v3):
        return jax.lax.sort((k, v1, v2, v3), num_keys=1)
    results["sort_key_3payload_12.5M"] = t(sort3, key, vals, fvals, b_ids)

    # 7. gather: permutation apply (N from N) and remap (N from 131072)
    perm = jnp.asarray(rng.permutation(N).astype(np.int32))

    @jax.jit
    def gatherN(v, p):
        return v[p]
    results["gather_N_from_N"] = t(gatherN, vals, perm)

    small_tab = jnp.asarray(rng.integers(0, 99, G, dtype=np.int32))

    @jax.jit
    def gather_small(k, tab):
        return tab[k]
    results["gather_N_from_131072"] = t(gather_small, key, small_tab)

    tab1k = jnp.asarray(rng.integers(0, 99, 1024, dtype=np.int32))

    @jax.jit
    def gather_1k(k, tab):
        return tab[k % 1024]
    results["gather_N_from_1024"] = t(gather_1k, key, tab1k)

    # 8. blocked minor-onehot masked max (G=1024), the partitioned-max path
    @jax.jit
    def blocked_max_minor(bk, v):
        nblk = N // BLK
        kb = (bk[: nblk * BLK] % 1024).reshape(nblk, BLK)
        vb = v[: nblk * BLK].reshape(nblk, BLK)
        iota = jnp.arange(1024, dtype=jnp.int32)
        neg = jnp.float32(-3.4e38)

        def body(acc, xs):
            kk, vv = xs
            m = jnp.where(kk[:, None] == iota[None, :], vv[:, None], neg)
            return jnp.maximum(acc, m.max(0)), None

        acc0 = jnp.full((1024,), neg, jnp.float32)
        acc, _ = jax.lax.scan(body, acc0, (kb, vb))
        return acc
    results["blocked_max_minor_G1024"] = t(blocked_max_minor, b_ids, fvals)

    # 9. cumsum ranks for counting sort: [BLK, 128] within-block cumsum scan
    @jax.jit
    def count_ranks(ka):
        nblk = N // BLK
        kaa = ka[: nblk * BLK].reshape(nblk, BLK)
        iota = jnp.arange(A, dtype=jnp.int32)

        def body(offs, kk):
            oh = (kk[:, None] == iota[None, :]).astype(jnp.int32)
            within = jnp.cumsum(oh, axis=0) - oh
            rank = offs[None, :] + within
            pos = (rank * oh).sum(1)
            return offs + oh.sum(0), pos

        offs0 = jnp.zeros((A,), jnp.int32)
        _, pos = jax.lax.scan(body, offs0, kaa)
        return pos
    results["counting_ranks_A128"] = t(count_ranks, a_ids)

    # 10. full pipeline estimate: ranks + 4x gather
    for k, v in results.items():
        rate = N / v / 1e6
        print(f"{k:42s} {v*1e3:9.2f} ms   {rate:9.0f} M rows/s")


if __name__ == "__main__":
    main()
