"""Profile the headline bench queries: where do the milliseconds go?

Breaks the groupBy/topN execution into phases (device program, host merge,
finish) at bench-identical per-segment scale. Run on the real chip.
"""
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ROWS = int(os.environ.get("PROF_ROWS", 25_000_000))
NSEG = int(os.environ.get("PROF_SEGMENTS", 2))


def log(msg):
    print(msg, flush=True)


def timeit(label, fn, iters=3):
    fn()  # warm
    best = float("inf")
    for _ in range(iters):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    n = ROWS
    log(f"{label:48s} {best*1e3:9.1f} ms   {n/best/1e6:8.0f} M rows/s")
    return best


def main():
    import jax
    log(f"devices: {jax.devices()}")

    from druid_tpu.data.generator import ColumnSpec, DataGenerator
    from druid_tpu.engine import QueryExecutor
    from druid_tpu.engine import engines
    from druid_tpu.engine.grouping import run_grouped_aggregate
    from druid_tpu.parallel import make_mesh
    from druid_tpu.query.aggregators import (CountAggregator,
                                             FloatMaxAggregator,
                                             LongSumAggregator)
    from druid_tpu.query.filters import BoundFilter, InFilter
    from druid_tpu.query.model import (DefaultDimensionSpec, GroupByQuery,
                                       TopNQuery)
    from druid_tpu.utils.intervals import Interval

    schema = (
        ColumnSpec("dimA", "string", cardinality=100, distribution="uniform"),
        ColumnSpec("dimB", "string", cardinality=1000, distribution="zipf"),
        ColumnSpec("metLong", "long", low=0, high=10_000),
        ColumnSpec("metFloat", "float", distribution="normal", mean=100.0,
                   std=25.0),
    )
    interval = Interval.of("2026-01-01", "2026-01-02")
    t0 = time.time()
    gen = DataGenerator(schema, seed=1234)
    segments = gen.segments(NSEG, ROWS // NSEG, interval, datasource="bench")
    log(f"generated {sum(s.n_rows for s in segments):,} rows "
        f"({time.time()-t0:.1f}s)")

    groupby = GroupByQuery.of(
        "bench", [interval],
        [DefaultDimensionSpec("dimA"), DefaultDimensionSpec("dimB")],
        [CountAggregator("rows"), LongSumAggregator("lsum", "metLong"),
         FloatMaxAggregator("fmax", "metFloat")],
        granularity="all",
        filter=BoundFilter("metLong", lower=100, upper=9_900,
                           ordering="numeric"))
    dimA_vals = list(segments[0].dims["dimA"].dictionary.values)
    topn = TopNQuery.of(
        "bench", [interval], "dimB", "lsum", 100,
        [CountAggregator("rows"), LongSumAggregator("lsum", "metLong")],
        granularity="all",
        filter=InFilter("dimA", dimA_vals[0:100:2]))

    ex_mesh = QueryExecutor(segments, mesh=make_mesh(1))
    ex_nomesh = QueryExecutor(segments, mesh=None)

    # strategy report
    from druid_tpu.engine import grouping
    orig = grouping.select_strategy
    picks = []

    def spy(*a, **kw):
        r = orig(*a, **kw)
        picks.append(r)
        return r
    grouping.select_strategy = spy
    import druid_tpu.parallel.distributed as dist
    dist.select_strategy = spy
    ex_mesh.run(groupby)
    log(f"groupBy strategy picks (mesh): {picks}")
    picks.clear()
    ex_nomesh.run(groupby)
    log(f"groupBy strategy picks (no mesh): {picks}")
    picks.clear()
    ex_mesh.run(topn)
    log(f"topN strategy picks (mesh): {picks}")
    picks.clear()
    grouping.select_strategy = orig
    dist.select_strategy = orig

    timeit("groupBy full (mesh)", lambda: ex_mesh.run(groupby))
    timeit("groupBy full (no mesh)", lambda: ex_nomesh.run(groupby))
    timeit("topN full (mesh)", lambda: ex_mesh.run(topn))
    timeit("topN full (no mesh)", lambda: ex_nomesh.run(topn))

    # phase split: partials vs finish (no-mesh path)
    ap_holder = {}

    def partials_only(q):
        ap_holder["ap"] = engines.make_aggregate_partials(q, segments)

    timeit("groupBy partials only (no mesh)",
           lambda: partials_only(groupby))
    ap = ap_holder["ap"]
    timeit("groupBy finish only",
           lambda: engines.finish_groupby(groupby, ap))
    timeit("topN partials only (no mesh)", lambda: partials_only(topn))
    ap = ap_holder["ap"]
    timeit("topN finish only", lambda: engines.finish_topn(topn, ap))

    # single-segment device program, full pipeline vs raw
    s0 = segments[0]
    ivs = [interval]

    def one_seg_gb():
        run_grouped_aggregate(
            s0, ivs, groupby.granularity,
            [grouping.KeyDim("dimA", 100, None),
             grouping.KeyDim("dimB", 1000, None)],
            groupby.aggregations, groupby.filter)

    t = timeit("groupBy 1seg run_grouped_aggregate", one_seg_gb, iters=3)
    log(f"  (per-row at 1 seg: {ROWS/NSEG/t/1e6:.0f} M rows/s)")


if __name__ == "__main__":
    main()
