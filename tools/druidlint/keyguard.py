"""keyguard: whole-program cache-key soundness analysis.

Every compressed-execution layer in this tree specializes a device
program on some descriptor, and every specialization is a cache-key
obligation: the jit caches, the device-segment pool, the plan digests
and the dedupe keys must each distinguish every input that changes the
built value. The invariant has been hand-enforced since PR 9, and the
review history shows what a missed key member costs (a silently-shared
log2m program, two subscribers with different emission policies sharing
one standing program). keyguard makes the obligation machine-checked,
riding raceguard's whole-program index (same module set, binder and
mtime/size cache signature) the way leakguard does.

Three rules on the shared registry/baseline/suppression machinery:

  * `unkeyed-trace-input` — at every build-on-miss cache site
    (``CACHE[sig] = build(...)`` guarded by a ``.get``/``in`` miss
    check, ``CACHE.setdefault(sig, build(...))``, and
    ``pool.get_or_build(owner, key, lambda: ...)``), the build's input
    chains must each have dataflow into the key expression. Also checks
    configured key-derivation functions (`keyguard-key-fns`,
    "path::qual" entries): every parameter must flow into the returned
    signature — deleting one descriptor from `_structure_sig`'s fold is
    caught here.
  * `impure-eligibility` — functions named in `keyguard-eligibility`
    (packed/cascade eligibility, standing `check_eligible`, broker
    `fusable`) must be pure functions of column stats, descriptors and
    query structure: no os.environ, clock, random or device-pool reads
    at query time (own statements plus same-module callees, two deep).
  * `env-flag-latch` — a ``DRUID_TPU_*`` read inside plan/build modules
    (`keyguard-plan-modules`) must match its declared semantics in the
    flags catalog (druid_tpu/config/flags.py): latch flags are read at
    import only, live flags are read at call time only and must be
    declared key members — a mid-process flag flip must never alias a
    cached program.

The dataflow is over *dotted chains* (``ref.kds``, ``mesh.shape``),
expanded transitively through local assignments on both the key and the
build side, so ``sig = _structure_sig(spec, ...)`` keys and
``fn = _build(...)`` inserts resolve to their real inputs. A build
chain is covered when some key chain equals it or is a dotted prefix of
it in either direction (keying on ``x.key`` covers inserting ``x``).
"""
from __future__ import annotations

import ast
import builtins
import fnmatch
from pathlib import Path
from types import SimpleNamespace
from typing import Dict, Iterable, List, Optional, Set, Tuple

from tools.druidlint.core import Finding, ModuleContext, rule
from tools.druidlint.raceguard import ModuleInfo, Program, _program_for

_FUNC_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)

Chain = Tuple[str, ...]

#: receiver methods treated as "writes into the receiver" by the
#: chain/param dataflow (x.append(e) makes e reach x)
_MUTATORS = {"append", "add", "update", "extend", "insert", "appendleft",
             "setdefault"}

#: receiver methods that mark an instance attribute as mutable state
#: (beyond _MUTATORS: removal also proves the attr changes over time)
_STATE_MUTATORS = _MUTATORS | {"pop", "popitem", "clear", "remove",
                               "discard"}

#: builtins whose result carries no content fingerprint of their
#: arguments — `K = len(chunk)` does NOT put `chunk` into a key
_SIZE_ONLY = {"len", "bool", "type", "isinstance", "any", "all"}

#: constructors recognized as fresh per-call dicts (alongside literal
#: displays) — a local accumulator, not a cross-call cache
_DICT_CTORS = {"dict", "OrderedDict", "defaultdict", "Counter"}

_TIME_FNS = {"time", "monotonic", "perf_counter", "time_ns",
             "process_time"}

#: pool-probe terminals: reading (or populating) device-pool state from
#: an eligibility predicate makes eligibility depend on what happens to
#: be resident — two identical queries would plan differently
_POOL_PROBES = {"device_contains", "device_take", "peek",
                "resident_bytes", "stats", "get_or_build"}


# ---------------------------------------------------------------------------
# Flags catalog (AST-parsed, never imported — same pattern as the
# metric-name rule's METRICS catalog)
# ---------------------------------------------------------------------------

#: parsed catalogs keyed by absolute path; value = ((mtime_ns, size), {..})
_FLAG_CACHE: Dict[str, Tuple[Tuple[int, int], Dict[str, dict]]] = {}


def flag_catalog(root: str, rel: str) -> Dict[str, dict]:
    """{env name: {"semantics", "key_member", "default"}} parsed from the
    FLAGS dict literal (config `flags-catalog`). A missing or unparseable
    catalog declares nothing — env-flag-latch then stays silent and the
    flag-name rule flags every read, so the gate fails loudly."""
    p = Path(root) / rel
    try:
        st = p.stat()
        key = (st.st_mtime_ns, st.st_size)
    except OSError:
        return {}
    hit = _FLAG_CACHE.get(str(p))
    if hit is not None and hit[0] == key:
        return hit[1]
    try:
        tree = ast.parse(p.read_text())
    except (OSError, SyntaxError):
        return {}
    out: Dict[str, dict] = {}
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name) and t.id == "FLAGS"
                        for t in node.targets)
                and isinstance(node.value, ast.Dict)):
            continue
        for k, v in zip(node.value.keys, node.value.values):
            if not (isinstance(k, ast.Constant)
                    and isinstance(k.value, str)):
                continue
            decl = {"semantics": "latch", "key_member": False,
                    "default": ""}
            if isinstance(v, ast.Call):
                for kw in v.keywords:
                    if kw.arg in decl and isinstance(kw.value,
                                                     ast.Constant):
                        decl[kw.arg] = kw.value.value
            out[k.value] = decl
    _FLAG_CACHE[str(p)] = (key, out)
    return out


# ---------------------------------------------------------------------------
# Dotted-chain extraction + local dataflow
# ---------------------------------------------------------------------------

def _chain_of(node: ast.AST) -> Optional[Chain]:
    """('ref', 'kds') for a pure dotted expression, None otherwise."""
    if isinstance(node, ast.Name):
        return (node.id,)
    if isinstance(node, ast.Attribute):
        base = _chain_of(node.value)
        return base + (node.attr,) if base is not None else None
    return None


def _chains_in(node: ast.AST,
               bound: Iterable[str] = ()) -> Set[Chain]:
    """Maximal dotted chains read by an expression. Callee names are not
    data (``len(x)`` yields only ``x``; ``mod.helper(x)`` yields ``mod``
    via the receiver, which the module-binding exemption then drops), and
    comprehension targets/lambda params resolve to their iterators."""
    out: Set[Chain] = set()

    def visit(n: ast.AST, shadowed: Set[str]) -> None:
        ch = _chain_of(n)
        if ch is not None:
            if ch[0] not in shadowed:
                out.add(ch)
            return
        if isinstance(n, ast.Call):
            if isinstance(n.func, ast.Name) and n.func.id in _SIZE_ONLY:
                return        # len(x) etc. carry no content of x
            if isinstance(n.func, ast.Attribute):
                visit(n.func.value, shadowed)
            for a in n.args:
                visit(a, shadowed)
            for kw in n.keywords:
                visit(kw.value, shadowed)
            return
        if isinstance(n, (ast.ListComp, ast.SetComp, ast.GeneratorExp,
                          ast.DictComp)):
            targets: Set[str] = set()
            for g in n.generators:
                visit(g.iter, shadowed | targets)
                targets |= {x.id for x in ast.walk(g.target)
                            if isinstance(x, ast.Name)}
                for cond in g.ifs:
                    visit(cond, shadowed | targets)
            inner = shadowed | targets
            if isinstance(n, ast.DictComp):
                visit(n.key, inner)
                visit(n.value, inner)
            else:
                visit(n.elt, inner)
            return
        if isinstance(n, ast.Lambda):
            a = n.args
            params = {x.arg for x in (*a.posonlyargs, *a.args,
                                      *a.kwonlyargs)}
            for extra in (a.vararg, a.kwarg):
                if extra is not None:
                    params.add(extra.arg)
            visit(n.body, shadowed | params)
            return
        for c in ast.iter_child_nodes(n):
            visit(c, shadowed)

    visit(node, set(bound))
    return out


def _own_nodes(fn: ast.AST) -> Iterable[ast.AST]:
    """Every node in `fn`'s body except nested def/class bodies (their
    locals are a different scope; nested lambdas stay in — they close
    over this scope)."""
    stack = list(getattr(fn, "body", []))
    while stack:
        n = stack.pop()
        yield n
        for c in ast.iter_child_nodes(n):
            if isinstance(c, (*_FUNC_DEFS, ast.ClassDef)):
                continue
            stack.append(c)


def _local_defs(own: List[ast.AST]) -> Dict[str, List[Tuple[ast.AST,
                                                             bool]]]:
    """name → [(value node, elementwise)] for everything ever assigned
    or accumulated into it in this function. `elementwise` marks
    bindings where the name holds an ELEMENT of the value (loop targets,
    tuple unpacking, .append args): attribute projections carry through
    (``for s in segments`` makes ``s.id`` resolve to ``segments.id``)."""
    out: Dict[str, List[Tuple[ast.AST, bool]]] = {}

    def put(name: str, node: ast.AST, elementwise: bool) -> None:
        out.setdefault(name, []).append((node, elementwise))

    def put_target(t: ast.AST, node: ast.AST, elementwise: bool) -> None:
        if isinstance(t, ast.Name):
            put(t.id, node, elementwise)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                put_target(e, node, True)
        elif isinstance(t, ast.Starred):
            put_target(t.value, node, True)

    for n in own:
        if isinstance(n, ast.Assign):
            for t in n.targets:
                put_target(t, n.value, False)
        elif isinstance(n, ast.AugAssign) and isinstance(n.target,
                                                         ast.Name):
            put(n.target.id, n.value, False)
        elif isinstance(n, ast.AnnAssign) and n.value is not None \
                and isinstance(n.target, ast.Name):
            put(n.target.id, n.value, False)
        elif isinstance(n, (ast.For, ast.AsyncFor)):
            put_target(n.target, n.iter, True)
        elif isinstance(n, ast.withitem) and n.optional_vars is not None:
            put_target(n.optional_vars, n.context_expr, False)
        elif isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                and n.func.attr in _MUTATORS:
            recv = _chain_of(n.func.value)
            if recv is not None and len(recv) == 1:
                for a in n.args:
                    put(recv[0], a, True)
                for kw in n.keywords:
                    put(recv[0], kw.value, True)
    return out


def _resolve_chain(chain: Chain,
                   defs: Dict[str, List[Tuple[ast.AST, bool]]],
                   seen: frozenset) -> Set[Chain]:
    """Ground forms of `chain` by SUBSTITUTING locally-assigned roots
    with what they were assigned from — ``ref.kds`` with
    ``ref = chunk[0]`` becomes ``chunk.kds``, keeping projections
    distinct (accumulating ancestors instead would collapse ``ref.kds``
    and ``ref.spec`` into one origin and hide unkeyed inputs). Cycles
    (self-referential accumulators) return the chain unresolved; the
    caller drops still-local roots."""
    root, rest = chain[0], chain[1:]
    entries = defs.get(root)
    if not entries or root in seen:
        return {chain}
    out: Set[Chain] = set()
    nxt = seen | {root}
    for node, elementwise in entries:
        base = _chain_of(node)
        if base is not None:
            out |= _resolve_chain(base + rest, defs, nxt)
            continue
        keep_rest = elementwise or isinstance(node, ast.Subscript)
        for c in _chains_in(node):
            out |= _resolve_chain(c + rest if keep_rest else c,
                                  defs, nxt)
    return out or {chain}


def _resolve_set(seeds: Set[Chain],
                 defs: Dict[str, List[Tuple[ast.AST, bool]]]) \
        -> Set[Chain]:
    out: Set[Chain] = set()
    for c in seeds:
        out |= _resolve_chain(c, defs, frozenset())
    return out


def _covers(key_chains: Set[Chain], b: Chain) -> bool:
    """A key chain covers build chain `b` when equal or a dotted prefix
    in either direction (keying on `x.key` covers inserting `x`; keying
    on `mesh` covers reading `mesh.shape`)."""
    for k in key_chains:
        m = min(len(k), len(b))
        if m and k[:m] == b[:m]:
            return True
    return False


def _exempt_roots(mi: Optional[ModuleInfo], tree: ast.AST) -> Set[str]:
    """Root names that are never trace-affecting data: imports, module
    functions/classes, and module constants (every toplevel assignment a
    literal). Module vars assigned non-constant expressions — latched
    flags, descriptor tables — stay checkable."""
    roots: Set[str] = set()
    if mi is not None:
        roots |= set(mi.imports)
        for name, kind in mi.globals.items():
            if kind and kind[0] in ("func", "class"):
                roots.add(name)
    const: Set[str] = set()
    nonconst: Set[str] = set()
    for stmt in getattr(tree, "body", []):
        targets = []
        value = None
        if isinstance(stmt, ast.Assign):
            targets, value = stmt.targets, stmt.value
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            targets, value = [stmt.target], stmt.value
        else:
            continue
        names = [t.id for t in targets if isinstance(t, ast.Name)]
        if isinstance(value, ast.Constant):
            const.update(names)
        else:
            nonconst.update(names)
    roots |= const - nonconst
    return roots


# ---------------------------------------------------------------------------
# unkeyed-trace-input: cache sites
# ---------------------------------------------------------------------------

def _fmt(ch: Chain) -> str:
    return ".".join(ch)


_BUILTINS = frozenset(dir(builtins))


#: (rebind chains, content-mutation chains) — the no-mutation default
_NO_MUT: Tuple[Set[Chain], Set[Chain]] = (set(), set())


def _checkable(ch: Chain, cont: Optional[Chain],
               defs: Dict[str, List[Tuple[ast.AST, bool]]],
               exempt: Set[str],
               self_mut: Tuple[Set[Chain], Set[Chain]]) -> bool:
    root = ch[0]
    if root == "cls" or ch == ("self",):
        return False
    if root == "self":
        # frozen construction state (only ever assigned in __init__)
        # cannot alias two builds — only live instance state counts.
        # A REBIND (self.x = ..., outside __init__) taints every chain
        # through x in either prefix direction; an in-place CONTENT
        # mutation (self.x[k] = / self.x.append) taints only reads of
        # the container itself — pool.add(row) never moves pool.name
        rel = ch[1:]
        rebind, content = self_mut
        live = rel in content or any(
            m[:len(rel)] == rel or rel[:len(m)] == m for m in rebind)
        if not live:
            return False
    if root in defs:      # unresolved cycle (self-referential local)
        return False
    if root in exempt or root in _BUILTINS or root.startswith("__"):
        return False
    if cont is not None and len(ch) >= len(cont) \
            and ch[:len(cont)] == cont:
        return False          # the cache itself (double-check reads)
    return True


def _self_rel(node: ast.AST) -> Optional[Tuple[Chain, bool]]:
    """(chain after 'self', is_content_mutation) for a self.* store
    target: plain attribute targets rebind, subscript stores mutate
    contents in place."""
    content = isinstance(node, ast.Subscript)
    if content:
        node = node.value
    ch = _chain_of(node)
    if ch is not None and len(ch) >= 2 and ch[0] == "self":
        return ch[1:], content
    return None


def _mutated_attrs(cls: ast.ClassDef) -> Tuple[Set[Chain], Set[Chain]]:
    """Self-relative chains mutated OUTSIDE __init__/__new__, split into
    (rebound, content-mutated) — the state whose value can differ
    between two builds under the same key."""
    rebind: Set[Chain] = set()
    content: Set[Chain] = set()
    for m in ast.walk(cls):
        if not isinstance(m, _FUNC_DEFS) \
                or m.name in ("__init__", "__new__"):
            continue
        for n in ast.walk(m):
            targets: List[ast.AST] = []
            if isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = n.targets if isinstance(n, ast.Assign) \
                    else [n.target]
            elif isinstance(n, ast.Delete):
                targets = list(n.targets)
            elif isinstance(n, ast.Call) \
                    and isinstance(n.func, ast.Attribute) \
                    and n.func.attr in _STATE_MUTATORS:
                recv = _chain_of(n.func.value)
                if recv is not None and len(recv) >= 2 \
                        and recv[0] == "self":
                    content.add(recv[1:])
                continue
            for t in targets:
                got = _self_rel(t)
                if got is not None:
                    (content if got[1] else rebind).add(got[0])
    return rebind, content


def _class_map(tree: ast.AST) -> Dict[int, ast.ClassDef]:
    """id(function node) → nearest enclosing ClassDef."""
    out: Dict[int, ast.ClassDef] = {}

    def walk(node: ast.AST, cls: Optional[ast.ClassDef]) -> None:
        for c in ast.iter_child_nodes(node):
            if isinstance(c, ast.ClassDef):
                walk(c, c)
            else:
                if isinstance(c, _FUNC_DEFS) and cls is not None:
                    out[id(c)] = cls
                walk(c, cls)

    walk(tree, None)
    return out


def _if_context(fn: ast.AST) -> Tuple[Dict[int, List[ast.expr]],
                                      List[ast.expr]]:
    """(id(stmt) → enclosing If tests, tests that guard an early return).
    Both forms of the build-on-miss shape leave their miss check here:
    the insert nested under ``if hit is None:`` or a hit path that
    returns early above an unconditional build."""
    enclosing: Dict[int, List[ast.expr]] = {}
    ret_tests: List[ast.expr] = []

    def walk(body: List[ast.stmt], tests: List[ast.expr]) -> None:
        for s in body:
            enclosing[id(s)] = tests
            if isinstance(s, ast.Return):
                ret_tests.extend(tests)
            if isinstance(s, ast.If):
                walk(s.body, tests + [s.test])
                walk(s.orelse, tests + [s.test])
            elif isinstance(s, (ast.For, ast.AsyncFor, ast.While)):
                walk(s.body, tests)
                walk(s.orelse, tests)
            elif isinstance(s, (ast.With, ast.AsyncWith)):
                walk(s.body, tests)
            elif isinstance(s, ast.Try):
                for b in (s.body, s.orelse, s.finalbody):
                    walk(b, tests)
                for h in s.handlers:
                    walk(h.body, tests)
            elif isinstance(s, ast.Match):
                for case in s.cases:
                    walk(case.body, tests)

    walk(list(getattr(fn, "body", [])), [])
    return enclosing, ret_tests


def _scan_fn_sites(path: str, fn: ast.AST, exempt: Set[str],
                   self_mut: Tuple[Set[Chain], Set[Chain]], add) -> None:
    own = list(_own_nodes(fn))
    defs = _local_defs(own)
    # nested defs are code, not data — their closures read this scope's
    # locals, which the chain dataflow already tracks by name
    exempt = exempt | {n.name for n in own
                       if isinstance(n, (*_FUNC_DEFS, ast.ClassDef))}

    # miss-check evidence: container chain → key expressions it was
    # probed with (.get(k) / k in C). An insert only counts as a cache
    # site when the SAME container was miss-checked with the SAME key
    # expression — that is the build-on-miss shape; registries and
    # merge-dicts probed with other keys stay out
    checked: Dict[Chain, Set[str]] = {}
    local_dicts: Set[str] = set()
    missvars: Set[str] = set()     # names holding a miss-probe result
    for n in own:
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                and n.func.attr == "get" and n.args:
            ch = _chain_of(n.func.value)
            if ch is not None:
                checked.setdefault(ch, set()).add(ast.dump(n.args[0]))
        elif isinstance(n, ast.Compare) \
                and any(isinstance(op, (ast.In, ast.NotIn))
                        for op in n.ops):
            for cmp in n.comparators:
                ch = _chain_of(cmp)
                if ch is not None:
                    checked.setdefault(ch, set()).add(ast.dump(n.left))
        elif isinstance(n, (ast.Assign, ast.AnnAssign)):
            value = n.value
            if isinstance(value, (ast.Dict, ast.DictComp)) \
                    or (isinstance(value, ast.Call)
                        and isinstance(value.func, ast.Name)
                        and value.func.id in _DICT_CTORS):
                targets = n.targets if isinstance(n, ast.Assign) \
                    else [n.target]
                local_dicts |= {t.id for t in targets
                                if isinstance(t, ast.Name)}
    for n in own:
        value = None
        targets: List[ast.AST] = []
        if isinstance(n, ast.Assign):
            value, targets = n.value, n.targets
        elif isinstance(n, ast.NamedExpr):
            value, targets = n.value, [n.target]
        if isinstance(value, ast.Call) \
                and isinstance(value.func, ast.Attribute) \
                and value.func.attr == "get" \
                and _chain_of(value.func.value) in checked:
            missvars |= {t.id for t in targets if isinstance(t, ast.Name)}

    enclosing, ret_tests = _if_context(fn)

    def _mentions(test: ast.expr, cont: Chain) -> bool:
        for ch in _chains_in(test):
            if ch[:len(cont)] == cont or ch[0] in missvars:
                return True
        return False

    def _miss_guarded(site: ast.AST, cont: Chain) -> bool:
        """The insert is control-dependent on the miss check — nested
        under an If that tests the container/probe result, or downstream
        of a hit path that returned early on one. Unconditional stores
        (registries, last-write-wins maps) are not build-on-miss caches."""
        return any(_mentions(t, cont)
                   for t in enclosing.get(id(site), ())) \
            or any(_mentions(t, cont) for t in ret_tests)

    sites = []   # (anchor, container-chain, key expr, build chains)
    for n in own:
        if isinstance(n, ast.Assign):
            for t in n.targets:
                if not isinstance(t, ast.Subscript):
                    continue
                cont = _chain_of(t.value)
                if cont is None \
                        or ast.dump(t.slice) not in checked.get(cont, ()):
                    continue     # no build-on-miss evidence: not a cache
                if not _miss_guarded(n, cont):
                    continue     # unconditional store: registry, not cache
                if len(cont) == 1 and cont[0] in local_dicts:
                    continue     # per-call dict, dies with the frame
                if isinstance(n.value, ast.Constant):
                    continue     # sentinel insert
                raw = _chains_in(n.value)
                if any(len(c) >= len(cont) and c[:len(cont)] == cont
                       for c in raw):
                    continue     # d[k] = d.get(k, 0) + v — accumulator
                sites.append((n, cont, t.slice, raw))
        elif isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute):
            if n.func.attr == "setdefault" and len(n.args) >= 2:
                cont = _chain_of(n.func.value)
                if cont is None:
                    continue
                if len(cont) == 1 and cont[0] in local_dicts:
                    continue
                if isinstance(n.args[1], ast.Constant):
                    continue
                sites.append((n, cont, n.args[0],
                              _chains_in(n.args[1])))
            elif n.func.attr == "get_or_build" and len(n.args) >= 3:
                cont = _chain_of(n.func.value)
                builder = n.args[2]
                if isinstance(builder, ast.Name):
                    # look through `build = lambda: ...` locals; any
                    # other callable value is opaque (caller-supplied)
                    for s in own:
                        if isinstance(s, ast.Assign) \
                                and isinstance(s.value, ast.Lambda) \
                                and any(isinstance(t, ast.Name)
                                        and t.id == builder.id
                                        for t in s.targets):
                            builder = s.value
                            break
                if not isinstance(builder, ast.Lambda):
                    continue
                a = builder.args
                params = {x.arg for x in (*a.posonlyargs, *a.args,
                                          *a.kwonlyargs)}
                sites.append((n, cont, n.args[1],
                              _chains_in(builder.body, params)))

    for anchor, cont, key_expr, raw_build in sites:
        raw_key = _chains_in(key_expr)
        if not raw_key:
            continue    # constant-keyed default-fill, not a keyed cache
        key_chains = raw_key | _resolve_set(raw_key, defs)
        build_chains = _resolve_set(raw_build, defs)
        uncovered = sorted(
            _fmt(b) for b in build_chains
            if _checkable(b, cont, defs, exempt, self_mut)
            and not _covers(key_chains, b))
        if uncovered:
            name = _fmt(cont) if cont is not None else "cache"
            add("unkeyed-trace-input", path, anchor.lineno,
                anchor.col_offset,
                f"cache '{name}': build input(s) "
                f"{', '.join(uncovered)} have no dataflow into the key "
                f"— two different builds can alias under one cached "
                f"entry; key them or suppress with the invariant that "
                f"keeps them equal per key")


# ---------------------------------------------------------------------------
# unkeyed-trace-input: key-derivation functions (param → return flow)
# ---------------------------------------------------------------------------

def _names_in(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _param_flow_missing(fn: ast.AST) -> List[str]:
    """Parameters of a key function with no dataflow into any return —
    the produced signature cannot distinguish their values."""
    a = fn.args
    params = [x.arg for x in (*a.posonlyargs, *a.args, *a.kwonlyargs)]
    for extra in (a.vararg, a.kwarg):
        if extra is not None:
            params.append(extra.arg)
    params = [p for p in params
              if p not in ("self", "cls") and not p.startswith("_")]
    own = list(_own_nodes(fn))
    needed: Set[str] = set()
    for n in own:
        if isinstance(n, ast.Return) and n.value is not None:
            needed |= _names_in(n.value)
    changed = True
    while changed:
        changed = False
        for n in own:
            src, dsts = None, []
            if isinstance(n, ast.Assign):
                src, dsts = n.value, n.targets
            elif isinstance(n, ast.AugAssign):
                src, dsts = n.value, [n.target]
            elif isinstance(n, ast.AnnAssign) and n.value is not None:
                src, dsts = n.value, [n.target]
            elif isinstance(n, (ast.For, ast.AsyncFor)):
                src, dsts = n.iter, [n.target]
            elif isinstance(n, ast.Call) \
                    and isinstance(n.func, ast.Attribute) \
                    and n.func.attr in _MUTATORS:
                recv = _chain_of(n.func.value)
                if recv is not None and recv[0] in needed:
                    new: Set[str] = set()
                    for x in n.args:
                        new |= _names_in(x)
                    for kw in n.keywords:
                        new |= _names_in(kw.value)
                    if not new <= needed:
                        needed |= new
                        changed = True
                continue
            else:
                continue
            dst_names: Set[str] = set()
            for d in dsts:
                dst_names |= _names_in(d)
            if dst_names & needed:
                new = _names_in(src)
                if not new <= needed:
                    needed |= new
                    changed = True
    return [p for p in params if p not in needed]


def _qual_funcs(tree: ast.AST) -> Dict[str, ast.AST]:
    out: Dict[str, ast.AST] = {}

    def walk(node: ast.AST, prefix: str) -> None:
        for c in ast.iter_child_nodes(node):
            if isinstance(c, _FUNC_DEFS):
                q = prefix + c.name
                out[q] = c
                walk(c, q + ".")
            elif isinstance(c, ast.ClassDef):
                walk(c, prefix + c.name + ".")
            else:
                walk(c, prefix)

    walk(tree, "")
    return out


def _match_entries(path: str, entries: List[str]) -> List[str]:
    quals = []
    for e in entries:
        if "::" not in e:
            continue
        p, q = e.split("::", 1)
        if fnmatch.fnmatch(path, p) or path == p:
            quals.append(q)
    return quals


def _scan_key_fns(path: str, tree: ast.AST, entries: List[str],
                  add) -> None:
    mine = _match_entries(path, entries)
    if not mine:
        return
    funcs = _qual_funcs(tree)
    for qual, fn in sorted(funcs.items()):
        if not any(fnmatch.fnmatch(qual, pat) for pat in mine):
            continue
        for p in _param_flow_missing(fn):
            add("unkeyed-trace-input", path, fn.lineno, fn.col_offset,
                f"key function '{qual}': parameter '{p}' has no "
                f"dataflow into the returned signature — the key "
                f"cannot distinguish values of it")


# ---------------------------------------------------------------------------
# impure-eligibility
# ---------------------------------------------------------------------------

def _impurity(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Call):
        ch = _chain_of(node.func) or ()
        if ch[-2:] == ("environ", "get") or (ch and ch[-1] == "getenv"):
            return "reads os.environ at query time"
        if len(ch) == 2 and ch[0] == "time" and ch[1] in _TIME_FNS:
            return f"calls time.{ch[1]}() at query time"
        if len(ch) == 2 and ch[0] == "random":
            return f"calls random.{ch[1]}() at query time"
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in _POOL_PROBES:
            recv = _chain_of(node.func.value) or ()
            if any("pool" in seg.lower() for seg in recv):
                return f"probes device-pool state ({node.func.attr})"
    elif isinstance(node, ast.Subscript):
        ch = _chain_of(node.value) or ()
        if ch and ch[-1] == "environ" \
                and isinstance(node.ctx, ast.Load):
            return "reads os.environ at query time"
    return None


def _scan_eligibility(path: str, tree: ast.AST, entries: List[str],
                      add) -> None:
    mine = _match_entries(path, entries)
    if not mine:
        return
    funcs = _qual_funcs(tree)
    top = {q: f for q, f in funcs.items() if "." not in q}
    for qual, fn in sorted(funcs.items()):
        if not any(fnmatch.fnmatch(qual, pat) for pat in mine):
            continue
        layer, seen, gathered = [fn], {fn}, [fn]
        for _ in range(2):        # own stmts + same-module callees, 2 deep
            nxt = []
            for f in layer:
                for n in _own_nodes(f):
                    if isinstance(n, ast.Call) \
                            and isinstance(n.func, ast.Name):
                        callee = top.get(n.func.id)
                        if callee is not None and callee not in seen:
                            seen.add(callee)
                            nxt.append(callee)
                            gathered.append(callee)
            layer = nxt
        for f in gathered:
            for n in _own_nodes(f):
                why = _impurity(n)
                if why is None:
                    continue
                via = "" if f is fn else f"(via {f.name}) "
                add("impure-eligibility", path, n.lineno, n.col_offset,
                    f"eligibility function '{qual}' {via}{why} — "
                    f"eligibility must be a pure function of "
                    f"descriptors/stats/query structure, or two "
                    f"identical queries plan differently")


# ---------------------------------------------------------------------------
# env-flag-latch
# ---------------------------------------------------------------------------

def _env_read(node: ast.AST) -> Optional[Tuple[str, ast.AST]]:
    """(flag name, node) for a literal DRUID_TPU_* environment read."""
    if isinstance(node, ast.Call):
        ch = _chain_of(node.func) or ()
        if (ch[-2:] == ("environ", "get")
                or (ch and ch[-1] == "getenv")) \
                and node.args \
                and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str) \
                and node.args[0].value.startswith("DRUID_TPU_"):
            return node.args[0].value, node
    elif isinstance(node, ast.Subscript) \
            and isinstance(node.ctx, ast.Load):
        ch = _chain_of(node.value) or ()
        sl = node.slice
        if ch and ch[-1] == "environ" and isinstance(sl, ast.Constant) \
                and isinstance(sl.value, str) \
                and sl.value.startswith("DRUID_TPU_"):
            return sl.value, node
    return None


def _scan_env_latch(path: str, tree: ast.AST, catalog: Dict[str, dict],
                    add) -> None:
    if not catalog:
        return
    owned: Dict[int, str] = {}     # id(node) → enclosing function name
    for fn in (n for n in ast.walk(tree) if isinstance(n, _FUNC_DEFS)):
        for n in ast.walk(fn):
            if n is not fn:
                owned.setdefault(id(n), fn.name)
    for n in ast.walk(tree):
        got = _env_read(n)
        if got is None:
            continue
        name, node = got
        decl = catalog.get(name)
        if decl is None:
            continue               # undeclared: the flag-name rule's job
        infn = owned.get(id(node))
        sem, km = decl["semantics"], decl["key_member"]
        if sem == "latch" and infn is not None:
            add("env-flag-latch", path, node.lineno, node.col_offset,
                f"{name} is declared 'latch' but read inside "
                f"{infn}() — a mid-process flip would alias cached "
                f"programs; latch it into a module global at import, "
                f"or declare it live with key_member=True")
        elif sem == "live" and infn is None:
            add("env-flag-latch", path, node.lineno, node.col_offset,
                f"{name} is declared 'live' but read at import time — "
                f"fix the catalog semantics or move the read to call "
                f"time")
        elif sem == "live" and infn is not None and not km:
            add("env-flag-latch", path, node.lineno, node.col_offset,
                f"live flag {name} read inside {infn}() is not a "
                f"declared key member — its value must join every "
                f"cache/plan key (key_member=True in the catalog) or "
                f"the read must be latched")


# ---------------------------------------------------------------------------
# Orchestration + rule shims
# ---------------------------------------------------------------------------

def _config_key(config) -> tuple:
    p = Path(config.root) / config.flags_catalog
    try:
        st = p.stat()
        cat = (st.st_mtime_ns, st.st_size)
    except OSError:
        cat = None
    return (tuple(config.keyguard_key_fns),
            tuple(config.keyguard_eligibility),
            tuple(config.keyguard_plan_modules),
            config.flags_catalog, cat)


def keyguard_findings(prog: Program, config) \
        -> Dict[str, Dict[str, List[Tuple[int, int, str]]]]:
    """rule → path → [(line, col, message)], computed once per program
    per effective keyguard config (the program object is memoized across
    runs on its file signature; the keyguard keys are not part of that
    signature, so the memo carries its own)."""
    key = _config_key(config)
    got = getattr(prog, "_keyguard_findings", None)
    if got is not None and got[0] == key:
        return got[1]
    findings: Dict[str, Dict[str, List[Tuple[int, int, str]]]] = {}

    def add(rule_name: str, path: str, line: int, col: int,
            message: str) -> None:
        findings.setdefault(rule_name, {}).setdefault(path, []) \
            .append((line, col, message))

    catalog = flag_catalog(config.root, config.flags_catalog)
    for path, mi in sorted(prog.modules.items()):
        tree = mi.tree
        exempt = _exempt_roots(mi, tree)
        cmap = _class_map(tree)
        mut_sets: Dict[int, Tuple[Set[Chain], Set[Chain]]] = {}
        for fn in (n for n in ast.walk(tree)
                   if isinstance(n, _FUNC_DEFS)):
            cls = cmap.get(id(fn))
            if cls is None:
                self_mut = _NO_MUT
            else:
                if id(cls) not in mut_sets:
                    mut_sets[id(cls)] = _mutated_attrs(cls)
                self_mut = mut_sets[id(cls)]
            _scan_fn_sites(path, fn, exempt, self_mut, add)
        _scan_key_fns(path, tree, list(config.keyguard_key_fns), add)
        _scan_eligibility(path, tree,
                          list(config.keyguard_eligibility), add)
        if any(fnmatch.fnmatch(path, pat)
               for pat in config.keyguard_plan_modules):
            _scan_env_latch(path, tree, catalog, add)
    prog._keyguard_findings = (key, findings)
    return findings


def _emit(ctx: ModuleContext, rule_name: str) -> Iterable[Finding]:
    if not ctx.path_matches(ctx.config.raceguard_modules):
        return
    prog = _program_for(ctx)
    data = keyguard_findings(prog, ctx.config)
    for line, col, message in sorted(
            data.get(rule_name, {}).get(ctx.path, ())):
        yield ctx.finding(SimpleNamespace(lineno=line, col_offset=col),
                          message)


@rule("unkeyed-trace-input", "error",
      "cache build input with no dataflow into the cache key")
def check_unkeyed_trace_input(ctx: ModuleContext) -> Iterable[Finding]:
    """At every build-on-miss cache site (dict caches with a .get/`in`
    miss check, .setdefault builds, pool.get_or_build), every input the
    build reads must have dataflow into the key expression — an unkeyed
    trace input lets two different builds alias under one cached entry.
    Also enforces, for the key functions configured in
    `keyguard-key-fns`, that every parameter flows into the returned
    signature (deleting a descriptor from `_structure_sig`'s fold is
    caught here)."""
    yield from _emit(ctx, "unkeyed-trace-input")


@rule("impure-eligibility", "error",
      "eligibility predicate reads mutable runtime state")
def check_impure_eligibility(ctx: ModuleContext) -> Iterable[Finding]:
    """Eligibility/planning predicates configured in
    `keyguard-eligibility` (packed/cascade eligibility, standing
    check_eligible, broker fusable) must be pure functions of column
    stats, descriptors and query structure. An os.environ, clock,
    random or device-pool read at query time makes two identical
    queries plan differently — and the resulting descriptors key every
    downstream cache."""
    yield from _emit(ctx, "impure-eligibility")


@rule("env-flag-latch", "error",
      "DRUID_TPU_* read in plan/build code violates its declared "
      "latch/live semantics")
def check_env_flag_latch(ctx: ModuleContext) -> Iterable[Finding]:
    """Inside plan/build modules (`keyguard-plan-modules`), every
    DRUID_TPU_* environment read must match its declared semantics in
    the flags catalog (config `flags-catalog`): latch flags are read
    once at import into a module global; live flags are read at call
    time and must be declared key members (their value joins every
    cache/plan key). A mid-process flip of an unlatched, unkeyed flag
    aliases cached programs built under the old value."""
    yield from _emit(ctx, "env-flag-latch")
