"""lockwitness: a dynamic witness for raceguard's static lock-order graph.

Static analysis is only as good as its model: if raceguard's call-graph
binder misses an edge, the lock-order-cycle rule silently under-reports
forever. The witness closes that loop by observing REALITY — it wraps every
lock the project constructs, records which locks are actually held when
another is acquired, and asserts the observed order graph is a SUBGRAPH of
the static one. An observed edge the analyzer did not predict fails the
witness test: either the binder needs fixing or the code grew an
acquisition path the model cannot see (both are things we want to know
before a deadlock ships).

Mechanics:
  * install() monkeypatches threading.Lock / threading.RLock with factories
    that inspect the CALLER's frame — only constructions from files under
    the configured prefixes (default druid_tpu/) are wrapped; jax, stdlib,
    and test-local locks pass through untouched. The (relpath, lineno) of
    the construction site is exactly the key raceguard's Program.lock_sites
    exposes, so runtime locks map onto static identities with no cooperation
    from the instrumented code.
  * WitnessLock keeps a per-thread held stack; acquiring L2 with L1 held
    records the edge (site(L1), site(L2)). Reentrant re-acquisition records
    nothing (an RLock nested in itself is not an ordering event).
    Condition-protocol methods (_release_save / _acquire_restore /
    _is_owned) are implemented so threading.Condition built on a witnessed
    lock keeps the stack balanced across wait().
  * watch(obj, attrs, lock) rebinds obj's class to a recording subclass:
    any write to a watched attribute while `lock` is NOT held by the
    writing thread is a mutation violation — the dynamic analog of the
    unguarded-shared-write rule, used by the stress test to prove the
    guard discipline holds under real concurrency.
  * order_violations() reports edges observed in BOTH directions (an
    actual ABBA interleaving happened); unexplained_edges(program) reports
    observed edges absent from the static MAY graph.

Same-lock-id edges (two INSTANCES of one class nesting) are excluded from
the static comparison: raceguard's identity is per class, so it cannot
distinguish instance A→B from B→A — the static self-deadlock check and
this witness's order_violations() cover that shape instead.

Test-only: nothing in druid_tpu imports this module.
"""
from __future__ import annotations

import os
import sys
import threading
from typing import Dict, List, Optional, Sequence, Set, Tuple

Site = Tuple[str, int]                    # (repo-relative path, lineno)

_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock

#: process-wide session witness (see session_witness)
_SESSION: Optional["LockWitness"] = None


def session_witness(root: Optional[str] = None,
                    prefixes: Sequence[str] = ("druid_tpu",)
                    ) -> Optional["LockWitness"]:
    """Process-wide singleton install. conftest.py may execute TWICE in one
    process (pytest loads it as `conftest`, while `from tests.conftest
    import ...` in test modules executes it again as `tests.conftest`) — a
    second install would shadow the first witness and swallow every
    recording the reporting hook never sees. This module has exactly one
    sys.modules entry, so the singleton lives here. First call (with
    `root`) installs; later calls return the same witness."""
    global _SESSION
    if _SESSION is None and root is not None:
        _SESSION = LockWitness(root, prefixes).install()
    return _SESSION


def end_session_witness() -> Optional["LockWitness"]:
    """Uninstall and detach the session witness (reporting hook)."""
    global _SESSION
    w, _SESSION = _SESSION, None
    if w is not None:
        w.uninstall()
    return w


class LockWitness:
    """Holds observed state for one install()/uninstall() span."""

    def __init__(self, root: str, prefixes: Sequence[str] = ("druid_tpu",)):
        self.root = os.path.abspath(root)
        self.prefixes = tuple(prefixes)
        self._meta = _REAL_LOCK()        # guards the witness's own records
        self._tls = threading.local()
        #: observed acquisition-order edges: (site_a, site_b) → count
        self.edges: Dict[Tuple[Site, Site], int] = {}
        #: construction counts per site (sanity/visibility)
        self.constructed: Dict[Site, int] = {}
        #: mutation-watch violations: (cls, attr, thread, site-ish)
        self.mutation_violations: List[str] = []
        self._installed = False
        self._watched: List[Tuple[object, type]] = []
        self._rewrapped: List[Tuple[object, str, object]] = []
        self._prev_factories = None      # what install() displaced

    # ---- interception ---------------------------------------------------
    def _rel_under_prefixes(self, path: str) -> Optional[str]:
        """Repo-relative form of `path` when it lives under a configured
        prefix, else None — the ONE site-eligibility rule both caller
        sites and rewrapped module locks key on."""
        path = os.path.abspath(path)
        if not path.startswith(self.root + os.sep):
            return None
        rel = os.path.relpath(path, self.root).replace(os.sep, "/")
        if not any(rel.startswith(p.rstrip("/") + "/") or rel == p
                   for p in self.prefixes):
            return None
        return rel

    def _site_of_caller(self) -> Optional[Site]:
        f = sys._getframe(2)             # caller of the Lock()/RLock() call
        rel = self._rel_under_prefixes(f.f_code.co_filename)
        return None if rel is None else (rel, f.f_lineno)

    def install(self) -> "LockWitness":
        if self._installed:
            return self
        witness = self

        def make_lock():
            site = witness._site_of_caller()
            inner = _REAL_LOCK()
            if site is None:
                return inner
            with witness._meta:
                witness.constructed[site] = \
                    witness.constructed.get(site, 0) + 1
            return WitnessLock(witness, inner, site, reentrant=False)

        def make_rlock():
            site = witness._site_of_caller()
            inner = _REAL_RLOCK()
            if site is None:
                return inner
            with witness._meta:
                witness.constructed[site] = \
                    witness.constructed.get(site, 0) + 1
            return WitnessLock(witness, inner, site, reentrant=True)

        # stack-aware: restore whatever was installed BEFORE this witness
        # (a per-test witness nested inside a session-wide one must not
        # strip the outer one on uninstall)
        self._prev_factories = (threading.Lock, threading.RLock)
        threading.Lock = make_lock
        threading.RLock = make_rlock
        self._installed = True
        return self

    def uninstall(self) -> None:
        if self._installed:
            threading.Lock, threading.RLock = self._prev_factories
            self._prev_factories = None
            self._installed = False
        for obj, cls in self._watched:
            obj.__class__ = cls
        self._watched.clear()
        # put the raw locks back where rewrap_module_locks swapped them —
        # a later witness (or none) must not record into this dead one
        for mod, name, raw in self._rewrapped:
            if isinstance(getattr(mod, name, None), WitnessLock):
                setattr(mod, name, raw)
        self._rewrapped.clear()

    def __enter__(self) -> "LockWitness":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    # ---- held-stack bookkeeping ----------------------------------------
    def _stack(self) -> List["WitnessLock"]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _on_acquired(self, lock: "WitnessLock") -> None:
        stack = self._stack()
        if not any(h is lock for h in stack):
            held_sites = []
            seen: Set[Site] = set()
            for h in stack:
                if h.site != lock.site and h.site not in seen:
                    seen.add(h.site)
                    held_sites.append(h.site)
            if held_sites:
                with self._meta:
                    for hs in held_sites:
                        key = (hs, lock.site)
                        self.edges[key] = self.edges.get(key, 0) + 1
        stack.append(lock)

    def _on_released(self, lock: "WitnessLock") -> None:
        stack = self._stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is lock:
                del stack[i]
                return

    def held_by_current(self, lock: "WitnessLock") -> bool:
        return any(h is lock for h in self._stack())

    # ---- re-wrap of pre-install module-level locks ----------------------
    def rewrap_module_locks(self, modules: Optional[Sequence] = None) -> int:
        """Wrap locks that were constructed BEFORE install(): module-level
        globals like the jit caches' `_JIT_CACHE_LOCK` (engine/grouping,
        engine/batching), distributed's `_CACHE_LOCK`, and the native
        registry `_lock` are built at import time, so a witness installed
        mid-session never sees them — blinding the sweep to exactly the
        compile-cache edges raceguard models.

        For every already-imported project module (or the explicit
        `modules`), the module SOURCE is ast-scanned for top-level
        `NAME = threading.Lock()/RLock()` assignments; the live lock
        object is wrapped in a WitnessLock keyed on the assignment's
        (relpath, lineno) — the same site identity raceguard's
        Program.lock_sites derives statically — and the module global is
        swapped for the wrapper. Existing holders are unaffected: the
        wrapper delegates to the SAME inner lock object, so mutual
        exclusion is preserved; only acquisitions through the module
        global after the swap are recorded (which is every future one —
        the project always reaches these locks via their module global).
        Idempotent: already-wrapped globals are skipped. Returns the
        number of locks wrapped."""
        import ast

        lock_type = type(_REAL_LOCK())
        rlock_type = type(_REAL_RLOCK())
        if modules is None:
            modules = [m for m in list(sys.modules.values())
                       if self._module_site(m) is not None]
        wrapped = 0
        for mod in modules:
            rel = self._module_site(mod)
            if rel is None:
                continue
            try:
                with open(mod.__file__, "r") as fh:
                    tree = ast.parse(fh.read())
            except (OSError, SyntaxError):
                continue
            for node in tree.body:
                if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                    continue
                value = node.value
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                if not isinstance(value, ast.Call):
                    continue
                fn = value.func
                name = fn.attr if isinstance(fn, ast.Attribute) else \
                    fn.id if isinstance(fn, ast.Name) else None
                if name not in ("Lock", "RLock"):
                    continue
                for tgt in targets:
                    if not isinstance(tgt, ast.Name):
                        continue
                    obj = getattr(mod, tgt.id, None)
                    if isinstance(obj, WitnessLock):
                        continue     # post-install construction / rerun
                    if not isinstance(obj, (lock_type, rlock_type)):
                        continue
                    site = (rel, node.lineno)
                    self._rewrapped.append((mod, tgt.id, obj))
                    setattr(mod, tgt.id, WitnessLock(
                        self, obj, site,
                        reentrant=isinstance(obj, rlock_type)))
                    with self._meta:
                        self.constructed[site] = \
                            self.constructed.get(site, 0) + 1
                    wrapped += 1
        return wrapped

    def _module_site(self, mod) -> Optional[str]:
        """The module's repo-relative path when it lives under a
        configured prefix, else None."""
        path = getattr(mod, "__file__", None)
        return None if not path else self._rel_under_prefixes(path)

    # ---- mutation watch -------------------------------------------------
    def watch(self, obj, attrs: Sequence[str], lock: "WitnessLock") -> None:
        """Record a violation whenever obj.<attr in attrs> is assigned by a
        thread that does not hold `lock`. Restored by uninstall()."""
        witness = self
        watched = frozenset(attrs)
        base = type(obj)

        class _Watched(base):
            def __setattr__(self, name, value):
                if name in watched \
                        and not witness.held_by_current(lock):
                    witness.record_mutation_violation(
                        f"{base.__name__}.{name} assigned without "
                        f"{lock.site[0]}:{lock.site[1]} held "
                        f"(thread {threading.current_thread().name})")
                super().__setattr__(name, value)

        _Watched.__name__ = base.__name__
        _Watched.__qualname__ = base.__qualname__
        obj.__class__ = _Watched
        self._watched.append((obj, base))

    def record_mutation_violation(self, desc: str) -> None:
        with self._meta:
            self.mutation_violations.append(desc)

    # ---- reporting ------------------------------------------------------
    def observed_edges(self) -> Dict[Tuple[Site, Site], int]:
        with self._meta:
            return dict(self.edges)

    def order_violations(self) -> List[Tuple[Site, Site]]:
        """Site pairs observed in BOTH orders — an actual ABBA interleaving
        ran; with unlucky timing those threads deadlock."""
        with self._meta:
            out = []
            for a, b in self.edges:
                if (b, a) in self.edges and (a, b) <= (b, a):
                    out.append((a, b))
            return sorted(out)

    def unexplained_edges(self, program) -> List[str]:
        """Observed edges whose BOTH endpoints map to static lock ids but
        which the static MAY order graph does not contain — raceguard's
        model missed a real acquisition path. `program` is a
        raceguard.Program (analyze_tree of the same root)."""
        sites = program.lock_sites()
        static = set(program.order_edges)
        out = []
        for (sa, sb), count in sorted(self.observed_edges().items()):
            ia, ib = sites.get(sa), sites.get(sb)
            if ia is None or ib is None:
                continue            # lock the static index never saw
            if ia == ib:
                continue            # per-class identity: instances collapse
            if (ia, ib) not in static:
                out.append(f"{ia} -> {ib} (observed {count}x at "
                           f"{sa[0]}:{sa[1]} -> {sb[0]}:{sb[1]}, "
                           f"not in the static order graph)")
        return out


class WitnessLock:
    """A recording wrapper around one project lock. Not a subclass: the
    real lock types are C objects; delegation plus the Condition protocol
    methods below cover every way the project uses them."""

    __slots__ = ("_witness", "_inner", "site", "reentrant")

    def __init__(self, witness: LockWitness, inner, site: Site,
                 reentrant: bool):
        self._witness = witness
        self._inner = inner
        self.site = site
        self.reentrant = reentrant

    # -- core lock protocol --
    def acquire(self, blocking=True, timeout=-1):
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._witness._on_acquired(self)
        return got

    def release(self):
        self._inner.release()
        self._witness._on_released(self)

    def locked(self):
        return self._inner.locked() if hasattr(self._inner, "locked") \
            else self._inner._is_owned()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()

    # -- Condition protocol (threading.Condition(witnessed_lock)) --
    def _release_save(self):
        if hasattr(self._inner, "_release_save"):
            state = self._inner._release_save()
        else:
            self._inner.release()
            state = None
        # wait() dropped the lock entirely: clear every stack entry
        stack = self._witness._stack()
        n = sum(1 for h in stack if h is self)
        for _ in range(n):
            self._witness._on_released(self)
        return (state, n)

    def _acquire_restore(self, saved):
        state, n = saved
        if hasattr(self._inner, "_acquire_restore"):
            self._inner._acquire_restore(state)
        else:
            self._inner.acquire()
        for _ in range(max(n, 1)):
            self._witness._on_acquired(self)

    def _is_owned(self):
        if hasattr(self._inner, "_is_owned"):
            return self._inner._is_owned()
        return self._witness.held_by_current(self)

    def __repr__(self):
        return (f"<WitnessLock {self.site[0]}:{self.site[1]} "
                f"{'r' if self.reentrant else ''}lock>")
