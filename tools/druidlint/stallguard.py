"""stallguard: whole-program deadline-propagation analysis — no
request-path thread may park forever.

The sixth analyzer family, riding raceguard's shared program index (same
module set, binder, call graph, thread roots, cache signature). Where
raceguard proves lock discipline and leakguard proves resource lifecycle,
stallguard proves DEADLINE discipline: every blocking primitive
(`Condition.wait`, `Event.wait`, `Lock.acquire`, `Queue.get`,
`future.result`, `thread.join`, `proc.wait`, `urlopen`/socket connect,
`time.sleep`) is discovered and classified by the thread class that
reaches it — request path (HTTP handler / configured request roots such
as the broker scatter and the long-poll hub), thread-root loop, or
shutdown path — and five rules enforce that a budget admitted at the
HTTP edge actually bounds every park under it:

  unbounded-blocking-call   request-path park with no timeout argument
                            and no enclosing bounded-retry loop
  deadline-not-propagated   a function holding a deadline/timeout/budget
                            parameter parks without threading the
                            remaining budget into the park
  unclamped-external-timeout a wire/context/user-supplied timeout reaches
                            a park (or bounds a park loop) without a
                            clamp (min / MAX_* / Deadline.clamp) — the
                            PR 14 `timeoutMs=inf` long-poll bug,
                            generalized
  sleep-on-request-path     fixed time.sleep serving a request must be
                            deadline-guarded and jittered
                            (decorrelated_jitter)
  stop-signal-coverage      every `while True` in a thread root must
                            consult its stop event/flag each iteration —
                            the graceful-shutdown dual of leakguard's
                            unjoined-thread

The dynamic peer is tools/druidlint/stallwitness.py: it times real parks
at druid_tpu call sites suite-wide (DRUID_TPU_STALL_WITNESS=1) and fails
the session on any untimed park outside a shutdown scope — observed
parks must be a subset of the statically-predicted bounded sites.

Like keyguard, findings are memoized on the Program PER config key:
the request-root list is config, not program state.
"""
from __future__ import annotations

import ast
import fnmatch
import re
from types import SimpleNamespace
from typing import Dict, Iterable, List, Optional, Set, Tuple

from tools.druidlint.core import Finding, ModuleContext, rule
from tools.druidlint.leakguard import ENTRY_METHODS  # noqa: F401 (witness)
from tools.druidlint.raceguard import FuncInfo, Program, Site, _own
from tools.druidlint.rules import (_DEADLINE_CONSULTS, _FUNC_DEFS,
                                   _deadline_names, _loop_bounded,
                                   _terminal)

# ---------------------------------------------------------------------------
# blocking-primitive discovery
# ---------------------------------------------------------------------------

#: keyword names a park accepts its bound under
_TIMEOUT_KWS = ("timeout", "timeout_s", "timeout_ms", "timeout_sec")

#: parameter names that carry a remaining budget into a function
_BUDGET_PARAM = re.compile(r"deadline|timeout|budget")

#: substrings marking a name as a stop signal (self._stopping,
#: self._shutdown, stop_event, closed, cancelled, ...)
_STOPISH = ("stop", "shutdown", "shutting", "halt", "exit", "quit",
            "teardown", "closed", "closing", "cancel", "abort")


def _is_none(e: Optional[ast.AST]) -> bool:
    return isinstance(e, ast.Constant) and e.value is None


def _all_args(fn: ast.AST) -> List[ast.arg]:
    a = fn.args
    return list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)


def _classify_park(call: ast.Call) -> Optional[Tuple[str,
                                                     Optional[ast.AST],
                                                     bool]]:
    """(kind, timeout_expr, bounded) for a blocking-primitive call, else
    None. Purely syntactic (terminal attribute + argument shape): inside
    the druid_tpu program set these terminals overwhelmingly ARE the
    threading/queue/subprocess/socket primitives, and the rules that
    consume this are path-classified, so a stray same-named method on a
    non-primitive costs one suppression, not soundness."""
    f = call.func
    t = _terminal(f)
    kws = {k.arg: k.value for k in call.keywords if k.arg}
    tkw = next((kws[k] for k in _TIMEOUT_KWS if k in kws), None)
    if t in ("wait", "wait_futures"):
        if isinstance(f, ast.Attribute):
            # Condition/Event/Popen .wait([timeout])
            expr = call.args[0] if call.args else tkw
            return ("wait", expr, expr is not None and not _is_none(expr))
        if isinstance(f, ast.Name) and (call.args or tkw is not None):
            # concurrent.futures.wait(fs, timeout=...) or an alias of it
            expr = call.args[1] if len(call.args) > 1 else tkw
            return ("wait", expr, expr is not None and not _is_none(expr))
        return None
    if t == "acquire" and isinstance(f, ast.Attribute):
        blocking = kws.get("blocking",
                           call.args[0] if call.args else None)
        expr = call.args[1] if len(call.args) > 1 else tkw
        bounded = (expr is not None and not _is_none(expr)) or \
            (isinstance(blocking, ast.Constant) and blocking.value is False)
        return ("acquire", expr, bounded)
    if t == "get" and isinstance(f, ast.Attribute):
        recv = _terminal(f.value).lower()
        if not (recv in ("q", "inbox") or recv.endswith("_q")
                or "queue" in recv):
            return None                   # dict.get, not Queue.get
        block = kws.get("block", call.args[0] if call.args else None)
        expr = call.args[1] if len(call.args) > 1 else tkw
        bounded = (expr is not None and not _is_none(expr)) or \
            (isinstance(block, ast.Constant) and block.value is False)
        return ("queue-get", expr, bounded)
    if t == "result" and isinstance(f, ast.Attribute):
        expr = call.args[0] if call.args else tkw
        return ("future-result", expr,
                expr is not None and not _is_none(expr))
    if t == "join" and isinstance(f, ast.Attribute):
        if isinstance(f.value, ast.Constant):
            return None                   # ", ".join(parts)
        expr = call.args[0] if call.args else tkw
        if expr is None and (call.args or call.keywords):
            return None                   # non-thread join shape
        return ("join", expr, expr is not None and not _is_none(expr))
    if t == "urlopen":
        return ("urlopen", tkw, tkw is not None and not _is_none(tkw))
    if t == "create_connection":
        expr = call.args[1] if len(call.args) > 1 else tkw
        return ("connect", expr, expr is not None and not _is_none(expr))
    if t == "sleep":
        expr = call.args[0] if call.args else tkw
        return ("sleep", expr, True)      # bounded by its own argument
    return None


def _own_sorted(fi: FuncInfo) -> List[ast.AST]:
    return sorted((n for n in _own(fi) if hasattr(n, "lineno")),
                  key=lambda n: (n.lineno, n.col_offset))


def _parents_of(fi: FuncInfo) -> Dict[ast.AST, ast.AST]:
    """Child → parent over fi's own scope (nested def/class bodies are
    separate FuncInfos and excluded, mirroring _own)."""
    out: Dict[ast.AST, ast.AST] = {}
    stack = [fi.node]
    while stack:
        node = stack.pop()
        if node is not fi.node and isinstance(
                node, _FUNC_DEFS + (ast.ClassDef,)):
            continue
        for child in ast.iter_child_nodes(node):
            out[child] = node
            stack.append(child)
    return out


def _enclosing_loops(parents: Dict[ast.AST, ast.AST],
                     node: ast.AST) -> Iterable[ast.AST]:
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.For, ast.While)):
            yield cur
        cur = parents.get(cur)


def _mentions(node: ast.AST, names: Set[str]) -> bool:
    return any(isinstance(n, ast.Name) and n.id in names
               for n in ast.walk(node))


def _call_args_mention(call: ast.Call, names: Set[str]) -> bool:
    return any(_mentions(a, names) for a in call.args) or \
        any(_mentions(k.value, names) for k in call.keywords)


def _consults_names(loop: ast.AST, names: Set[str]) -> bool:
    """The loop re-checks one of `names` as a budget: a Deadline-style
    consult call on it, or a comparison involving it."""
    for n in ast.walk(loop):
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                and n.func.attr in _DEADLINE_CONSULTS \
                and _terminal(n.func.value) in names:
            return True
        if isinstance(n, ast.Compare) and _mentions(n, names):
            return True
    return False


# ---------------------------------------------------------------------------
# thread-class classification over the shared program index
# ---------------------------------------------------------------------------

def _match_fid(fid: str, entries: List[str]) -> bool:
    path, _, qual = fid.partition("::")
    for e in entries:
        ep, _, eq = e.partition("::")
        if fnmatch.fnmatch(path, ep) and fnmatch.fnmatch(qual, eq):
            return True
    return False


def _request_fids(prog: Program, config) -> Dict[str, str]:
    """func_id → human-readable origin, for every function reachable from
    an HTTP handler root or a configured request root
    (`stallguard-request-roots`), following the binder's call edges."""
    seeds: Dict[str, str] = {}
    for fid, kind in prog.roots.items():
        if kind == "handler":
            seeds[fid] = f"HTTP handler {fid.partition('::')[2]}"
    roots_cfg = list(getattr(config, "stallguard_request_roots", []) or [])
    for fid in prog.funcs:
        if _match_fid(fid, roots_cfg):
            seeds.setdefault(
                fid, f"request root {fid.partition('::')[2]}")
    out = dict(seeds)
    work = list(seeds)
    while work:
        fid = work.pop()
        fi = prog.funcs.get(fid)
        if fi is None:
            continue
        for callee, _held, _site, _recv in fi.calls:
            if callee not in out and callee in prog.funcs:
                out[callee] = out[fid]
                work.append(callee)
    return out


def _thread_root_fids(prog: Program, config) -> List[str]:
    """Thread-root entry functions whose duty loops must stay
    stop-responsive: Thread targets plus configured extra roots, minus
    anything declared a REQUEST root (a long-poll entry point runs on a
    handler thread; its loop is bounded by the poll deadline, not a stop
    flag)."""
    roots_cfg = list(getattr(config, "stallguard_request_roots", []) or [])
    return [fid for fid, kind in prog.roots.items()
            if kind in ("thread", "extra")
            and not _match_fid(fid, roots_cfg)]


# ---------------------------------------------------------------------------
# the five checks
# ---------------------------------------------------------------------------

def _check_unbounded(prog: Program, config, add,
                     request: Dict[str, str]) -> None:
    for fid, origin in request.items():
        fi = prog.funcs.get(fid)
        if fi is None or not isinstance(fi.node, _FUNC_DEFS):
            continue
        dl_names = _deadline_names(fi.node)
        parents = _parents_of(fi)
        for node in _own(fi):
            if not isinstance(node, ast.Call):
                continue
            park = _classify_park(node)
            if park is None:
                continue
            kind, _expr, bounded = park
            if bounded or kind == "sleep":
                continue
            if any(_loop_bounded(lp, dl_names)
                   for lp in _enclosing_loops(parents, node)):
                continue                  # bounded-retry / deadline loop
            add("unbounded-blocking-call",
                Site(fi.path, node.lineno, node.col_offset),
                f"{kind} parks with no timeout on the request path "
                f"(reachable from {origin}) — pass a bound "
                f"(deadline.clamp(...)) or move the park off the "
                f"request path")


def _check_propagation(prog: Program, config, add) -> None:
    for fid, fi in prog.funcs.items():
        fn = fi.node
        if not isinstance(fn, _FUNC_DEFS):
            continue
        dl_names = _deadline_names(fn)
        params = {a.arg for a in _all_args(fn)
                  if a.arg not in ("self", "cls")
                  and not a.arg.startswith("_")
                  and (_BUDGET_PARAM.search(a.arg.lower())
                       or a.arg in dl_names)}
        if not params:
            continue
        derived = set(params)
        own = _own(fi)
        changed = True
        while changed:                    # forward dataflow to a fixpoint
            changed = False
            for node in own:
                if isinstance(node, (ast.Assign, ast.AnnAssign,
                                     ast.AugAssign)):
                    value = node.value
                    if value is None or not _mentions(value, derived):
                        continue
                    targets = node.targets \
                        if isinstance(node, ast.Assign) else [node.target]
                    for t in targets:
                        if isinstance(t, ast.Name) and t.id not in derived:
                            derived.add(t.id)
                            changed = True
        parents = _parents_of(fi)
        for node in own:
            if not isinstance(node, ast.Call):
                continue
            park = _classify_park(node)
            if park is None or park[0] == "sleep":
                continue
            if _call_args_mention(node, derived):
                continue                  # budget threaded into the park
            if any(_consults_names(lp, derived)
                   for lp in _enclosing_loops(parents, node)):
                continue                  # poll quantum + budget re-check
            add("deadline-not-propagated",
                Site(fi.path, node.lineno, node.col_offset),
                f"{fi.qual} receives a budget ({', '.join(sorted(params))})"
                f" but this {park[0]} ignores it — bound the park with the"
                f" remaining budget (deadline.clamp(...)) or re-check the"
                f" deadline in the enclosing loop")


def _expr_clamped(e: ast.AST, raw: Set[str]) -> bool:
    """The expression's value is bounded independently of any raw
    external timeout: a constant, a clamped local, min()/Deadline.clamp()
    with at least one bounded argument, or a MAX_*-style ceiling."""
    if isinstance(e, ast.Constant):
        return isinstance(e.value, (int, float))
    if isinstance(e, ast.Name):
        return e.id not in raw
    if isinstance(e, ast.Attribute):
        return True                       # self.MAX_..., module constant
    if isinstance(e, ast.Call):
        t = _terminal(e.func)
        if t == "min" or (isinstance(e.func, ast.Attribute)
                          and e.func.attr == "clamp"):
            return any(_expr_clamped(a, raw) for a in e.args)
        return False
    if isinstance(e, ast.BinOp):
        return _expr_clamped(e.left, raw) and _expr_clamped(e.right, raw)
    return False


def _check_unclamped(prog: Program, config, add,
                     request: Dict[str, str]) -> None:
    for fid, origin in request.items():
        fi = prog.funcs.get(fid)
        if fi is None or not isinstance(fi.node, _FUNC_DEFS):
            continue
        fn = fi.node
        params = {a.arg for a in _all_args(fn)
                  if a.arg not in ("self", "cls")
                  and "timeout" in a.arg.lower()}
        if not params:
            continue
        raw = set(params)
        for node in _own_sorted(fi):
            if isinstance(node, ast.Assign):
                names = {t.id for t in node.targets
                         if isinstance(t, ast.Name)}
                if not names:
                    continue
                if not _mentions(node.value, raw):
                    raw -= names          # rebound from something else
                elif _expr_clamped(node.value, raw):
                    raw -= names          # timeout_s = min(timeout_s, MAX)
                else:
                    raw |= names          # deadline = Deadline(timeout_ms)
            elif isinstance(node, (ast.While, ast.For)):
                # a park loop whose bound is the raw external value parks
                # (in quanta or in one go) for as long as the wire asked
                has_park = any(isinstance(n, ast.Call)
                               and _classify_park(n) is not None
                               for n in ast.walk(node))
                if has_park and _consults_names(node, raw):
                    add("unclamped-external-timeout",
                        Site(fi.path, node.lineno, node.col_offset),
                        f"loop in {fi.qual} parks under an unclamped "
                        f"external timeout ({', '.join(sorted(params))}) "
                        f"— clamp it (min(..., MAX_*) / Deadline.clamp) "
                        f"before it bounds a request-path park")
            elif isinstance(node, ast.Call):
                park = _classify_park(node)
                if park is None:
                    continue
                _kind, expr, _b = park
                if expr is not None and _mentions(expr, raw) \
                        and not _expr_clamped(expr, raw):
                    add("unclamped-external-timeout",
                        Site(fi.path, node.lineno, node.col_offset),
                        f"external timeout ({', '.join(sorted(params))}) "
                        f"reaches this {_kind} unclamped — a wire value "
                        f"of inf parks the handler thread forever; clamp "
                        f"with min(..., MAX_*) or Deadline.clamp")


def _check_sleep(prog: Program, config, add,
                 request: Dict[str, str]) -> None:
    for fid, origin in request.items():
        fi = prog.funcs.get(fid)
        if fi is None or not isinstance(fi.node, _FUNC_DEFS):
            continue
        own = _own(fi)
        jittered: Set[str] = set()
        for node in own:
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call) \
                    and "jitter" in _terminal(node.value.func).lower():
                jittered |= {t.id for t in node.targets
                             if isinstance(t, ast.Name)}
        dl_names = _deadline_names(fi.node) | \
            {n.id for n in ast.walk(fi.node)
             if isinstance(n, ast.Name) and "deadline" in n.id.lower()}
        guarded = any(
            isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)
            and n.func.attr in _DEADLINE_CONSULTS
            and (_terminal(n.func.value) in dl_names
                 or "deadline" in _terminal(n.func.value).lower())
            for fnode in own for n in ast.walk(fnode))
        for node in own:
            if not isinstance(node, ast.Call):
                continue
            park = _classify_park(node)
            if park is None or park[0] != "sleep":
                continue
            expr = park[1]
            jitter_ok = expr is not None and (
                _mentions(expr, jittered)
                or (isinstance(expr, ast.Call)
                    and "jitter" in _terminal(expr.func).lower()))
            if jitter_ok and guarded:
                continue
            add("sleep-on-request-path",
                Site(fi.path, node.lineno, node.col_offset),
                f"fixed sleep on the request path (reachable from "
                f"{origin}) — derive the pause from decorrelated_jitter "
                f"and guard it with the remaining deadline, or use a "
                f"stop-responsive wait")


def _consults_stop(loop: ast.AST) -> bool:
    for n in ast.walk(loop):
        name = n.attr if isinstance(n, ast.Attribute) \
            else n.id if isinstance(n, ast.Name) else None
        if name and any(k in name.lstrip("_").lower() for k in _STOPISH):
            return True
    return False


def _check_stop_coverage(prog: Program, config, add,
                         thread_roots: List[str]) -> None:
    for fid in thread_roots:
        fi = prog.funcs.get(fid)
        if fi is None or not isinstance(fi.node, _FUNC_DEFS):
            continue
        for node in _own(fi):
            if not isinstance(node, ast.While):
                continue
            test = node.test
            infinite = isinstance(test, ast.Constant) and bool(test.value)
            if not infinite or _loop_bounded(node):
                continue
            if _consults_stop(node):
                continue
            add("stop-signal-coverage",
                Site(fi.path, node.lineno, node.col_offset),
                f"infinite loop in thread root {fi.qual} never consults "
                f"a stop signal — check a stop event/flag each iteration "
                f"so shutdown can end the thread")


# ---------------------------------------------------------------------------
# findings assembly + rule shims (leakguard's structure, keyguard's
# config-keyed memo: the request-root list is config, not program state)
# ---------------------------------------------------------------------------

def _config_key(config) -> tuple:
    return (tuple(getattr(config, "stallguard_request_roots", []) or []),
            tuple(config.raceguard_modules))


def stall_findings(prog: Program, config) \
        -> Dict[str, Dict[str, List[Tuple]]]:
    key = _config_key(config)
    got = getattr(prog, "_stall_findings", None)
    if got is not None and got[0] == key:
        return got[1]
    findings: Dict[str, Dict[str, List[Tuple]]] = {}

    def add(rule_name: str, site: Site, message: str) -> None:
        findings.setdefault(rule_name, {}).setdefault(
            site.path, []).append((site.line, site.col, message))

    request = _request_fids(prog, config)
    _check_unbounded(prog, config, add, request)
    _check_propagation(prog, config, add)
    _check_unclamped(prog, config, add, request)
    _check_sleep(prog, config, add, request)
    _check_stop_coverage(prog, config, add,
                         _thread_root_fids(prog, config))
    prog._stall_findings = (key, findings)
    return findings


def _program_for(ctx: ModuleContext) -> Program:
    from tools.druidlint.raceguard import _program_for as rg_program
    return rg_program(ctx)


def _emit(ctx: ModuleContext, rule_name: str) -> Iterable[Finding]:
    if not ctx.path_matches(ctx.config.raceguard_modules):
        return
    prog = _program_for(ctx)
    data = stall_findings(prog, ctx.config)
    for line, col, message in sorted(
            data.get(rule_name, {}).get(ctx.path, ())):
        yield ctx.finding(SimpleNamespace(lineno=line, col_offset=col),
                          message)


@rule("unbounded-blocking-call", "error",
      "request-path blocking call with no timeout and no bounded loop")
def check_unbounded_blocking_call(ctx: ModuleContext) -> Iterable[Finding]:
    """A blocking primitive (wait/acquire/Queue.get/result/join/urlopen/
    connect) reachable from an HTTP handler or a configured request root
    (`stallguard-request-roots`) parks with no timeout argument and no
    enclosing bounded-retry loop. One such park is one handler thread
    gone for as long as the peer cares to stall — the exact failure mode
    of the wedged-tunnel bench hangs. Bound the park with the query's
    remaining budget (`deadline.clamp(...)`) or take a rationale
    suppression for parks that provably complete (e.g. `.result()` on an
    already-done future)."""
    yield from _emit(ctx, "unbounded-blocking-call")


@rule("deadline-not-propagated", "error",
      "function receives a budget but parks without threading it in")
def check_deadline_not_propagated(ctx: ModuleContext) -> Iterable[Finding]:
    """A function that RECEIVES a deadline/timeout/budget value (by
    parameter name, or a parameter of the shared Deadline type) calls a
    blocking primitive without the budget — or anything derived from it —
    in the call's arguments, and without a budget re-check in the
    enclosing loop. The budget dies at this frame: callers time out while
    the callee parks on its own clock. Thread the remaining budget into
    the park (`deadline.clamp(quantum)`) or consult the deadline each
    loop iteration (the scheduler's `_await` poll idiom)."""
    yield from _emit(ctx, "deadline-not-propagated")


@rule("unclamped-external-timeout", "error",
      "wire/context timeout reaches a park without a clamp")
def check_unclamped_external_timeout(ctx: ModuleContext) \
        -> Iterable[Finding]:
    """A timeout parameter entering a request-path function flows into a
    park's bound — directly or as the bound of a park loop — without
    passing a clamp (`min(..., MAX_*)`, `Deadline.clamp`). External
    values are adversarial: `timeoutMs=inf` on the PR 14 long-poll parked
    a handler thread forever and defeated the idle sweep that would have
    reclaimed it. Clamp at the edge, like SubscriptionHub's
    MAX_POLL_TIMEOUT_S."""
    yield from _emit(ctx, "unclamped-external-timeout")


@rule("sleep-on-request-path", "error",
      "fixed time.sleep on a request-serving path")
def check_sleep_on_request_path(ctx: ModuleContext) -> Iterable[Finding]:
    """A fixed `time.sleep` on a request-serving path burns the caller's
    budget invisibly and, under a retry storm, re-synchronizes every
    client onto one instant (the next shed wave). A request-path pause
    must be derived from `decorrelated_jitter` AND guarded by the
    remaining deadline — the remote client's 429 back-off is the
    canonical shape."""
    yield from _emit(ctx, "sleep-on-request-path")


@rule("stop-signal-coverage", "error",
      "thread-root infinite loop never consults a stop signal")
def check_stop_signal_coverage(ctx: ModuleContext) -> Iterable[Finding]:
    """Every `while True` in a thread-root function must consult its stop
    event/flag each iteration (`self._stopping`, a stop Event wait, a
    shutdown re-check) — otherwise stop() can only abandon the thread,
    and leakguard's join discipline turns into a 5-second hang per
    orphan at every teardown. The graceful-shutdown dual of
    unjoined-thread."""
    yield from _emit(ctx, "stop-signal-coverage")
