"""leakwitness: a dynamic return-to-baseline witness for leakguard.

leakguard's static model proves a release call is REACHABLE; the witness
proves it actually RAN. It snapshots the three resource axes a long-running
query service bleeds on — live project-started threads, open file
descriptors, and device-pool resident bytes — and asserts that after a unit
of work (a fixture, a soak cycle, the whole suite) the process returns to
its baseline. A leak the static analyzer cannot see (a release behind a
condition that never held, a thread whose join silently timed out) shows up
here with the site that started it, exactly like lockwitness closes the
loop on raceguard's order graph.

Mechanics:
  * install() monkeypatches threading.Thread.start: when any thread starts
    while a frame under the configured prefixes (default druid_tpu/) is on
    the caller's stack, the witness records (weakref(thread), site, name) —
    the site is the nearest project frame, so executor workers attribute to
    the submit/executor construction site and servers to their start().
    Threads started from jax, pytest or the stdlib alone pass unrecorded.
  * snapshot() captures a watermark into that append-only start log, the
    open-fd table from /proc/self/fd (fd -> readlink target; platforms
    without procfs degrade to no fd tracking), and the device pool's
    resident bytes/entries (0 when druid_tpu.data.devicepool was never
    imported).
  * leaks(baseline) polls with gc.collect() until clean or a grace
    deadline: project threads started AFTER the baseline must be dead, the
    multiset of leak-worthy descriptor targets must not have grown
    (regular files and sockets count; anon inodes, pipes, /dev, /proc and
    shared-library mappings are runtime noise — counted by readlink
    target, not fd number, which the kernel reuses), and pool resident
    bytes must return to
    baseline within a slack. gc runs inside the loop because CPython closes
    GC'd files/sockets and the pool purges dead owners at the next
    snapshot() — "released by collection" is not a leak, it is the
    ownership-transfer idiom working.

Whole-suite mode: DRUID_TPU_LEAK_WITNESS=1 makes conftest install a session
witness before the first druid_tpu import and fail the run from
pytest_unconfigure if the suite did not return to its post-import baseline.

Test-only: nothing in druid_tpu imports this module.
"""
from __future__ import annotations

import gc
import os
import sys
import threading
import time
import weakref
from collections import Counter
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Set, Tuple

Site = Tuple[str, int]                    # (repo-relative path, lineno)

#: process-wide session witness (see session_witness; same two-conftest
#: rationale as lockwitness.session_witness)
_SESSION: Optional["LeakWitness"] = None


def session_witness(root: Optional[str] = None,
                    prefixes: Sequence[str] = ("druid_tpu",)
                    ) -> Optional["LeakWitness"]:
    """Singleton install-and-baseline. First call (with `root`) installs
    the witness and captures the session baseline; later calls return the
    same witness. conftest executes twice per process (as `conftest` and as
    `tests.conftest`) — a second witness would reset the baseline and
    shadow the start log."""
    global _SESSION
    if _SESSION is None and root is not None:
        _SESSION = LeakWitness(root, prefixes).install()
        _SESSION.baseline = _SESSION.snapshot()
    return _SESSION


def end_session_witness() -> Optional["LeakWitness"]:
    global _SESSION
    w, _SESSION = _SESSION, None
    if w is not None:
        w.uninstall()
    return w


#: readlink targets that are runtime noise, not project leaks: event/epoll
#: anon inodes and pipes back thread pools and jax's runtime, /dev and
#: /proc churn with the platform, and .so targets appear when a library
#: dlopens lazily mid-session.
_FD_NOISE_PREFIXES = ("anon_inode:", "pipe:", "/dev/", "/proc/", "/sys/")
_FD_NOISE_SUFFIXES = (".so",)

#: once-per-process note that the fd axis is skipped (non-procfs)
_FD_AXIS_NOTE = {"emitted": False}


def _fd_leakworthy(target: str) -> bool:
    if target.startswith(_FD_NOISE_PREFIXES):
        return False
    if target.endswith(_FD_NOISE_SUFFIXES) or ".so." in target:
        return False
    return True


@dataclass(frozen=True)
class LeakSnapshot:
    """One point-in-time resource baseline."""
    started_watermark: int                # len() of the witness start log
    thread_count: int                     # all alive threads (visibility)
    fds: Tuple[Tuple[int, str], ...]      # (fd, readlink target)
    pool_resident: int
    pool_entries: int
    #: whether /proc/self/fd was readable when this snapshot was taken —
    #: False on non-procfs platforms, where the fd axis is SKIPPED (with
    #: a one-line note) and the thread/pool axes carry the gate alone
    fd_axis: bool = True


class LeakWitness:
    """Holds the project-thread start log for one install()/uninstall()
    span plus snapshot/compare logic. `baseline` is set by session_witness
    (or by the caller) for the session-wide mode."""

    def __init__(self, root: str, prefixes: Sequence[str] = ("druid_tpu",)):
        self.root = os.path.abspath(root)
        self.prefixes = tuple(prefixes)
        self._meta = threading.Lock()
        #: append-only: (weakref to thread, start site, thread name)
        self._started: List[Tuple[weakref.ref, Site, str]] = []
        self._installed = False
        self._real_start = None
        self.baseline: Optional[LeakSnapshot] = None

    # ---- interception ---------------------------------------------------
    def _rel_under_prefixes(self, path: str) -> Optional[str]:
        path = os.path.abspath(path)
        if not path.startswith(self.root + os.sep):
            return None
        rel = os.path.relpath(path, self.root).replace(os.sep, "/")
        if not any(rel.startswith(p.rstrip("/") + "/") or rel == p
                   for p in self.prefixes):
            return None
        return rel

    def _project_site_on_stack(self, frame) -> Optional[Site]:
        """Nearest frame under a configured prefix walking outward — the
        attribution site for a thread start reached through stdlib layers
        (executor submit, socketserver process_request)."""
        depth = 0
        while frame is not None and depth < 64:
            rel = self._rel_under_prefixes(frame.f_code.co_filename)
            if rel is not None:
                return (rel, frame.f_lineno)
            frame = frame.f_back
            depth += 1
        return None

    def install(self) -> "LeakWitness":
        if self._installed:
            return self
        witness = self
        real_start = threading.Thread.start

        def start(thread, *args, **kwargs):
            site = witness._project_site_on_stack(sys._getframe(1))
            if site is not None:
                with witness._meta:
                    witness._started.append(
                        (weakref.ref(thread), site, thread.name))
            return real_start(thread, *args, **kwargs)

        self._real_start = real_start
        threading.Thread.start = start
        self._installed = True
        return self

    def uninstall(self) -> None:
        if self._installed:
            threading.Thread.start = self._real_start
            self._real_start = None
            self._installed = False

    def __enter__(self) -> "LeakWitness":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    # ---- observation ----------------------------------------------------
    def live_project_threads(self, since: int = 0
                             ) -> List[Tuple[Site, str]]:
        """(site, name) of witnessed threads at log index >= `since` that
        are still alive."""
        with self._meta:
            entries = list(self._started[since:])
        out = []
        for ref, site, name in entries:
            t = ref()
            if t is not None and t.is_alive():
                out.append((site, name))
        return out

    @staticmethod
    def open_fds() -> Tuple[Tuple[int, str], ...]:
        return LeakWitness.fd_axis_snapshot()[0]

    @staticmethod
    def fd_axis_snapshot() -> Tuple[Tuple[Tuple[int, str], ...], bool]:
        """(fd table, axis available). On platforms without procfs the
        axis degrades gracefully: one logged note (once per process),
        empty table, available=False — _compare then skips the fd axis
        entirely while threads and pool stay active, instead of erroring
        or silently reading 'no fds open'."""
        out = []
        try:
            names = os.listdir("/proc/self/fd")
        except OSError:
            if not _FD_AXIS_NOTE["emitted"]:
                _FD_AXIS_NOTE["emitted"] = True
                import logging
                logging.getLogger(__name__).info(
                    "leakwitness: /proc/self/fd unavailable — fd axis "
                    "skipped; thread and pool axes remain active")
            return (), False
        for n in names:
            try:
                out.append((int(n), os.readlink(f"/proc/self/fd/{n}")))
            except (OSError, ValueError):
                pass                     # fd closed mid-listing
        return tuple(sorted(out)), True

    @staticmethod
    def pool_stats() -> Tuple[int, int]:
        """(resident_bytes, entries) — snapshot() drains finalizer-reported
        dead owners, so this reflects segment GC that already happened."""
        mod = sys.modules.get("druid_tpu.data.devicepool")
        if mod is None:
            return (0, 0)
        s = mod.device_pool().snapshot()
        return (s.resident_bytes, s.entries)

    def snapshot(self) -> LeakSnapshot:
        with self._meta:
            watermark = len(self._started)
        resident, entries = self.pool_stats()
        fds, fd_axis = self.fd_axis_snapshot()
        return LeakSnapshot(started_watermark=watermark,
                            thread_count=threading.active_count(),
                            fds=fds,
                            pool_resident=resident,
                            pool_entries=entries,
                            fd_axis=fd_axis)

    # ---- comparison -----------------------------------------------------
    def _compare(self, baseline: LeakSnapshot,
                 pool_slack_bytes: int) -> List[str]:
        out = []
        for site, name in self.live_project_threads(
                baseline.started_watermark):
            out.append(f"thread leak: '{name}' started at "
                       f"{site[0]}:{site[1]} is still alive")
        # fd axis: compare MULTISETS of leak-worthy readlink targets, not
        # (fd number, target) identity — the kernel reuses the lowest free
        # number, so a leaked re-open of a baseline file can land on the
        # baseline's own fd (invisible to an identity check), while a
        # legitimately re-opened baseline file on a higher number is not
        # growth and must not fail the gate. The axis is skipped whole
        # when /proc/self/fd was unavailable at EITHER end (non-procfs
        # platforms; the one-line note comes from fd_axis_snapshot) —
        # comparing a real table against a degraded empty one would only
        # manufacture phantom findings.
        current, cur_axis = self.fd_axis_snapshot()
        if baseline.fd_axis and cur_axis:
            base_counts = Counter(t for _, t in baseline.fds
                                  if _fd_leakworthy(t))
            excess = Counter(t for _, t in current
                             if _fd_leakworthy(t)) - base_counts
            for fd, target in current:
                if excess.get(target, 0) > 0:
                    excess[target] -= 1
                    out.append(f"fd leak: fd {fd} -> {target} (more open "
                               f"than at baseline)")
        resident, entries = self.pool_stats()
        if resident > baseline.pool_resident + pool_slack_bytes:
            out.append(f"device pool leak: resident {resident}B / "
                       f"{entries} entr(ies), baseline was "
                       f"{baseline.pool_resident}B / "
                       f"{baseline.pool_entries} — dead owners were not "
                       f"purged or live segments escaped the fixture")
        return out

    def leaks(self, baseline: Optional[LeakSnapshot] = None,
              grace_s: float = 5.0,
              pool_slack_bytes: int = 0) -> List[str]:
        """Violations vs `baseline` (default: the session baseline), after
        polling with gc.collect() for up to `grace_s` — a thread between
        join(timeout) returning and really exiting, a GC-owned socket, or
        an undrained pool owner gets its grace; a real leak stays."""
        baseline = baseline or self.baseline
        assert baseline is not None, "no baseline snapshot"
        deadline = time.monotonic() + grace_s
        while True:
            out = self._compare(baseline, pool_slack_bytes)
            if not out or time.monotonic() >= deadline:
                return out
            gc.collect()
            time.sleep(0.05)
