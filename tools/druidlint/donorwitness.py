"""donorwitness: a dynamic witness for donorguard's buffer-ownership
protocol — take → dispatch → re-park, proven on real pool objects.

donorguard proves the ownership discipline statically, but a dataflow
edge is not an identity: only the runtime can see WHICH array object was
popped, donated, re-parked, or silently dropped. The witness closes that
loop by tracking array identity (id + weakref) across the cycle the
engine actually runs:

  * `DeviceSegmentPool.take` — every leaf of a popped entry moves from
    the RESIDENT registry to the OUTSTANDING registry: the caller now
    owns it and owes the pool a re-park, a return, or an explicit
    discard.
  * `DeviceSegmentPool.get_or_build` — every leaf of the returned entry
    is registered RESIDENT (the pool references it); leaves that were
    outstanding are discharged (the re-park leg of the cycle).
  * the donating dispatch (`grouping._build_device_fn`'s product, the
    only donate_argnums construction in the tree) — before the call,
    any carry leaf still RESIDENT is a cached-entry donation (donating
    a buffer the pool still references poisons every future hit: the
    dynamic twin of donorguard's `donate-cached-entry`). After a
    SUCCESSFUL call, outstanding carry leaves are discharged and their
    device buffers deleted — donation is SIMULATED on CPU, where jit
    ignores donate_argnums, so a post-dispatch touch of a donated
    argument raises exactly as it would on TPU (`read-after-donate`,
    enforced in vivo while donation itself stays off).
  * `megakernel.discard_carries` — the explicit failure-path discharge;
    its leaves leave the outstanding registry (the fix donorguard's
    `take-without-repark` demands).

A buffer that dies — or is still live at teardown — while OUTSTANDING
was popped and never re-parked, returned, or discarded: the pool's byte
accounting (decremented at take) now lies about real device memory.
Both are violations.

Only the process-wide pool SINGLETON (devicepool._POOL at install time)
is witnessed: test fixtures build isolated pools with synthetic owner
tokens and drop takes deliberately. Host numpy leaves (fresh_carries
placeholders) carry no device buffer — they are skipped explicitly; the
protocol governs device buffers.

Session mode mirrors lock/leak/key/stallwitness: DRUID_TPU_DONOR_WITNESS=1
installs a process-wide singleton from tests/conftest.py and fails the
run on any violation in pytest_unconfigure.

Test-only: nothing in druid_tpu imports this module.
"""
from __future__ import annotations

import threading
import weakref
from typing import Callable, Dict, List, Optional, Tuple

#: process-wide session witness (see session_witness)
_SESSION: Optional["DonorWitness"] = None


def session_witness(root: Optional[str] = None) -> Optional["DonorWitness"]:
    """Process-wide singleton install (same double-conftest rationale as
    lockwitness.session_witness). First call (with `root`) installs;
    later calls return the same witness."""
    global _SESSION
    if _SESSION is None and root is not None:
        _SESSION = DonorWitness(root).install()
    return _SESSION


def end_session_witness() -> Optional["DonorWitness"]:
    """Uninstall and detach the session witness (reporting hook)."""
    global _SESSION
    w, _SESSION = _SESSION, None
    if w is not None:
        w.uninstall()
    return w


def _leaves(value, depth: int = 6) -> List[object]:
    """Array leaves of a pool entry / carry tuple (dtype+shape duck
    type), recursing through the container shapes entries actually use."""
    if depth <= 0:
        return []
    if hasattr(value, "dtype") and hasattr(value, "shape"):
        if type(value).__module__.partition(".")[0] == "numpy":
            return []             # host placeholder: no device buffer
        return [value]
    if isinstance(value, (tuple, list)):
        out: List[object] = []
        for v in value:
            out.extend(_leaves(v, depth - 1))
        return out
    if isinstance(value, dict):
        out = []
        for v in value.values():
            out.extend(_leaves(v, depth - 1))
        return out
    return []


def _describe(leaf) -> str:
    return f"arr({getattr(leaf, 'dtype', '?')}," \
           f"{tuple(getattr(leaf, 'shape', ()))})"


class DonorWitness:
    """Holds observed ownership state for one install()/uninstall() span."""

    def __init__(self, root: str):
        self.root = root
        # reentrant: weakref death callbacks can fire wherever a refcount
        # drops, including on a thread already inside a locked region
        self._meta = threading.RLock()
        #: id(leaf) → (weakref, description, origin key) for popped-but-
        #: not-yet-discharged buffers the caller owes the pool for
        self.outstanding: Dict[int, Tuple[object, str, str]] = {}
        #: id(leaf) → weakref for buffers a pool entry still references
        self.resident: Dict[int, object] = {}
        #: protocol violations (cached-entry donation, post-dispatch
        #: touch via simulated-donation delete, dropped/unreparked takes)
        self.violations: List[str] = []
        #: event counters: takes / reparks / dispatches / discards /
        #: donated leaves deleted
        self.counts: Dict[str, int] = {}
        self._installed = False
        self._saved: List[Tuple[object, str, object]] = []
        #: the production pool singleton captured at install(); accesses
        #: through any OTHER pool instance (test fixtures) are unrecorded
        self._prod_pool: Optional[object] = None

    # ---- registries -----------------------------------------------------
    def _count(self, kind: str) -> None:
        with self._meta:
            self.counts[kind] = self.counts.get(kind, 0) + 1

    def _ref(self, leaf, on_dead: Optional[Callable] = None):
        try:
            return weakref.ref(leaf, on_dead) if on_dead is not None \
                else weakref.ref(leaf)
        except TypeError:
            return None               # weakref-less type: untrackable

    def _note_take(self, value, key: str) -> None:
        self._count("take")
        for leaf in _leaves(value):
            lid = id(leaf)
            desc = _describe(leaf)

            def on_dead(_ref, lid=lid, desc=desc, key=key):
                # the buffer died while the pool was still owed its
                # re-park: ownership was dropped silently, and the pool's
                # byte accounting (decremented at take) now lies
                with self._meta:
                    if self.outstanding.pop(lid, None) is not None:
                        self.violations.append(
                            f"popped buffer {desc} (take of {key}) was "
                            f"garbage-collected while outstanding — no "
                            f"re-park, return, or explicit discard "
                            f"discharged the ownership the take popped")

            ref = self._ref(leaf, on_dead)
            if ref is None:
                continue
            with self._meta:
                self.resident.pop(lid, None)
                self.outstanding[lid] = (ref, desc, key)

    def _note_park(self, value) -> None:
        self._count("repark")
        for leaf in _leaves(value):
            lid = id(leaf)
            with self._meta:
                self.outstanding.pop(lid, None)
            ref = self._ref(leaf)
            if ref is not None:
                with self._meta:
                    self.resident[lid] = ref

    def _discharge(self, value, kind: str) -> None:
        self._count(kind)
        for leaf in _leaves(value):
            with self._meta:
                self.outstanding.pop(id(leaf), None)

    # ---- the donating dispatch -----------------------------------------
    def _before_dispatch(self, carries) -> None:
        self._count("dispatch")
        for leaf in _leaves(carries):
            with self._meta:
                ref = self.resident.get(id(leaf))
                got = ref() if ref is not None else None
                if got is leaf:
                    self.violations.append(
                        f"cached-entry donation: carry leaf "
                        f"{_describe(leaf)} entered a donated position "
                        f"while a pool entry still references it — pop it "
                        f"with take()/device_take() before the dispatch")

    def _after_dispatch(self, carries) -> None:
        """Success path: donation consumed the carries. Discharge the
        ownership and delete the buffers — jit on CPU ignored
        donate_argnums, so deleting here makes any later touch raise
        exactly as the donated-away buffer would on TPU."""
        for leaf in _leaves(carries):
            lid = id(leaf)
            with self._meta:
                owned = self.outstanding.pop(lid, None) is not None
            if not owned:
                continue              # fresh host zeros / caller-owned
            delete = getattr(leaf, "delete", None)
            if delete is None:
                continue
            try:
                delete()
                self._count("donated-delete")
            except Exception:  # druidlint: disable=swallowed-exception
                pass          # already invalidated: the goal holds

    # ---- install/uninstall ---------------------------------------------
    def install(self) -> "DonorWitness":
        if self._installed:
            return self
        witness = self

        from druid_tpu.data import devicepool
        # bind the singleton NOW: fixtures monkeypatch devicepool._POOL to
        # fresh pools, so a call-time re-read would witness those too
        self._prod_pool = devicepool._POOL

        real_take = devicepool.DeviceSegmentPool.take

        def take(pool_self, owner, key):
            value = real_take(pool_self, owner, key)
            if value is not None and pool_self is witness._prod_pool \
                    and witness._installed:
                witness._note_take(value, repr((owner,) + tuple(key)))
            return value

        self._saved.append((devicepool.DeviceSegmentPool, "take", real_take))
        devicepool.DeviceSegmentPool.take = take

        real_gob = devicepool.DeviceSegmentPool.get_or_build

        def get_or_build(pool_self, owner, key, build):
            value = real_gob(pool_self, owner, key, build)
            if pool_self is witness._prod_pool and witness._installed:
                witness._note_park(value)
            return value

        self._saved.append(
            (devicepool.DeviceSegmentPool, "get_or_build", real_gob))
        devicepool.DeviceSegmentPool.get_or_build = get_or_build

        from druid_tpu.engine import grouping, megakernel

        real_builder = grouping._build_device_fn

        def build_device_fn(*args, **kwargs):
            fn = real_builder(*args, **kwargs)

            def dispatched(*fargs, **fkwargs):
                carries = fargs[2] if len(fargs) > 2 else ()
                armed = witness._installed and carries
                if armed:
                    witness._before_dispatch(carries)
                out = fn(*fargs, **fkwargs)
                if armed:
                    witness._after_dispatch(carries)
                return out

            return dispatched

        self._saved.append((grouping, "_build_device_fn", real_builder))
        grouping._build_device_fn = build_device_fn

        real_discard = megakernel.discard_carries

        def discard_carries(carries):
            if witness._installed:
                witness._discharge(carries, "discard")
            return real_discard(carries)

        self._saved.append((megakernel, "discard_carries", real_discard))
        megakernel.discard_carries = discard_carries

        self._installed = True
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        # wrapped dispatch callables may live on in _JIT_CACHE entries;
        # they check _installed and pass through once the witness is gone
        self._installed = False
        for obj, attr, original in reversed(self._saved):
            setattr(obj, attr, original)
        self._saved.clear()

    def __enter__(self) -> "DonorWitness":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    # ---- reporting ------------------------------------------------------
    def unreparked(self) -> List[str]:
        """Takes still outstanding: buffers the pool is owed at teardown."""
        with self._meta:
            out = []
            for lid, (ref, desc, key) in sorted(self.outstanding.items()):
                if ref() is not None:
                    out.append(
                        f"popped buffer {desc} (take of {key}) still "
                        f"outstanding at teardown — re-park it "
                        f"(device_cached/get_or_build) or discard it "
                        f"explicitly (megakernel.discard_carries)")
            return out

    def all_violations(self) -> List[str]:
        with self._meta:
            live = list(self.violations)
        return live + self.unreparked()

    def summary(self) -> str:
        with self._meta:
            c = self.counts
            n_viol = len(self.violations)
        return (f"{c.get('take', 0)} take(s), {c.get('repark', 0)} "
                f"re-park(s), {c.get('dispatch', 0)} donating "
                f"dispatch(es), {c.get('donated-delete', 0)} donated "
                f"leaf(ves) invalidated, {c.get('discard', 0)} explicit "
                f"discard(s), {n_viol + len(self.unreparked())} "
                f"violation(s)")
