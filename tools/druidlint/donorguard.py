"""donorguard: whole-program device-buffer ownership & donation
soundness — a donated buffer is gone, and the pool's books must agree.

The seventh analyzer family, riding raceguard's shared program index
(same module set, binder, call graph, cache signature). Every path built
since PR 11 leans on donated accumulator grids
(`jax.jit(donate_argnums=...)` plus the `DeviceSegmentPool.take`/re-park
protocol), yet donation has only ever executed DISABLED on CPU: the
first real-TPU run with donation on is the first time a donated buffer
is genuinely invalidated, and every ownership sloppiness the parity
suite cannot see today surfaces there as silent corruption or an HBM
leak. donorguard discovers every donation site (literal
``donate_argnums`` in a jit call, and every "donating builder" — a
function that returns such a jit), every pool ownership transfer
(`take`/`device_take` pops, `put`/`get_or_build`/`device_cached`/
`adopt_carries_from` parks), and enforces five rules:

  read-after-donate       a local passed in a donated position is
                          referenced again after the dispatch — on a
                          donating backend that buffer no longer exists
  donate-cached-entry     a `get_or_build`/`device_cached`/`peek` result
                          flows into a donated argnum without an
                          intervening ownership-popping take — donating
                          a buffer the pool still references poisons
                          every future hit
  take-without-repark     popped ownership is not re-parked, returned,
                          or explicitly discarded on every path,
                          including exception paths — leakguard's
                          lifecycle discipline extended to device
                          buffers
  donate-platform-gate    every backend/platform comparison must live in
                          a configured shared predicate
                          (`donorguard-platform-gate`) — a scattered
                          donation-enable decision is the CPU-segfault
                          class
  carry-grid-init         a pallas program reachable from a donating jit
                          must re-initialize its accumulator grids at
                          grid step 0 (`@pl.when(i == 0)`), the PR 11
                          bit-identity discipline; a fresh-init design
                          declares itself with a rationale suppression

The dynamic peer is tools/druidlint/donorwitness.py: armed suite-wide by
DRUID_TPU_DONOR_WITNESS=1, it tracks array identity across the
take → dispatch → re-park cycle and fails the session on a cached-entry
donation, a post-dispatch touch of a donated argument, or un-reparked
takes at teardown — so the ownership PROTOCOL is enforced even while
donation itself stays off on CPU.

Analysis model: lineno-linear within a function (loop back-edges are
ignored; the dispatch loops in this tree rebind their carries at the
loop top, so the linear view is the honest one), donation positions are
literal-only (`donate_argnums=(2,)`), and emission is gated to the
raceguard module set. Findings are memoized on the Program per config
key, keyguard-style: the blessed-gate list is config, not program state.
"""
from __future__ import annotations

import ast
import fnmatch
from types import SimpleNamespace
from typing import Dict, FrozenSet, Iterable, List, Optional, Set, Tuple

from tools.druidlint.core import Finding, ModuleContext, rule
from tools.druidlint.raceguard import (FuncInfo, ModuleInfo, Program, Site,
                                       _own, _resolve_import)
from tools.druidlint.rules import _FUNC_DEFS, _terminal

# ---------------------------------------------------------------------------
# ownership vocabulary
# ---------------------------------------------------------------------------

#: method terminals that POP pool ownership into the caller
_TAKE_VERBS = ("take", "device_take")

#: method terminals that PARK ownership back into a pool / registry
_PARK_VERBS = ("put", "get_or_build", "device_cached", "adopt_carries_from")

#: call terminals whose result is a still-pool-referenced cached entry
_CACHE_GETTERS = ("get_or_build", "device_cached", "peek")


def _discardish(terminal: str) -> bool:
    """An explicit ownership-discharge verb (megakernel.discard_carries,
    a drop_* helper): consumes a popped buffer on a failure path."""
    t = terminal.lower()
    return "discard" in t or "drop" in t


# ---------------------------------------------------------------------------
# shared AST helpers (stallguard's shapes)
# ---------------------------------------------------------------------------

def _match_fid(fid: str, entries: List[str]) -> bool:
    path, _, qual = fid.partition("::")
    for e in entries:
        ep, _, eq = e.partition("::")
        if fnmatch.fnmatch(path, ep) and fnmatch.fnmatch(qual, eq):
            return True
    return False


def _own_sorted(fi: FuncInfo) -> List[ast.AST]:
    return sorted((n for n in _own(fi) if hasattr(n, "lineno")),
                  key=lambda n: (n.lineno, n.col_offset))


def _parents_of(fi: FuncInfo) -> Dict[ast.AST, ast.AST]:
    """Child → parent over fi's own scope (nested def/class bodies are
    separate FuncInfos and excluded, mirroring _own)."""
    out: Dict[ast.AST, ast.AST] = {}
    stack = [fi.node]
    while stack:
        node = stack.pop()
        if node is not fi.node and isinstance(
                node, _FUNC_DEFS + (ast.ClassDef,)):
            continue
        for child in ast.iter_child_nodes(node):
            out[child] = node
            stack.append(child)
    return out


def _mentions(node: ast.AST, names: Set[str]) -> bool:
    return any(isinstance(n, ast.Name) and n.id in names
               for n in ast.walk(node))


def _call_args_mention(call: ast.Call, names: Set[str]) -> bool:
    return any(_mentions(a, names) for a in call.args) or \
        any(_mentions(k.value, names) for k in call.keywords)


def _chain(parents: Dict[ast.AST, ast.AST], node: ast.AST) -> List[ast.AST]:
    out = [node]
    cur = parents.get(node)
    while cur is not None:
        out.append(cur)
        cur = parents.get(cur)
    return out


# ---------------------------------------------------------------------------
# donation-site discovery
# ---------------------------------------------------------------------------

def _donate_positions(node: ast.AST) -> Optional[FrozenSet[int]]:
    """Literal donate_argnums positions of a jit(...) call, else None.
    Non-literal argnums donate *something* but the positions are
    unknowable statically — those sites are skipped (the tree only uses
    literal tuples; keeping the analysis literal-only keeps it quiet)."""
    if not isinstance(node, ast.Call) or _terminal(node.func) != "jit":
        return None
    for k in node.keywords:
        if k.arg != "donate_argnums":
            continue
        v = k.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return frozenset((v.value,))
        if isinstance(v, (ast.Tuple, ast.List)):
            out: Set[int] = set()
            for e in v.elts:
                if not (isinstance(e, ast.Constant)
                        and isinstance(e.value, int)):
                    return None
                out.add(e.value)
            return frozenset(out)
        return None
    return None


def _donating_builders(prog: Program) -> Dict[str, FrozenSet[int]]:
    """func_id → donated positions, for every function that RETURNS a
    jit-with-donate on some path (grouping._build_device_fn's shape:
    strategy decides which jit construction is returned; the union of
    the donated positions over all return sites is the may-set)."""
    out: Dict[str, FrozenSet[int]] = {}
    for fid, fi in prog.funcs.items():
        if not isinstance(fi.node, _FUNC_DEFS):
            continue
        pos: Set[int] = set()
        for node in _own(fi):
            if not isinstance(node, ast.Return) or node.value is None:
                continue
            for sub in ast.walk(node.value):
                p = _donate_positions(sub)
                if p:
                    pos |= p
        if pos:
            out[fid] = frozenset(pos)
    return out


def _resolve_name_func(prog: Program, mod: Optional[ModuleInfo],
                       fi: Optional[FuncInfo],
                       name: str) -> Optional[str]:
    """A bare Name in fi's scope → program func_id: nested def,
    module-level function, or imported symbol (re-export chains via
    raceguard's resolver)."""
    if fi is not None:
        cand = f"{fi.path}::{fi.qual}.<locals>.{name}"
        if cand in prog.funcs:
            return cand
    if mod is None:
        return None
    got = mod.globals.get(name)
    if got is not None and got[0] == "func":
        return got[1]
    imp = mod.imports.get(name)
    if imp is not None:
        r = _resolve_import(prog, ("import",) + imp)
        if r is not None and r[0] == "func":
            return r[1]
    return None


def _callee_fid(prog: Program, mod: Optional[ModuleInfo], fi: FuncInfo,
                call: ast.Call) -> Optional[str]:
    """Resolve a call's callee to a program func_id (Name or
    one-level module-attribute form), else None."""
    f = call.func
    if isinstance(f, ast.Name):
        return _resolve_name_func(prog, mod, fi, f.id)
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
            and mod is not None:
        imp = mod.imports.get(f.value.id)
        if imp is not None:
            r = _resolve_import(prog, ("import",) + imp)
            if r is not None and r[0] == "module" and r[1] is not None:
                cand = f"{r[1]}::{f.attr}"
                if cand in prog.funcs:
                    return cand
    return None


def _module_donating(prog: Program, mod: ModuleInfo,
                     builders: Dict[str, FrozenSet[int]]) \
        -> Dict[str, FrozenSet[int]]:
    """Module-level name → donated positions, for globals assigned from a
    direct jit-with-donate or a donating-builder call."""
    out: Dict[str, FrozenSet[int]] = {}
    for node in mod.tree.body:
        if not isinstance(node, ast.Assign):
            continue
        v = node.value
        p = _donate_positions(v)
        if p is None and isinstance(v, ast.Call):
            callee = _callee_fid(prog, mod, None, v) \
                if not isinstance(v.func, ast.Name) else \
                _resolve_name_func(prog, mod, None, v.func.id)
            if callee in builders:
                p = builders[callee]
        if p:
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out[t.id] = out.get(t.id, frozenset()) | p
    return out


def _donating_names(prog: Program, mod: Optional[ModuleInfo], fi: FuncInfo,
                    builders: Dict[str, FrozenSet[int]],
                    mod_donating: Dict[str, FrozenSet[int]]) \
        -> Dict[str, FrozenSet[int]]:
    """Local (and visible module-global) name → donated positions, from
    ANY assignment whose value is a direct jit-with-donate or a call to
    a donating builder. May-analysis: the grouping dispatch loop binds
    `fn` from the jit cache OR the builder; either binding donating
    makes every `fn(...)` call a donating dispatch."""
    out: Dict[str, FrozenSet[int]] = dict(mod_donating)
    for node in _own(fi):
        if not isinstance(node, ast.Assign):
            continue
        v = node.value
        p = _donate_positions(v)
        if p is None and isinstance(v, ast.Call):
            callee = _callee_fid(prog, mod, fi, v)
            if callee in builders:
                p = builders[callee]
        if p:
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out[t.id] = out.get(t.id, frozenset()) | p
    return out


def _dispatches(fi: FuncInfo,
                donating: Dict[str, FrozenSet[int]]) \
        -> List[Tuple[ast.Call, Set[str]]]:
    """Donating dispatch calls in fi's own scope, each with the set of
    local names mentioned in its donated positional arguments."""
    out: List[Tuple[ast.Call, Set[str]]] = []
    for node in _own(fi):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)):
            continue
        pos = donating.get(node.func.id)
        if not pos:
            continue
        names: Set[str] = set()
        for i in sorted(pos):
            if i < len(node.args):
                names |= {n.id for n in ast.walk(node.args[i])
                          if isinstance(n, ast.Name)}
        out.append((node, names))
    return out


# ---------------------------------------------------------------------------
# the five checks
# ---------------------------------------------------------------------------

def _in_discard(parents: Dict[ast.AST, ast.AST], node: ast.AST) -> bool:
    for anc in _chain(parents, node)[1:]:
        if isinstance(anc, ast.Call) and _discardish(_terminal(anc.func)):
            return True
    return False


def _check_read_after_donate(prog: Program, config, add,
                             builders, mod_donating) -> None:
    for fid, fi in prog.funcs.items():
        if not isinstance(fi.node, _FUNC_DEFS):
            continue
        mod = prog.modules.get(fi.path)
        donating = _donating_names(prog, mod, fi, builders,
                                   mod_donating.get(fi.path, {}))
        if not donating:
            continue
        dispatches = _dispatches(fi, donating)
        if not dispatches:
            continue
        parents = _parents_of(fi)
        own = _own_sorted(fi)
        for dnode, names in dispatches:
            end = getattr(dnode, "end_lineno", None) or dnode.lineno
            live = set(names)
            reported: Set[str] = set()
            for node in own:
                if node.lineno <= end or not live:
                    continue
                if isinstance(node, ast.Name) and node.id in live:
                    if isinstance(node.ctx, ast.Store):
                        live.discard(node.id)   # rebound; later reads fine
                    elif isinstance(node.ctx, ast.Load) \
                            and node.id not in reported \
                            and not _in_discard(parents, node):
                        reported.add(node.id)
                        add("read-after-donate",
                            Site(fi.path, node.lineno, node.col_offset),
                            f"`{node.id}` was passed in a donated position "
                            f"at line {dnode.lineno} — on a donating "
                            f"backend its buffer no longer exists; compute "
                            f"from it before the dispatch, rebind it, or "
                            f"discard it explicitly")


_CTRL = (ast.If, ast.While, ast.For, ast.Try, ast.ExceptHandler)


def _ctrl_of(parents: Dict[ast.AST, ast.AST],
             node: ast.AST) -> FrozenSet[int]:
    """Identity set of the node's control-region ancestors. A clears B's
    taint only when ctrl(A) ⊆ ctrl(B): every path to B then passes
    through A's block — the dominance proxy that keeps the cached-entry
    state a MAY-set across branches (a fallback assignment inside an
    `if carried is None` must not launder a cached entry taken on the
    other branch)."""
    return frozenset(id(a) for a in _chain(parents, node)[1:]
                     if isinstance(a, _CTRL))


def _check_cached_entry(prog: Program, config, add,
                        builders, mod_donating) -> None:
    for fid, fi in prog.funcs.items():
        if not isinstance(fi.node, _FUNC_DEFS):
            continue
        mod = prog.modules.get(fi.path)
        donating = _donating_names(prog, mod, fi, builders,
                                   mod_donating.get(fi.path, {}))
        if not donating:
            continue
        dispatches = _dispatches(fi, donating)
        if not dispatches:
            continue
        parents = _parents_of(fi)
        own = _own_sorted(fi)
        for dnode, _names in dispatches:
            dctrl = _ctrl_of(parents, dnode)
            cached: Set[str] = set()
            for node in own:
                if node.lineno >= dnode.lineno:
                    break
                if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                    continue
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                names = {t.id for t in targets if isinstance(t, ast.Name)}
                v = node.value
                if not names or v is None:
                    continue
                if any(isinstance(sub, ast.Call)
                       and _terminal(sub.func) in _CACHE_GETTERS
                       for sub in ast.walk(v)):
                    cached |= names         # pool still references this
                elif _mentions(v, cached):
                    cached |= names         # derived from a cached entry
                elif _ctrl_of(parents, node) <= dctrl:
                    # ownership-popping take or clean rebind — clears the
                    # taint only when it dominates the dispatch; a branch
                    # the dispatch can skip does not launder the entry
                    cached -= names
            pos = donating.get(dnode.func.id) or frozenset()
            for i in sorted(pos):
                if i < len(dnode.args) \
                        and _mentions(dnode.args[i], cached):
                    add("donate-cached-entry",
                        Site(fi.path, dnode.args[i].lineno,
                             dnode.args[i].col_offset),
                        f"donated argument {i} of `{dnode.func.id}` "
                        f"derives from a cached pool entry "
                        f"(get_or_build/device_cached/peek) with no "
                        f"ownership-popping take in between — the "
                        f"pool's next hit returns an invalidated "
                        f"buffer; pop it with take()/device_take() "
                        f"first")
                    break


def _check_take_repark(prog: Program, config, add,
                       builders, mod_donating) -> None:
    for fid, fi in prog.funcs.items():
        if not isinstance(fi.node, _FUNC_DEFS):
            continue
        takes: List[Tuple[str, ast.Assign]] = []
        for node in _own(fi):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call) \
                    and isinstance(node.value.func, ast.Attribute) \
                    and _terminal(node.value.func) in _TAKE_VERBS:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        takes.append((t.id, node))
        if not takes:
            continue
        mod = prog.modules.get(fi.path)
        donating = _donating_names(prog, mod, fi, builders,
                                   mod_donating.get(fi.path, {}))
        parents = _parents_of(fi)
        for name, tnode in takes:
            consumes: List[Tuple[ast.AST, bool]] = []   # (node, can_raise)
            for node in _own(fi):
                if isinstance(node, ast.Call):
                    t = _terminal(node.func)
                    is_dispatch = isinstance(node.func, ast.Name) \
                        and bool(donating.get(node.func.id))
                    if (t in _PARK_VERBS or _discardish(t) or is_dispatch) \
                            and _call_args_mention(node, {name}):
                        consumes.append((node, is_dispatch))
                elif isinstance(node, ast.Return) and node.value is not None \
                        and _mentions(node.value, {name}):
                    consumes.append((node, False))
                elif isinstance(node, ast.Delete) and any(
                        isinstance(d, ast.Name) and d.id == name
                        for d in node.targets):
                    consumes.append((node, False))
            if not consumes:
                add("take-without-repark",
                    Site(fi.path, tnode.lineno, tnode.col_offset),
                    f"take pops `{name}` from the pool but no path "
                    f"re-parks, returns, or discards it — the popped "
                    f"buffer dangles as untracked device memory; park it "
                    f"back (put/device_cached) or discard it explicitly")
                continue
            # exception-path coverage: a consume that can raise mid-donation
            # (the donating dispatch) must have SOME enclosing try whose
            # handler/finalbody also consumes the popped name — otherwise
            # the exception path drops ownership silently
            consume_chains = [(_chain(parents, n), n) for n, _ in consumes]
            for cnode, can_raise in consumes:
                if not can_raise:
                    continue
                covered = False
                unprotected = True
                for anc in _chain(parents, cnode)[1:]:
                    if not isinstance(anc, ast.Try):
                        continue
                    unprotected = False
                    for ch, other in consume_chains:
                        if other is cnode or anc not in ch:
                            continue
                        child = ch[ch.index(anc) - 1]
                        if isinstance(child, ast.ExceptHandler) or \
                                any(child is x for x in anc.finalbody):
                            covered = True
                            break
                    if covered:
                        break
                if not covered and not unprotected:
                    add("take-without-repark",
                        Site(fi.path, tnode.lineno, tnode.col_offset),
                        f"take pops `{name}` but the donating dispatch at "
                        f"line {cnode.lineno} sits in a try whose handlers "
                        f"never re-park or discard it — a dispatch failure "
                        f"drops the popped buffer and the pool's byte "
                        f"accounting drifts; discard it in an except/"
                        f"finally (megakernel.discard_carries)")


def _platform_probe(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call) \
                and _terminal(sub.func) == "default_backend":
            return True
        if isinstance(sub, ast.Attribute) and sub.attr == "platform" \
                and _terminal(sub.value) != "sys":
            return True
    return False


def _check_platform_gate(prog: Program, config, add) -> None:
    allowed = list(getattr(config, "donorguard_platform_gate", []) or [])
    for path, mod in prog.modules.items():
        stack: List[Tuple[ast.AST, str, str]] = [(mod.tree, "", "module")]
        while stack:
            node, qual, kind = stack.pop()
            for child in ast.iter_child_nodes(node):
                if isinstance(child, _FUNC_DEFS):
                    sep = ".<locals>." if kind == "func" else \
                        "." if qual else ""
                    stack.append((child, f"{qual}{sep}{child.name}",
                                  "func"))
                elif isinstance(child, ast.ClassDef):
                    cq = f"{qual}.{child.name}" if qual else child.name
                    stack.append((child, cq, "class"))
                elif isinstance(child, ast.Compare):
                    if _platform_probe(child):
                        fid = f"{path}::{qual or '<module>'}"
                        if not _match_fid(fid, allowed):
                            add("donate-platform-gate",
                                Site(path, child.lineno, child.col_offset),
                                f"backend/platform comparison outside the "
                                f"shared gate ({qual or '<module>'}) — "
                                f"every donation-enable decision must "
                                f"route through contracts."
                                f"donation_supported (or be declared in "
                                f"`donorguard-platform-gate`)")
                else:
                    stack.append((child, qual, kind))


def _is_zero(e: ast.AST) -> bool:
    """0, or a one-argument cast of 0 (jnp.int32(0))."""
    if isinstance(e, ast.Constant):
        return e.value == 0 and not isinstance(e.value, bool)
    if isinstance(e, ast.Call) and len(e.args) == 1 and not e.keywords:
        return _is_zero(e.args[0])
    return False


def _has_step0_init(prog: Program, host: FuncInfo) -> bool:
    """Some def nested under `host` carries a `@pl.when(i == 0)`-shaped
    decorator (either comparand a literal/cast zero) — the grid-step-0
    re-initialization that makes donated reuse bit-identical to fresh
    zeros."""
    pref = host.qual + "."
    for fid, fi in prog.funcs.items():
        if fi.path != host.path or not fi.qual.startswith(pref):
            continue
        for dec in getattr(fi.node, "decorator_list", ()):
            if not (isinstance(dec, ast.Call)
                    and _terminal(dec.func) == "when" and dec.args):
                continue
            cmp = dec.args[0]
            if isinstance(cmp, ast.Compare) and len(cmp.ops) == 1 \
                    and isinstance(cmp.ops[0], ast.Eq) \
                    and (_is_zero(cmp.left)
                         or _is_zero(cmp.comparators[0])):
                return True
    return False


def _check_carry_init(prog: Program, config, add, builders) -> None:
    seen: Set[str] = set()
    for fid, fi in prog.funcs.items():
        if not isinstance(fi.node, _FUNC_DEFS):
            continue
        mod = prog.modules.get(fi.path)
        for node in _own(fi):
            if _donate_positions(node) is None:
                continue
            if not node.args or not isinstance(node.args[0], ast.Name):
                continue
            entry = _resolve_name_func(prog, mod, fi, node.args[0].id)
            if entry is None:
                continue
            # everything the donated program reaches, over the binder's
            # call edges
            reach = {entry}
            work = [entry]
            while work:
                f = prog.funcs.get(work.pop())
                if f is None:
                    continue
                for callee, _held, _site, _recv in f.calls:
                    if callee not in reach and callee in prog.funcs:
                        reach.add(callee)
                        work.append(callee)
            for rfid in sorted(reach):
                if rfid in seen:
                    continue
                host = prog.funcs[rfid]
                pc = next((n for n in _own(host)
                           if isinstance(n, ast.Call)
                           and _terminal(n.func) == "pallas_call"), None)
                if pc is None:
                    continue
                seen.add(rfid)
                if not _has_step0_init(prog, host):
                    add("carry-grid-init",
                        Site(host.path, pc.lineno, pc.col_offset),
                        f"{host.qual} is reachable from a donating jit "
                        f"(donate_argnums at {fi.path}:{node.lineno}) but "
                        f"its kernel never re-initializes the accumulator "
                        f"grids at grid step 0 (`@pl.when(i == 0)`) — "
                        f"donated reuse replays the previous execution's "
                        f"state; add the step-0 init or declare fresh-init "
                        f"with a rationale suppression")


# ---------------------------------------------------------------------------
# findings assembly + rule shims (stallguard's structure, keyguard's
# config-keyed memo: the blessed-gate list is config, not program state)
# ---------------------------------------------------------------------------

def _config_key(config) -> tuple:
    return (tuple(getattr(config, "donorguard_platform_gate", []) or []),
            tuple(config.raceguard_modules))


def donor_findings(prog: Program, config) \
        -> Dict[str, Dict[str, List[Tuple]]]:
    key = _config_key(config)
    got = getattr(prog, "_donor_findings", None)
    if got is not None and got[0] == key:
        return got[1]
    findings: Dict[str, Dict[str, List[Tuple]]] = {}

    def add(rule_name: str, site: Site, message: str) -> None:
        findings.setdefault(rule_name, {}).setdefault(
            site.path, []).append((site.line, site.col, message))

    builders = _donating_builders(prog)
    mod_donating = {path: _module_donating(prog, mod, builders)
                    for path, mod in prog.modules.items()}
    _check_read_after_donate(prog, config, add, builders, mod_donating)
    _check_cached_entry(prog, config, add, builders, mod_donating)
    _check_take_repark(prog, config, add, builders, mod_donating)
    _check_platform_gate(prog, config, add)
    _check_carry_init(prog, config, add, builders)
    prog._donor_findings = (key, findings)
    return findings


def _program_for(ctx: ModuleContext) -> Program:
    from tools.druidlint.raceguard import _program_for as rg_program
    return rg_program(ctx)


def _emit(ctx: ModuleContext, rule_name: str) -> Iterable[Finding]:
    if not ctx.path_matches(ctx.config.raceguard_modules):
        return
    prog = _program_for(ctx)
    data = donor_findings(prog, ctx.config)
    for line, col, message in sorted(
            data.get(rule_name, {}).get(ctx.path, ())):
        yield ctx.finding(SimpleNamespace(lineno=line, col_offset=col),
                          message)


@rule("read-after-donate", "error",
      "donated argument referenced again after the dispatch")
def check_read_after_donate(ctx: ModuleContext) -> Iterable[Finding]:
    """A local passed in a donated position (`donate_argnums`) is
    referenced again after the donating dispatch. On CPU, where donation
    is silently ignored, the read returns stale-but-valid data and every
    parity test passes; on TPU the buffer was invalidated at dispatch
    and the same read is garbage or a crash — the exact class the owed
    real-TPU bench would be first to hit. Compute what you need from the
    buffer BEFORE the dispatch (the grouping loop's donated_nbytes
    shape), rebind the name, or route the reference through an explicit
    discard helper."""
    yield from _emit(ctx, "read-after-donate")


@rule("donate-cached-entry", "error",
      "cached pool entry flows into a donated argnum without a take")
def check_donate_cached_entry(ctx: ModuleContext) -> Iterable[Finding]:
    """A `get_or_build`/`device_cached`/`peek` result — a buffer the
    DeviceSegmentPool still references — flows into a donated position
    with no ownership-popping `take`/`device_take` in between. Donation
    invalidates the buffer but the pool entry survives, so every future
    cache hit returns poison. The take→dispatch→re-park cycle exists
    precisely to pop the entry first; the dynamic donorwitness enforces
    the same invariant on real pool objects at test time."""
    yield from _emit(ctx, "donate-cached-entry")


@rule("take-without-repark", "error",
      "popped pool ownership not re-parked on every path")
def check_take_without_repark(ctx: ModuleContext) -> Iterable[Finding]:
    """A `take`/`device_take` pops a buffer from the pool (the pool's
    byte accounting is decremented at pop), but some path — including
    the exception path out of a donating dispatch — neither re-parks
    (put/device_cached), returns, nor explicitly discards it
    (megakernel.discard_carries). The buffer dangles as untracked device
    memory while the books claim the bytes were freed: leakguard's
    lifecycle discipline extended to device buffers. Discharge ownership
    in an except/finally on the dispatch."""
    yield from _emit(ctx, "take-without-repark")


@rule("donate-platform-gate", "error",
      "backend/platform comparison outside the shared donation gate")
def check_donate_platform_gate(ctx: ModuleContext) -> Iterable[Finding]:
    """Every backend/platform comparison (`jax.default_backend() == ...`,
    `device.platform == ...`) must live in a predicate named by
    `donorguard-platform-gate` — by default the ONE donation gate
    (contracts.donation_supported, which also owns the tri-state
    DRUID_TPU_DONATE flag) and the pallas availability probe
    (pallas_agg.backend_ok). A scattered inline check is how one call
    site ends up donating on a backend the rest of the engine thinks is
    non-donating: the CPU-segfault class."""
    yield from _emit(ctx, "donate-platform-gate")


@rule("carry-grid-init", "error",
      "donated-accumulator program lacks a grid-step-0 re-init")
def check_carry_grid_init(ctx: ModuleContext) -> Iterable[Finding]:
    """A pallas program reachable from a donating jit construction must
    re-initialize its accumulator grids at grid step 0
    (`@pl.when(i == 0)` on the kernel's init block) — PR 11's
    bit-identity discipline: donated reuse of last execution's grids
    must be indistinguishable from fresh zeros. Without the step-0 init
    the donated buffers replay stale partial aggregates. A kernel whose
    design genuinely allocates fresh grids per dispatch declares it with
    a rationale suppression on the pallas_call."""
    yield from _emit(ctx, "carry-grid-init")
