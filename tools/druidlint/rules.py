"""druidlint rules — each one encodes a real hazard in this tree.

Rules receive a ModuleContext and yield Findings. They are deliberately
syntactic: no import resolution, no type inference. Where a rule needs a
semantic boundary (which modules are leader-duty code, which face the
wire), that boundary is configuration, not guesswork.
"""
from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Set, Tuple

from tools.druidlint.core import Finding, ModuleContext, rule

_FUNC_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)


def _dotted(node: ast.AST) -> str:
    """Best-effort dotted name of an expression ('jax.jit', 'self._lock');
    non-name parts collapse to '?'."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return f"{_dotted(node.value)}.{node.attr}"
    if isinstance(node, ast.Call):
        return _dotted(node.func) + "()"
    return "?"


def _terminal(node: ast.AST) -> str:
    """Last identifier of a possibly-dotted expression."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


# ---- unfenced-metadata-write ---------------------------------------------

FENCED_MUTATORS = {"publish_segments", "mark_unused", "mark_used",
                   "delete_segments", "insert_task", "update_task_status"}


@rule("unfenced-metadata-write", "error",
      "lease-protected MetadataStore mutation without a fencing term")
def check_unfenced_metadata_write(ctx: ModuleContext) -> Iterable[Finding]:
    """In leader-duty modules (config `duty-modules`), every call to a
    fence-capable MetadataStore mutator must pass `fence=` — a deposed
    leader that writes without threading its term bypasses StaleTermError
    and breaks single-writer-per-term."""
    if not ctx.path_matches(ctx.config.duty_modules):
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = _terminal(node.func)
        if name in FENCED_MUTATORS and isinstance(node.func, ast.Attribute):
            if not any(kw.arg == "fence" for kw in node.keywords):
                yield ctx.finding(
                    node, f"{name}() without fence= — thread the leader's "
                          f"(service, term, holder) so stale-term writes "
                          f"are rejected")


# ---- jit-in-hot-path ------------------------------------------------------

_JIT_CTORS = {"jit", "pjit", "pmap", "shard_map", "xmap"}
_CACHE_DECORATORS = {"lru_cache", "cache"}


def _decorator_names(fn: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for d in getattr(fn, "decorator_list", []):
        if isinstance(d, ast.Call):
            d = d.func
        out.add(_terminal(d))
    return out


def _call_is_cache_guarded(ctx: ModuleContext, call: ast.Call) -> bool:
    """True when the builder call's result is memoized: either stored
    directly into a subscript of a cache (`CACHE[k] = build(...)`), passed
    to `.setdefault`, or assigned to a variable that is then stored into a
    subscript (`fn = build(...); CACHE[sig] = fn`) within the same scope."""
    scope = ctx.enclosing_function(call) or ctx.tree
    parent = ctx.parent(call)
    if isinstance(parent, ast.Call) and \
            _terminal(parent.func) == "setdefault":
        return True
    bound: Optional[str] = None
    if isinstance(parent, ast.Assign):
        if any(isinstance(t, ast.Subscript) for t in parent.targets):
            return True
        if len(parent.targets) == 1 and isinstance(parent.targets[0],
                                                   ast.Name):
            bound = parent.targets[0].id
    if bound is None:
        return False
    for node in ast.walk(scope):
        if isinstance(node, ast.Assign) \
                and any(isinstance(t, ast.Subscript) for t in node.targets) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == bound:
            return True
    return False


@rule("jit-in-hot-path", "error",
      "jax.jit/shard_map constructed per call instead of cached")
def check_jit_in_hot_path(ctx: ModuleContext) -> Iterable[Finding]:
    """`jax.jit` / `shard_map` / `pmap` construction inside a function body
    re-traces (and on TPU recompiles) on every call — per-query/per-segment
    paths must construct once at module level, behind functools.lru_cache,
    or behind a module-level cache (`fn = CACHE.get(sig)` / `CACHE[sig] =
    build(...)`). A builder function is accepted when every call site in the
    module stores its result into such a cache."""
    jit_calls: List[ast.Call] = []
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call) and _terminal(node.func) in _JIT_CTORS:
            jit_calls.append(node)
    if not jit_calls:
        return

    # all Call sites per function name, for builder-guard analysis
    calls_by_name: Dict[str, List[ast.Call]] = {}
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
            calls_by_name.setdefault(node.func.id, []).append(node)

    for call in jit_calls:
        fn = ctx.enclosing_function(call)
        if fn is None:
            continue                       # module level: traced once
        if isinstance(fn, _FUNC_DEFS) and \
                _decorator_names(fn) & _CACHE_DECORATORS:
            continue                       # memoized builder
        fname = fn.name if isinstance(fn, _FUNC_DEFS) else "<lambda>"
        sites = calls_by_name.get(fname, [])
        if sites and all(_call_is_cache_guarded(ctx, s) for s in sites):
            continue                       # every call site memoizes
        ctor = _terminal(call.func)
        yield ctx.finding(
            call, f"{ctor}() constructed inside {fname}() — cache the "
                  f"compiled callable (lru_cache or a module-level cache "
                  f"keyed on the static structure) so repeated "
                  f"queries/segments do not retrace")


# ---- host-device-sync -----------------------------------------------------

_TRACE_ENTRIES = {"jit", "pjit", "pmap", "vmap", "shard_map", "scan",
                  "while_loop", "fori_loop", "cond", "checkpoint", "remat",
                  "grad", "value_and_grad", "custom_vjp", "custom_jvp"}
_NUMPY_NAMES = {"np", "numpy", "onp"}
_SYNC_METHODS = {"item", "tolist"}
_NUMPY_MATERIALIZERS = {"asarray", "array", "copy"}


def _collect_traced_functions(ctx: ModuleContext,
                              extra_entries: frozenset = frozenset()
                              ) -> List[ast.AST]:
    """Function defs whose bodies are traced device code: seeds are
    functions passed (by name) to jit/vmap/shard_map/scan/... or decorated
    with them; closure is taken over bare-name calls within traced bodies
    (a helper invoked during tracing is itself traced). `extra_entries`
    widens the seed set (tracecheck adds pallas_call so kernel bodies are
    treated as traced code)."""
    entries = _TRACE_ENTRIES | extra_entries
    defs_by_name: Dict[str, List[ast.AST]] = {}
    for node in ast.walk(ctx.tree):
        if isinstance(node, _FUNC_DEFS):
            defs_by_name.setdefault(node.name, []).append(node)

    traced: Set[ast.AST] = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call) and \
                _terminal(node.func) in entries:
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, ast.Name):
                    traced.update(defs_by_name.get(arg.id, []))
        if isinstance(node, _FUNC_DEFS) and \
                _decorator_names(node) & entries:
            traced.add(node)

    changed = True
    while changed:
        changed = False
        for fn in list(traced):
            for node in ast.walk(fn):
                if isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Name):
                    for d in defs_by_name.get(node.func.id, []):
                        if d not in traced:
                            traced.add(d)
                            changed = True
    return sorted(traced, key=lambda n: n.lineno)


@rule("host-device-sync", "error",
      "host sync / host materialization inside traced device code")
def check_host_device_sync(ctx: ModuleContext) -> Iterable[Finding]:
    """Inside functions traced by jit/vmap/shard_map/scan (config
    `device-modules`), `.item()`, `.tolist()`, `np.asarray`/`np.array`, and
    `float()`/`int()`/`bool()` on traced values either fail at trace time
    or force a device→host transfer per call — keep kernel bodies on
    device and do host conversion outside the traced region."""
    if not ctx.path_matches(ctx.config.device_modules):
        return
    for fn in _collect_traced_functions(ctx):
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if isinstance(func, ast.Attribute) and \
                    func.attr in _SYNC_METHODS:
                yield ctx.finding(
                    node, f".{func.attr}() in traced function "
                          f"{getattr(fn, 'name', '<fn>')}() forces a "
                          f"host sync")
            elif isinstance(func, ast.Attribute) \
                    and isinstance(func.value, ast.Name) \
                    and func.value.id in _NUMPY_NAMES \
                    and func.attr in _NUMPY_MATERIALIZERS:
                yield ctx.finding(
                    node, f"np.{func.attr}() in traced function "
                          f"{getattr(fn, 'name', '<fn>')}() materializes "
                          f"on host — use jnp inside device code")
            elif isinstance(func, ast.Name) \
                    and func.id in ("float", "int", "bool") \
                    and node.args \
                    and not isinstance(node.args[0], ast.Constant):
                yield ctx.finding(
                    node, f"{func.id}() on a traced value in "
                          f"{getattr(fn, 'name', '<fn>')}() forces a "
                          f"host sync (concretization)")


# ---- no-executable-deserialization ---------------------------------------

_BANNED_SERDE_MODULES = {"pickle", "cPickle", "dill", "marshal", "shelve"}
_BANNED_CALLS = {"eval", "exec"}
_REDUCE_HOOKS = {"__reduce__", "__reduce_ex__"}


@rule("no-executable-deserialization", "error",
      "executable payload deserialization in a wire-facing module")
def check_no_executable_deserialization(ctx: ModuleContext
                                        ) -> Iterable[Finding]:
    """Wire-facing modules (config `wire-modules`) must never deserialize
    executable payloads: no pickle/dill/marshal/shelve, no eval/exec, no
    __reduce__ hooks. A hostile peer's bytes may at worst poison data,
    never execute code (see cluster/wire.py's tensor-bundle format)."""
    if not ctx.path_matches(ctx.config.wire_modules):
        return
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name.split(".")[0] in _BANNED_SERDE_MODULES:
                    yield ctx.finding(
                        node, f"import {alias.name} — executable "
                              f"deserialization is banned on the wire")
        elif isinstance(node, ast.ImportFrom):
            if node.module and \
                    node.module.split(".")[0] in _BANNED_SERDE_MODULES:
                yield ctx.finding(
                    node, f"from {node.module} import ... — executable "
                          f"deserialization is banned on the wire")
        elif isinstance(node, ast.Call):
            func = node.func
            if isinstance(func, ast.Name) and func.id in _BANNED_CALLS:
                yield ctx.finding(
                    node, f"{func.id}() in a wire-facing module")
            elif isinstance(func, ast.Attribute) \
                    and isinstance(func.value, ast.Name) \
                    and func.value.id in _BANNED_SERDE_MODULES:
                yield ctx.finding(
                    node, f"{func.value.id}.{func.attr}() in a "
                          f"wire-facing module")
        elif isinstance(node, _FUNC_DEFS) and node.name in _REDUCE_HOOKS:
            yield ctx.finding(
                node, f"{node.name} defined in a wire-facing module — "
                      f"reduce hooks are pickle's code-execution vector")


# ---- wire-decoded-rows ----------------------------------------------------

_COLUMN_ATTRS = {"values", "ids"}


def _is_column_chain(node: ast.AST) -> bool:
    """True for attribute chains ending in a column-rows accessor
    (`col.values`, `self.metrics[name].ids`, …)."""
    return isinstance(node, ast.Attribute) and node.attr in _COLUMN_ATTRS


@rule("wire-decoded-rows", "error",
      "decoded column rows materialized in a compressed-path module")
def check_wire_decoded_rows(ctx: ModuleContext) -> Iterable[Finding]:
    """Modules on the compressed data path (config `wire-modules` — the
    wire codec and the format-V2 loader) must not materialize decoded
    column rows: `np.asarray(col.values)` / `col.ids.tolist()` silently
    re-decodes what the cascade format exists to keep compressed, turning
    a zero-copy path into a full-column host decode. Explicit V1-compat /
    lazy-materialization paths carry an inline
    `# druidlint: disable=wire-decoded-rows`."""
    if not ctx.path_matches(ctx.config.wire_modules):
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if isinstance(func, ast.Attribute) \
                and func.attr in ("asarray", "frombuffer") \
                and _terminal(func.value) in ("np", "numpy") \
                and node.args and _is_column_chain(node.args[0]):
            yield ctx.finding(
                node, f"np.{func.attr}({_dotted(node.args[0])}) "
                      f"materializes decoded rows on the compressed path")
        elif isinstance(func, ast.Attribute) \
                and func.attr in ("tolist", "astype") \
                and _is_column_chain(func.value):
            yield ctx.finding(
                node, f"{_dotted(func.value)}.{func.attr}() materializes "
                      f"decoded rows on the compressed path")
        elif isinstance(func, ast.Name) and func.id == "bytes" \
                and node.args and _is_column_chain(node.args[0]):
            yield ctx.finding(
                node, f"bytes({_dotted(node.args[0])}) copies decoded "
                      f"rows to host bytes on the compressed path")


# ---- swallowed-exception --------------------------------------------------

_BROAD_TYPES = {"Exception", "BaseException"}
_LOG_METHODS = {"debug", "info", "warning", "warn", "error", "exception",
                "critical", "fatal", "log"}
_EMIT_METHODS = {"emit", "emit_metric", "emit_alert"}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    if isinstance(t, ast.Tuple):
        return any(_terminal(e) in _BROAD_TYPES for e in t.elts)
    return _terminal(t) in _BROAD_TYPES


def _handler_observes(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in (_LOG_METHODS | _EMIT_METHODS):
            return True
        if handler.name and isinstance(node, ast.Name) \
                and node.id == handler.name \
                and isinstance(node.ctx, ast.Load):
            return True             # exception bound AND used (recorded)
    return False


@rule("swallowed-exception", "warning",
      "broad except that neither logs, re-raises, nor records the error")
def check_swallowed_exception(ctx: ModuleContext) -> Iterable[Finding]:
    """Bare `except:` and `except Exception:` handlers must observe the
    failure: log it with context, emit it, re-raise, or capture-and-record
    the bound exception. Silent `pass`/`continue` hides real faults (a
    partitioned lease store looks identical to a healthy idle one)."""
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.ExceptHandler) and _is_broad(node) \
                and not _handler_observes(node):
            what = "bare except" if node.type is None else \
                f"except {_dotted(node.type)}"
            yield ctx.finding(
                node, f"{what} swallows the error — log with context, "
                      f"narrow the type, or re-raise")


# ---- lock-scope -----------------------------------------------------------

_BLOCKING_ATTRS = _EMIT_METHODS | {"sleep", "urlopen"}
_BLOCKING_PREFIXES = ("requests.", "subprocess.", "urllib.request.")
_SQL_ATTRS = {"execute", "executemany", "executescript"}


def _is_lockish(expr: ast.AST) -> bool:
    name = _terminal(expr).lower()
    return ("lock" in name or "mutex" in name) and "unlock" not in name


@rule("lock-scope", "warning",
      "blocking call (emit / sleep / I/O / SQL) while holding a lock")
def check_lock_scope(ctx: ModuleContext) -> Iterable[Finding]:
    """Emitter calls, sleeps, HTTP, subprocesses, and SQL execution inside
    a `with <lock>:` body serialize unrelated threads behind one slow
    operation (and deadlock when the callee re-enters). Compute under the
    lock, do the blocking work outside it. Modules whose lock exists to
    serialize the blocking resource itself are exempt via
    `lock-scope-exclude`."""
    if ctx.path_matches(ctx.config.lock_scope_exclude):
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.With):
            continue
        if not any(_is_lockish(item.context_expr) for item in node.items):
            continue
        for sub in ast.walk(node):
            # deferred bodies run after the with-block: not under the lock
            if isinstance(sub, _FUNC_DEFS + (ast.Lambda,)) :
                continue
            if not isinstance(sub, ast.Call):
                continue
            if _enclosed_in_deferred(ctx, sub, node):
                continue
            dotted = _dotted(sub.func)
            attr = _terminal(sub.func)
            if attr in _BLOCKING_ATTRS \
                    or dotted.startswith(_BLOCKING_PREFIXES):
                yield ctx.finding(
                    sub, f"{dotted}() while holding "
                         f"{_dotted(node.items[0].context_expr)} — move "
                         f"the blocking call outside the lock")
            elif attr in _SQL_ATTRS and isinstance(sub.func, ast.Attribute):
                yield ctx.finding(
                    sub, f"SQL {attr}() while holding "
                         f"{_dotted(node.items[0].context_expr)} — "
                         f"queries under an unrelated lock serialize "
                         f"readers behind the store")


def _enclosed_in_deferred(ctx: ModuleContext, node: ast.AST,
                          stop: ast.AST) -> bool:
    cur = ctx.parent(node)
    while cur is not None and cur is not stop:
        if isinstance(cur, _FUNC_DEFS + (ast.Lambda,)):
            return True
        cur = ctx.parent(cur)
    return False


# ---- unbounded-retry ------------------------------------------------------

#: exception names whose catch-and-retry marks a NETWORK/CAPACITY retry
#: loop (connectivity, remote errors, sheds, socket timeouts). Broad
#: `except Exception` is deliberately NOT in this set — that is the
#: swallowed-exception rule's domain, and flagging it here would indict
#: every skip-and-continue iteration loop (inventory sync, liveness).
_RETRYABLE_ERRORS = {"ConnectionError", "ConnectionResetError",
                     "ConnectionRefusedError", "BrokenPipeError",
                     "TimeoutError", "timeout", "OSError", "URLError",
                     "HTTPError", "QueryCapacityError", "QueryTimeoutError",
                     "RemoteQueryError"}

#: a call with one of these attrs on a receiver named like a deadline
#: counts as consulting the bound
_DEADLINE_CONSULTS = {"check", "expired", "remaining_ms", "remaining",
                      "clamp"}


def _deadline_names(tree: ast.AST) -> Set[str]:
    """Names bound to the shared Deadline type anywhere in the module:
    locals assigned from `Deadline(...)` / `Deadline.for_query(...)` /
    `.after_s(...)` / `.until(...)`, and parameters annotated `Deadline`.
    A consult through one of these counts even when the receiver is not
    named "*deadline*" (server/deadline.py is the one carrier type; the
    name heuristic alone would miss e.g. `window.remaining()`)."""
    out: Set[str] = set()
    for n in ast.walk(tree):
        if isinstance(n, (ast.Assign, ast.AnnAssign)):
            v = n.value
            is_dl = isinstance(v, ast.Call) and (
                _terminal(v.func) == "Deadline"
                or (isinstance(v.func, ast.Attribute)
                    and _terminal(v.func.value) == "Deadline"))
            if not is_dl:
                continue
            targets = n.targets if isinstance(n, ast.Assign) else [n.target]
            for t in targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
        elif isinstance(n, _FUNC_DEFS):
            args = n.args
            for a in (args.posonlyargs + args.args + args.kwonlyargs):
                ann = a.annotation
                if (isinstance(ann, ast.Name) and ann.id == "Deadline") or \
                        (isinstance(ann, ast.Constant)
                         and ann.value == "Deadline"):
                    out.add(a.arg)
    return out


def _same_loop_children(stmts) -> Iterable[ast.AST]:
    """Walk statements WITHOUT descending into nested loops, function
    defs, or classes — a Try in a nested loop retries THAT loop (which
    gets its own check), not this one."""
    for s in stmts:
        if isinstance(s, (ast.For, ast.AsyncFor, ast.While, ast.ClassDef,
                          ast.Lambda) + _FUNC_DEFS):
            continue
        yield s
        for field in ("body", "orelse", "finalbody", "handlers"):
            sub = getattr(s, field, None)
            if sub:
                if field == "handlers":
                    for h in sub:
                        yield h
                        yield from _same_loop_children(h.body)
                else:
                    yield from _same_loop_children(sub)


def _catches_retryable(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return False                       # bare except: swallowed-exception
    elts = t.elts if isinstance(t, ast.Tuple) else [t]
    return any(_terminal(e) in _RETRYABLE_ERRORS for e in elts)


def _handler_retries(handler: ast.ExceptHandler) -> bool:
    """True when the handler can reach the next loop iteration: it does
    not END in an unconditional raise/return/break. (A conditional abort
    followed by fall-through still retries.)"""
    last = handler.body[-1]
    return not isinstance(last, (ast.Raise, ast.Return, ast.Break))


def _consults_deadline(loop: ast.AST,
                       dl_names: Set[str] = frozenset()) -> bool:
    for n in ast.walk(loop):
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                and n.func.attr in _DEADLINE_CONSULTS:
            recv = _terminal(n.func.value)
            if "deadline" in recv.lower() or recv in dl_names:
                return True
    return False


def _loop_bounded(loop, dl_names: Set[str] = frozenset()) -> bool:
    if isinstance(loop, ast.For):
        it = loop.iter
        if isinstance(it, (ast.Tuple, ast.List, ast.Set)):
            return True                    # for attempt in (0, 1)
        if isinstance(it, ast.Call) and _terminal(it.func) == "range":
            return True                    # for _ in range(retries + 1)
        if isinstance(it, ast.Call) and _terminal(it.func) == "enumerate" \
                and it.args and isinstance(it.args[0],
                                           (ast.Tuple, ast.List)):
            return True
    elif isinstance(loop.test, ast.Compare):
        return True                        # while attempt < self.max_...
    return _consults_deadline(loop, dl_names)


@rule("unbounded-retry", "error",
      "catch-and-retry of a network/capacity error with no reachable "
      "Deadline or attempt bound in the loop")
def check_unbounded_retry(ctx: ModuleContext) -> Iterable[Finding]:
    """In data-plane modules (config `retry-modules`), any loop that
    catches a network/capacity error (connection, timeout, 429/capacity,
    remote query error) and can fall through to another iteration must
    carry a bound reachable in the loop: a finite `for` iteration
    (range()/literal sequence), a condition-bounded `while`, or a
    Deadline consult (`deadline.check()` / `.expired()` /
    `.remaining_ms()`). An unbounded retry turns one dead replica into a
    client spinning past its caller's deadline — the hang the chaos
    suite's no-hang contract forbids."""
    if not ctx.path_matches(ctx.config.retry_modules):
        return
    dl_names = _deadline_names(ctx.tree)
    for loop in ast.walk(ctx.tree):
        if not isinstance(loop, (ast.For, ast.While)):
            continue
        handlers = [n for n in _same_loop_children(loop.body)
                    if isinstance(n, ast.ExceptHandler)
                    and _catches_retryable(n) and _handler_retries(n)]
        if not handlers or _loop_bounded(loop, dl_names):
            continue
        for h in handlers:
            yield ctx.finding(
                h, f"retrying {_dotted(h.type) if h.type else 'error'} "
                   f"in an unbounded loop — bound the attempts "
                   f"(range/literal) or consult a Deadline "
                   f"(.check()/.expired()/.remaining_ms()) in the loop")


# ---- metric-name ----------------------------------------------------------

#: parsed catalogs keyed by absolute path; value = ((mtime_ns, size), names)
_CATALOG_CACHE: Dict[str, Tuple[Tuple[int, int], frozenset]] = {}


def _catalog_names(root: str, rel: str) -> frozenset:
    """Metric names declared in the catalog module's METRICS dict literal
    (config `metrics-catalog`). Read with ast — no project imports — and
    memoized on (mtime, size). A missing/unparseable catalog declares
    nothing, so every emitted literal is flagged (the gate fails loudly
    instead of silently passing)."""
    p = Path(root) / rel
    try:
        st = p.stat()
        key = (st.st_mtime_ns, st.st_size)
    except OSError:
        return frozenset()
    hit = _CATALOG_CACHE.get(str(p))
    if hit is not None and hit[0] == key:
        return hit[1]
    try:
        tree = ast.parse(p.read_text())
    except (OSError, SyntaxError):
        return frozenset()
    names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) \
                and any(isinstance(t, ast.Name) and t.id == "METRICS"
                        for t in node.targets) \
                and isinstance(node.value, ast.Dict):
            for k in node.value.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    names.add(k.value)
    out = frozenset(names)
    _CATALOG_CACHE[str(p)] = (key, out)
    return out


@rule("metric-name", "error",
      "emitted metric name not declared in the obs/catalog.py catalog")
def check_metric_name(ctx: ModuleContext) -> Iterable[Finding]:
    """Every `emitter.metric("...")` literal in modules matching config
    `metric-modules` must be declared in the single metrics catalog
    (config `metrics-catalog`, default druid_tpu/obs/catalog.py) — a
    renamed or typoed metric name silently orphans its dashboards and
    alerts; the catalog makes the name set a reviewed, single-source
    surface. Non-literal names are not checkable and pass."""
    if not ctx.path_matches(ctx.config.metric_modules):
        return
    cat_rel = ctx.config.metrics_catalog
    if ctx.path == cat_rel:
        return
    declared = _catalog_names(ctx.config.root, cat_rel)
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "metric" \
                and node.args \
                and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            name = node.args[0].value
            if name not in declared:
                yield ctx.finding(
                    node, f"metric {name!r} is not declared in {cat_rel} — "
                          f"add it to METRICS (name, unit, dims, site) or "
                          f"fix the name drift")


# ---- flag-name ------------------------------------------------------------


@rule("flag-name", "error",
      "DRUID_TPU_* env read not declared in the config/flags.py catalog")
def check_flag_name(ctx: ModuleContext) -> Iterable[Finding]:
    """Every literal ``DRUID_TPU_*`` environment read in modules matching
    config `flag-modules` must name a flag declared in the single flags
    catalog (config `flags-catalog`, default druid_tpu/config/flags.py) —
    a typoed flag read silently falls back to its default forever; the
    catalog makes the flag set a reviewed, single-source surface (the
    `metric-name` pattern). The catalog also carries the latch/live
    semantics keyguard's `env-flag-latch` rule enforces. Non-literal
    names are not checkable and pass."""
    if not ctx.path_matches(ctx.config.flag_modules):
        return
    cat_rel = ctx.config.flags_catalog
    if ctx.path == cat_rel:
        return
    from tools.druidlint.keyguard import _env_read, flag_catalog
    declared = flag_catalog(ctx.config.root, cat_rel)
    for node in ast.walk(ctx.tree):
        got = _env_read(node)
        if got is not None and got[0] not in declared:
            yield ctx.finding(
                got[1], f"flag {got[0]!r} is not declared in {cat_rel} — "
                        f"add a Flag(default, semantics, doc) entry to "
                        f"FLAGS or fix the name drift")


# ---- unused-suppression ---------------------------------------------------


@rule("unused-suppression", "warning",
      "druidlint disable pragma that suppresses nothing")
def check_unused_suppression(ctx: ModuleContext) -> Iterable[Finding]:
    """A `# druidlint: disable=<rule>` comment that silences no finding is
    dead weight: burned-clean files accumulate pragmas that hide future
    regressions on that line, and a typoed rule name suppresses nothing at
    all. Findings are generated by core.check_source (the only place that
    knows which suppressions matched); the registration here gives the rule
    a severity, `--list-rules` visibility, and `--only` addressability.
    Reported only under `--report-unused-suppressions` (config
    `report-unused-suppressions`)."""
    return ()
